"""Unit tests for the graftlint v3 intra-procedural CFG builder.

Each test parses one small function, builds its graph with
``build_cfg``, and asserts structural properties: the edges that must
exist (branch/back/exception), the edges that must NOT exist (no false
edge out of ``while True``), and the finally-duplication lowering that
makes path-sensitive must-release analysis exact.
"""
from __future__ import annotations

import ast
import textwrap

from autoscaler_tpu.analysis.cfg import (
    ENTRY,
    EXIT,
    RAISES,
    build_cfg,
    stmt_can_raise,
)


def _cfg(src: str):
    tree = ast.parse(textwrap.dedent(src).lstrip("\n"))
    func = tree.body[0]
    assert isinstance(func, (ast.FunctionDef, ast.AsyncFunctionDef))
    return build_cfg(func)


def _node_at(cfg, line: int):
    hits = [n for n in cfg.nodes if n.stmt is not None and n.line == line]
    assert hits, f"no statement node at line {line}"
    return hits[0]


def _kinds_out(cfg, idx: int):
    return sorted(e.kind for e in cfg.succ.get(idx, []))


def _reaches(cfg, src: int, dst: int) -> bool:
    seen = set()
    work = [src]
    while work:
        n = work.pop()
        if n == dst:
            return True
        if n in seen:
            continue
        seen.add(n)
        work.extend(e.dst for e in cfg.succ.get(n, []))
    return False


def test_if_else_branches_rejoin():
    cfg = _cfg(
        """
        def f(x):
            if x:
                a = 1
            else:
                a = 2
            return a
        """
    )
    test = _node_at(cfg, 2)
    assert _kinds_out(cfg, test.idx) == ["false", "true"]
    then = _node_at(cfg, 3)
    other = _node_at(cfg, 5)
    ret = _node_at(cfg, 6)
    # both arms flow into the join statement, which returns
    assert {e.src for e in cfg.pred[ret.idx]} == {then.idx, other.idx}
    assert any(e.dst == EXIT for e in cfg.succ[ret.idx])


def test_if_without_else_gets_fallthrough_false_edge():
    cfg = _cfg(
        """
        def f(x):
            if x:
                a = 1
            return x
        """
    )
    test = _node_at(cfg, 2)
    ret = _node_at(cfg, 4)
    kinds = {(e.kind, e.dst) for e in cfg.succ[test.idx]}
    assert ("false", ret.idx) in kinds


def test_while_loop_has_back_edge_and_false_exit():
    cfg = _cfg(
        """
        def f(n):
            while n:
                n -= 1
            return n
        """
    )
    head = _node_at(cfg, 2)
    body = _node_at(cfg, 3)
    assert any(e.dst == head.idx and e.kind == "back" for e in cfg.succ[body.idx])
    assert any(e.kind == "false" for e in cfg.succ[head.idx])


def test_while_true_has_no_false_exit():
    cfg = _cfg(
        """
        def f(q):
            while True:
                item = q.pop()
                if item is None:
                    break
            return 1
        """
    )
    head = _node_at(cfg, 2)
    assert not any(e.kind == "false" for e in cfg.succ[head.idx])
    # break is still a real path to the return
    brk = _node_at(cfg, 5)
    ret = _node_at(cfg, 6)
    assert _reaches(cfg, brk.idx, ret.idx)


def test_except_dispatch_routes_to_handler_and_propagates_unmatched():
    cfg = _cfg(
        """
        def f(x):
            try:
                g(x)
            except ValueError:
                return None
            return x
        """
    )
    call = _node_at(cfg, 3)
    # the call's exc edge targets the synthetic dispatch node
    exc = [e for e in cfg.succ[call.idx] if e.kind == "exc"]
    assert len(exc) == 1
    dispatch = cfg.nodes[exc[0].dst]
    assert dispatch.label == "except-dispatch"
    out = {(e.kind, cfg.nodes[e.dst].label) for e in cfg.succ[dispatch.idx]}
    # one matched-handler edge, plus propagation for non-ValueError
    assert ("except", "handler") in out
    assert any(e.kind == "exc" and e.dst == RAISES for e in cfg.succ[dispatch.idx])


def test_catch_all_except_does_not_propagate():
    cfg = _cfg(
        """
        def f(x):
            try:
                g(x)
            except Exception:
                pass
            return x
        """
    )
    call = _node_at(cfg, 3)
    (exc,) = [e for e in cfg.succ[call.idx] if e.kind == "exc"]
    assert not any(
        e.kind == "exc" and e.dst == RAISES for e in cfg.succ[exc.dst]
    )
    assert not _reaches(cfg, call.idx, RAISES)


def test_finally_duplicated_per_exit_kind():
    cfg = _cfg(
        """
        def f(x):
            try:
                g(x)
                return 1
            finally:
                release(x)
        """
    )
    # one finally copy for the return exit, one for the exception exit —
    # the release statement appears once per pending exit kind
    releases = [n for n in cfg.nodes if n.stmt is not None and n.line == 6]
    assert len(releases) == 2
    # every copy eventually leaves the function, and each exit node is
    # fed by exactly one of the copies
    assert any(_reaches(cfg, n.idx, EXIT) for n in releases)
    assert any(_reaches(cfg, n.idx, RAISES) for n in releases)
    # the return cannot bypass the finally suite
    ret = _node_at(cfg, 4)
    (out,) = cfg.succ[ret.idx]
    assert cfg.nodes[out.dst].label == "finally"


def test_break_through_finally_reaches_loop_exit_via_copy():
    cfg = _cfg(
        """
        def f(items):
            for it in items:
                try:
                    if it:
                        break
                finally:
                    note(it)
            return 1
        """
    )
    brk = _node_at(cfg, 5)
    ret = _node_at(cfg, 8)
    # the break exits the loop, but only through a finally copy
    (out,) = cfg.succ[brk.idx]
    assert cfg.nodes[out.dst].label == "finally"
    assert _reaches(cfg, brk.idx, ret.idx)


def test_raise_only_exits_via_exception_edge():
    cfg = _cfg(
        """
        def f():
            raise ValueError("boom")
        """
    )
    r = _node_at(cfg, 2)
    assert [(e.kind, e.dst) for e in cfg.succ[r.idx]] == [("exc", RAISES)]
    assert not _reaches(cfg, ENTRY, EXIT)


def test_with_body_is_linear_and_context_call_may_raise():
    cfg = _cfg(
        """
        def f(tr):
            with tr.span("tick"):
                work()
            return 1
        """
    )
    w = _node_at(cfg, 2)
    body = _node_at(cfg, 3)
    assert any(e.dst == body.idx and e.kind == "next" for e in cfg.succ[w.idx])
    assert any(e.kind == "exc" for e in cfg.succ[w.idx])


def test_try_else_runs_only_on_clean_body_and_escapes_handlers():
    cfg = _cfg(
        """
        def f(x):
            try:
                g(x)
            except ValueError:
                return 0
            else:
                h(x)
            return 1
        """
    )
    els = _node_at(cfg, 7)
    # else's own exception is NOT dispatched to this try's handlers
    exc = [e for e in cfg.succ[els.idx] if e.kind == "exc"]
    assert exc and exc[0].dst == RAISES
    # and the else block is NOT reachable from the handler
    handler = [n for n in cfg.nodes if n.label == "handler"][0]
    assert not _reaches(cfg, handler.idx, els.idx)


def test_deterministic_rebuild():
    src = """
        def f(x):
            try:
                for i in x:
                    if i:
                        continue
                    g(i)
            finally:
                done()
            return x
        """
    a, b = _cfg(src), _cfg(src)
    assert [(n.idx, n.label, n.line) for n in a.nodes] == [
        (n.idx, n.label, n.line) for n in b.nodes
    ]
    assert a.edges == b.edges


def test_stmt_can_raise_classification():
    mod = ast.parse(
        textwrap.dedent(
            """
            x = 1
            y = g()
            assert x
            raise ValueError
            def nested():
                boom()
            """
        )
    )
    assign, call, asrt, rais, nested = mod.body
    assert not stmt_can_raise(assign)
    assert stmt_can_raise(call)
    assert stmt_can_raise(asrt)
    assert stmt_can_raise(rais)
    assert not stmt_can_raise(nested)  # defining doesn't run the body
