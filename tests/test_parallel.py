"""Sharded what-if tests on the 8-device virtual CPU mesh (conftest forces
--xla_force_host_platform_device_count=8), mirroring how the driver validates
multi-chip via __graft_entry__.dryrun_multichip."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from autoscaler_tpu.estimator.reference_impl import ffd_binpack_reference_groups
from autoscaler_tpu.kube.objects import CPU, MEMORY, PODS
from autoscaler_tpu.parallel.mesh import (
    UNSCHEDULED_PENALTY,
    factor_mesh,
    make_mesh,
    whatif_best_options,
)


def test_factor_mesh():
    assert factor_mesh(8) == (4, 2)
    assert factor_mesh(4) == (2, 2)
    assert factor_mesh(1) == (1, 1)
    assert factor_mesh(6) == (3, 2)
    assert factor_mesh(7) == (7, 1)


def build_whatif(S, G, P_, seed=0):
    rng = np.random.default_rng(seed)
    pod_req = np.zeros((P_, 6), np.float32)
    pod_req[:, CPU] = rng.integers(100, 900, P_)
    pod_req[:, MEMORY] = rng.integers(128, 1024, P_)
    pod_req[:, PODS] = 1
    masks = np.ones((G, P_), bool)
    allocs = np.zeros((S, G, 6), np.float32)
    allocs[:, :, CPU] = rng.integers(2000, 8000, (S, G))
    allocs[:, :, MEMORY] = rng.integers(4096, 16384, (S, G))
    allocs[:, :, PODS] = 110
    prices = rng.uniform(0.5, 3.0, (S, G)).astype(np.float32)
    caps = np.full(G, 32, np.int32)
    return pod_req, masks, allocs, prices, caps


def test_whatif_multidevice_matches_reference():
    assert len(jax.devices()) == 8, "conftest must provide 8 virtual devices"
    mesh = make_mesh()
    S, G, P_ = 8, 4, 64
    pod_req, masks, allocs, prices, caps = build_whatif(S, G, P_)
    res = whatif_best_options(
        mesh,
        jnp.asarray(pod_req),
        jnp.asarray(masks),
        jnp.asarray(allocs),
        jnp.asarray(prices),
        jnp.asarray(caps),
        max_nodes=32,
    )
    counts = np.asarray(res.node_counts)
    # serial oracle per scenario
    for s in range(S):
        ref_counts, ref_scheds = ffd_binpack_reference_groups(
            pod_req, masks, allocs[s], max_nodes=32
        )
        np.testing.assert_array_equal(counts[s], ref_counts)
        pending = P_ - ref_scheds.sum(axis=1)
        ref_cost = prices[s] * ref_counts + UNSCHEDULED_PENALTY * pending
        assert int(res.best_group[s]) == int(np.argmin(ref_cost))
        assert float(res.best_cost[s]) == pytest.approx(float(ref_cost.min()), rel=1e-5)


def test_whatif_single_device_mesh():
    mesh = make_mesh(jax.devices()[:1])
    S, G, P_ = 2, 3, 32
    pod_req, masks, allocs, prices, caps = build_whatif(S, G, P_, seed=5)
    res = whatif_best_options(
        mesh,
        jnp.asarray(pod_req),
        jnp.asarray(masks),
        jnp.asarray(allocs),
        jnp.asarray(prices),
        jnp.asarray(caps),
        max_nodes=16,
    )
    assert res.node_counts.shape == (S, G)
    assert res.best_group.shape == (S,)


class _StubDevice:
    def __init__(self, pid, did):
        self.process_index = pid
        self.id = did

    def __repr__(self):
        return f"d{self.process_index}.{self.id}"


class TestMultihostLayout:
    """arrange_devices_for_hosts: the group axis (the only collective) must
    stay within one host's ICI domain; scenarios span hosts over DCN."""

    def test_single_host_matches_flat_factorization(self):
        from autoscaler_tpu.parallel.mesh import (
            arrange_devices_for_hosts,
            factor_mesh,
        )

        devs = [_StubDevice(0, i) for i in range(8)]
        grid = arrange_devices_for_hosts(devs)
        assert grid.shape == factor_mesh(8)

    def test_group_axis_never_crosses_hosts(self):
        from autoscaler_tpu.parallel.mesh import arrange_devices_for_hosts

        for n_hosts, per_host in ((2, 4), (4, 8), (3, 4)):
            devs = [
                _StubDevice(h, h * per_host + i)
                for h in range(n_hosts)
                for i in range(per_host)
            ]
            grid = arrange_devices_for_hosts(devs)
            assert grid.size == n_hosts * per_host
            # group axis spans the WHOLE ICI domain of a host
            assert grid.shape == (n_hosts, per_host)
            # every row of the grid (one scenario slice) holds devices of
            # exactly one host: the group all_gather stays on ICI
            for row in grid:
                hosts_in_row = {d.process_index for d in row}
                assert len(hosts_in_row) == 1, (n_hosts, per_host, row)
            # and all hosts participate in the scenario axis
            assert {d.process_index for d in grid[:, 0]} == set(range(n_hosts))

    def test_heterogeneous_fleet_rejected(self):
        from autoscaler_tpu.parallel.mesh import arrange_devices_for_hosts

        devs = [_StubDevice(0, 0), _StubDevice(0, 1), _StubDevice(1, 2)]
        with pytest.raises(ValueError):
            arrange_devices_for_hosts(devs)

    def test_multihost_mesh_runs_whatif_on_virtual_devices(self):
        """All 8 virtual CPU devices share process 0, so this exercises the
        single-host degenerate path end-to-end through a real Mesh."""
        import jax

        from autoscaler_tpu.parallel.mesh import (
            make_multihost_mesh,
            whatif_best_options,
        )

        devices = jax.devices()[:8]
        mesh = make_multihost_mesh(devices)
        rng = np.random.default_rng(5)
        s_dim, g_dim = mesh.shape["scenario"], mesh.shape["group"]
        S, G, P_, M = 2 * s_dim, 2 * g_dim, 16, 8
        pod_req = np.zeros((P_, 6), np.float32)
        pod_req[:, CPU] = rng.integers(100, 1500, P_)
        pod_req[:, PODS] = 1
        allocs = np.zeros((S, G, 6), np.float32)
        allocs[:, :, CPU] = rng.integers(2000, 8000, (S, G))
        allocs[:, :, PODS] = 110
        prices = rng.uniform(0.5, 3.0, (S, G)).astype(np.float32)
        masks = np.ones((G, P_), bool)
        caps = np.full(G, M, np.int32)
        res = whatif_best_options(
            mesh, jnp.asarray(pod_req), jnp.asarray(masks), jnp.asarray(allocs),
            jnp.asarray(prices), jnp.asarray(caps), max_nodes=M,
        )
        assert res.best_group.shape == (S,)
        assert (np.asarray(res.node_counts) >= 1).all()


class TestShardedKernelFleet:
    """Round-4 VERDICT item: the kernels people actually deploy — the Pallas
    FFD twin, the dynamic-affinity(+spread) scan, and the scale-down refit —
    certified under shard_map on the virtual 8-device mesh, not just vanilla
    FFD. Workloads come from autoscaler_tpu.utils.sharded_worlds — the SAME
    builders the driver-visible dryrun (__graft_entry__._dryrun_kernel_fleet)
    runs, so the suite and the dryrun cannot drift apart. Parity bases: the
    serial oracles where one exists, the unsharded single-device kernel
    otherwise (which the rest of the suite locks to its own oracle)."""

    def test_pallas_whatif_matches_reference(self):
        from autoscaler_tpu.ops.pallas_binpack import ffd_binpack_groups_pallas
        from autoscaler_tpu.parallel.mesh import make_mesh, whatif_best_options

        mesh = make_mesh()
        S, G, P_, M = 4, 4, 96, 16
        pod_req, masks, allocs, prices, caps = build_whatif(S, G, P_, seed=11)
        caps = np.full(G, M, np.int32)
        res = whatif_best_options(
            mesh, jnp.asarray(pod_req), jnp.asarray(masks), jnp.asarray(allocs),
            jnp.asarray(prices), jnp.asarray(caps), max_nodes=M,
            binpack_fn=ffd_binpack_groups_pallas, scenario_loop=True,
        )
        counts = np.asarray(res.node_counts)
        for s in range(S):
            ref_counts, ref_scheds = ffd_binpack_reference_groups(
                pod_req, masks, allocs[s], max_nodes=M
            )
            np.testing.assert_array_equal(counts[s], np.minimum(ref_counts, M))
            pending = P_ - ref_scheds.sum(axis=1)
            ref_cost = prices[s] * np.minimum(ref_counts, M) \
                + UNSCHEDULED_PENALTY * pending
            assert int(res.best_group[s]) == int(np.argmin(ref_cost))

    @pytest.mark.parametrize("seed", [0, 3])
    def test_sharded_affinity_matches_oracle(self, seed):
        from autoscaler_tpu.estimator.reference_impl import (
            ffd_binpack_reference_affinity,
        )
        from autoscaler_tpu.parallel.mesh import sharded_affinity_estimate
        from autoscaler_tpu.utils.sharded_worlds import affinity_world
        from jax.sharding import Mesh

        mesh = Mesh(np.asarray(jax.devices()[:8]), ("group",))
        G, P_, T, M = 8, 96, 4, 24
        w = affinity_world(G, P_, T, M, seed=seed)
        counts, scheds, _ = sharded_affinity_estimate(
            mesh, jnp.asarray(w["pod_req"]), jnp.asarray(w["pod_masks"]),
            jnp.asarray(w["template_allocs"]), jnp.asarray(w["node_caps"]), M,
            jnp.asarray(w["match"]), jnp.asarray(w["aff_of"]),
            jnp.asarray(w["anti_of"]), jnp.asarray(w["node_level"]),
            jnp.asarray(w["has_label"]),
        )
        counts = np.asarray(counts)
        scheds = np.asarray(scheds)
        for g in range(G):
            c, s = ffd_binpack_reference_affinity(
                w["pod_req"], w["pod_masks"][g], w["template_allocs"][g], M,
                w["match"], w["aff_of"], w["anti_of"], w["node_level"],
                w["has_label"][g],
            )
            assert counts[g] == c, f"group {g}"
            np.testing.assert_array_equal(scheds[g], s, err_msg=f"group {g}")

    def test_sharded_affinity_pallas_gate_rejects_oversize(self):
        """use_pallas=True on a shape past the VMEM byte model must fail
        loud at dispatch (advisor r4: this public entry point had no gate —
        the shape would die in Mosaic compilation mid-shard_map)."""
        from autoscaler_tpu.parallel.mesh import sharded_affinity_estimate
        from autoscaler_tpu.utils.sharded_worlds import affinity_world
        from jax.sharding import Mesh

        mesh = Mesh(np.asarray(jax.devices()[:8]), ("group",))
        G, P_, T, M = 8, 96, 4, 24
        w = affinity_world(G, P_, T, M, seed=0)
        with pytest.raises(ValueError, match="VMEM gate"):
            sharded_affinity_estimate(
                mesh, jnp.asarray(w["pod_req"]), jnp.asarray(w["pod_masks"]),
                jnp.asarray(w["template_allocs"]),
                jnp.asarray(w["node_caps"]), 65536,  # cap far past budget
                jnp.asarray(w["match"]), jnp.asarray(w["aff_of"]),
                jnp.asarray(w["anti_of"]), jnp.asarray(w["node_level"]),
                jnp.asarray(w["has_label"]), use_pallas=True,
            )

    def test_sharded_affinity_spread_matches_unsharded(self):
        """With hard topology-spread terms in play the sharded run must be
        bit-identical to the single-device kernel (which
        tests/test_spread_binpack.py locks to its serial oracle)."""
        from autoscaler_tpu.ops.binpack import ffd_binpack_groups_affinity
        from autoscaler_tpu.parallel.mesh import sharded_affinity_estimate
        from autoscaler_tpu.utils.sharded_worlds import spread_world
        from jax.sharding import Mesh

        mesh = Mesh(np.asarray(jax.devices()[:8]), ("group",))
        G, M = 8, 12
        spw, spread = spread_world(G, 24, M)
        jargs = {k: jnp.asarray(v) for k, v in spw.items()}
        counts_sh, scheds_sh, _ = sharded_affinity_estimate(
            mesh, jargs["pod_req"], jargs["pod_masks"],
            jargs["template_allocs"], jargs["node_caps"], M, jargs["match"],
            jargs["aff_of"], jargs["anti_of"], jargs["node_level"],
            jargs["has_label"], spread=spread,
        )
        ref = ffd_binpack_groups_affinity(
            jargs["pod_req"], jargs["pod_masks"], jargs["template_allocs"],
            max_nodes=M, match=jargs["match"], aff_of=jargs["aff_of"],
            anti_of=jargs["anti_of"], node_level=jargs["node_level"],
            has_label=jargs["has_label"], node_caps=jargs["node_caps"],
            spread=spread,
        )
        np.testing.assert_array_equal(np.asarray(counts_sh), np.asarray(ref.node_count))
        np.testing.assert_array_equal(np.asarray(scheds_sh), np.asarray(ref.scheduled))
        # the spread terms actually bit: some pod was refused placement
        assert not np.asarray(ref.scheduled).all()

    def test_sharded_scaledown_step_matches_unsharded(self):
        from autoscaler_tpu.ops.scaledown import (
            joint_removal_feasibility,
            removal_feasibility,
        )
        from autoscaler_tpu.parallel.mesh import sharded_scaledown_step
        from autoscaler_tpu.utils.sharded_worlds import scaledown_world
        from jax.sharding import Mesh

        mesh = Mesh(np.asarray(jax.devices()[:8]), ("candidate",))
        snap, cand, pod_slots, blocked, excluded = scaledown_world(24, 64, 8, 6)
        per_sh, joint_sh = sharded_scaledown_step(
            mesh, snap, jnp.asarray(cand), jnp.asarray(pod_slots),
            jnp.asarray(blocked), jnp.asarray(excluded),
        )
        per_ref = removal_feasibility(
            snap, jnp.asarray(cand), jnp.asarray(pod_slots), jnp.asarray(blocked)
        )
        joint_ref = joint_removal_feasibility(
            snap, jnp.asarray(cand), jnp.asarray(pod_slots), jnp.asarray(excluded)
        )
        for a, b in zip(per_sh, per_ref):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(joint_sh, joint_ref):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        # non-vacuous: mixed feasibility in the per-candidate verdicts
        assert np.asarray(per_ref.feasible).any()

    def test_sharded_scaledown_spread_matches_unsharded(self):
        """The spread-carrying refit trio (spread8 + static_counts +
        cand_sub) through shard_map: per-candidate and joint results must
        equal the unsharded kernels on a world where every mover carries a
        hard zone constraint."""
        from autoscaler_tpu.ops.scaledown import (
            joint_removal_feasibility_spread,
            removal_feasibility_spread,
        )
        from autoscaler_tpu.parallel.mesh import sharded_scaledown_step
        from autoscaler_tpu.utils.sharded_worlds import scaledown_spread_world
        from jax.sharding import Mesh

        mesh = Mesh(np.asarray(jax.devices()[:8]), ("candidate",))
        (snap, cand, pod_slots, blocked, excluded,
         spread8, static_counts, cand_sub) = scaledown_spread_world()
        assert spread8 is not None and len(cand) == 8
        per_sh, joint_sh = sharded_scaledown_step(
            mesh, snap, jnp.asarray(cand), jnp.asarray(pod_slots),
            jnp.asarray(blocked), jnp.asarray(excluded),
            spread=spread8, static_counts=static_counts,
            cand_sub=jnp.asarray(cand_sub),
        )
        per_ref = removal_feasibility_spread(
            snap, jnp.asarray(cand), jnp.asarray(pod_slots),
            jnp.asarray(blocked), spread8, static_counts,
            jnp.asarray(cand_sub),
        )
        joint_ref = joint_removal_feasibility_spread(
            snap, jnp.asarray(cand), jnp.asarray(pod_slots),
            jnp.asarray(excluded), spread8, static_counts,
            jnp.asarray(cand_sub),
        )
        for a, b in zip(per_sh, per_ref):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(joint_sh, joint_ref):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert np.asarray(per_ref.feasible).any()

    def test_sharded_scaledown_partial_spread_args_rejected(self):
        from autoscaler_tpu.parallel.mesh import sharded_scaledown_step
        from autoscaler_tpu.utils.sharded_worlds import scaledown_world
        from jax.sharding import Mesh

        mesh = Mesh(np.asarray(jax.devices()[:8]), ("candidate",))
        snap, cand, pod_slots, blocked, excluded = scaledown_world(24, 64, 8, 6)
        with pytest.raises(AssertionError, match="all-or-none"):
            sharded_scaledown_step(
                mesh, snap, jnp.asarray(cand), jnp.asarray(pod_slots),
                jnp.asarray(blocked), jnp.asarray(excluded),
                spread=((),) * 8,
            )


class TestInertSpreadGate:
    def test_sharded_affinity_inert_spread_rides_pallas_gate(self):
        """ADVICE r5 — a padded-but-undeclared spread tuple (no pod sets
        sp_of) must gate as S=0 like the estimator route, not hard-fail the
        S>32 check: inert terms cannot affect placement. Results must match
        the spread-free dispatch bit-for-bit on both kernel routes."""
        from autoscaler_tpu.parallel.mesh import sharded_affinity_estimate
        from autoscaler_tpu.utils.sharded_worlds import affinity_world
        from jax.sharding import Mesh

        mesh = Mesh(np.asarray(jax.devices()[:8]), ("group",))
        G, P_, T, M = 8, 96, 4, 24
        w = affinity_world(G, P_, T, M, seed=1)
        S = 40  # past the 32-term Pallas payload, but every term inert
        inert = (
            np.zeros((P_, S), bool),          # sp_of.T — nothing declared
            np.zeros((P_, S), bool),          # sp_match.T
            np.zeros((S,), bool),             # node_level
            np.zeros((S,), np.int32),         # max_skew
            np.zeros((S,), np.int32),         # min_domains
            np.zeros((G, S), bool),           # has_label
            np.zeros((G, S), np.int32),       # static_count
            np.zeros((G, S), np.int32),       # min_others
            np.zeros((G, S), np.int32),       # static_min
            np.zeros((G, S), np.int32),       # static_domnum
            np.zeros((G, S), bool),           # force_zero
        )
        args = (
            mesh, jnp.asarray(w["pod_req"]), jnp.asarray(w["pod_masks"]),
            jnp.asarray(w["template_allocs"]), jnp.asarray(w["node_caps"]), M,
            jnp.asarray(w["match"]), jnp.asarray(w["aff_of"]),
            jnp.asarray(w["anti_of"]), jnp.asarray(w["node_level"]),
            jnp.asarray(w["has_label"]),
        )
        for use_pallas in (False, True):
            base = sharded_affinity_estimate(*args, use_pallas=use_pallas)
            got = sharded_affinity_estimate(
                *args, spread=tuple(jnp.asarray(a) for a in inert),
                use_pallas=use_pallas,
            )
            np.testing.assert_array_equal(
                np.asarray(got[0]), np.asarray(base[0]),
                err_msg=f"use_pallas={use_pallas}",
            )
            np.testing.assert_array_equal(
                np.asarray(got[1]), np.asarray(base[1]),
                err_msg=f"use_pallas={use_pallas}",
            )

    def test_sharded_affinity_declared_wide_spread_still_rejected(self):
        """A DECLARED >32-term spread tuple keeps failing the Pallas gate
        loudly (the payload really can't carry it)."""
        from autoscaler_tpu.parallel.mesh import sharded_affinity_estimate
        from autoscaler_tpu.utils.sharded_worlds import affinity_world
        from jax.sharding import Mesh

        mesh = Mesh(np.asarray(jax.devices()[:8]), ("group",))
        G, P_, T, M = 8, 96, 4, 24
        w = affinity_world(G, P_, T, M, seed=1)
        S = 40
        declared = [np.zeros((P_, S), bool) for _ in range(2)]
        declared[0][0, 0] = True  # one pod declares one term
        spread = (
            jnp.asarray(declared[0]), jnp.asarray(declared[1]),
            jnp.asarray(np.zeros((S,), bool)),
            jnp.asarray(np.zeros((S,), np.int32)),
            jnp.asarray(np.zeros((S,), np.int32)),
            jnp.asarray(np.zeros((G, S), bool)),
            jnp.asarray(np.zeros((G, S), np.int32)),
            jnp.asarray(np.zeros((G, S), np.int32)),
            jnp.asarray(np.zeros((G, S), np.int32)),
            jnp.asarray(np.zeros((G, S), np.int32)),
            jnp.asarray(np.zeros((G, S), bool)),
        )
        with pytest.raises(ValueError, match="VMEM gate"):
            sharded_affinity_estimate(
                mesh, jnp.asarray(w["pod_req"]), jnp.asarray(w["pod_masks"]),
                jnp.asarray(w["template_allocs"]),
                jnp.asarray(w["node_caps"]), M,
                jnp.asarray(w["match"]), jnp.asarray(w["aff_of"]),
                jnp.asarray(w["anti_of"]), jnp.asarray(w["node_level"]),
                jnp.asarray(w["has_label"]), spread=spread, use_pallas=True,
            )
