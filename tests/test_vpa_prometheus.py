"""Prometheus history provider: query-string parity with the reference,
recorded-server round trip, and a recommender warm-start replay.

Reference: vertical-pod-autoscaler/pkg/recommender/input/history/
history_provider.go (GetClusterHistory :263, readResourceHistory :186,
readLastLabels :225) and its own test expectations
(history_provider_test.go:34-38)."""
from __future__ import annotations

import json
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, HTTPServer

import pytest

from autoscaler_tpu.kube.objects import LabelSelector
from autoscaler_tpu.vpa.api import Vpa
from autoscaler_tpu.vpa.feeder import ClusterStateFeeder
from autoscaler_tpu.vpa.prometheus_history import (
    PrometheusHistoryConfig,
    PrometheusHistorySource,
    parse_duration_s,
)
from autoscaler_tpu.vpa.recommender import (
    ClusterStateModel,
    ContainerKey,
    PercentileRecommender,
)

GB = 1024 ** 3


class TestDurations:
    @pytest.mark.parametrize("s,expect", [
        ("30s", 30.0), ("5m", 300.0), ("1h", 3600.0),
        ("8d", 8 * 86400.0), ("2w", 14 * 86400.0), ("1y", 365 * 86400.0),
        ("250ms", 0.25),
        # compound durations (prommodel.ParseDuration: descending units,
        # each at most once) — operators migrating reference configs use
        # forms like 1d12h for --history-length
        ("1h30m", 5400.0), ("1d12h", 36 * 3600.0),
        ("2m30s", 150.0), ("1s500ms", 1.5),
        ("0", 0.0),   # prommodel special-cases the bare zero
    ])
    def test_prometheus_duration_grammar(self, s, expect):
        assert parse_duration_s(s) == expect

    @pytest.mark.parametrize(
        "bad", ["", "8", "d8", "1.5h", "8dd", "1m1m", "30m1h", "1h 30m"]
    )
    def test_invalid_rejected(self, bad):
        with pytest.raises(ValueError):
            parse_duration_s(bad)


class TestQueryStrings:
    """Byte-for-byte the selector structure the reference builds
    (GetClusterHistory :263; expectations history_provider_test.go:34-38)."""

    def _source(self, **kw):
        cfg = PrometheusHistoryConfig(
            address="http://prom:9090", history_resolution="30s", **kw
        )
        return PrometheusHistorySource(cfg)

    def test_cpu_query_matches_reference_expectation(self):
        assert self._source().cpu_query() == (
            'rate(container_cpu_usage_seconds_total{job="kubernetes-cadvisor", '
            'pod_name=~".+", name!="POD", name!=""}[30s])'
        )

    def test_memory_query_matches_reference_expectation(self):
        assert self._source().memory_query() == (
            'container_memory_working_set_bytes{job="kubernetes-cadvisor", '
            'pod_name=~".+", name!="POD", name!=""}'
        )

    def test_namespaced_query(self):
        assert self._source(namespace="kube-system").cpu_query() == (
            'rate(container_cpu_usage_seconds_total{job="kubernetes-cadvisor", '
            'pod_name=~".+", name!="POD", name!="", namespace="kube-system"}'
            "[30s])"
        )

    def test_no_job_matcher_when_job_name_empty(self):
        q = self._source(cadvisor_job_name="").cpu_query()
        assert q.startswith(
            'rate(container_cpu_usage_seconds_total{pod_name=~".+"'
        )


def _matrix(series):
    return {
        "status": "success",
        "data": {"resultType": "matrix", "result": series},
    }


class _RecordedProm(BaseHTTPRequestHandler):
    """A reference-shaped Prometheus /api/v1 endpoint: answers the three
    provider queries from canned matrices and records every request."""

    requests: list = []

    def do_GET(self):  # noqa: N802
        parsed = urllib.parse.urlparse(self.path)
        params = dict(urllib.parse.parse_qsl(parsed.query))
        type(self).requests.append((parsed.path, params))
        query = params.get("query", "")
        if parsed.path == "/api/v1/query_range":
            if query.startswith("rate(container_cpu_usage_seconds_total"):
                body = _matrix([
                    {
                        "metric": {"namespace": "default", "pod_name": "web-1",
                                   "name": "main"},
                        "values": [[i * 60.0, "0.5"] for i in range(50)],
                    },
                    {
                        "metric": {"namespace": "default", "pod_name": "web-1",
                                   "name": "main"},
                        # second chunk for the same container: must append
                        "values": [[(50 + i) * 60.0, "0.7"] for i in range(50)],
                    },
                ])
            else:
                body = _matrix([
                    {
                        "metric": {"namespace": "default", "pod_name": "web-1",
                                   "name": "main"},
                        "values": [[i * 60.0, str(1 * GB)] for i in range(100)],
                    },
                ])
        elif parsed.path == "/api/v1/query":
            body = _matrix([
                {
                    "metric": {
                        "kubernetes_namespace": "default",
                        "kubernetes_pod_name": "web-1",
                        "pod_label_app": "web",
                        "job": "kube-state-metrics",
                    },
                    "values": [[900.0, "1"]],
                },
                {
                    # staler duplicate with different labels: must lose
                    "metric": {
                        "kubernetes_namespace": "default",
                        "kubernetes_pod_name": "web-1",
                        "pod_label_app": "stale",
                    },
                    "values": [[100.0, "1"]],
                },
            ])
        else:
            self.send_error(404)
            return
        payload = json.dumps(body).encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    def log_message(self, *a):  # silence
        pass


@pytest.fixture()
def prom_server():
    _RecordedProm.requests = []
    srv = HTTPServer(("127.0.0.1", 0), _RecordedProm)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{srv.server_port}"
    srv.shutdown()


class TestRecordedServer:
    def test_round_trip_and_request_shape(self, prom_server):
        src = PrometheusHistorySource(PrometheusHistoryConfig(
            address=prom_server, history_length="8d", history_resolution="1h",
        ))
        cpu = src.cpu_series()
        mem = src.memory_series()
        labels = src.pod_labels()

        key = ("default", "web-1", "main")
        assert len(cpu[key]) == 100  # both chunks appended, sorted
        assert cpu[key][0] == (0.0, 0.5)
        assert cpu[key][-1] == (99 * 60.0, 0.7)
        assert len(mem[key]) == 100
        # label prefix stripped; freshest sample wins over the stale series
        assert labels[("default", "web-1")] == {"app": "web"}

        paths = [p for p, _ in _RecordedProm.requests]
        assert paths == ["/api/v1/query_range", "/api/v1/query_range",
                         "/api/v1/query"]
        # range params: an 8d window at 1h step — sent as plain float
        # seconds, the one form Prometheus accepts for ANY resolution
        # (a composed "0.5s" duration string would be rejected for
        # sub-second steps like --history-resolution=500ms)
        _, params = _RecordedProm.requests[0]
        assert params["step"] == "3600"
        assert float(params["end"]) - float(params["start"]) == pytest.approx(
            8 * 86400.0, abs=5.0
        )
        # the three queries only fire once: accessors reuse the cache
        src.cpu_series()
        assert len(_RecordedProm.requests) == 3

    def test_warm_start_replay(self, prom_server):
        """Full warm start: recorded server → HistorySource → feeder replay →
        the recommender produces a target with ZERO live samples (the
        reference's InitFromHistoryProvider behavior)."""
        src = PrometheusHistorySource(PrometheusHistoryConfig(
            address=prom_server,
        ))
        model = ClusterStateModel()
        vpa = Vpa(name="my-vpa",
                  target_selector=LabelSelector.from_dict({"app": "web"}))
        n = ClusterStateFeeder(model, [vpa]).replay_history(src)
        assert n == 200  # 100 cpu + 100 memory points
        recs = PercentileRecommender(model).recommend(now_ts=100 * 60.0)
        rec = recs[ContainerKey("my-vpa", "main")]
        # p90 over 50x0.5 + 50x0.7 cores ~ 0.7, +15% margin
        assert rec.target_cpu == pytest.approx(0.7 * 1.15, rel=0.1)
        assert rec.target_memory >= 1 * GB

    def test_error_envelope_raises(self, prom_server):
        src = PrometheusHistorySource(PrometheusHistoryConfig(
            address=prom_server,
        ))

        def failing_open(url, timeout):
            import io
            import contextlib

            @contextlib.contextmanager
            def cm():
                yield io.BytesIO(json.dumps(
                    {"status": "error", "error": "query too long"}
                ).encode())
            return cm()

        src._open = failing_open
        with pytest.raises(RuntimeError, match="query too long"):
            src.cpu_series()

    def test_missing_container_label_raises(self, prom_server):
        """A scrape config whose series lack the configured container label
        must fail loudly (reference getContainerIDFromLabels hard-fails),
        not silently drop all history."""
        src = PrometheusHistorySource(PrometheusHistoryConfig(
            address=prom_server, ctr_name_label="container_name",
        ))
        with pytest.raises(RuntimeError, match="container_name"):
            src.cpu_series()
