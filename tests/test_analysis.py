"""graftlint (autoscaler_tpu/analysis): per-rule positive/negative fixtures,
pragma suppression, baseline round-trip + stale ratchet, whole-program
rules (cross-module GL006 reach, GL007 kernel contracts, GL008 lock order,
GL009 flag wiring), CLI contract (formats, exit codes, summary table,
byte-stable JSON), and the self-check that the repo (with its shipped
baseline) and the analysis package itself scan clean.

Fixture paths are *virtual* — ``check_source``/``analyze_sources`` scope
rules on the path string, no file need exist — except for the CLI/baseline
tests, which build a real miniature ``autoscaler_tpu/`` tree in tmp_path.
"""
from __future__ import annotations

import json
import re
import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

from autoscaler_tpu.analysis import baseline as baseline_mod
from autoscaler_tpu.analysis import analyze_sources, check_source, scan_paths
from autoscaler_tpu.analysis.cli import main as cli_main
from autoscaler_tpu.analysis.engine import display_path, module_path
from autoscaler_tpu.analysis.rules import function_label_taxonomy

REPO = Path(__file__).resolve().parent.parent


def findings(source: str, path: str):
    return check_source(textwrap.dedent(source), path)


def multi_findings(sources: dict):
    found, _ = analyze_sources(
        {p: textwrap.dedent(s) for p, s in sources.items()}
    )
    return found


def rules_of(found):
    return [f.rule for f in found]


# -- engine plumbing ----------------------------------------------------------


def test_path_normalization():
    assert (
        display_path("/tmp/x/autoscaler_tpu/loadgen/driver.py")
        == "autoscaler_tpu/loadgen/driver.py"
    )
    assert module_path("/tmp/x/autoscaler_tpu/core/a.py") == "core/a.py"
    assert module_path("/tmp/elsewhere/tool.py") is None


def test_taxonomy_extracted_without_import():
    tax = function_label_taxonomy()
    assert {"main", "estimate", "deviceDispatch", "kubeRequest"} <= tax


# -- GL001 wall clock / randomness -------------------------------------------


def test_gl001_flags_wall_clock_in_replay_scope():
    found = findings(
        """
        import time

        def tick():
            return time.time()
        """,
        "autoscaler_tpu/loadgen/fixture.py",
    )
    assert rules_of(found) == ["GL001"]
    assert "time.time" in found[0].message


def test_gl001_resolves_import_aliases():
    found = findings(
        """
        import time as t
        from time import monotonic as mono

        def f():
            return t.sleep(1) or mono()
        """,
        "autoscaler_tpu/core/fixture.py",
    )
    assert rules_of(found) == ["GL001", "GL001"]


def test_gl001_flags_ambient_randomness_allows_seeded():
    found = findings(
        """
        import random
        import numpy as np

        def bad():
            return random.random() + np.random.rand()

        def good(seed):
            return random.Random(seed).random() + np.random.default_rng(seed).random()
        """,
        "autoscaler_tpu/expander/fixture.py",
    )
    assert rules_of(found) == ["GL001", "GL001"]


def test_gl001_injected_default_reference_is_the_seam():
    found = findings(
        """
        import time
        from typing import Callable

        def run(clock: Callable[[], float] = time.monotonic) -> float:
            return clock()
        """,
        "autoscaler_tpu/core/fixture.py",
    )
    assert found == []


def test_gl001_parameter_shadowing_module_name_is_a_seam():
    # an injected rng/clock PARAMETER named `random`/`time` is the
    # sanctioned seam shape, not the ambient module
    found = findings(
        """
        def pick(random, time):
            time.sleep(0)
            return random.choice([1, 2])
        """,
        "autoscaler_tpu/core/fixture.py",
    )
    assert found == []


def test_gl001_out_of_scope_module_not_flagged():
    found = findings(
        """
        import time

        def f():
            return time.time()
        """,
        "autoscaler_tpu/kube/fixture.py",  # not a replay-reachable scope
    )
    assert found == []


# -- GL002 span-name taxonomy -------------------------------------------------


def test_gl002_flags_non_taxonomy_span_literal():
    found = findings(
        """
        from autoscaler_tpu import trace

        def f():
            with trace.span("totallyNewPhase"):
                pass
        """,
        "autoscaler_tpu/core/fixture.py",
    )
    assert rules_of(found) == ["GL002"]
    assert "totallyNewPhase" in found[0].message


def test_gl002_taxonomy_literal_and_constant_ok():
    found = findings(
        """
        from autoscaler_tpu import trace
        from autoscaler_tpu.metrics import metrics as metrics_mod

        def f(tracer):
            with trace.span("estimate"):
                pass
            with tracer.tick(metrics_mod.MAIN):
                pass
        """,
        "autoscaler_tpu/core/fixture.py",
    )
    assert found == []


def test_gl002_regex_match_span_not_flagged():
    found = findings(
        """
        import re

        def f(m: "re.Match"):
            return m.span("group")
        """,
        "autoscaler_tpu/core/fixture.py",
    )
    assert found == []


# -- GL003 ladder bypass ------------------------------------------------------

_DISPATCH_SRC = """
    from autoscaler_tpu.ops.binpack import ffd_binpack

    def f(req, mask, alloc):
        return ffd_binpack(req, mask, alloc, max_nodes=8)
    """


def test_gl003_flags_dispatch_outside_ladder_modules():
    found = findings(_DISPATCH_SRC, "autoscaler_tpu/core/fixture.py")
    assert rules_of(found) == ["GL003"]
    assert "_walk_ladder" in found[0].message


def test_gl003_estimator_and_ops_allowed():
    assert findings(_DISPATCH_SRC, "autoscaler_tpu/estimator/fixture.py") == []
    assert findings(_DISPATCH_SRC, "autoscaler_tpu/ops/fixture.py") == []


def test_gl003_pallas_call_only_in_ops():
    src = """
        import jax.experimental.pallas as pl

        def f(kernel, x):
            return pl.pallas_call(kernel, out_shape=x)(x)
        """
    assert rules_of(findings(src, "autoscaler_tpu/estimator/fixture.py")) == [
        "GL003"
    ]
    assert findings(src, "autoscaler_tpu/ops/fixture.py") == []


# -- GL004 lock discipline ----------------------------------------------------


def test_gl004_flags_unlocked_write():
    found = findings(
        """
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = []

            def put(self, x):
                self._items = [x]
        """,
        "autoscaler_tpu/metrics/fixture.py",
    )
    assert rules_of(found) == ["GL004"]
    assert "Box.put" in found[0].message


def test_gl004_locked_write_init_and_locked_suffix_ok():
    found = findings(
        """
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = []

            def put(self, x):
                with self._lock:
                    self._items.append(x)
                    self._count = len(self._items)

            def _reset_locked(self):
                self._items = []
        """,
        "autoscaler_tpu/metrics/fixture.py",
    )
    assert found == []


def test_gl004_nested_def_does_not_inherit_lock():
    # a closure defined under `with self._lock:` runs LATER, lock released
    found = findings(
        """
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0

            def deferred(self):
                with self._lock:
                    def later():
                        self._n = 1
                    return later
        """,
        "autoscaler_tpu/utils/circuit.py",
    )
    assert rules_of(found) == ["GL004"]


def test_gl004_nested_class_lock_does_not_leak_to_enclosing():
    found = findings(
        """
        import threading

        class Outer:
            class Inner:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._n = 0

                def bump(self):
                    self._n += 1

            def set(self, v):
                self._v = v
        """,
        "autoscaler_tpu/metrics/fixture.py",
    )
    # Outer has no lock -> Outer.set is fine; Inner.bump IS flagged
    assert [(f.rule, "Inner.bump" in f.message) for f in found] == [
        ("GL004", True)
    ]


def test_gl004_bare_annotation_is_not_a_write():
    found = findings(
        """
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()

            def declare(self):
                self._x: int
        """,
        "autoscaler_tpu/metrics/fixture.py",
    )
    assert found == []


def test_gl004_class_without_lock_not_checked():
    found = findings(
        """
        class Free:
            def put(self, x):
                self._items = [x]
        """,
        "autoscaler_tpu/metrics/fixture.py",
    )
    assert found == []


# -- GL005 error boundary -----------------------------------------------------


def test_gl005_flags_swallowed_exception_in_core():
    found = findings(
        """
        def run_once():
            try:
                work()
            except Exception:
                pass
        """,
        "autoscaler_tpu/core/fixture.py",
    )
    assert rules_of(found) == ["GL005"]
    assert "run_once" in found[0].message


def test_gl005_routed_or_reraised_ok_and_scope_limited():
    src = """
        from autoscaler_tpu.utils.errors import to_autoscaler_error

        def a():
            try:
                work()
            except Exception as e:
                err = to_autoscaler_error(e)
                log(err)

        def b():
            try:
                work()
            except Exception:
                raise
        """
    assert findings(src, "autoscaler_tpu/core/fixture.py") == []
    swallow = """
        def f():
            try:
                work()
            except Exception:
                pass
        """
    # estimator/ has its own contract (the ladder records failures); GL005
    # polices only the run_once path
    assert findings(swallow, "autoscaler_tpu/estimator/fixture.py") == []


# -- GL006 jit purity ---------------------------------------------------------


def test_gl006_flags_print_under_partial_jit_decorator():
    found = findings(
        """
        from functools import partial
        import jax

        @partial(jax.jit, static_argnames=("n",))
        def kernel(x, n):
            print(x)
            return x * n
        """,
        "autoscaler_tpu/ops/fixture.py",
    )
    assert rules_of(found) == ["GL006"]
    assert "print()" in found[0].message


def test_gl006_transitive_local_helper_and_metrics():
    found = findings(
        """
        import jax

        def helper(m, x):
            m.metrics.dispatches.inc()
            return x

        def outer(m, x):
            return jax.jit(traced)(x)

        def traced(x):
            return helper(None, x)
        """,
        "autoscaler_tpu/ops/fixture.py",
    )
    assert rules_of(found) == ["GL006"]
    assert "metrics" in found[0].message


def test_gl006_host_side_effects_outside_jit_ok():
    found = findings(
        """
        import jax

        @jax.jit
        def kernel(x):
            return x + 1

        def host(m, x):
            print("dispatching")
            m.metrics.dispatches.inc()
            return kernel(x)
        """,
        "autoscaler_tpu/ops/fixture.py",
    )
    assert found == []


def test_gl006_cross_module_transitive_reach():
    """The whole-program upgrade: a jitted function in ops/ calling a
    helper imported from ANOTHER module taints that helper too — the old
    per-file rule stopped at the module boundary."""
    found = multi_findings({
        "autoscaler_tpu/ops/kernel.py": """
            import jax
            from autoscaler_tpu.snapshot.helpers import leaky

            @jax.jit
            def kernel(x):
                return leaky(x)
            """,
        "autoscaler_tpu/snapshot/helpers.py": """
            def leaky(x):
                print(x)
                return x
            """,
    })
    assert rules_of(found) == ["GL006"]
    assert found[0].path == "autoscaler_tpu/snapshot/helpers.py"
    assert "print()" in found[0].message


def test_gl006_relative_import_in_package_init_resolves():
    """A level-1 relative import inside a package __init__.py anchors on
    the package ITSELF (`from .helpers import leaky` in snapshot/__init__
    is snapshot.helpers.leaky) — resolving one level too high drops the
    edge and GL006 goes blind."""
    found = multi_findings({
        "autoscaler_tpu/snapshot/__init__.py": """
            import jax
            from .helpers import leaky

            @jax.jit
            def reexported_kernel(x):
                return leaky(x)
            """,
        "autoscaler_tpu/snapshot/helpers.py": """
            def leaky(x):
                print(x)
                return x
            """,
    })
    assert rules_of(found) == ["GL006"]
    assert found[0].path == "autoscaler_tpu/snapshot/helpers.py"


def test_explicit_rules_subset_skips_program_rules():
    """scan entry points with an explicit per-file `rules` subset must not
    silently run the whole-program rules too (pre-whole-program API
    scoping): program rules run only by default or when asked for."""
    from autoscaler_tpu.analysis import rules as rules_mod

    sources = {
        "autoscaler_tpu/ops/kernel.py": textwrap.dedent("""
            import jax

            @jax.jit
            def kernel(x):
                print(x)
                return x
            """),
    }
    scoped, _ = analyze_sources(sources, rules=[rules_mod.WallClockInReplayPath()])
    assert scoped == []
    default, _ = analyze_sources(sources)
    assert rules_of(default) == ["GL006"]
    explicit, _ = analyze_sources(
        sources, rules=(), program_rules=[rules_mod.JitPurity()]
    )
    assert rules_of(explicit) == ["GL006"]


def test_gl006_cross_module_respects_import_aliases():
    found = multi_findings({
        "autoscaler_tpu/ops/kernel.py": """
            import jax
            from autoscaler_tpu.snapshot.helpers import leaky as quiet

            def outer(x):
                return jax.jit(traced)(x)

            def traced(x):
                return quiet(x)
            """,
        "autoscaler_tpu/snapshot/helpers.py": """
            def leaky(x):
                print(x)
                return x
            """,
    })
    assert rules_of(found) == ["GL006"]


def test_gl006_unreached_cross_module_helper_not_flagged():
    found = multi_findings({
        "autoscaler_tpu/ops/kernel.py": """
            import jax
            from autoscaler_tpu.snapshot.helpers import leaky

            @jax.jit
            def kernel(x):
                return x + 1

            def host(x):
                return leaky(kernel(x))
            """,
        "autoscaler_tpu/snapshot/helpers.py": """
            def leaky(x):
                print(x)
                return x
            """,
    })
    assert found == []


# -- GL007 kernel contracts ---------------------------------------------------

_KERNEL_MODULE = """
    import jax
    from jax.experimental import pallas as pl

    _STEP_TILE = 8

    KERNEL_CONTRACTS = {
        "my_kernel": {
            "args": {
                "pod_req": {"dims": ["P", "R"], "dtype": "f32"},
                "pod_masks": {"dims": ["G", "P"], "dtype": "bool"},
            },
            "static": {"chunk": {"multiple_of": "_STEP_TILE", "min": 8}},
            "pad": {"P_pad": ["P", "chunk"]},
            "grid": ["P_pad // chunk"],
        },
    }


    def my_kernel(pod_req, pod_masks, chunk, max_nodes=8):
        if chunk % _STEP_TILE != 0:
            raise ValueError("chunk must be a multiple of the tile")
        P = pod_req.shape[0]
        P_pad = P + (-P) % chunk
        return pl.pallas_call(
            _body,
            grid=(P_pad // chunk,),
        )(pod_req)


    def _body(ref):
        pass
    """


def test_gl007_seeded_chunk_violation_with_dispatch_trace():
    """The acceptance-criteria case: chunk=12 against _STEP_TILE=8 caught
    at lint time, message carries the dispatch-site→kernel trace."""
    found = multi_findings({
        "autoscaler_tpu/ops/mykernel.py": _KERNEL_MODULE,
        "autoscaler_tpu/estimator/dispatch.py": """
            from autoscaler_tpu.ops.mykernel import my_kernel

            def estimate(req, masks):
                return my_kernel(req, masks, chunk=12)
            """,
    })
    assert rules_of(found) == ["GL007"]
    f = found[0]
    assert f.path == "autoscaler_tpu/estimator/dispatch.py"
    assert "chunk=12" in f.message
    assert "autoscaler_tpu.estimator.dispatch.estimate" in f.message
    assert "my_kernel" in f.message
    assert "_STEP_TILE(=8)" in f.message


def test_gl007_aligned_dispatch_clean():
    found = multi_findings({
        "autoscaler_tpu/ops/mykernel.py": _KERNEL_MODULE,
        "autoscaler_tpu/estimator/dispatch.py": """
            from autoscaler_tpu.ops.mykernel import my_kernel

            def estimate(req, masks):
                return my_kernel(req, masks, chunk=16)
            """,
    })
    assert found == []


def test_gl007_rank_and_symbol_conflicts_from_shape_inference():
    found = multi_findings({
        "autoscaler_tpu/ops/mykernel.py": _KERNEL_MODULE,
        "autoscaler_tpu/estimator/dispatch.py": """
            import numpy as np
            from autoscaler_tpu.ops.mykernel import my_kernel

            def bad_rank():
                req = np.zeros((100,))
                masks = np.zeros((4, 100))
                return my_kernel(req, masks, chunk=8)

            def bad_symbol():
                req = np.zeros((100, 6))
                masks = np.zeros((4, 101))
                return my_kernel(req, masks, chunk=8)

            def fine():
                req = np.zeros((100, 6))
                masks = np.zeros((4, 100))
                return my_kernel(req, masks, chunk=8)
            """,
    })
    assert rules_of(found) == ["GL007", "GL007"]
    assert "rank 1" in found[0].message
    assert "dim symbol P" in found[1].message


def test_gl007_shape_env_is_flow_conservative():
    """Rebinding a dispatch operand (after the call, or path-dependently)
    must not produce findings: ShapeEnv only acts on single, dominating
    bindings — the fatal gate cannot afford flow-insensitive false
    positives."""
    found = multi_findings({
        "autoscaler_tpu/ops/mykernel.py": _KERNEL_MODULE,
        "autoscaler_tpu/estimator/dispatch.py": """
            import numpy as np
            from autoscaler_tpu.ops.mykernel import my_kernel

            def rebound_after_call(masks):
                req = np.zeros((100, 6))
                out = my_kernel(req, masks, chunk=8)
                req = req[0]
                return out, req

            def branch_dependent(small, masks):
                if small:
                    req = np.zeros((3,))
                else:
                    req = np.zeros((100, 6))
                return my_kernel(req, masks, chunk=8)

            def param_shadow(req, masks):
                if req is None:
                    req = np.zeros((5,))
                return my_kernel(req, masks, chunk=8)

            def bound_after_call_only(req, masks):
                out = my_kernel(req, masks, chunk=8)
                req = np.zeros((7,))
                return out, req
            """,
    })
    assert found == []


def test_gl007_grid_via_local_variable():
    """`grid = (...)` then `pallas_call(..., grid=grid)` (the ops/pallas_fit
    idiom) must still be matched against the declared grid — and drift
    between the two must be caught, not silently skipped."""
    var_grid = _KERNEL_MODULE.replace(
        "        return pl.pallas_call(\n"
        "            _body,\n"
        "            grid=(P_pad // chunk,),\n"
        "        )(pod_req)",
        "        grid = (P_pad // chunk,)\n"
        "        return pl.pallas_call(\n"
        "            _body,\n"
        "            grid=grid,\n"
        "        )(pod_req)",
    )
    assert "grid = (P_pad // chunk,)" in var_grid  # replacement applied
    clean = multi_findings({"autoscaler_tpu/ops/mykernel.py": var_grid})
    assert clean == []
    drifted = multi_findings({
        "autoscaler_tpu/ops/mykernel.py": var_grid.replace(
            '"grid": ["P_pad // chunk"],',
            '"grid": ["P_pad // chunk", "N_pad // chunk"],',
        ),
    })
    assert "GL007" in rules_of(drifted)
    assert any("no pallas_call in the module uses it" in f.message
               for f in drifted)


def test_gl007_pad_witness_symbolic_divisor_mismatch():
    """Contract divisor `chunk` vs idiom divisor `other` where neither
    resolves to a module constant is drift, not agreement (None == None
    must not excuse the mismatch)."""
    drifted = _KERNEL_MODULE.replace(
        "def my_kernel(pod_req, pod_masks, chunk, max_nodes=8):",
        "def my_kernel(pod_req, pod_masks, chunk, other=8, max_nodes=8):",
    ).replace(
        "P_pad = P + (-P) % chunk", "P_pad = P + (-P) % other"
    )
    found = multi_findings({"autoscaler_tpu/ops/mykernel.py": drifted})
    assert "GL007" in rules_of(found)
    assert any("witnessing idiom" in f.message for f in found)


def test_gl007_step_slice_and_axis_stack_are_unknown_not_wrong():
    """`x[::2]` halves the axis and `np.stack(..., axis=1)` transposes the
    dims — both must infer as unknown rather than produce a provably
    wrong shape that fails the fatal gate on correct dispatch code."""
    found = multi_findings({
        "autoscaler_tpu/ops/mykernel.py": _KERNEL_MODULE,
        "autoscaler_tpu/estimator/dispatch.py": """
            import numpy as np
            from autoscaler_tpu.ops.mykernel import my_kernel

            def step_slice(masks):
                big = np.zeros((100, 6))
                req = big[::2]
                m = np.zeros((4, 50))
                return my_kernel(req, m, chunk=8)

            def axis_stack():
                a = np.zeros((6,))
                req = np.stack([a, a, a], axis=1)
                m = np.zeros((4, 6))
                return my_kernel(req, m, chunk=8)

            def multi_arg_arange():
                req = np.zeros((100, 6))
                m = np.stack([np.arange(1, 101), np.arange(1, 101)])
                return my_kernel(req, m, chunk=8)
            """,
    })
    assert found == []


def test_gl007_guard_on_wrong_divisor_is_not_a_witness():
    """A raise-guard on `chunk % 2` does not witness a `multiple_of:
    _STEP_TILE` (=8) declaration — the guard must check the contract's
    own tile."""
    wrong = _KERNEL_MODULE.replace(
        "if chunk % _STEP_TILE != 0:", "if chunk % 2 != 0:"
    )
    found = multi_findings({"autoscaler_tpu/ops/mykernel.py": wrong})
    assert "GL007" in rules_of(found)
    assert any("no runtime guard" in f.message for f in found)


def test_gl006_nested_def_does_not_shadow_imported_name():
    """A function-LOCAL nested def is out of scope at other call sites:
    a bare call must resolve to the imported name, not the same-spelled
    nested def (both directions: no false positive on a pure import, no
    false negative on a leaky one)."""
    factory = """
        import jax
        from autoscaler_tpu.snapshot.helpers import {NAME}

        def factory():
            def {NAME}(x):
                {BODY}
                return x
            return {NAME}

        @jax.jit
        def kernel(x):
            return {NAME}(x)
        """
    # imported helper pure, nested def leaky: clean
    clean = multi_findings({
        "autoscaler_tpu/ops/kernel.py": textwrap.dedent(factory).format(
            NAME="quiet", BODY="print(x)"
        ),
        "autoscaler_tpu/snapshot/helpers.py": """
            def quiet(x):
                return x
            """,
    })
    assert clean == []
    # imported helper leaky, nested def pure: flagged
    leaky = multi_findings({
        "autoscaler_tpu/ops/kernel.py": textwrap.dedent(factory).format(
            NAME="leaky", BODY="pass"
        ),
        "autoscaler_tpu/snapshot/helpers.py": """
            def leaky(x):
                print(x)
                return x
            """,
    })
    assert rules_of(leaky) == ["GL006"]
    assert leaky[0].path == "autoscaler_tpu/snapshot/helpers.py"


def test_gl006_bare_call_resolves_to_function_not_method():
    """A bare `helper(x)` call can never reach `Cls.helper`; resolution
    must land on the module-level function even when a method shares the
    bare name (and sorts first)."""
    found = multi_findings({
        "autoscaler_tpu/ops/kernel.py": """
            import jax

            class B:
                def helper(self):
                    return 1

            def helper(x):
                print(x)
                return x

            @jax.jit
            def kernel(x):
                return helper(x)
            """,
    })
    assert rules_of(found) == ["GL006"]


def test_gl007_ellipsis_subscript_is_unknown_not_wrong():
    """`arr[..., 0]` must infer as unknown (no finding), not as a rank-0
    shape that would trip a false rank-mismatch in the fatal gate."""
    found = multi_findings({
        "autoscaler_tpu/ops/mykernel.py": _KERNEL_MODULE,
        "autoscaler_tpu/estimator/dispatch.py": """
            import numpy as np
            from autoscaler_tpu.ops.mykernel import my_kernel

            def ellipsis_view(masks):
                cube = np.zeros((100, 6, 3))
                req = cube[..., 0]
                return my_kernel(req, masks, chunk=8)
            """,
    })
    assert found == []


def test_gl007_unwitnessed_pad_and_inexact_grid():
    broken = _KERNEL_MODULE.replace(
        "P_pad = P + (-P) % chunk", "P_pad = P"
    )
    found = multi_findings({"autoscaler_tpu/ops/mykernel.py": broken})
    msgs = " | ".join(f.message for f in found)
    assert rules_of(found) == ["GL007", "GL007"]
    assert "witnessing idiom" in msgs
    assert "not provably exact" in msgs


def test_gl007_missing_runtime_guard():
    unguarded = _KERNEL_MODULE.replace(
        '        if chunk % _STEP_TILE != 0:\n'
        '            raise ValueError("chunk must be a multiple of the tile")\n',
        "",
    )
    found = multi_findings({"autoscaler_tpu/ops/mykernel.py": unguarded})
    assert rules_of(found) == ["GL007"]
    assert "no runtime guard" in found[0].message


def test_gl007_contract_for_unknown_function():
    found = multi_findings({
        "autoscaler_tpu/ops/ghost.py": """
            KERNEL_CONTRACTS = {"nonexistent": {"args": {}}}
            """,
    })
    assert rules_of(found) == ["GL007"]
    assert "no such module-level function" in found[0].message


def test_gl007_twin_contracts_must_agree_on_rank_and_dtype():
    twin = """
        KERNEL_CONTRACTS = {
            "twin_kernel": {
                "args": {"pod_req": {"dims": ["P"], "dtype": "i32"}},
            },
        }

        def twin_kernel(pod_req):
            return pod_req
        """
    base = """
        KERNEL_CONTRACTS = {
            "base_kernel": {
                "args": {"pod_req": {"dims": ["P", "R"], "dtype": "f32"}},
            },
        }

        def base_kernel(pod_req):
            return pod_req
        """
    found = multi_findings({
        "autoscaler_tpu/ops/a_base.py": base,
        "autoscaler_tpu/ops/b_twin.py": twin,
    })
    assert rules_of(found) == ["GL007"]
    assert "twin kernels must agree" in found[0].message


def test_gl007_real_ops_contracts_scan_clean_and_nonvacuous():
    """The shipped ops/ contracts hold over the real estimator dispatch
    path (no findings), and the extraction is non-vacuous (contracts exist
    for the Pallas kernels)."""
    from autoscaler_tpu.analysis.contracts import load_module_contracts

    contracts, consts = load_module_contracts(
        str(REPO / "autoscaler_tpu" / "ops" / "pallas_binpack.py")
    )
    assert "ffd_binpack_groups_pallas" in contracts
    assert consts["_STEP_TILE"] == 8
    assert scan_paths([str(REPO / "autoscaler_tpu" / "ops")]) == []


# -- GL008 lock order ---------------------------------------------------------


def test_gl008_cross_file_cycle_detected():
    found = multi_findings({
        "autoscaler_tpu/trace/recorder.py": """
            import threading

            class Recorder:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.breaker = None

                def record(self):
                    with self._lock:
                        self.breaker.trip_breaker()

                def pin_trace(self):
                    with self._lock:
                        pass
            """,
        "autoscaler_tpu/utils/circuit.py": """
            import threading

            class Breaker:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.recorder = None

                def trip_breaker(self):
                    with self._lock:
                        pass

                def note(self):
                    with self._lock:
                        self.recorder.pin_trace()
            """,
    })
    assert rules_of(found) == ["GL008"]
    assert "lock-order cycle" in found[0].message
    assert "Recorder._lock" in found[0].message
    assert "Breaker._lock" in found[0].message


def test_gl008_one_directional_edges_are_fine():
    found = multi_findings({
        "autoscaler_tpu/utils/circuit.py": """
            import threading

            class Breaker:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.metrics = None

                def trip_breaker(self):
                    with self._lock:
                        self.metrics.observe_transition(1)
            """,
        "autoscaler_tpu/metrics/series.py": """
            import threading

            class Series:
                def __init__(self):
                    self._lock = threading.Lock()

                def observe_transition(self, v):
                    with self._lock:
                        pass
            """,
    })
    assert found == []


def test_gl008_self_deadlock_on_plain_lock_not_rlock():
    src = """
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.{LOCK}()

            def outer_op(self):
                with self._lock:
                    self.inner_op()

            def inner_op(self):
                with self._lock:
                    pass
        """
    plain = multi_findings(
        {"autoscaler_tpu/metrics/box.py": src.replace("{LOCK}", "Lock")}
    )
    assert rules_of(plain) == ["GL008"]
    reentrant = multi_findings(
        {"autoscaler_tpu/metrics/box.py": src.replace("{LOCK}", "RLock")}
    )
    assert reentrant == []


def test_gl008_nested_class_owns_its_lock():
    """A nested class's `self._*lock` binding belongs to the nested class,
    not the outer one — flat ast.walk attribution would fabricate cycles
    through locks the outer class never holds."""
    from autoscaler_tpu.analysis.engine import FileModel
    from autoscaler_tpu.analysis.lockgraph import _class_locks

    model = FileModel("autoscaler_tpu/metrics/nested.py", textwrap.dedent("""
        import threading

        class Outer:
            def __init__(self):
                self._lock = threading.Lock()

            class Inner:
                def __init__(self):
                    self._cachelock = threading.RLock()
        """))
    outer = model.tree.body[1]
    locks = _class_locks(model, outer)
    assert set(locks) == {"_lock"}
    inner = outer.body[1]
    assert set(_class_locks(model, inner)) == {"_cachelock"}


def test_gl008_directly_nested_same_plain_lock():
    """`with self._lock:` nested directly inside `with self._lock:` on a
    plain Lock is a guaranteed self-deadlock — caught without any call
    mediation; the RLock form is fine."""
    src = """
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.{LOCK}()

            def doubled_op(self):
                with self._lock:
                    x = 1
                    with self._lock:
                        pass
        """
    plain = multi_findings(
        {"autoscaler_tpu/metrics/box.py": src.replace("{LOCK}", "Lock")}
    )
    assert rules_of(plain) == ["GL008"]
    assert "re-enters" in plain[0].message
    reentrant = multi_findings(
        {"autoscaler_tpu/metrics/box.py": src.replace("{LOCK}", "RLock")}
    )
    assert reentrant == []


def test_gl008_transitive_acquisition_through_unlocked_helper():
    """A.f holds the lock and calls B.helper, which (without a lock of its
    own) calls back into A.locked_op — the cycle closes transitively."""
    found = multi_findings({
        "autoscaler_tpu/metrics/a.py": """
            import threading

            class Alpha:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.beta = None

                def step_one(self):
                    with self._lock:
                        self.beta.relay_call()

                def step_two(self):
                    with self._lock:
                        pass
            """,
        "autoscaler_tpu/metrics/b.py": """
            import threading

            class Beta:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.alpha = None

                def relay_call(self):
                    with self._lock:
                        pass

                def other_path(self):
                    with self._lock:
                        self.alpha.step_two()
            """,
    })
    assert rules_of(found) == ["GL008"]


# -- GL009 flag wiring --------------------------------------------------------


def test_gl009_orphan_option_field():
    found = multi_findings({
        "autoscaler_tpu/config/options.py": """
            from dataclasses import dataclass

            @dataclass
            class AutoscalingOptions:
                scan_interval_s: float = 10.0
                dead_knob: int = 0
            """,
        "autoscaler_tpu/core/loop.py": """
            def run(opts):
                return opts.scan_interval_s
            """,
    })
    assert rules_of(found) == ["GL009"]
    assert "dead_knob" in found[0].message


def test_gl009_orphan_cli_flag():
    found = multi_findings({
        "autoscaler_tpu/main.py": """
            import argparse

            def build():
                p = argparse.ArgumentParser()
                p.add_argument("--scan-interval", type=float, default=10.0)
                p.add_argument("--ghost-flag", type=int, default=0)
                return p

            def main():
                args = build().parse_args()
                return args.scan_interval
            """,
    })
    assert rules_of(found) == ["GL009"]
    assert "--ghost-flag" in found[0].message
    assert "args.ghost_flag" in found[0].message


def test_gl009_getattr_read_counts_as_wired():
    found = multi_findings({
        "autoscaler_tpu/main.py": """
            import argparse

            def build():
                p = argparse.ArgumentParser()
                p.add_argument("--dyn-flag", type=int, default=0)
                return p

            def main():
                args = build().parse_args()
                return getattr(args, "dyn_flag")
            """,
    })
    assert found == []


def test_gl009_silent_on_partial_disk_scan():
    """Scanning only config/ (readers live elsewhere on disk) must not
    flag live options as orphans: 'never read anywhere in the package'
    cannot be proven by a subtree scan, so GL009 silences itself."""
    found = scan_paths([str(REPO / "autoscaler_tpu" / "config")])
    assert [f for f in found if f.rule == "GL009"] == []


def test_gl008_multi_item_with_orders_like_nested():
    """`with self._a, self._b:` acquires left to right — the inter-item
    ordering edge must be recorded just like the nested form, so the
    classic fwd/rev two-lock deadlock is caught."""
    found = multi_findings({
        "autoscaler_tpu/metrics/pair.py": """
            import threading

            class Pair:
                def __init__(self):
                    self._alock = threading.Lock()
                    self._block = threading.Lock()

                def fwd(self):
                    with self._alock, self._block:
                        pass

                def rev(self):
                    with self._block, self._alock:
                        pass
            """,
    })
    assert rules_of(found) == ["GL008"]
    assert "lock-order cycle" in found[0].message


def test_gl008_witness_messages_carry_no_line_numbers():
    """The baseline fingerprints on (path, rule, message): GL008 witness
    text names files but not lines, so grandfathered cycles don't churn
    on unrelated line drift."""
    found = multi_findings({
        "autoscaler_tpu/metrics/box.py": """
            import threading

            class Box:
                def __init__(self):
                    self._lock = threading.Lock()

                def doubled_op(self):
                    with self._lock:
                        with self._lock:
                            pass
            """,
    })
    assert rules_of(found) == ["GL008"]
    assert re.search(r"\.py:\d", found[0].message) is None


# -- suppression pragmas ------------------------------------------------------


def test_pragma_with_reason_suppresses():
    found = findings(
        """
        import time

        def f():
            return time.time()  # graftlint: disable=GL001 — fixture: injected upstream
        """,
        "autoscaler_tpu/loadgen/fixture.py",
    )
    assert found == []


def test_pragma_on_preceding_comment_line_suppresses():
    found = findings(
        """
        import time

        def f():
            # graftlint: disable=GL001 — fixture: injected upstream
            return time.time()
        """,
        "autoscaler_tpu/loadgen/fixture.py",
    )
    assert found == []


def test_pragma_without_reason_is_gl000():
    found = findings(
        """
        import time

        def f():
            return time.time()  # graftlint: disable=GL001
        """,
        "autoscaler_tpu/loadgen/fixture.py",
    )
    assert rules_of(found) == ["GL000"]  # GL001 suppressed, hygiene flagged


def test_gl000_is_unsuppressible():
    # disable=GL000,GL001 with no reason must not waive the very contract
    # it violates: GL001 is suppressed, the hygiene finding survives
    found = findings(
        """
        import time

        def f():
            return time.time()  # graftlint: disable=GL000,GL001
        """,
        "autoscaler_tpu/loadgen/fixture.py",
    )
    assert rules_of(found) == ["GL000"]


def test_pragma_for_other_rule_does_not_suppress():
    found = findings(
        """
        import time

        def f():
            return time.time()  # graftlint: disable=GL004 — wrong rule
        """,
        "autoscaler_tpu/loadgen/fixture.py",
    )
    assert rules_of(found) == ["GL001"]


# -- baseline round-trip + ratchet -------------------------------------------

_VIOLATION = "import time\n\n\ndef f():\n    return time.time()\n"


def _mini_repo(tmp_path: Path) -> Path:
    pkg = tmp_path / "autoscaler_tpu" / "loadgen"
    pkg.mkdir(parents=True)
    (pkg / "bad.py").write_text(_VIOLATION)
    (pkg / "clean.py").write_text("def ok():\n    return 1\n")
    return tmp_path


def test_baseline_round_trip_and_stale_ratchet(tmp_path):
    root = _mini_repo(tmp_path)
    scan_dir = str(root / "autoscaler_tpu")
    bl = root / "hack" / "lint-baseline.json"

    # no baseline: the violation fails the run
    assert cli_main([scan_dir, "--no-baseline"]) == 1
    # grandfather it
    assert cli_main([scan_dir, "--baseline", str(bl), "--update-baseline"]) == 0
    doc = json.loads(bl.read_text())
    assert [e["rule"] for e in doc["findings"]] == ["GL001"]
    # baselined: clean
    assert cli_main([scan_dir, "--baseline", str(bl)]) == 0
    # a SECOND violation of the same fingerprint exceeds the count: fails
    (root / "autoscaler_tpu" / "loadgen" / "bad.py").write_text(
        _VIOLATION + "\n\ndef g():\n    return time.time()\n"
    )
    assert cli_main([scan_dir, "--baseline", str(bl)]) == 1
    # fixing the violation entirely makes the entry STALE: also fails
    (root / "autoscaler_tpu" / "loadgen" / "bad.py").write_text(
        "def fixed():\n    return 0\n"
    )
    assert cli_main([scan_dir, "--baseline", str(bl)]) == 1
    # striking it restores green
    assert cli_main([scan_dir, "--baseline", str(bl), "--update-baseline"]) == 0
    assert cli_main([scan_dir, "--baseline", str(bl)]) == 0
    assert json.loads(bl.read_text())["findings"] == []


def test_partial_scan_neither_reports_nor_strikes_unscanned_stale(tmp_path):
    """A one-file scan must not read the rest of the ledger as stale, and a
    one-file --update-baseline must not strike the unscanned entries."""
    root = _mini_repo(tmp_path)
    (root / "autoscaler_tpu" / "loadgen" / "bad2.py").write_text(_VIOLATION)
    scan_dir = str(root / "autoscaler_tpu")
    bl = root / "hack" / "lint-baseline.json"
    assert cli_main([scan_dir, "--baseline", str(bl), "--update-baseline"]) == 0
    assert len(json.loads(bl.read_text())["findings"]) == 2
    one_file = str(root / "autoscaler_tpu" / "loadgen" / "bad2.py")
    # partial scan: bad.py's entry is out of scope, not stale
    assert cli_main([one_file, "--baseline", str(bl)]) == 0
    # fix bad2 only; partial update strikes ITS entry, preserves bad.py's
    Path(one_file).write_text("def fixed():\n    return 0\n")
    assert cli_main([one_file, "--baseline", str(bl), "--update-baseline"]) == 0
    kept = json.loads(bl.read_text())["findings"]
    assert [e["path"] for e in kept] == ["autoscaler_tpu/loadgen/bad.py"]
    assert cli_main([scan_dir, "--baseline", str(bl)]) == 0


def test_deleted_file_under_scanned_dir_reads_stale(tmp_path):
    """The ratchet must survive file deletion: an entry for a file that no
    longer exists under a scanned directory is stale, not invisible."""
    root = _mini_repo(tmp_path)
    scan_dir = str(root / "autoscaler_tpu")
    bl = root / "hack" / "lint-baseline.json"
    assert cli_main([scan_dir, "--baseline", str(bl), "--update-baseline"]) == 0
    (root / "autoscaler_tpu" / "loadgen" / "bad.py").unlink()
    assert cli_main([scan_dir, "--baseline", str(bl)]) == 1  # stale
    assert cli_main([scan_dir, "--baseline", str(bl), "--update-baseline"]) == 0
    assert json.loads(bl.read_text())["findings"] == []
    assert cli_main([scan_dir, "--baseline", str(bl)]) == 0


def test_explicit_missing_baseline_is_usage_error(tmp_path):
    root = _mini_repo(tmp_path)
    rc = cli_main(
        [str(root / "autoscaler_tpu"), "--baseline", str(root / "typo.json")]
    )
    assert rc == 2


def test_repo_partial_scan_single_file_passes(monkeypatch):
    # pre-commit-style invocation: one clean file + the shipped full-repo
    # baseline must not surface the unscanned ledger as stale
    monkeypatch.chdir(REPO)
    assert cli_main(["autoscaler_tpu/loadgen/faults.py"]) == 0


def test_baseline_diff_excess_surfaces_newest_lines():
    f1 = check_source(_VIOLATION, "autoscaler_tpu/loadgen/bad.py")
    assert len(f1) == 1
    base = {f1[0].fingerprint: 1}
    two = check_source(
        _VIOLATION + "\n\ndef g():\n    return time.time()\n",
        "autoscaler_tpu/loadgen/bad.py",
    )
    new, stale = baseline_mod.diff(two, base)
    assert len(new) == 1 and new[0].line > f1[0].line
    assert stale == []


# -- repo self-checks + CLI contract -----------------------------------------


def test_analysis_package_scans_clean_over_itself():
    assert scan_paths([str(REPO / "autoscaler_tpu" / "analysis")]) == []


def test_repo_scans_clean_with_shipped_baseline(monkeypatch):
    monkeypatch.chdir(REPO)
    assert cli_main(["autoscaler_tpu"]) == 0


def test_findings_render_and_sort_deterministically():
    found = findings(
        """
        import time

        def b():
            return time.sleep(1)

        def a():
            return time.time()
        """,
        "autoscaler_tpu/loadgen/fixture.py",
    )
    assert [f.line for f in found] == sorted(f.line for f in found)
    rendered = found[0].render()
    assert rendered.startswith("autoscaler_tpu/loadgen/fixture.py:")
    assert ": GL001 " in rendered


def test_cli_module_entry_point_seeded_violation(tmp_path):
    """The real `python -m autoscaler_tpu.analysis` contract: nonzero +
    path:line: RULE output on a seeded violation, 0 on a clean tree."""
    root = _mini_repo(tmp_path)
    proc = subprocess.run(
        [sys.executable, "-m", "autoscaler_tpu.analysis", "--no-baseline",
         str(root / "autoscaler_tpu")],
        capture_output=True, text=True, cwd=str(REPO),
    )
    assert proc.returncode == 1
    assert "autoscaler_tpu/loadgen/bad.py:5: GL001" in proc.stdout
    proc2 = subprocess.run(
        [sys.executable, "-m", "autoscaler_tpu.analysis", "--no-baseline",
         str(root / "autoscaler_tpu" / "loadgen" / "clean.py")],
        capture_output=True, text=True, cwd=str(REPO),
    )
    assert proc2.returncode == 0


def test_cli_missing_path_is_usage_error(tmp_path):
    assert cli_main([str(tmp_path / "nope")]) == 2


def test_cli_contradictory_baseline_flags_are_usage_error(tmp_path):
    root = _mini_repo(tmp_path)
    rc = cli_main(
        [str(root / "autoscaler_tpu"), "--no-baseline", "--update-baseline"]
    )
    assert rc == 2


def test_nul_byte_file_degrades_to_parse_finding():
    found = check_source("\x00bad", "autoscaler_tpu/core/corrupt.py")
    assert rules_of(found) == ["GL000"]
    assert "does not parse" in found[0].message


# -- CLI formats, exit codes, summary table -----------------------------------


def test_cli_json_format_structure_and_exit_code(tmp_path, capsys):
    root = _mini_repo(tmp_path)
    rc = cli_main(
        [str(root / "autoscaler_tpu"), "--no-baseline", "--format=json"]
    )
    assert rc == 1
    doc = json.loads(capsys.readouterr().out)
    assert doc["version"] == 1
    assert doc["files"] == 2
    assert [f["rule"] for f in doc["findings"]] == ["GL001"]
    assert doc["findings"][0]["path"] == "autoscaler_tpu/loadgen/bad.py"
    assert doc["stale"] == []
    assert doc["summary"]["GL001"]["findings"] == 1
    assert set(doc["summary"]) >= {"GL000", "GL001", "GL007", "GL008", "GL009"}


def test_cli_json_output_byte_identical_across_runs(tmp_path, capsys):
    """The determinism gate hack/verify.sh enforces: two identical runs
    must produce byte-identical JSON, independent of dict/set iteration."""
    root = _mini_repo(tmp_path)
    args = [str(root / "autoscaler_tpu"), "--no-baseline", "--format=json"]
    cli_main(args)
    first = capsys.readouterr().out
    cli_main(args)
    second = capsys.readouterr().out
    assert first == second
    json.loads(first)  # and it parses


def test_cli_github_format_annotation_lines(tmp_path, capsys):
    root = _mini_repo(tmp_path)
    rc = cli_main(
        [str(root / "autoscaler_tpu"), "--no-baseline", "--format=github"]
    )
    assert rc == 1
    out = capsys.readouterr().out.splitlines()
    assert out[0].startswith(
        "::error file=autoscaler_tpu/loadgen/bad.py,line=5,title=graftlint GL001::"
    )


def test_cli_text_format_prints_summary_table(tmp_path, capsys):
    root = _mini_repo(tmp_path)
    cli_main([str(root / "autoscaler_tpu"), "--no-baseline"])
    err = capsys.readouterr().err
    assert "rule   findings  suppressed  baselined" in err
    assert "GL001" in err and "GL009" in err


def test_cli_internal_analyzer_error_exits_2(tmp_path, monkeypatch):
    """Findings are 1, a crash in the analyzer itself is 2 — CI must be
    able to tell a failed ratchet from a broken gate."""
    from autoscaler_tpu.analysis import cli as cli_mod

    root = _mini_repo(tmp_path)

    def boom(sources, **kwargs):
        raise RuntimeError("synthetic analyzer crash")

    monkeypatch.setattr(cli_mod, "analyze_sources", boom)
    assert cli_main([str(root / "autoscaler_tpu"), "--no-baseline"]) == 2
