"""graftlint (autoscaler_tpu/analysis): per-rule positive/negative fixtures,
pragma suppression, baseline round-trip + stale ratchet, CLI contract, and
the self-check that the repo (with its shipped baseline) and the analysis
package itself scan clean.

Fixture paths are *virtual* — ``check_source`` scopes rules on the path
string, no file need exist — except for the CLI/baseline tests, which
build a real miniature ``autoscaler_tpu/`` tree in tmp_path.
"""
from __future__ import annotations

import json
import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

from autoscaler_tpu.analysis import baseline as baseline_mod
from autoscaler_tpu.analysis import check_source, scan_paths
from autoscaler_tpu.analysis.cli import main as cli_main
from autoscaler_tpu.analysis.engine import display_path, module_path
from autoscaler_tpu.analysis.rules import function_label_taxonomy

REPO = Path(__file__).resolve().parent.parent


def findings(source: str, path: str):
    return check_source(textwrap.dedent(source), path)


def rules_of(found):
    return [f.rule for f in found]


# -- engine plumbing ----------------------------------------------------------


def test_path_normalization():
    assert (
        display_path("/tmp/x/autoscaler_tpu/loadgen/driver.py")
        == "autoscaler_tpu/loadgen/driver.py"
    )
    assert module_path("/tmp/x/autoscaler_tpu/core/a.py") == "core/a.py"
    assert module_path("/tmp/elsewhere/tool.py") is None


def test_taxonomy_extracted_without_import():
    tax = function_label_taxonomy()
    assert {"main", "estimate", "deviceDispatch", "kubeRequest"} <= tax


# -- GL001 wall clock / randomness -------------------------------------------


def test_gl001_flags_wall_clock_in_replay_scope():
    found = findings(
        """
        import time

        def tick():
            return time.time()
        """,
        "autoscaler_tpu/loadgen/fixture.py",
    )
    assert rules_of(found) == ["GL001"]
    assert "time.time" in found[0].message


def test_gl001_resolves_import_aliases():
    found = findings(
        """
        import time as t
        from time import monotonic as mono

        def f():
            return t.sleep(1) or mono()
        """,
        "autoscaler_tpu/core/fixture.py",
    )
    assert rules_of(found) == ["GL001", "GL001"]


def test_gl001_flags_ambient_randomness_allows_seeded():
    found = findings(
        """
        import random
        import numpy as np

        def bad():
            return random.random() + np.random.rand()

        def good(seed):
            return random.Random(seed).random() + np.random.default_rng(seed).random()
        """,
        "autoscaler_tpu/expander/fixture.py",
    )
    assert rules_of(found) == ["GL001", "GL001"]


def test_gl001_injected_default_reference_is_the_seam():
    found = findings(
        """
        import time
        from typing import Callable

        def run(clock: Callable[[], float] = time.monotonic) -> float:
            return clock()
        """,
        "autoscaler_tpu/core/fixture.py",
    )
    assert found == []


def test_gl001_parameter_shadowing_module_name_is_a_seam():
    # an injected rng/clock PARAMETER named `random`/`time` is the
    # sanctioned seam shape, not the ambient module
    found = findings(
        """
        def pick(random, time):
            time.sleep(0)
            return random.choice([1, 2])
        """,
        "autoscaler_tpu/core/fixture.py",
    )
    assert found == []


def test_gl001_out_of_scope_module_not_flagged():
    found = findings(
        """
        import time

        def f():
            return time.time()
        """,
        "autoscaler_tpu/kube/fixture.py",  # not a replay-reachable scope
    )
    assert found == []


# -- GL002 span-name taxonomy -------------------------------------------------


def test_gl002_flags_non_taxonomy_span_literal():
    found = findings(
        """
        from autoscaler_tpu import trace

        def f():
            with trace.span("totallyNewPhase"):
                pass
        """,
        "autoscaler_tpu/core/fixture.py",
    )
    assert rules_of(found) == ["GL002"]
    assert "totallyNewPhase" in found[0].message


def test_gl002_taxonomy_literal_and_constant_ok():
    found = findings(
        """
        from autoscaler_tpu import trace
        from autoscaler_tpu.metrics import metrics as metrics_mod

        def f(tracer):
            with trace.span("estimate"):
                pass
            with tracer.tick(metrics_mod.MAIN):
                pass
        """,
        "autoscaler_tpu/core/fixture.py",
    )
    assert found == []


def test_gl002_regex_match_span_not_flagged():
    found = findings(
        """
        import re

        def f(m: "re.Match"):
            return m.span("group")
        """,
        "autoscaler_tpu/core/fixture.py",
    )
    assert found == []


# -- GL003 ladder bypass ------------------------------------------------------

_DISPATCH_SRC = """
    from autoscaler_tpu.ops.binpack import ffd_binpack

    def f(req, mask, alloc):
        return ffd_binpack(req, mask, alloc, max_nodes=8)
    """


def test_gl003_flags_dispatch_outside_ladder_modules():
    found = findings(_DISPATCH_SRC, "autoscaler_tpu/core/fixture.py")
    assert rules_of(found) == ["GL003"]
    assert "_walk_ladder" in found[0].message


def test_gl003_estimator_and_ops_allowed():
    assert findings(_DISPATCH_SRC, "autoscaler_tpu/estimator/fixture.py") == []
    assert findings(_DISPATCH_SRC, "autoscaler_tpu/ops/fixture.py") == []


def test_gl003_pallas_call_only_in_ops():
    src = """
        import jax.experimental.pallas as pl

        def f(kernel, x):
            return pl.pallas_call(kernel, out_shape=x)(x)
        """
    assert rules_of(findings(src, "autoscaler_tpu/estimator/fixture.py")) == [
        "GL003"
    ]
    assert findings(src, "autoscaler_tpu/ops/fixture.py") == []


# -- GL004 lock discipline ----------------------------------------------------


def test_gl004_flags_unlocked_write():
    found = findings(
        """
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = []

            def put(self, x):
                self._items = [x]
        """,
        "autoscaler_tpu/metrics/fixture.py",
    )
    assert rules_of(found) == ["GL004"]
    assert "Box.put" in found[0].message


def test_gl004_locked_write_init_and_locked_suffix_ok():
    found = findings(
        """
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = []

            def put(self, x):
                with self._lock:
                    self._items.append(x)
                    self._count = len(self._items)

            def _reset_locked(self):
                self._items = []
        """,
        "autoscaler_tpu/metrics/fixture.py",
    )
    assert found == []


def test_gl004_nested_def_does_not_inherit_lock():
    # a closure defined under `with self._lock:` runs LATER, lock released
    found = findings(
        """
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0

            def deferred(self):
                with self._lock:
                    def later():
                        self._n = 1
                    return later
        """,
        "autoscaler_tpu/utils/circuit.py",
    )
    assert rules_of(found) == ["GL004"]


def test_gl004_nested_class_lock_does_not_leak_to_enclosing():
    found = findings(
        """
        import threading

        class Outer:
            class Inner:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._n = 0

                def bump(self):
                    self._n += 1

            def set(self, v):
                self._v = v
        """,
        "autoscaler_tpu/metrics/fixture.py",
    )
    # Outer has no lock -> Outer.set is fine; Inner.bump IS flagged
    assert [(f.rule, "Inner.bump" in f.message) for f in found] == [
        ("GL004", True)
    ]


def test_gl004_bare_annotation_is_not_a_write():
    found = findings(
        """
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()

            def declare(self):
                self._x: int
        """,
        "autoscaler_tpu/metrics/fixture.py",
    )
    assert found == []


def test_gl004_class_without_lock_not_checked():
    found = findings(
        """
        class Free:
            def put(self, x):
                self._items = [x]
        """,
        "autoscaler_tpu/metrics/fixture.py",
    )
    assert found == []


# -- GL005 error boundary -----------------------------------------------------


def test_gl005_flags_swallowed_exception_in_core():
    found = findings(
        """
        def run_once():
            try:
                work()
            except Exception:
                pass
        """,
        "autoscaler_tpu/core/fixture.py",
    )
    assert rules_of(found) == ["GL005"]
    assert "run_once" in found[0].message


def test_gl005_routed_or_reraised_ok_and_scope_limited():
    src = """
        from autoscaler_tpu.utils.errors import to_autoscaler_error

        def a():
            try:
                work()
            except Exception as e:
                err = to_autoscaler_error(e)
                log(err)

        def b():
            try:
                work()
            except Exception:
                raise
        """
    assert findings(src, "autoscaler_tpu/core/fixture.py") == []
    swallow = """
        def f():
            try:
                work()
            except Exception:
                pass
        """
    # estimator/ has its own contract (the ladder records failures); GL005
    # polices only the run_once path
    assert findings(swallow, "autoscaler_tpu/estimator/fixture.py") == []


# -- GL006 jit purity ---------------------------------------------------------


def test_gl006_flags_print_under_partial_jit_decorator():
    found = findings(
        """
        from functools import partial
        import jax

        @partial(jax.jit, static_argnames=("n",))
        def kernel(x, n):
            print(x)
            return x * n
        """,
        "autoscaler_tpu/ops/fixture.py",
    )
    assert rules_of(found) == ["GL006"]
    assert "print()" in found[0].message


def test_gl006_transitive_local_helper_and_metrics():
    found = findings(
        """
        import jax

        def helper(m, x):
            m.metrics.dispatches.inc()
            return x

        def outer(m, x):
            return jax.jit(traced)(x)

        def traced(x):
            return helper(None, x)
        """,
        "autoscaler_tpu/ops/fixture.py",
    )
    assert rules_of(found) == ["GL006"]
    assert "metrics" in found[0].message


def test_gl006_host_side_effects_outside_jit_ok():
    found = findings(
        """
        import jax

        @jax.jit
        def kernel(x):
            return x + 1

        def host(m, x):
            print("dispatching")
            m.metrics.dispatches.inc()
            return kernel(x)
        """,
        "autoscaler_tpu/ops/fixture.py",
    )
    assert found == []


# -- suppression pragmas ------------------------------------------------------


def test_pragma_with_reason_suppresses():
    found = findings(
        """
        import time

        def f():
            return time.time()  # graftlint: disable=GL001 — fixture: injected upstream
        """,
        "autoscaler_tpu/loadgen/fixture.py",
    )
    assert found == []


def test_pragma_on_preceding_comment_line_suppresses():
    found = findings(
        """
        import time

        def f():
            # graftlint: disable=GL001 — fixture: injected upstream
            return time.time()
        """,
        "autoscaler_tpu/loadgen/fixture.py",
    )
    assert found == []


def test_pragma_without_reason_is_gl000():
    found = findings(
        """
        import time

        def f():
            return time.time()  # graftlint: disable=GL001
        """,
        "autoscaler_tpu/loadgen/fixture.py",
    )
    assert rules_of(found) == ["GL000"]  # GL001 suppressed, hygiene flagged


def test_gl000_is_unsuppressible():
    # disable=GL000,GL001 with no reason must not waive the very contract
    # it violates: GL001 is suppressed, the hygiene finding survives
    found = findings(
        """
        import time

        def f():
            return time.time()  # graftlint: disable=GL000,GL001
        """,
        "autoscaler_tpu/loadgen/fixture.py",
    )
    assert rules_of(found) == ["GL000"]


def test_pragma_for_other_rule_does_not_suppress():
    found = findings(
        """
        import time

        def f():
            return time.time()  # graftlint: disable=GL004 — wrong rule
        """,
        "autoscaler_tpu/loadgen/fixture.py",
    )
    assert rules_of(found) == ["GL001"]


# -- baseline round-trip + ratchet -------------------------------------------

_VIOLATION = "import time\n\n\ndef f():\n    return time.time()\n"


def _mini_repo(tmp_path: Path) -> Path:
    pkg = tmp_path / "autoscaler_tpu" / "loadgen"
    pkg.mkdir(parents=True)
    (pkg / "bad.py").write_text(_VIOLATION)
    (pkg / "clean.py").write_text("def ok():\n    return 1\n")
    return tmp_path


def test_baseline_round_trip_and_stale_ratchet(tmp_path):
    root = _mini_repo(tmp_path)
    scan_dir = str(root / "autoscaler_tpu")
    bl = root / "hack" / "lint-baseline.json"

    # no baseline: the violation fails the run
    assert cli_main([scan_dir, "--no-baseline"]) == 1
    # grandfather it
    assert cli_main([scan_dir, "--baseline", str(bl), "--update-baseline"]) == 0
    doc = json.loads(bl.read_text())
    assert [e["rule"] for e in doc["findings"]] == ["GL001"]
    # baselined: clean
    assert cli_main([scan_dir, "--baseline", str(bl)]) == 0
    # a SECOND violation of the same fingerprint exceeds the count: fails
    (root / "autoscaler_tpu" / "loadgen" / "bad.py").write_text(
        _VIOLATION + "\n\ndef g():\n    return time.time()\n"
    )
    assert cli_main([scan_dir, "--baseline", str(bl)]) == 1
    # fixing the violation entirely makes the entry STALE: also fails
    (root / "autoscaler_tpu" / "loadgen" / "bad.py").write_text(
        "def fixed():\n    return 0\n"
    )
    assert cli_main([scan_dir, "--baseline", str(bl)]) == 1
    # striking it restores green
    assert cli_main([scan_dir, "--baseline", str(bl), "--update-baseline"]) == 0
    assert cli_main([scan_dir, "--baseline", str(bl)]) == 0
    assert json.loads(bl.read_text())["findings"] == []


def test_partial_scan_neither_reports_nor_strikes_unscanned_stale(tmp_path):
    """A one-file scan must not read the rest of the ledger as stale, and a
    one-file --update-baseline must not strike the unscanned entries."""
    root = _mini_repo(tmp_path)
    (root / "autoscaler_tpu" / "loadgen" / "bad2.py").write_text(_VIOLATION)
    scan_dir = str(root / "autoscaler_tpu")
    bl = root / "hack" / "lint-baseline.json"
    assert cli_main([scan_dir, "--baseline", str(bl), "--update-baseline"]) == 0
    assert len(json.loads(bl.read_text())["findings"]) == 2
    one_file = str(root / "autoscaler_tpu" / "loadgen" / "bad2.py")
    # partial scan: bad.py's entry is out of scope, not stale
    assert cli_main([one_file, "--baseline", str(bl)]) == 0
    # fix bad2 only; partial update strikes ITS entry, preserves bad.py's
    Path(one_file).write_text("def fixed():\n    return 0\n")
    assert cli_main([one_file, "--baseline", str(bl), "--update-baseline"]) == 0
    kept = json.loads(bl.read_text())["findings"]
    assert [e["path"] for e in kept] == ["autoscaler_tpu/loadgen/bad.py"]
    assert cli_main([scan_dir, "--baseline", str(bl)]) == 0


def test_deleted_file_under_scanned_dir_reads_stale(tmp_path):
    """The ratchet must survive file deletion: an entry for a file that no
    longer exists under a scanned directory is stale, not invisible."""
    root = _mini_repo(tmp_path)
    scan_dir = str(root / "autoscaler_tpu")
    bl = root / "hack" / "lint-baseline.json"
    assert cli_main([scan_dir, "--baseline", str(bl), "--update-baseline"]) == 0
    (root / "autoscaler_tpu" / "loadgen" / "bad.py").unlink()
    assert cli_main([scan_dir, "--baseline", str(bl)]) == 1  # stale
    assert cli_main([scan_dir, "--baseline", str(bl), "--update-baseline"]) == 0
    assert json.loads(bl.read_text())["findings"] == []
    assert cli_main([scan_dir, "--baseline", str(bl)]) == 0


def test_explicit_missing_baseline_is_usage_error(tmp_path):
    root = _mini_repo(tmp_path)
    rc = cli_main(
        [str(root / "autoscaler_tpu"), "--baseline", str(root / "typo.json")]
    )
    assert rc == 2


def test_repo_partial_scan_single_file_passes(monkeypatch):
    # pre-commit-style invocation: one clean file + the shipped full-repo
    # baseline must not surface the unscanned ledger as stale
    monkeypatch.chdir(REPO)
    assert cli_main(["autoscaler_tpu/loadgen/faults.py"]) == 0


def test_baseline_diff_excess_surfaces_newest_lines():
    f1 = check_source(_VIOLATION, "autoscaler_tpu/loadgen/bad.py")
    assert len(f1) == 1
    base = {f1[0].fingerprint: 1}
    two = check_source(
        _VIOLATION + "\n\ndef g():\n    return time.time()\n",
        "autoscaler_tpu/loadgen/bad.py",
    )
    new, stale = baseline_mod.diff(two, base)
    assert len(new) == 1 and new[0].line > f1[0].line
    assert stale == []


# -- repo self-checks + CLI contract -----------------------------------------


def test_analysis_package_scans_clean_over_itself():
    assert scan_paths([str(REPO / "autoscaler_tpu" / "analysis")]) == []


def test_repo_scans_clean_with_shipped_baseline(monkeypatch):
    monkeypatch.chdir(REPO)
    assert cli_main(["autoscaler_tpu"]) == 0


def test_findings_render_and_sort_deterministically():
    found = findings(
        """
        import time

        def b():
            return time.sleep(1)

        def a():
            return time.time()
        """,
        "autoscaler_tpu/loadgen/fixture.py",
    )
    assert [f.line for f in found] == sorted(f.line for f in found)
    rendered = found[0].render()
    assert rendered.startswith("autoscaler_tpu/loadgen/fixture.py:")
    assert ": GL001 " in rendered


def test_cli_module_entry_point_seeded_violation(tmp_path):
    """The real `python -m autoscaler_tpu.analysis` contract: nonzero +
    path:line: RULE output on a seeded violation, 0 on a clean tree."""
    root = _mini_repo(tmp_path)
    proc = subprocess.run(
        [sys.executable, "-m", "autoscaler_tpu.analysis", "--no-baseline",
         str(root / "autoscaler_tpu")],
        capture_output=True, text=True, cwd=str(REPO),
    )
    assert proc.returncode == 1
    assert "autoscaler_tpu/loadgen/bad.py:5: GL001" in proc.stdout
    proc2 = subprocess.run(
        [sys.executable, "-m", "autoscaler_tpu.analysis", "--no-baseline",
         str(root / "autoscaler_tpu" / "loadgen" / "clean.py")],
        capture_output=True, text=True, cwd=str(REPO),
    )
    assert proc2.returncode == 0


def test_cli_missing_path_is_usage_error(tmp_path):
    assert cli_main([str(tmp_path / "nope")]) == 2


def test_cli_contradictory_baseline_flags_are_usage_error(tmp_path):
    root = _mini_repo(tmp_path)
    rc = cli_main(
        [str(root / "autoscaler_tpu"), "--no-baseline", "--update-baseline"]
    )
    assert rc == 2


def test_nul_byte_file_degrades_to_parse_finding():
    found = check_source("\x00bad", "autoscaler_tpu/core/corrupt.py")
    assert rules_of(found) == ["GL000"]
    assert "does not parse" in found[0].message
