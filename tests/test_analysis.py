"""graftlint (autoscaler_tpu/analysis): per-rule positive/negative fixtures,
pragma suppression, baseline round-trip + stale ratchet, whole-program
rules (cross-module GL006 reach, GL007 kernel contracts, GL008 lock order,
GL009 flag wiring), CLI contract (formats, exit codes, summary table,
byte-stable JSON), and the self-check that the repo (with its shipped
baseline) and the analysis package itself scan clean.

Fixture paths are *virtual* — ``check_source``/``analyze_sources`` scope
rules on the path string, no file need exist — except for the CLI/baseline
tests, which build a real miniature ``autoscaler_tpu/`` tree in tmp_path.
"""
from __future__ import annotations

import json
import re
import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

from autoscaler_tpu.analysis import baseline as baseline_mod
from autoscaler_tpu.analysis import analyze_sources, check_source, scan_paths
from autoscaler_tpu.analysis.cli import main as cli_main
from autoscaler_tpu.analysis.engine import display_path, module_path
from autoscaler_tpu.analysis.rules import function_label_taxonomy

REPO = Path(__file__).resolve().parent.parent


def findings(source: str, path: str):
    return check_source(textwrap.dedent(source), path)


def multi_findings(sources: dict):
    found, _ = analyze_sources(
        {p: textwrap.dedent(s) for p, s in sources.items()}
    )
    return found


def rules_of(found):
    return [f.rule for f in found]


# -- engine plumbing ----------------------------------------------------------


def test_path_normalization():
    assert (
        display_path("/tmp/x/autoscaler_tpu/loadgen/driver.py")
        == "autoscaler_tpu/loadgen/driver.py"
    )
    assert module_path("/tmp/x/autoscaler_tpu/core/a.py") == "core/a.py"
    assert module_path("/tmp/elsewhere/tool.py") is None


def test_taxonomy_extracted_without_import():
    tax = function_label_taxonomy()
    assert {"main", "estimate", "deviceDispatch", "kubeRequest"} <= tax


# -- GL001 wall clock / randomness -------------------------------------------


def test_gl001_flags_wall_clock_in_replay_scope():
    found = findings(
        """
        import time

        def tick():
            return time.time()
        """,
        "autoscaler_tpu/loadgen/fixture.py",
    )
    assert rules_of(found) == ["GL001"]
    assert "time.time" in found[0].message


def test_gl001_resolves_import_aliases():
    found = findings(
        """
        import time as t
        from time import monotonic as mono

        def f():
            return t.sleep(1) or mono()
        """,
        "autoscaler_tpu/core/fixture.py",
    )
    assert rules_of(found) == ["GL001", "GL001"]


def test_gl001_flags_ambient_randomness_allows_seeded():
    found = findings(
        """
        import random
        import numpy as np

        def bad():
            return random.random() + np.random.rand()

        def good(seed):
            return random.Random(seed).random() + np.random.default_rng(seed).random()
        """,
        "autoscaler_tpu/expander/fixture.py",
    )
    assert rules_of(found) == ["GL001", "GL001"]


def test_gl001_injected_default_reference_is_the_seam():
    found = findings(
        """
        import time
        from typing import Callable

        def run(clock: Callable[[], float] = time.monotonic) -> float:
            return clock()
        """,
        "autoscaler_tpu/core/fixture.py",
    )
    assert found == []


def test_gl001_parameter_shadowing_module_name_is_a_seam():
    # an injected rng/clock PARAMETER named `random`/`time` is the
    # sanctioned seam shape, not the ambient module
    found = findings(
        """
        def pick(random, time):
            time.sleep(0)
            return random.choice([1, 2])
        """,
        "autoscaler_tpu/core/fixture.py",
    )
    assert found == []


def test_gl001_out_of_scope_module_not_flagged():
    found = findings(
        """
        import time

        def f():
            return time.time()
        """,
        "autoscaler_tpu/kube/fixture.py",  # not a replay-reachable scope
    )
    assert found == []


# -- GL002 span-name taxonomy -------------------------------------------------


def test_gl002_flags_non_taxonomy_span_literal():
    found = findings(
        """
        from autoscaler_tpu import trace

        def f():
            with trace.span("totallyNewPhase"):
                pass
        """,
        "autoscaler_tpu/core/fixture.py",
    )
    assert rules_of(found) == ["GL002"]
    assert "totallyNewPhase" in found[0].message


def test_gl002_taxonomy_literal_and_constant_ok():
    found = findings(
        """
        from autoscaler_tpu import trace
        from autoscaler_tpu.metrics import metrics as metrics_mod

        def f(tracer):
            with trace.span("estimate"):
                pass
            with tracer.tick(metrics_mod.MAIN):
                pass
        """,
        "autoscaler_tpu/core/fixture.py",
    )
    assert found == []


def test_gl002_regex_match_span_not_flagged():
    found = findings(
        """
        import re

        def f(m: "re.Match"):
            return m.span("group")
        """,
        "autoscaler_tpu/core/fixture.py",
    )
    assert found == []


# -- GL003 ladder bypass ------------------------------------------------------

_DISPATCH_SRC = """
    from autoscaler_tpu.ops.binpack import ffd_binpack

    def f(req, mask, alloc):
        return ffd_binpack(req, mask, alloc, max_nodes=8)
    """


def test_gl003_flags_dispatch_outside_ladder_modules():
    found = findings(_DISPATCH_SRC, "autoscaler_tpu/core/fixture.py")
    assert rules_of(found) == ["GL003"]
    assert "_walk_ladder" in found[0].message


def test_gl003_estimator_and_ops_allowed():
    assert findings(_DISPATCH_SRC, "autoscaler_tpu/estimator/fixture.py") == []
    assert findings(_DISPATCH_SRC, "autoscaler_tpu/ops/fixture.py") == []


def test_gl003_pallas_call_only_in_ops():
    src = """
        import jax.experimental.pallas as pl

        def f(kernel, x):
            return pl.pallas_call(kernel, out_shape=x)(x)
        """
    assert rules_of(findings(src, "autoscaler_tpu/estimator/fixture.py")) == [
        "GL003"
    ]
    assert findings(src, "autoscaler_tpu/ops/fixture.py") == []


# -- GL004 lock discipline ----------------------------------------------------


def test_gl004_flags_unlocked_write():
    found = findings(
        """
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = []

            def put(self, x):
                self._items = [x]
        """,
        "autoscaler_tpu/metrics/fixture.py",
    )
    assert rules_of(found) == ["GL004"]
    assert "Box.put" in found[0].message


def test_gl004_locked_write_init_and_locked_suffix_ok():
    found = findings(
        """
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = []

            def put(self, x):
                with self._lock:
                    self._items.append(x)
                    self._count = len(self._items)

            def _reset_locked(self):
                self._items = []
        """,
        "autoscaler_tpu/metrics/fixture.py",
    )
    assert found == []


def test_gl004_nested_def_does_not_inherit_lock():
    # a closure defined under `with self._lock:` runs LATER, lock released
    found = findings(
        """
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0

            def deferred(self):
                with self._lock:
                    def later():
                        self._n = 1
                    return later
        """,
        "autoscaler_tpu/utils/circuit.py",
    )
    assert rules_of(found) == ["GL004"]


def test_gl004_nested_class_lock_does_not_leak_to_enclosing():
    found = findings(
        """
        import threading

        class Outer:
            class Inner:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._n = 0

                def bump(self):
                    self._n += 1

            def set(self, v):
                self._v = v
        """,
        "autoscaler_tpu/metrics/fixture.py",
    )
    # Outer has no lock -> Outer.set is fine; Inner.bump IS flagged
    assert [(f.rule, "Inner.bump" in f.message) for f in found] == [
        ("GL004", True)
    ]


def test_gl004_bare_annotation_is_not_a_write():
    found = findings(
        """
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()

            def declare(self):
                self._x: int
        """,
        "autoscaler_tpu/metrics/fixture.py",
    )
    assert found == []


def test_gl004_class_without_lock_not_checked():
    found = findings(
        """
        class Free:
            def put(self, x):
                self._items = [x]
        """,
        "autoscaler_tpu/metrics/fixture.py",
    )
    assert found == []


# -- GL005 error boundary -----------------------------------------------------


def test_gl005_flags_swallowed_exception_in_core():
    found = findings(
        """
        def run_once():
            try:
                work()
            except Exception:
                pass
        """,
        "autoscaler_tpu/core/fixture.py",
    )
    assert rules_of(found) == ["GL005"]
    assert "run_once" in found[0].message


def test_gl005_routed_or_reraised_ok_and_scope_limited():
    src = """
        from autoscaler_tpu.utils.errors import to_autoscaler_error

        def a():
            try:
                work()
            except Exception as e:
                err = to_autoscaler_error(e)
                log(err)

        def b():
            try:
                work()
            except Exception:
                raise
        """
    assert findings(src, "autoscaler_tpu/core/fixture.py") == []
    swallow = """
        def f():
            try:
                work()
            except Exception:
                pass
        """
    # estimator/ has its own contract (the ladder records failures); GL005
    # polices only the run_once path
    assert findings(swallow, "autoscaler_tpu/estimator/fixture.py") == []


# -- GL006 jit purity ---------------------------------------------------------


def test_gl006_flags_print_under_partial_jit_decorator():
    found = findings(
        """
        from functools import partial
        import jax

        @partial(jax.jit, static_argnames=("n",))
        def kernel(x, n):
            print(x)
            return x * n
        """,
        "autoscaler_tpu/ops/fixture.py",
    )
    assert rules_of(found) == ["GL006"]
    assert "print()" in found[0].message


def test_gl006_transitive_local_helper_and_metrics():
    found = findings(
        """
        import jax

        def helper(m, x):
            m.metrics.dispatches.inc()
            return x

        def outer(m, x):
            return jax.jit(traced)(x)

        def traced(x):
            return helper(None, x)
        """,
        "autoscaler_tpu/ops/fixture.py",
    )
    assert rules_of(found) == ["GL006"]
    assert "metrics" in found[0].message


def test_gl006_host_side_effects_outside_jit_ok():
    found = findings(
        """
        import jax

        @jax.jit
        def kernel(x):
            return x + 1

        def host(m, x):
            print("dispatching")
            m.metrics.dispatches.inc()
            return kernel(x)
        """,
        "autoscaler_tpu/ops/fixture.py",
    )
    assert found == []


def test_gl006_cross_module_transitive_reach():
    """The whole-program upgrade: a jitted function in ops/ calling a
    helper imported from ANOTHER module taints that helper too — the old
    per-file rule stopped at the module boundary."""
    found = multi_findings({
        "autoscaler_tpu/ops/kernel.py": """
            import jax
            from autoscaler_tpu.snapshot.helpers import leaky

            @jax.jit
            def kernel(x):
                return leaky(x)
            """,
        "autoscaler_tpu/snapshot/helpers.py": """
            def leaky(x):
                print(x)
                return x
            """,
    })
    assert rules_of(found) == ["GL006"]
    assert found[0].path == "autoscaler_tpu/snapshot/helpers.py"
    assert "print()" in found[0].message


def test_gl006_relative_import_in_package_init_resolves():
    """A level-1 relative import inside a package __init__.py anchors on
    the package ITSELF (`from .helpers import leaky` in snapshot/__init__
    is snapshot.helpers.leaky) — resolving one level too high drops the
    edge and GL006 goes blind."""
    found = multi_findings({
        "autoscaler_tpu/snapshot/__init__.py": """
            import jax
            from .helpers import leaky

            @jax.jit
            def reexported_kernel(x):
                return leaky(x)
            """,
        "autoscaler_tpu/snapshot/helpers.py": """
            def leaky(x):
                print(x)
                return x
            """,
    })
    assert rules_of(found) == ["GL006"]
    assert found[0].path == "autoscaler_tpu/snapshot/helpers.py"


def test_explicit_rules_subset_skips_program_rules():
    """scan entry points with an explicit per-file `rules` subset must not
    silently run the whole-program rules too (pre-whole-program API
    scoping): program rules run only by default or when asked for."""
    from autoscaler_tpu.analysis import rules as rules_mod

    sources = {
        "autoscaler_tpu/ops/kernel.py": textwrap.dedent("""
            import jax

            @jax.jit
            def kernel(x):
                print(x)
                return x
            """),
    }
    scoped, _ = analyze_sources(sources, rules=[rules_mod.WallClockInReplayPath()])
    assert scoped == []
    default, _ = analyze_sources(sources)
    assert rules_of(default) == ["GL006"]
    explicit, _ = analyze_sources(
        sources, rules=(), program_rules=[rules_mod.JitPurity()]
    )
    assert rules_of(explicit) == ["GL006"]


def test_gl006_cross_module_respects_import_aliases():
    found = multi_findings({
        "autoscaler_tpu/ops/kernel.py": """
            import jax
            from autoscaler_tpu.snapshot.helpers import leaky as quiet

            def outer(x):
                return jax.jit(traced)(x)

            def traced(x):
                return quiet(x)
            """,
        "autoscaler_tpu/snapshot/helpers.py": """
            def leaky(x):
                print(x)
                return x
            """,
    })
    assert rules_of(found) == ["GL006"]


def test_gl006_unreached_cross_module_helper_not_flagged():
    found = multi_findings({
        "autoscaler_tpu/ops/kernel.py": """
            import jax
            from autoscaler_tpu.snapshot.helpers import leaky

            @jax.jit
            def kernel(x):
                return x + 1

            def host(x):
                return leaky(kernel(x))
            """,
        "autoscaler_tpu/snapshot/helpers.py": """
            def leaky(x):
                print(x)
                return x
            """,
    })
    assert found == []


# -- GL007 kernel contracts ---------------------------------------------------

_KERNEL_MODULE = """
    import jax
    from jax.experimental import pallas as pl

    _STEP_TILE = 8

    KERNEL_CONTRACTS = {
        "my_kernel": {
            "args": {
                "pod_req": {"dims": ["P", "R"], "dtype": "f32"},
                "pod_masks": {"dims": ["G", "P"], "dtype": "bool"},
            },
            "static": {"chunk": {"multiple_of": "_STEP_TILE", "min": 8}},
            "pad": {"P_pad": ["P", "chunk"]},
            "grid": ["P_pad // chunk"],
        },
    }


    def my_kernel(pod_req, pod_masks, chunk, max_nodes=8):
        if chunk % _STEP_TILE != 0:
            raise ValueError("chunk must be a multiple of the tile")
        P = pod_req.shape[0]
        P_pad = P + (-P) % chunk
        return pl.pallas_call(
            _body,
            grid=(P_pad // chunk,),
        )(pod_req)


    def _body(ref):
        pass
    """


def test_gl007_seeded_chunk_violation_with_dispatch_trace():
    """The acceptance-criteria case: chunk=12 against _STEP_TILE=8 caught
    at lint time, message carries the dispatch-site→kernel trace."""
    found = multi_findings({
        "autoscaler_tpu/ops/mykernel.py": _KERNEL_MODULE,
        "autoscaler_tpu/estimator/dispatch.py": """
            from autoscaler_tpu.ops.mykernel import my_kernel

            def estimate(req, masks):
                return my_kernel(req, masks, chunk=12)
            """,
    })
    assert rules_of(found) == ["GL007"]
    f = found[0]
    assert f.path == "autoscaler_tpu/estimator/dispatch.py"
    assert "chunk=12" in f.message
    assert "autoscaler_tpu.estimator.dispatch.estimate" in f.message
    assert "my_kernel" in f.message
    assert "_STEP_TILE(=8)" in f.message


def test_gl007_aligned_dispatch_clean():
    found = multi_findings({
        "autoscaler_tpu/ops/mykernel.py": _KERNEL_MODULE,
        "autoscaler_tpu/estimator/dispatch.py": """
            from autoscaler_tpu.ops.mykernel import my_kernel

            def estimate(req, masks):
                return my_kernel(req, masks, chunk=16)
            """,
    })
    assert found == []


def test_gl007_rank_and_symbol_conflicts_from_shape_inference():
    found = multi_findings({
        "autoscaler_tpu/ops/mykernel.py": _KERNEL_MODULE,
        "autoscaler_tpu/estimator/dispatch.py": """
            import numpy as np
            from autoscaler_tpu.ops.mykernel import my_kernel

            def bad_rank():
                req = np.zeros((100,))
                masks = np.zeros((4, 100))
                return my_kernel(req, masks, chunk=8)

            def bad_symbol():
                req = np.zeros((100, 6))
                masks = np.zeros((4, 101))
                return my_kernel(req, masks, chunk=8)

            def fine():
                req = np.zeros((100, 6))
                masks = np.zeros((4, 100))
                return my_kernel(req, masks, chunk=8)
            """,
    })
    assert rules_of(found) == ["GL007", "GL007"]
    assert "rank 1" in found[0].message
    assert "dim symbol P" in found[1].message


def test_gl007_shape_env_is_flow_conservative():
    """Rebinding a dispatch operand (after the call, or path-dependently)
    must not produce findings: ShapeEnv only acts on single, dominating
    bindings — the fatal gate cannot afford flow-insensitive false
    positives."""
    found = multi_findings({
        "autoscaler_tpu/ops/mykernel.py": _KERNEL_MODULE,
        "autoscaler_tpu/estimator/dispatch.py": """
            import numpy as np
            from autoscaler_tpu.ops.mykernel import my_kernel

            def rebound_after_call(masks):
                req = np.zeros((100, 6))
                out = my_kernel(req, masks, chunk=8)
                req = req[0]
                return out, req

            def branch_dependent(small, masks):
                if small:
                    req = np.zeros((3,))
                else:
                    req = np.zeros((100, 6))
                return my_kernel(req, masks, chunk=8)

            def param_shadow(req, masks):
                if req is None:
                    req = np.zeros((5,))
                return my_kernel(req, masks, chunk=8)

            def bound_after_call_only(req, masks):
                out = my_kernel(req, masks, chunk=8)
                req = np.zeros((7,))
                return out, req
            """,
    })
    assert found == []


def test_gl007_grid_via_local_variable():
    """`grid = (...)` then `pallas_call(..., grid=grid)` (the ops/pallas_fit
    idiom) must still be matched against the declared grid — and drift
    between the two must be caught, not silently skipped."""
    var_grid = _KERNEL_MODULE.replace(
        "        return pl.pallas_call(\n"
        "            _body,\n"
        "            grid=(P_pad // chunk,),\n"
        "        )(pod_req)",
        "        grid = (P_pad // chunk,)\n"
        "        return pl.pallas_call(\n"
        "            _body,\n"
        "            grid=grid,\n"
        "        )(pod_req)",
    )
    assert "grid = (P_pad // chunk,)" in var_grid  # replacement applied
    clean = multi_findings({"autoscaler_tpu/ops/mykernel.py": var_grid})
    assert clean == []
    drifted = multi_findings({
        "autoscaler_tpu/ops/mykernel.py": var_grid.replace(
            '"grid": ["P_pad // chunk"],',
            '"grid": ["P_pad // chunk", "N_pad // chunk"],',
        ),
    })
    assert "GL007" in rules_of(drifted)
    assert any("no pallas_call in the module uses it" in f.message
               for f in drifted)


def test_gl007_pad_witness_symbolic_divisor_mismatch():
    """Contract divisor `chunk` vs idiom divisor `other` where neither
    resolves to a module constant is drift, not agreement (None == None
    must not excuse the mismatch)."""
    drifted = _KERNEL_MODULE.replace(
        "def my_kernel(pod_req, pod_masks, chunk, max_nodes=8):",
        "def my_kernel(pod_req, pod_masks, chunk, other=8, max_nodes=8):",
    ).replace(
        "P_pad = P + (-P) % chunk", "P_pad = P + (-P) % other"
    )
    found = multi_findings({"autoscaler_tpu/ops/mykernel.py": drifted})
    assert "GL007" in rules_of(found)
    assert any("witnessing idiom" in f.message for f in found)


def test_gl007_step_slice_and_axis_stack_are_unknown_not_wrong():
    """`x[::2]` halves the axis and `np.stack(..., axis=1)` transposes the
    dims — both must infer as unknown rather than produce a provably
    wrong shape that fails the fatal gate on correct dispatch code."""
    found = multi_findings({
        "autoscaler_tpu/ops/mykernel.py": _KERNEL_MODULE,
        "autoscaler_tpu/estimator/dispatch.py": """
            import numpy as np
            from autoscaler_tpu.ops.mykernel import my_kernel

            def step_slice(masks):
                big = np.zeros((100, 6))
                req = big[::2]
                m = np.zeros((4, 50))
                return my_kernel(req, m, chunk=8)

            def axis_stack():
                a = np.zeros((6,))
                req = np.stack([a, a, a], axis=1)
                m = np.zeros((4, 6))
                return my_kernel(req, m, chunk=8)

            def multi_arg_arange():
                req = np.zeros((100, 6))
                m = np.stack([np.arange(1, 101), np.arange(1, 101)])
                return my_kernel(req, m, chunk=8)
            """,
    })
    assert found == []


def test_gl007_guard_on_wrong_divisor_is_not_a_witness():
    """A raise-guard on `chunk % 2` does not witness a `multiple_of:
    _STEP_TILE` (=8) declaration — the guard must check the contract's
    own tile."""
    wrong = _KERNEL_MODULE.replace(
        "if chunk % _STEP_TILE != 0:", "if chunk % 2 != 0:"
    )
    found = multi_findings({"autoscaler_tpu/ops/mykernel.py": wrong})
    assert "GL007" in rules_of(found)
    assert any("no runtime guard" in f.message for f in found)


def test_gl006_nested_def_does_not_shadow_imported_name():
    """A function-LOCAL nested def is out of scope at other call sites:
    a bare call must resolve to the imported name, not the same-spelled
    nested def (both directions: no false positive on a pure import, no
    false negative on a leaky one)."""
    factory = """
        import jax
        from autoscaler_tpu.snapshot.helpers import {NAME}

        def factory():
            def {NAME}(x):
                {BODY}
                return x
            return {NAME}

        @jax.jit
        def kernel(x):
            return {NAME}(x)
        """
    # imported helper pure, nested def leaky: clean
    clean = multi_findings({
        "autoscaler_tpu/ops/kernel.py": textwrap.dedent(factory).format(
            NAME="quiet", BODY="print(x)"
        ),
        "autoscaler_tpu/snapshot/helpers.py": """
            def quiet(x):
                return x
            """,
    })
    assert clean == []
    # imported helper leaky, nested def pure: flagged
    leaky = multi_findings({
        "autoscaler_tpu/ops/kernel.py": textwrap.dedent(factory).format(
            NAME="leaky", BODY="pass"
        ),
        "autoscaler_tpu/snapshot/helpers.py": """
            def leaky(x):
                print(x)
                return x
            """,
    })
    assert rules_of(leaky) == ["GL006"]
    assert leaky[0].path == "autoscaler_tpu/snapshot/helpers.py"


def test_gl006_bare_call_resolves_to_function_not_method():
    """A bare `helper(x)` call can never reach `Cls.helper`; resolution
    must land on the module-level function even when a method shares the
    bare name (and sorts first)."""
    found = multi_findings({
        "autoscaler_tpu/ops/kernel.py": """
            import jax

            class B:
                def helper(self):
                    return 1

            def helper(x):
                print(x)
                return x

            @jax.jit
            def kernel(x):
                return helper(x)
            """,
    })
    assert rules_of(found) == ["GL006"]


def test_gl007_ellipsis_subscript_is_unknown_not_wrong():
    """`arr[..., 0]` must infer as unknown (no finding), not as a rank-0
    shape that would trip a false rank-mismatch in the fatal gate."""
    found = multi_findings({
        "autoscaler_tpu/ops/mykernel.py": _KERNEL_MODULE,
        "autoscaler_tpu/estimator/dispatch.py": """
            import numpy as np
            from autoscaler_tpu.ops.mykernel import my_kernel

            def ellipsis_view(masks):
                cube = np.zeros((100, 6, 3))
                req = cube[..., 0]
                return my_kernel(req, masks, chunk=8)
            """,
    })
    assert found == []


def test_gl007_unwitnessed_pad_and_inexact_grid():
    broken = _KERNEL_MODULE.replace(
        "P_pad = P + (-P) % chunk", "P_pad = P"
    )
    found = multi_findings({"autoscaler_tpu/ops/mykernel.py": broken})
    msgs = " | ".join(f.message for f in found)
    assert rules_of(found) == ["GL007", "GL007"]
    assert "witnessing idiom" in msgs
    assert "not provably exact" in msgs


def test_gl007_missing_runtime_guard():
    unguarded = _KERNEL_MODULE.replace(
        '        if chunk % _STEP_TILE != 0:\n'
        '            raise ValueError("chunk must be a multiple of the tile")\n',
        "",
    )
    found = multi_findings({"autoscaler_tpu/ops/mykernel.py": unguarded})
    assert rules_of(found) == ["GL007"]
    assert "no runtime guard" in found[0].message


def test_gl007_contract_for_unknown_function():
    found = multi_findings({
        "autoscaler_tpu/ops/ghost.py": """
            KERNEL_CONTRACTS = {"nonexistent": {"args": {}}}
            """,
    })
    assert rules_of(found) == ["GL007"]
    assert "no such module-level function" in found[0].message


def test_gl007_twin_contracts_must_agree_on_rank_and_dtype():
    twin = """
        KERNEL_CONTRACTS = {
            "twin_kernel": {
                "args": {"pod_req": {"dims": ["P"], "dtype": "i32"}},
            },
        }

        def twin_kernel(pod_req):
            return pod_req
        """
    base = """
        KERNEL_CONTRACTS = {
            "base_kernel": {
                "args": {"pod_req": {"dims": ["P", "R"], "dtype": "f32"}},
            },
        }

        def base_kernel(pod_req):
            return pod_req
        """
    found = multi_findings({
        "autoscaler_tpu/ops/a_base.py": base,
        "autoscaler_tpu/ops/b_twin.py": twin,
    })
    assert rules_of(found) == ["GL007"]
    assert "twin kernels must agree" in found[0].message


def test_gl007_real_ops_contracts_scan_clean_and_nonvacuous():
    """The shipped ops/ contracts hold over the real estimator dispatch
    path (no findings), and the extraction is non-vacuous (contracts exist
    for the Pallas kernels)."""
    from autoscaler_tpu.analysis.contracts import load_module_contracts

    contracts, consts = load_module_contracts(
        str(REPO / "autoscaler_tpu" / "ops" / "pallas_binpack.py")
    )
    assert "ffd_binpack_groups_pallas" in contracts
    assert consts["_STEP_TILE"] == 8
    assert scan_paths([str(REPO / "autoscaler_tpu" / "ops")]) == []


# -- GL008 lock order ---------------------------------------------------------


def test_gl008_cross_file_cycle_detected():
    found = multi_findings({
        "autoscaler_tpu/trace/recorder.py": """
            import threading

            class Recorder:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.breaker = None

                def record(self):
                    with self._lock:
                        self.breaker.trip_breaker()

                def pin_trace(self):
                    with self._lock:
                        pass
            """,
        "autoscaler_tpu/utils/circuit.py": """
            import threading

            class Breaker:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.recorder = None

                def trip_breaker(self):
                    with self._lock:
                        pass

                def note(self):
                    with self._lock:
                        self.recorder.pin_trace()
            """,
    })
    assert rules_of(found) == ["GL008"]
    assert "lock-order cycle" in found[0].message
    assert "Recorder._lock" in found[0].message
    assert "Breaker._lock" in found[0].message


def test_gl008_one_directional_edges_are_fine():
    found = multi_findings({
        "autoscaler_tpu/utils/circuit.py": """
            import threading

            class Breaker:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.metrics = None

                def trip_breaker(self):
                    with self._lock:
                        self.metrics.observe_transition(1)
            """,
        "autoscaler_tpu/metrics/series.py": """
            import threading

            class Series:
                def __init__(self):
                    self._lock = threading.Lock()

                def observe_transition(self, v):
                    with self._lock:
                        pass
            """,
    })
    assert found == []


def test_gl008_self_deadlock_on_plain_lock_not_rlock():
    src = """
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.{LOCK}()

            def outer_op(self):
                with self._lock:
                    self.inner_op()

            def inner_op(self):
                with self._lock:
                    pass
        """
    plain = multi_findings(
        {"autoscaler_tpu/metrics/box.py": src.replace("{LOCK}", "Lock")}
    )
    assert rules_of(plain) == ["GL008"]
    reentrant = multi_findings(
        {"autoscaler_tpu/metrics/box.py": src.replace("{LOCK}", "RLock")}
    )
    assert reentrant == []


def test_gl008_nested_class_owns_its_lock():
    """A nested class's `self._*lock` binding belongs to the nested class,
    not the outer one — flat ast.walk attribution would fabricate cycles
    through locks the outer class never holds."""
    from autoscaler_tpu.analysis.engine import FileModel
    from autoscaler_tpu.analysis.lockgraph import _class_locks

    model = FileModel("autoscaler_tpu/metrics/nested.py", textwrap.dedent("""
        import threading

        class Outer:
            def __init__(self):
                self._lock = threading.Lock()

            class Inner:
                def __init__(self):
                    self._cachelock = threading.RLock()
        """))
    outer = model.tree.body[1]
    locks = _class_locks(model, outer)
    assert set(locks) == {"_lock"}
    inner = outer.body[1]
    assert set(_class_locks(model, inner)) == {"_cachelock"}


def test_gl008_directly_nested_same_plain_lock():
    """`with self._lock:` nested directly inside `with self._lock:` on a
    plain Lock is a guaranteed self-deadlock — caught without any call
    mediation; the RLock form is fine."""
    src = """
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.{LOCK}()

            def doubled_op(self):
                with self._lock:
                    x = 1
                    with self._lock:
                        pass
        """
    plain = multi_findings(
        {"autoscaler_tpu/metrics/box.py": src.replace("{LOCK}", "Lock")}
    )
    assert rules_of(plain) == ["GL008"]
    assert "re-enters" in plain[0].message
    reentrant = multi_findings(
        {"autoscaler_tpu/metrics/box.py": src.replace("{LOCK}", "RLock")}
    )
    assert reentrant == []


def test_gl008_transitive_acquisition_through_unlocked_helper():
    """A.f holds the lock and calls B.helper, which (without a lock of its
    own) calls back into A.locked_op — the cycle closes transitively."""
    found = multi_findings({
        "autoscaler_tpu/metrics/a.py": """
            import threading

            class Alpha:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.beta = None

                def step_one(self):
                    with self._lock:
                        self.beta.relay_call()

                def step_two(self):
                    with self._lock:
                        pass
            """,
        "autoscaler_tpu/metrics/b.py": """
            import threading

            class Beta:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.alpha = None

                def relay_call(self):
                    with self._lock:
                        pass

                def other_path(self):
                    with self._lock:
                        self.alpha.step_two()
            """,
    })
    assert rules_of(found) == ["GL008"]


# -- GL009 flag wiring --------------------------------------------------------


def test_gl009_orphan_option_field():
    found = multi_findings({
        "autoscaler_tpu/config/options.py": """
            from dataclasses import dataclass

            @dataclass
            class AutoscalingOptions:
                scan_interval_s: float = 10.0
                dead_knob: int = 0
            """,
        "autoscaler_tpu/core/loop.py": """
            def run(opts):
                return opts.scan_interval_s
            """,
    })
    assert rules_of(found) == ["GL009"]
    assert "dead_knob" in found[0].message


def test_gl009_orphan_cli_flag():
    found = multi_findings({
        "autoscaler_tpu/main.py": """
            import argparse

            def build():
                p = argparse.ArgumentParser()
                p.add_argument("--scan-interval", type=float, default=10.0)
                p.add_argument("--ghost-flag", type=int, default=0)
                return p

            def main():
                args = build().parse_args()
                return args.scan_interval
            """,
    })
    assert rules_of(found) == ["GL009"]
    assert "--ghost-flag" in found[0].message
    assert "args.ghost_flag" in found[0].message


def test_gl009_getattr_read_counts_as_wired():
    found = multi_findings({
        "autoscaler_tpu/main.py": """
            import argparse

            def build():
                p = argparse.ArgumentParser()
                p.add_argument("--dyn-flag", type=int, default=0)
                return p

            def main():
                args = build().parse_args()
                return getattr(args, "dyn_flag")
            """,
    })
    assert found == []


def test_gl009_silent_on_partial_disk_scan():
    """Scanning only config/ (readers live elsewhere on disk) must not
    flag live options as orphans: 'never read anywhere in the package'
    cannot be proven by a subtree scan, so GL009 silences itself."""
    found = scan_paths([str(REPO / "autoscaler_tpu" / "config")])
    assert [f for f in found if f.rule == "GL009"] == []


def test_gl008_multi_item_with_orders_like_nested():
    """`with self._a, self._b:` acquires left to right — the inter-item
    ordering edge must be recorded just like the nested form, so the
    classic fwd/rev two-lock deadlock is caught."""
    found = multi_findings({
        "autoscaler_tpu/metrics/pair.py": """
            import threading

            class Pair:
                def __init__(self):
                    self._alock = threading.Lock()
                    self._block = threading.Lock()

                def fwd(self):
                    with self._alock, self._block:
                        pass

                def rev(self):
                    with self._block, self._alock:
                        pass
            """,
    })
    assert rules_of(found) == ["GL008"]
    assert "lock-order cycle" in found[0].message


def test_gl008_witness_messages_carry_no_line_numbers():
    """The baseline fingerprints on (path, rule, message): GL008 witness
    text names files but not lines, so grandfathered cycles don't churn
    on unrelated line drift."""
    found = multi_findings({
        "autoscaler_tpu/metrics/box.py": """
            import threading

            class Box:
                def __init__(self):
                    self._lock = threading.Lock()

                def doubled_op(self):
                    with self._lock:
                        with self._lock:
                            pass
            """,
    })
    assert rules_of(found) == ["GL008"]
    assert re.search(r"\.py:\d", found[0].message) is None


# -- suppression pragmas ------------------------------------------------------


def test_pragma_with_reason_suppresses():
    found = findings(
        """
        import time

        def f():
            return time.time()  # graftlint: disable=GL001 — fixture: injected upstream
        """,
        "autoscaler_tpu/loadgen/fixture.py",
    )
    assert found == []


def test_pragma_on_preceding_comment_line_suppresses():
    found = findings(
        """
        import time

        def f():
            # graftlint: disable=GL001 — fixture: injected upstream
            return time.time()
        """,
        "autoscaler_tpu/loadgen/fixture.py",
    )
    assert found == []


def test_pragma_without_reason_is_gl000():
    found = findings(
        """
        import time

        def f():
            return time.time()  # graftlint: disable=GL001
        """,
        "autoscaler_tpu/loadgen/fixture.py",
    )
    assert rules_of(found) == ["GL000"]  # GL001 suppressed, hygiene flagged


def test_gl000_is_unsuppressible():
    # disable=GL000,GL001 with no reason must not waive the very contract
    # it violates: GL001 is suppressed, the hygiene finding survives
    found = findings(
        """
        import time

        def f():
            return time.time()  # graftlint: disable=GL000,GL001
        """,
        "autoscaler_tpu/loadgen/fixture.py",
    )
    assert rules_of(found) == ["GL000"]


def test_pragma_for_other_rule_does_not_suppress():
    found = findings(
        """
        import time

        def f():
            return time.time()  # graftlint: disable=GL004 — wrong rule
        """,
        "autoscaler_tpu/loadgen/fixture.py",
    )
    assert rules_of(found) == ["GL001"]


# -- baseline round-trip + ratchet -------------------------------------------

_VIOLATION = "import time\n\n\ndef f():\n    return time.time()\n"


def _mini_repo(tmp_path: Path) -> Path:
    pkg = tmp_path / "autoscaler_tpu" / "loadgen"
    pkg.mkdir(parents=True)
    (pkg / "bad.py").write_text(_VIOLATION)
    (pkg / "clean.py").write_text("def ok():\n    return 1\n")
    return tmp_path


def test_baseline_round_trip_and_stale_ratchet(tmp_path):
    root = _mini_repo(tmp_path)
    scan_dir = str(root / "autoscaler_tpu")
    bl = root / "hack" / "lint-baseline.json"

    # no baseline: the violation fails the run
    assert cli_main([scan_dir, "--no-baseline"]) == 1
    # grandfather it
    assert cli_main([scan_dir, "--baseline", str(bl), "--update-baseline"]) == 0
    doc = json.loads(bl.read_text())
    assert [e["rule"] for e in doc["findings"]] == ["GL001"]
    # baselined: clean
    assert cli_main([scan_dir, "--baseline", str(bl)]) == 0
    # a SECOND violation of the same fingerprint exceeds the count: fails
    (root / "autoscaler_tpu" / "loadgen" / "bad.py").write_text(
        _VIOLATION + "\n\ndef g():\n    return time.time()\n"
    )
    assert cli_main([scan_dir, "--baseline", str(bl)]) == 1
    # fixing the violation entirely makes the entry STALE: also fails
    (root / "autoscaler_tpu" / "loadgen" / "bad.py").write_text(
        "def fixed():\n    return 0\n"
    )
    assert cli_main([scan_dir, "--baseline", str(bl)]) == 1
    # striking it restores green
    assert cli_main([scan_dir, "--baseline", str(bl), "--update-baseline"]) == 0
    assert cli_main([scan_dir, "--baseline", str(bl)]) == 0
    assert json.loads(bl.read_text())["findings"] == []


def test_partial_scan_neither_reports_nor_strikes_unscanned_stale(tmp_path):
    """A one-file scan must not read the rest of the ledger as stale, and a
    one-file --update-baseline must not strike the unscanned entries."""
    root = _mini_repo(tmp_path)
    (root / "autoscaler_tpu" / "loadgen" / "bad2.py").write_text(_VIOLATION)
    scan_dir = str(root / "autoscaler_tpu")
    bl = root / "hack" / "lint-baseline.json"
    assert cli_main([scan_dir, "--baseline", str(bl), "--update-baseline"]) == 0
    assert len(json.loads(bl.read_text())["findings"]) == 2
    one_file = str(root / "autoscaler_tpu" / "loadgen" / "bad2.py")
    # partial scan: bad.py's entry is out of scope, not stale
    assert cli_main([one_file, "--baseline", str(bl)]) == 0
    # fix bad2 only; partial update strikes ITS entry, preserves bad.py's
    Path(one_file).write_text("def fixed():\n    return 0\n")
    assert cli_main([one_file, "--baseline", str(bl), "--update-baseline"]) == 0
    kept = json.loads(bl.read_text())["findings"]
    assert [e["path"] for e in kept] == ["autoscaler_tpu/loadgen/bad.py"]
    assert cli_main([scan_dir, "--baseline", str(bl)]) == 0


def test_deleted_file_under_scanned_dir_reads_stale(tmp_path):
    """The ratchet must survive file deletion: an entry for a file that no
    longer exists under a scanned directory is stale, not invisible."""
    root = _mini_repo(tmp_path)
    scan_dir = str(root / "autoscaler_tpu")
    bl = root / "hack" / "lint-baseline.json"
    assert cli_main([scan_dir, "--baseline", str(bl), "--update-baseline"]) == 0
    (root / "autoscaler_tpu" / "loadgen" / "bad.py").unlink()
    assert cli_main([scan_dir, "--baseline", str(bl)]) == 1  # stale
    assert cli_main([scan_dir, "--baseline", str(bl), "--update-baseline"]) == 0
    assert json.loads(bl.read_text())["findings"] == []
    assert cli_main([scan_dir, "--baseline", str(bl)]) == 0


def test_explicit_missing_baseline_is_usage_error(tmp_path):
    root = _mini_repo(tmp_path)
    rc = cli_main(
        [str(root / "autoscaler_tpu"), "--baseline", str(root / "typo.json")]
    )
    assert rc == 2


def test_repo_partial_scan_single_file_passes(monkeypatch):
    # pre-commit-style invocation: one clean file + the shipped full-repo
    # baseline must not surface the unscanned ledger as stale
    monkeypatch.chdir(REPO)
    assert cli_main(["autoscaler_tpu/loadgen/faults.py"]) == 0


def test_baseline_diff_excess_surfaces_newest_lines():
    f1 = check_source(_VIOLATION, "autoscaler_tpu/loadgen/bad.py")
    assert len(f1) == 1
    base = {f1[0].fingerprint: 1}
    two = check_source(
        _VIOLATION + "\n\ndef g():\n    return time.time()\n",
        "autoscaler_tpu/loadgen/bad.py",
    )
    new, stale = baseline_mod.diff(two, base)
    assert len(new) == 1 and new[0].line > f1[0].line
    assert stale == []


# -- repo self-checks + CLI contract -----------------------------------------


def test_analysis_package_scans_clean_over_itself():
    assert scan_paths([str(REPO / "autoscaler_tpu" / "analysis")]) == []


def test_repo_scans_clean_without_any_baseline(monkeypatch):
    """The burn-down end state (PR 20): the grandfather ledger is gone and
    the full self-scan is clean with no baseline at all — every finding
    either fixed at source or carrying a reasoned inline pragma."""
    monkeypatch.chdir(REPO)
    assert not (REPO / "hack" / "lint-baseline.json").exists()
    assert cli_main(["autoscaler_tpu", "--no-baseline"]) == 0
    # and the default run (baseline auto-discovery finds nothing) agrees
    assert cli_main(["autoscaler_tpu"]) == 0


def test_findings_render_and_sort_deterministically():
    found = findings(
        """
        import time

        def b():
            return time.sleep(1)

        def a():
            return time.time()
        """,
        "autoscaler_tpu/loadgen/fixture.py",
    )
    assert [f.line for f in found] == sorted(f.line for f in found)
    rendered = found[0].render()
    assert rendered.startswith("autoscaler_tpu/loadgen/fixture.py:")
    assert ": GL001 " in rendered


def test_cli_module_entry_point_seeded_violation(tmp_path):
    """The real `python -m autoscaler_tpu.analysis` contract: nonzero +
    path:line: RULE output on a seeded violation, 0 on a clean tree."""
    root = _mini_repo(tmp_path)
    proc = subprocess.run(
        [sys.executable, "-m", "autoscaler_tpu.analysis", "--no-baseline",
         str(root / "autoscaler_tpu")],
        capture_output=True, text=True, cwd=str(REPO),
    )
    assert proc.returncode == 1
    assert "autoscaler_tpu/loadgen/bad.py:5: GL001" in proc.stdout
    proc2 = subprocess.run(
        [sys.executable, "-m", "autoscaler_tpu.analysis", "--no-baseline",
         str(root / "autoscaler_tpu" / "loadgen" / "clean.py")],
        capture_output=True, text=True, cwd=str(REPO),
    )
    assert proc2.returncode == 0


def test_cli_missing_path_is_usage_error(tmp_path):
    assert cli_main([str(tmp_path / "nope")]) == 2


def test_cli_contradictory_baseline_flags_are_usage_error(tmp_path):
    root = _mini_repo(tmp_path)
    rc = cli_main(
        [str(root / "autoscaler_tpu"), "--no-baseline", "--update-baseline"]
    )
    assert rc == 2


def test_nul_byte_file_degrades_to_parse_finding():
    found = check_source("\x00bad", "autoscaler_tpu/core/corrupt.py")
    assert rules_of(found) == ["GL000"]
    assert "does not parse" in found[0].message


# -- CLI formats, exit codes, summary table -----------------------------------


def test_cli_json_format_structure_and_exit_code(tmp_path, capsys):
    root = _mini_repo(tmp_path)
    rc = cli_main(
        [str(root / "autoscaler_tpu"), "--no-baseline", "--format=json"]
    )
    assert rc == 1
    doc = json.loads(capsys.readouterr().out)
    assert doc["version"] == 1
    assert doc["files"] == 2
    assert [f["rule"] for f in doc["findings"]] == ["GL001"]
    assert doc["findings"][0]["path"] == "autoscaler_tpu/loadgen/bad.py"
    assert doc["stale"] == []
    assert doc["summary"]["GL001"]["findings"] == 1
    assert set(doc["summary"]) >= {"GL000", "GL001", "GL007", "GL008", "GL009"}


def test_cli_json_output_byte_identical_across_runs(tmp_path, capsys):
    """The determinism gate hack/verify.sh enforces: two identical runs
    must produce byte-identical JSON, independent of dict/set iteration."""
    root = _mini_repo(tmp_path)
    args = [str(root / "autoscaler_tpu"), "--no-baseline", "--format=json"]
    cli_main(args)
    first = capsys.readouterr().out
    cli_main(args)
    second = capsys.readouterr().out
    assert first == second
    json.loads(first)  # and it parses


def test_cli_github_format_annotation_lines(tmp_path, capsys):
    root = _mini_repo(tmp_path)
    rc = cli_main(
        [str(root / "autoscaler_tpu"), "--no-baseline", "--format=github"]
    )
    assert rc == 1
    out = capsys.readouterr().out.splitlines()
    assert out[0].startswith(
        "::error file=autoscaler_tpu/loadgen/bad.py,line=5,title=graftlint GL001::"
    )


def test_cli_github_format_emits_witness_flow_notices(tmp_path, capsys):
    """A finding carrying a witness path annotates every step as a
    ::notice beside the ::error, so the code-review UI can walk the
    leak path inline."""
    root = tmp_path / "repo"
    pkg = root / "autoscaler_tpu" / "fleet"
    pkg.mkdir(parents=True)
    pkg.joinpath("leak.py").write_text(
        "class FleetCoalescer:\n"
        "    def submit(self, req):\n"
        "        return object()\n"
        "\n"
        "def _validate(req):\n"
        "    if not req:\n"
        "        raise ValueError('empty')\n"
        "\n"
        "class Driver:\n"
        "    def run(self, req):\n"
        "        c = FleetCoalescer()\n"
        "        t = c.submit(req)\n"
        "        _validate(req)\n"
        "        t.resolve(None)\n",
        encoding="utf-8",
    )
    rc = cli_main(
        [str(root / "autoscaler_tpu"), "--no-baseline", "--format=github"]
    )
    assert rc == 1
    out = capsys.readouterr().out.splitlines()
    errors = [l for l in out if l.startswith("::error")]
    notices = [l for l in out if l.startswith("::notice")]
    assert any("GL016" in l for l in errors)
    assert notices, "witness path emitted no ::notice flow steps"
    steps = [l for l in notices if "graftlint GL016 path" in l]
    assert len(steps) >= 2
    assert all("file=autoscaler_tpu/fleet/leak.py" in l for l in steps)


def test_cli_explain_prints_the_rules_md_section(capsys):
    assert cli_main(["--explain", "GL016"]) == 0
    out = capsys.readouterr().out
    assert out.startswith("## GL016")
    assert "obligation" in out and "witness" in out
    assert cli_main(["--explain", "GL017"]) == 0
    out = capsys.readouterr().out
    assert out.startswith("## GL017")
    assert "SCHEMA_FIELDS" in out


def test_cli_explain_unknown_rule_is_usage_error(capsys):
    assert cli_main(["--explain", "GL999"]) == 2
    err = capsys.readouterr().err
    assert "GL999" in err and "GL016" in err  # lists the known rules


def test_cli_text_format_prints_summary_table(tmp_path, capsys):
    root = _mini_repo(tmp_path)
    cli_main([str(root / "autoscaler_tpu"), "--no-baseline"])
    err = capsys.readouterr().err
    assert "rule   findings  suppressed  baselined" in err
    assert "GL001" in err and "GL009" in err


def test_cli_internal_analyzer_error_exits_2(tmp_path, monkeypatch):
    """Findings are 1, a crash in the analyzer itself is 2 — CI must be
    able to tell a failed ratchet from a broken gate."""
    from autoscaler_tpu.analysis import cli as cli_mod

    root = _mini_repo(tmp_path)

    def boom(sources, **kwargs):
        raise RuntimeError("synthetic analyzer crash")

    monkeypatch.setattr(cli_mod, "analyze_sources", boom)
    assert cli_main([str(root / "autoscaler_tpu"), "--no-baseline"]) == 2


# -- GL010 taint-flow determinism ---------------------------------------------


def test_gl010_taint_through_assignment_and_container_to_ledger_sink():
    """The acceptance-criteria shape: a wall-clock value assigned, boxed
    in a dict, and handed to the record_line choke point — reported with
    the full source -> sink witness path."""
    found = findings(
        """
        import time

        def emit(ledger):
            now = time.time()
            rec = {"ts": now}
            ledger.write(record_line(rec))
        """,
        "autoscaler_tpu/perf/fixture.py",
    )
    # GL013 (the dedicated interprocedural ordering engine) co-fires on
    # the same walk — both carry the witness, each under its own pragma
    assert rules_of(found) == ["GL001", "GL010", "GL013"]
    taint = found[1]
    assert taint.line == 7  # the SINK line, not the source line
    assert "wall-clock at autoscaler_tpu/perf/fixture.py:5" in taint.message
    assert "record_line() ledger write" in taint.message
    assert " -> " in taint.message  # the rendered taint path


def test_gl010_interprocedural_return_hop_across_modules():
    """Taint crosses a module boundary through a helper's return; the
    finding lands at the sink with the call hop witnessed."""
    found = multi_findings({
        "autoscaler_tpu/perf/helper.py": """
            import time

            def stamp():
                return time.time()
            """,
        "autoscaler_tpu/perf/writer.py": """
            from autoscaler_tpu.perf.helper import stamp

            def emit():
                rec = {"t": stamp()}
                return record_line(rec)
            """,
    })
    gl010 = [f for f in found if f.rule == "GL010"]
    assert [f.path for f in gl010] == ["autoscaler_tpu/perf/writer.py"]
    msg = gl010[0].message
    assert "wall-clock at autoscaler_tpu/perf/helper.py:5" in msg
    assert "return of stamp()" in msg          # the interprocedural hop
    assert "record_line() ledger write" in msg


def test_gl010_interprocedural_param_to_sink_flags_the_caller():
    """A def that forwards its parameter into record_line is a sink for
    its callers: passing time.time() at the call site is the violation,
    and the message names the callee's internal sink."""
    found = multi_findings({
        "autoscaler_tpu/perf/sinkmod.py": """
            def emit(clock_value):
                return record_line({"t": clock_value})
            """,
        "autoscaler_tpu/perf/caller.py": """
            import time
            from autoscaler_tpu.perf.sinkmod import emit

            def tick():
                return emit(time.time())
            """,
    })
    gl010 = [f for f in found if f.rule == "GL010"]
    assert [f.path for f in gl010] == ["autoscaler_tpu/perf/caller.py"]
    assert "emit(arg 0)" in gl010[0].message
    assert "record_line() ledger write" in gl010[0].message


def test_gl010_set_iteration_order_flags_sorted_declassifies():
    """list() over a set realizes hash-seed-dependent order into a ledger
    line; sorted() is the sanctioned order-insensitive consumption."""
    found = findings(
        """
        def emit(ledger):
            groups = {"b", "a"}
            names = list(groups)
            ledger.write(record_line({"groups": names}))

        def emit_ok(ledger):
            groups = {"b", "a"}
            names = sorted(groups)
            ledger.write(record_line({"groups": names}))
        """,
        "autoscaler_tpu/fleet/fixture.py",
    )
    # GL013 co-fires on the realized set order; sorted() sanitizes both
    assert rules_of(found) == ["GL010", "GL013"]
    assert all("set-iteration-order" in f.message for f in found)


def test_gl010_declassifiers_timeline_now_and_injected_param():
    """The two sanctioned seams: trace.timeline_now() (replaced by the
    loadgen synthetic counter) and a value arriving through an injected
    parameter (unresolvable by design — never guessed at)."""
    found = findings(
        """
        from autoscaler_tpu import trace

        def emit():
            return record_line({"t": trace.timeline_now()})

        def emit2(clock):
            return record_line({"t": clock()})
        """,
        "autoscaler_tpu/perf/fixture.py",
    )
    assert found == []


def test_gl010_pragma_on_source_line_declassifies():
    found = findings(
        """
        import time

        def emit():
            now = time.time()  # graftlint: disable=GL001,GL010 — fixture: value is replay-stable by contract
            return record_line({"t": now})
        """,
        "autoscaler_tpu/perf/fixture.py",
    )
    # GL010's pragma surface is the SOURCE line (declassified here);
    # GL013 anchors at the sink and carries its own pragma surface there
    assert rules_of(found) == ["GL013"]


def test_gl010_raw_set_in_producer_return_flags_sorted_clean():
    """The in-tree class this rule landed on (perf/ledger.py summarize):
    a raw set inside a serialization producer's return is order-unstable;
    sorted()/len() consumption is clean."""
    found = findings(
        """
        def summarize(records):
            sigs = set()
            for r in records:
                sigs.add(r)
            return {"sigs": sigs}

        def summarize_ok(records):
            sigs = set()
            for r in records:
                sigs.add(r)
            return {"sigs": sorted(sigs), "n": len(sigs)}
        """,
        "autoscaler_tpu/perf/fixture.py",
    )
    assert rules_of(found) == ["GL010"]
    assert "raw set" in found[0].message
    assert "summarize()" in found[0].message


def test_gl010_fstring_realizes_set_order():
    found = findings(
        """
        def emit():
            groups = {"b", "a"}
            return record_line({"label": f"groups={groups}"})
        """,
        "autoscaler_tpu/explain/fixture.py",
    )
    assert rules_of(found) == ["GL010", "GL013"]
    assert all("set-iteration-order" in f.message for f in found)


def test_gl010_out_of_scope_module_not_flagged():
    found = findings(
        """
        import time

        def emit(ledger):
            ledger.write(record_line({"t": time.time()}))
        """,
        "autoscaler_tpu/kube/fixture.py",  # not a replay scope
    )
    assert found == []


def test_gl010_branch_taint_survives_set_typeness_does_not():
    """May/must polarity: taint on ONE branch still reaches the sink
    (real flow), but a value that is a set on only one branch is never
    order-flagged (must-intersect — no guessing)."""
    found = findings(
        """
        import time

        def one_branch_taint(flag):
            t = 0.0
            if flag:
                t = time.time()
            return record_line({"t": t})

        def one_branch_set(ledger, flag):
            if flag:
                xs = {1, 2}
            else:
                xs = [1, 2]
            return record_line({"xs": list(xs)})
        """,
        "autoscaler_tpu/perf/fixture.py",
    )
    # both taint engines agree: the one-branch flow is real, and neither
    # order-flags the one-branch set (shared must-intersect polarity)
    assert rules_of(found) == ["GL001", "GL010", "GL013"]
    assert "wall-clock" in found[1].message


# -- GL011 thread escape ------------------------------------------------------

_ESCAPE_SRC = """
    import threading

    class Box:
        def __init__(self):
            self._lock = threading.Lock()
            self._items = []

        def put(self, x):
            with self._lock:
                self._items = [x]

        def peek(self):
            {PEEK}
"""


def test_gl011_unlocked_read_with_locked_write_elsewhere():
    src = _ESCAPE_SRC.replace("{PEEK}", "return self._items")
    found = findings(src, "autoscaler_tpu/fleet/fixture.py")
    assert rules_of(found) == ["GL011"]
    msg = found[0].message
    # both witnessing access paths are named
    assert "Box.peek" in msg and "Box.put" in msg
    assert "under the lock" in msg


def test_gl011_dual_locking_is_clean():
    src = _ESCAPE_SRC.replace(
        "{PEEK}", "with self._lock:\n                return self._items"
    )
    assert findings(src, "autoscaler_tpu/fleet/fixture.py") == []


def test_gl011_confined_to_one_method_is_clean():
    found = findings(
        """
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0

            def bump(self):
                self._n += 1
                return self._n
        """,
        "autoscaler_tpu/perf/fixture.py",
    )
    # GL004 still owns the unlocked-write half; no GL011 (confined)
    assert "GL011" not in rules_of(found)


def test_gl011_init_only_write_is_immutable_after_publication():
    found = findings(
        """
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._capacity = 16

            def a(self):
                return self._capacity

            def b(self):
                return self._capacity + 1
        """,
        "autoscaler_tpu/fleet/fixture.py",
    )
    assert found == []


def test_gl011_private_helper_called_under_lock_inherits_protection():
    found = findings(
        """
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = []

            def put(self, x):
                with self._lock:
                    self._items = [x]
                    self._compact()

            def get(self):
                with self._lock:
                    return self._find()

            def _compact(self):
                self._items = list(self._items)

            def _find(self):
                return self._items
        """,
        "autoscaler_tpu/fleet/fixture.py",
    )
    # _compact/_find are called ONLY from locked regions: no escape (the
    # GL004 write check skips *_locked only, so _compact's write is its
    # finding to make — scope GL011 here)
    assert "GL011" not in rules_of(found)


def test_gl011_public_method_never_inherits_lock():
    found = findings(
        """
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = []

            def put(self, x):
                with self._lock:
                    self._items = [x]
                    self.refresh()

            def refresh(self):
                return self._items
        """,
        "autoscaler_tpu/fleet/fixture.py",
    )
    assert "GL011" in rules_of(found)


# -- GL012 surface gating + serialization choke -------------------------------


def test_gl012_ungated_endpoint_flags_gated_clean():
    ungated = findings(
        """
        class Handler:
            def do_GET(self):
                if self.path == "/metrics":
                    self._send(200, "ok")
                elif self.path.startswith("/tracez"):
                    self._send(200, self.tracer.list_json())
        """,
        "autoscaler_tpu/main.py",
    )
    assert rules_of(ungated) == ["GL012"]
    assert "'/tracez'" in ungated[0].message
    assert "tracing_enabled" in ungated[0].message
    gated = findings(
        """
        class Handler:
            def do_GET(self):
                if self.path.startswith("/tracez"):
                    if not self.options.tracing_enabled:
                        self._send(404, "disabled")
                        return
                    self._send(200, self.tracer.list_json())
        """,
        "autoscaler_tpu/main.py",
    )
    assert gated == []


def test_gl012_unknown_endpoint_must_be_registered():
    found = findings(
        """
        class Handler:
            def do_GET(self):
                if self.path == "/newz":
                    self._send(200, "hi")
        """,
        "autoscaler_tpu/main.py",
    )
    assert rules_of(found) == ["GL012"]
    assert "not a known surface" in found[0].message


def test_gl012_adhoc_json_dumps_needs_sort_keys():
    found = findings(
        """
        import json

        def dump(doc):
            return json.dumps(doc, indent=2)

        def dump_ok(doc):
            return json.dumps(doc, indent=2, sort_keys=True)
        """,
        "autoscaler_tpu/loadgen/fixture.py",
    )
    assert rules_of(found) == ["GL012"]
    assert "sort_keys=True" in found[0].message
    # out of replay scope: not this rule's business
    assert findings(
        """
        import json

        def dump(doc):
            return json.dumps(doc)
        """,
        "autoscaler_tpu/vpa/fixture.py",
    ) == []


# -- seeded-violation CLI exit codes for the new rules ------------------------


def _seeded_repo(tmp_path: Path) -> Path:
    pkg = tmp_path / "autoscaler_tpu"
    (pkg / "perf").mkdir(parents=True)
    (pkg / "fleet").mkdir()
    (pkg / "perf" / "taint.py").write_text(textwrap.dedent("""
        import time

        def emit(ledger):
            now = time.time()
            ledger.write(record_line({"t": now}))
        """))
    (pkg / "fleet" / "escape.py").write_text(textwrap.dedent("""
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = []

            def put(self, x):
                with self._lock:
                    self._items = [x]

            def peek(self):
                return self._items
        """))
    (pkg / "main.py").write_text(textwrap.dedent("""
        class Handler:
            def do_GET(self):
                if self.path.startswith("/tracez"):
                    self._send(200, "trace")
        """))
    return tmp_path


def test_cli_seeded_violations_for_new_rules_exit_1(tmp_path, capsys):
    root = _seeded_repo(tmp_path)
    rc = cli_main(
        [str(root / "autoscaler_tpu"), "--no-baseline", "--format=json"]
    )
    assert rc == 1
    doc = json.loads(capsys.readouterr().out)
    rules = {f["rule"] for f in doc["findings"]}
    assert {"GL010", "GL011", "GL012"} <= rules
    assert {"GL010", "GL011", "GL012"} <= set(doc["summary"])


def test_cli_github_format_renders_taint_path(tmp_path, capsys):
    root = _seeded_repo(tmp_path)
    rc = cli_main(
        [str(root / "autoscaler_tpu"), "--no-baseline", "--format=github"]
    )
    assert rc == 1
    out = capsys.readouterr().out
    gl010 = [l for l in out.splitlines() if "graftlint GL010" in l]
    assert gl010, out
    # the annotation carries the rendered source -> sink path
    assert "wall-clock at autoscaler_tpu/perf/taint.py" in gl010[0]
    assert " -> " in gl010[0]


# -- incremental cache --------------------------------------------------------


def test_cache_byte_identical_and_invalidation(tmp_path, capsys):
    """--cache must reproduce the uncached JSON byte-for-byte (cold AND
    warm), and a content change must invalidate the stale entries."""
    root = _seeded_repo(tmp_path)
    scan = str(root / "autoscaler_tpu")
    cache_dir = str(tmp_path / ".graftlint-cache")

    cli_main([scan, "--no-baseline", "--format=json"])
    uncached = capsys.readouterr().out
    cli_main([scan, "--no-baseline", "--format=json",
              "--cache", "--cache-dir", cache_dir])
    cold = capsys.readouterr().out
    cli_main([scan, "--no-baseline", "--format=json",
              "--cache", "--cache-dir", cache_dir])
    warm = capsys.readouterr().out
    assert uncached == cold == warm
    # entries live under a per-salt generation directory (stale
    # generations are pruned on analyzer change)
    assert Path(cache_dir).is_dir() and list(Path(cache_dir).glob("*/*.json"))

    # fix the taint violation: the cached findings must not resurrect it
    (root / "autoscaler_tpu" / "perf" / "taint.py").write_text(
        "def emit(ledger, now):\n"
        "    ledger.write(record_line({\"t\": now}))\n"
    )
    cli_main([scan, "--no-baseline", "--format=json"])
    fresh = capsys.readouterr().out
    cli_main([scan, "--no-baseline", "--format=json",
              "--cache", "--cache-dir", cache_dir])
    cached = capsys.readouterr().out
    assert fresh == cached
    assert "GL010" not in {
        f["rule"] for f in json.loads(fresh)["findings"]
    }


def test_cache_bypassed_for_explicit_rule_subsets(tmp_path):
    """analyze_sources with an explicit rules list must ignore the cache
    entirely — only the canonical full-rule scan is cacheable."""
    from autoscaler_tpu.analysis import rules as rules_mod
    from autoscaler_tpu.analysis.cache import LintCache

    cache = LintCache(str(tmp_path / "c"))
    sources = {"autoscaler_tpu/loadgen/bad.py": _VIOLATION}
    found, _ = analyze_sources(
        sources, rules=[rules_mod.WallClockInReplayPath()], cache=cache
    )
    assert rules_of(found) == ["GL001"]
    assert not (tmp_path / "c").exists()  # nothing written


def test_no_grandfather_ledger_ships():
    """Acceptance (PR 20): the baseline ratchet reached zero — the ledger
    file itself no longer ships. Combined with
    test_repo_scans_clean_without_any_baseline (which fails on ANY
    finding), this proves every rule holds over the repo with no
    grandfathered debt left."""
    assert not (REPO / "hack" / "lint-baseline.json").exists()


def test_gl010_bound_method_call_param_mapping():
    """`self.meth(a, b)` passes its receiver implicitly: summary param
    indices must shift by one at bound call sites — a tainted arg that
    never sinks must not flag, the one that sinks must."""
    found = findings(
        """
        import time

        class W:
            def f(self, a, b):
                return record_line({"a": a})

            def good(self):
                return self.f(0.0, time.time())

            def bad(self):
                return self.f(time.time(), 0.0)
        """,
        "autoscaler_tpu/perf/fixture.py",
    )
    gl010 = [f for f in found if f.rule == "GL010"]
    assert [f.line for f in gl010] == [12]  # only bad()'s call site
    assert "f(arg 0)" in gl010[0].message


def test_gl011_mutator_call_counts_as_write():
    """`self._items.append(x)` writes through the field (GL004 cannot see
    method-call mutation — GL011 must): locked-append writer + bare
    reader is the canonical escape."""
    found = findings(
        """
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = []

            def put(self, x):
                with self._lock:
                    self._items.append(x)

            def peek(self):
                return self._items[0]
        """,
        "autoscaler_tpu/fleet/fixture.py",
    )
    assert rules_of(found) == ["GL011"]
    assert "Box.peek" in found[0].message and "Box.put" in found[0].message


def test_gl010_pragma_above_must_be_comment_only_and_no_shadowing():
    """Dataflow pragma semantics match engine._suppressed: a GL010 pragma
    on a comment-only line above declassifies even when the source line
    carries a different rule's pragma; a pragma trailing unrelated CODE
    on the line above does not leak downward."""
    declassified = findings(
        """
        import time

        def emit():
            # graftlint: disable=GL010 — fixture: value is replay-stable by contract
            now = time.time()  # graftlint: disable=GL001 — fixture: sanctioned seam
            return record_line({"t": now})
        """,
        "autoscaler_tpu/perf/fixture.py",
    )
    # the comment-line pragma declassifies GL010 at its source line;
    # GL013's finding anchors at the sink line and is untouched by it
    assert rules_of(declassified) == ["GL013"]
    leaking = findings(
        """
        import time

        def emit():
            x = 1  # graftlint: disable=GL010 — fixture: pragma trails unrelated code
            now = time.time()  # graftlint: disable=GL001 — fixture: sanctioned seam
            return record_line({"t": now})
        """,
        "autoscaler_tpu/perf/fixture.py",
    )
    assert "GL010" in rules_of(leaking)  # the code-line pragma must not leak


def test_gl012_compound_path_test_checks_every_endpoint():
    found = findings(
        """
        class Handler:
            def do_GET(self):
                if self.path in ("/health-check", "/perfz"):
                    self._send(200, "ok")
        """,
        "autoscaler_tpu/main.py",
    )
    assert rules_of(found) == ["GL012"]
    assert "'/perfz'" in found[0].message and "perf_enabled" in found[0].message


def test_gl012_path_boundary_not_bare_prefix():
    """'/statusz' must not inherit '/status''s ungated standing; a real
    sub-path ('/debug/pprof/heap' under the gated '/debug/pprof') still
    maps to its parent's gate."""
    found = findings(
        """
        class Handler:
            def do_GET(self):
                if self.path == "/statusz":
                    self._send(200, "zz")
        """,
        "autoscaler_tpu/main.py",
    )
    assert rules_of(found) == ["GL012"]
    assert "not a known surface" in found[0].message
    gated_subpath = findings(
        """
        class Handler:
            def do_GET(self):
                if self.path == "/debug/pprof/heap":
                    if not profiling:
                        self._send(404, "off")
                        return
                    self._send(200, "heap")
        """,
        "autoscaler_tpu/main.py",
    )
    assert gated_subpath == []


def test_gl010_comprehension_targets_do_not_leak():
    """Comprehension variables neither clobber an outer clean binding
    (false positive) nor erase an outer tainted one (false negative)."""
    clean_outer = findings(
        """
        def emit():
            n = 0
            total = sum(n for n in {"a", "b"})
            return record_line({"n": n, "total": total})
        """,
        "autoscaler_tpu/perf/fixture.py",
    )
    assert clean_outer == []
    tainted_outer = findings(
        """
        import time

        def emit(items):
            x = time.time()
            ys = [x for x in items]
            return record_line({"t": x})
        """,
        "autoscaler_tpu/perf/fixture.py",
    )
    assert "GL010" in rules_of(tainted_outer)


def test_gl010_value_exposing_reductions_keep_taint_len_does_not():
    """max/min/sum expose the element values — max() of wall-clock stamps
    IS the wall-clock; len() is a pure count and stays clean."""
    exposed = findings(
        """
        import time

        def emit():
            ts = [time.time()]
            return record_line({"m": max(ts)})
        """,
        "autoscaler_tpu/perf/fixture.py",
    )
    assert "GL010" in rules_of(exposed)
    counted = findings(
        """
        import time

        def emit():
            ts = [time.time()]
            return record_line({"n": len(ts)})
        """,
        "autoscaler_tpu/perf/fixture.py",
    )
    assert "GL010" not in rules_of(counted)


def test_gl010_keyword_argument_flows_into_param_sink():
    found = multi_findings({
        "autoscaler_tpu/perf/sinkmod2.py": """
            def emit(v):
                return record_line({"t": v})
            """,
        "autoscaler_tpu/perf/caller2.py": """
            import time
            from autoscaler_tpu.perf.sinkmod2 import emit

            def tick():
                return emit(v=time.time())
            """,
    })
    gl010 = [f for f in found if f.rule == "GL010"]
    assert [f.path for f in gl010] == ["autoscaler_tpu/perf/caller2.py"]


def test_gl010_for_loop_set_source_is_scope_gated():
    """for-over-set outside replay scopes is not a source — equivalent
    spellings (loop vs comprehension vs list()) get equivalent verdicts."""
    found = findings(
        """
        def collect():
            out = []
            for x in {"a", "b"}:
                out.append(x)
            return out
        """,
        "autoscaler_tpu/kube/fixture.py",
    )
    assert found == []


def test_gl010_self_receiver_is_a_bound_method_not_a_container():
    """`self.update(...)` must resolve through the method's summary: no
    container-absorption false positive on `self`, and a method NAMED
    like a container mutator still gets its param->sink applied."""
    no_fp = findings(
        """
        import time

        class W:
            def tick(self):
                self.update(time.time())
                return record_line({"n": self._count})

            def update(self, t):
                self._count = 1
        """,
        "autoscaler_tpu/perf/fixture.py",
    )
    assert "GL010" not in rules_of(no_fp)
    no_fn = findings(
        """
        import time

        class W:
            def tick(self):
                return self.update(time.time())

            def update(self, rec):
                return record_line({"r": rec})
        """,
        "autoscaler_tpu/perf/fixture.py",
    )
    assert "GL010" in rules_of(no_fn)


def test_gl001_env_read_has_env_specific_guidance():
    found = findings(
        """
        import os

        def probe():
            return os.getenv("X")
        """,
        "autoscaler_tpu/perf/fixture.py",
    )
    assert rules_of(found) == ["GL001"]
    assert "startup" in found[0].message  # not the clock/rng seam advice


def test_gl010_for_loop_pragma_declassifies_order_not_element_taint():
    """A GL010 pragma above `for t in s:` sanctions the iteration ORDER;
    wall-clock taint carried by the set's elements still flows."""
    found = multi_findings({
        "autoscaler_tpu/perf/src3.py": """
            import time

            def stamps():
                return {time.time()}
            """,
        "autoscaler_tpu/perf/wr3.py": """
            from autoscaler_tpu.perf.src3 import stamps

            def emit():
                s = stamps()
                # graftlint: disable=GL010 — fixture: iteration order sanctioned
                for t in s:
                    record_line({"t": t})
            """,
    })
    gl010 = [f for f in found if f.rule == "GL010"]
    assert gl010 and all("wall-clock" in f.message for f in gl010), gl010


def test_gl010_ordering_builtins_scope_gated_like_siblings():
    """list()/tuple() over a set outside replay scopes is not a source —
    consistent with the for-loop/comprehension/f-string spellings."""
    found = findings(
        """
        def expand():
            xs = {1, 2}
            return list(xs)
        """,
        "autoscaler_tpu/kube/fixture.py",
    )
    assert found == []


def test_cache_prunes_stale_generations(tmp_path):
    from autoscaler_tpu.analysis.cache import LintCache

    stale = tmp_path / "deadbeef00000000"
    stale.mkdir()
    (stale / "x.json").write_text("{}")
    c = LintCache(str(tmp_path))
    c.put(c.file_key("a.py", "x = 1\n"), [])
    assert not stale.exists()
    assert (tmp_path / c.salt[:16]).is_dir()
