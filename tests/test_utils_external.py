"""Utils (tpu sanitizer, errors, cache, klogx, leader election) and the
external gRPC cloud provider — including the full autoscaler loop running
against an out-of-process provider."""
import threading

import numpy as np
import pytest

from autoscaler_tpu.cloudprovider.test_provider import TestCloudProvider
from autoscaler_tpu.config.options import AutoscalingOptions
from autoscaler_tpu.kube.objects import Resources
from autoscaler_tpu.utils.cache import ExpiringCache, QuotaLogger
from autoscaler_tpu.utils.errors import AutoscalerError, ErrorType, to_autoscaler_error
from autoscaler_tpu.utils.leaderelection import FileLease, LeaderElector
from autoscaler_tpu.utils.test_utils import GB, build_test_node, build_test_pod
from autoscaler_tpu.utils.tpu import LEGACY_TPU_PREFIX, clear_tpu_requests


class TestTpuSanitizer:
    def test_legacy_requests_stripped(self):
        pod = build_test_pod("p")
        pod.annotations[LEGACY_TPU_PREFIX + "v5e"] = "8"
        pod.requests = Resources(cpu_m=100, tpu=8)
        out = clear_tpu_requests([pod])
        assert out[0].requests.tpu == 0
        assert not any(k.startswith(LEGACY_TPU_PREFIX) for k in out[0].annotations)

    def test_native_requests_kept(self):
        pod = build_test_pod("p")
        pod.requests = Resources(cpu_m=100, tpu=4)
        out = clear_tpu_requests([pod])
        assert out[0] is pod  # identity: untouched
        assert out[0].requests.tpu == 4


class TestErrors:
    def test_types_and_retriability(self):
        e = AutoscalerError(ErrorType.TRANSIENT, "cloud hiccup")
        assert e.retriable
        assert not AutoscalerError(ErrorType.CONFIGURATION, "bad flag").retriable
        wrapped = to_autoscaler_error(ValueError("boom"))
        assert wrapped.error_type == ErrorType.INTERNAL
        assert "prefix: " in str(wrapped.prefixed("prefix: "))


class TestCaches:
    def test_expiring_cache(self):
        clock = [0.0]
        c = ExpiringCache(ttl_s=10, clock=lambda: clock[0])
        c.put("k", 42)
        assert c.get("k") == 42
        clock[0] = 11.0
        assert c.get("k") is None

    def test_quota_logger(self):
        q = QuotaLogger(quota=2)
        for i in range(5):
            q.log("msg %d", i)
        assert q.dropped == 3
        q.reset()
        assert q.dropped == 0


class TestLeaderElection:
    def test_single_holder(self, tmp_path):
        lease = FileLease(str(tmp_path / "lease"), ttl_s=100)
        assert lease.try_acquire("a", now_ts=0.0)
        assert not lease.try_acquire("b", now_ts=10.0)   # a holds
        assert lease.try_acquire("a", now_ts=10.0)       # renew
        assert lease.try_acquire("b", now_ts=200.0)      # expired → steal

    def test_release(self, tmp_path):
        lease = FileLease(str(tmp_path / "lease"), ttl_s=100)
        lease.try_acquire("a", 0.0)
        lease.release("a")
        assert lease.try_acquire("b", 1.0)

    def test_elector_runs_leader(self, tmp_path):
        lease = FileLease(str(tmp_path / "lease"), ttl_s=100)
        ran = []
        elector = LeaderElector(lease, identity="me", sleep=lambda s: None)
        elector.run(lambda still: ran.append(still()))
        assert ran == [True]
        # lease released on exit
        assert lease.try_acquire("other", 0.0)


@pytest.fixture()
def remote_provider():
    from autoscaler_tpu.cloudprovider.external_grpc import (
        ExternalGrpcCloudProvider,
        serve_cloud_provider,
    )

    backend = TestCloudProvider()
    backend.add_node_group(
        "pool", 0, 10, 1, build_test_node("tmpl", cpu_m=2000, mem=4 * GB)
    )
    node = build_test_node("pool-0", cpu_m=2000, mem=4 * GB)
    backend.add_node("pool", node)
    server, port = serve_cloud_provider(backend)
    client = ExternalGrpcCloudProvider(f"127.0.0.1:{port}")
    yield backend, client, node
    client.cleanup()
    server.stop(grace=None)


class TestExternalGrpcProvider:
    def test_node_groups_roundtrip(self, remote_provider):
        backend, client, node = remote_provider
        client.refresh()
        groups = client.node_groups()
        assert [g.id() for g in groups] == ["pool"]
        g = groups[0]
        assert (g.min_size(), g.max_size(), g.target_size()) == (0, 10, 1)
        tmpl = g.template_node_info()
        assert tmpl.allocatable.cpu_m == 2000

    def test_node_group_for_node(self, remote_provider):
        backend, client, node = remote_provider
        assert client.node_group_for_node(node).id() == "pool"
        ghost = build_test_node("ghost")
        assert client.node_group_for_node(ghost) is None

    def test_scale_up_via_rpc(self, remote_provider):
        backend, client, node = remote_provider
        g = client.node_groups()[0]
        g.increase_size(3)
        assert backend.scale_up_calls == [("pool", 3)]
        assert g.target_size() == 4

    def test_full_loop_against_remote_provider(self, remote_provider):
        from autoscaler_tpu.core.static_autoscaler import StaticAutoscaler
        from autoscaler_tpu.kube.api import FakeClusterAPI

        backend, client, node = remote_provider
        api = FakeClusterAPI()
        api.add_node(node)
        api.add_pod(build_test_pod("blocker", cpu_m=1800, node_name="pool-0"))
        api.add_pod(build_test_pod("pending", cpu_m=1500, mem=1 * GB))
        autoscaler = StaticAutoscaler(client, api, AutoscalingOptions())
        result = autoscaler.run_once(now_ts=0.0)
        assert result.scale_up is not None and result.scale_up.scaled_up
        assert backend.scale_up_calls  # the RPC crossed the boundary


class TestKlogx:
    """Quota-limited logging (reference utils/klogx/klogx_test.go)."""

    def setup_method(self):
        from autoscaler_tpu.utils import klogx

        klogx.set_verbosity(0)

    def teardown_method(self):
        from autoscaler_tpu.utils import klogx

        klogx.set_verbosity(0)

    def _capture(self, caplog_records, fn):
        import logging

        from autoscaler_tpu.utils import klogx

        records = []
        handler = logging.Handler()
        handler.emit = lambda r: records.append(r.getMessage())
        klogx.logger.addHandler(handler)
        klogx.logger.setLevel(logging.INFO)
        try:
            fn()
        finally:
            klogx.logger.removeHandler(handler)
        return records

    def test_up_to_quota_caps_lines(self):
        from autoscaler_tpu.utils import klogx

        klogx.set_verbosity(4)
        quota = klogx.new_logging_quota(3)

        def run():
            for i in range(10):
                klogx.v(4).up_to(quota).info("line %d", i)
            klogx.v(4).over(quota).info("%d skipped", -quota.left)

        records = self._capture(None, run)
        assert records == ["line 0", "line 1", "line 2", "7 skipped"]
        assert quota.left == -7

    def test_below_verbosity_consumes_no_quota(self):
        from autoscaler_tpu.utils import klogx

        klogx.set_verbosity(2)
        quota = klogx.new_logging_quota(3)

        def run():
            for i in range(10):
                klogx.v(4).up_to(quota).info("line %d", i)
            klogx.v(4).over(quota).info("skipped")

        records = self._capture(None, run)
        assert records == []
        # disabled Verbose never decrements the quota (klogx.go UpTo)
        assert quota.left == 3

    def test_over_silent_when_under_quota(self):
        from autoscaler_tpu.utils import klogx

        klogx.set_verbosity(4)
        quota = klogx.new_logging_quota(5)

        def run():
            for i in range(3):
                klogx.v(4).up_to(quota).info("line %d", i)
            klogx.v(4).over(quota).info("skipped")

        records = self._capture(None, run)
        assert records == ["line 0", "line 1", "line 2"]

    def test_pods_quota_scales_with_verbosity(self):
        from autoscaler_tpu.utils import klogx

        klogx.set_verbosity(4)
        assert klogx.pods_logging_quota().limit == klogx.MAX_PODS_LOGGED
        klogx.set_verbosity(5)
        assert klogx.pods_logging_quota().limit == klogx.MAX_PODS_LOGGED_V5

    def test_reset(self):
        from autoscaler_tpu.utils import klogx

        quota = klogx.new_logging_quota(2)
        quota.left = -5
        quota.reset()
        assert quota.left == 2

    def test_eligibility_emits_quota_bounded_lines(self):
        """30 candidate nodes at -v4: exactly 20 utilization lines + one
        summary for the other 10 (eligibility.go:71,100 semantics)."""
        from autoscaler_tpu.config.options import AutoscalingOptions
        from autoscaler_tpu.core.scaledown.eligibility import EligibilityChecker
        from autoscaler_tpu.snapshot.cluster_snapshot import ClusterSnapshot
        from autoscaler_tpu.utils import klogx
        from autoscaler_tpu.utils.test_utils import build_test_node, build_test_pod

        klogx.set_verbosity(4)
        snap = ClusterSnapshot()
        nodes = []
        for j in range(30):
            n = build_test_node(f"n{j}", cpu_m=4000)
            snap.add_node(n)
            nodes.append(n)
            p = build_test_pod(f"p{j}", cpu_m=200, node_name=n.name)
            snap.add_pod(p, n.name)
        checker = EligibilityChecker(AutoscalingOptions())

        def run():
            checker.filter_out_unremovable(snap, nodes, now_ts=0.0)

        records = self._capture(None, run)
        util_lines = [r for r in records if "utilization" in r and "Skipped" not in r]
        summaries = [r for r in records if "Skipped" in r]
        assert len(util_lines) == 20
        assert summaries == ["Skipped logging utilization for 10 other nodes"]


class TestPollLoop:
    def test_errors_do_not_kill_the_loop(self):
        from autoscaler_tpu.utils.poll import poll_loop

        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise RuntimeError("transient")

        rc = poll_loop(flaky, interval_s=0.0, max_iterations=5)
        assert rc == 0
        assert len(calls) == 5  # errors logged, loop continued

    def test_keyboard_interrupt_exits_cleanly(self):
        from autoscaler_tpu.utils.poll import poll_loop

        calls = []

        def interrupt():
            calls.append(1)
            if len(calls) > 1:
                # regression guard: if poll_loop ever swallowed the first
                # KeyboardInterrupt, fail fast instead of spinning forever
                pytest.fail("poll_loop swallowed KeyboardInterrupt")
            raise KeyboardInterrupt

        assert poll_loop(interrupt, interval_s=0.0, max_iterations=3) == 0
        assert calls == [1]

    def test_drift_compensated_sleep(self, monkeypatch):
        """A slow tick eats into the sleep instead of stacking on top."""
        from autoscaler_tpu.utils import poll

        sleeps = []
        clock = [0.0]

        def fake_monotonic():
            return clock[0]

        def fake_sleep(s):
            sleeps.append(s)
            clock[0] += s

        monkeypatch.setattr(poll.time, "monotonic", fake_monotonic)
        monkeypatch.setattr(poll.time, "sleep", fake_sleep)

        def tick():
            clock[0] += 0.3  # fn takes 0.3s of the 1.0s interval

        poll.poll_loop(tick, interval_s=1.0, max_iterations=2)
        assert sleeps and abs(sleeps[0] - 0.7) < 1e-9


class TestBackgroundRenewal:
    def test_long_iteration_keeps_lease_renewed(self, tmp_path):
        """The renewal thread keeps the lease fresh while the leading
        callback blocks longer than the TTL — a second replica must not be
        able to take the lease mid-iteration (split-brain guard)."""
        import time as _t

        from autoscaler_tpu.utils.leaderelection import FileLease, LeaderElector

        lease = FileLease(str(tmp_path / "lease"), ttl_s=0.3)
        challenger = FileLease(str(tmp_path / "lease"), ttl_s=0.3)
        stolen = []

        def long_iteration(still_leader):
            _t.sleep(1.0)  # 3x the TTL: without renewal the lease expires
            stolen.append(challenger.try_acquire("challenger", _t.time()))
            assert still_leader()

        elector = LeaderElector(lease, identity="leader", renew_period_s=0.05)
        elector.run(long_iteration)
        assert stolen == [False]  # renewals held the challenger off

    def test_transient_renew_failure_tolerated(self, tmp_path):
        """One failed renewal inside the deadline must not dethrone the
        leader; sustained failure past the deadline must."""
        import time as _t

        from autoscaler_tpu.utils.leaderelection import FileLease, LeaderElector

        class FlakyLease(FileLease):
            def __init__(self, path, fail_from, fail_until, **kw):
                super().__init__(path, **kw)
                self.fail_from = fail_from
                self.fail_until = fail_until

            def try_acquire(self, holder, now_ts):
                if self.fail_from < _t.monotonic() < self.fail_until:
                    raise OSError("apiserver hiccup")
                return super().try_acquire(holder, now_ts)

        t0 = _t.monotonic()
        lease = FlakyLease(str(tmp_path / "l"), t0 + 0.1, t0 + 0.25, ttl_s=100)
        seen = []

        def iteration(still_leader):
            _t.sleep(0.5)           # failures happen inside here
            seen.append(still_leader())

        elector = LeaderElector(
            lease, identity="leader", renew_period_s=0.05, renew_deadline_s=5.0
        )
        elector.run(iteration)
        assert seen == [True]       # hiccup < deadline → still leading

        # sustained failure past the deadline loses leadership
        acquired_once = []

        class FailAfterAcquire(FileLease):
            def try_acquire(self, holder, now_ts):
                if acquired_once:
                    raise OSError("down")
                acquired_once.append(1)
                return super().try_acquire(holder, now_ts)

        lease3 = FailAfterAcquire(str(tmp_path / "l3"), ttl_s=100)
        seen2 = []

        def iteration2(still_leader):
            _t.sleep(0.6)
            seen2.append(still_leader())

        elector2 = LeaderElector(
            lease3, identity="leader", renew_period_s=0.05,
            renew_deadline_s=0.2,
        )
        elector2.run(iteration2)
        assert seen2 == [False]     # renewals failing past deadline
