"""Fleet mission control (autoscaler_tpu/slo + cross-process tracing):
trace-context propagation, per-ticket lifecycle SLIs, the SLO burn-rate
engine, the window ledger, /sloz, and the loadgen byte-determinism
acceptance."""
import json
import subprocess
import sys
import threading
import urllib.error
import urllib.request
from pathlib import Path

import numpy as np
import pytest

from autoscaler_tpu import trace
from autoscaler_tpu.cloudprovider.test_provider import TestCloudProvider
from autoscaler_tpu.config.options import AutoscalingOptions
from autoscaler_tpu.core.static_autoscaler import StaticAutoscaler
from autoscaler_tpu.fleet import (
    OVERFLOW_TENANT,
    FleetCoalescer,
    FleetRequest,
)
from autoscaler_tpu.kube.api import FakeClusterAPI
from autoscaler_tpu.main import ObservabilityServer
from autoscaler_tpu.metrics.metrics import AutoscalerMetrics
from autoscaler_tpu.slo import (
    SCHEMA,
    SLI_FLEET_E2E,
    SLI_PENDING_POD,
    SLI_TICK_DURATION,
    SloEngine,
    SloError,
    SloSpec,
    default_slos,
    fleet_slos,
    record_line,
    summarize,
    validate_records,
)
from autoscaler_tpu.utils.test_utils import GB, build_test_node, build_test_pod

REPO = Path(__file__).resolve().parent.parent


def make_autoscaler(pods=(), **opt_kw):
    provider = TestCloudProvider()
    api = FakeClusterAPI()
    provider.add_node_group(
        "g", 0, 10, 1, build_test_node("t", cpu_m=1000, mem=2 * GB)
    )
    node = build_test_node("g-0", cpu_m=1000, mem=2 * GB)
    provider.add_node("g", node)
    api.add_node(node)
    for p in pods:
        api.add_pod(p)
    return StaticAutoscaler(provider, api, AutoscalingOptions(**opt_kw))


def _spec(**kw):
    base = dict(
        name="s", description="d", target=0.9, threshold_s=1.0,
        windows_s=(10.0, 100.0),
    )
    base.update(kw)
    return SloSpec(**base)


# -------------------------------------------------------- trace context
class TestTraceContext:
    def test_current_context_and_parse_round_trip(self):
        assert trace.current_context() is None
        t = trace.Tracer()
        with t.tick("main"):
            ctx = trace.current_context()
            assert trace.parse_context(ctx) == (0, 0)
            with trace.span("estimate"):
                assert trace.parse_context(trace.current_context()) == (0, 1)

    def test_parse_rejects_garbage(self):
        for bad in (None, "", "7", "a:b", "1:2:3x", 12):
            assert trace.parse_context(bad) is None
        assert trace.parse_context("12:3") == (12, 3)

    def test_tick_adopts_parent_context(self):
        t = trace.Tracer(recorder=trace.FlightRecorder(capacity=4))
        with t.tick("main", parent_context="7:3"):
            pass
        rec = t.recorder.traces()[-1]
        assert rec.trace_id == 7
        assert rec.root.attrs["parent_trace_id"] == 7
        assert rec.root.attrs["parent_span_id"] == 3
        # malformed context degrades to a local trace, no parent attrs —
        # and the local sequence has been advanced PAST the adopted id so
        # a context-less request can never collide with an adopted trace
        with t.tick("main", parent_context="nope"):
            pass
        rec = t.recorder.get(8)
        assert rec is not None
        assert "parent_trace_id" not in rec.root.attrs

    def test_openmetrics_counter_family_naming(self):
        """OM counters: TYPE/HELP name the FAMILY (sample name minus
        `_total`); counters not ending in `_total` gain the suffix on the
        sample — either way a strict OM parser accepts the scrape."""
        from autoscaler_tpu.metrics.metrics import MetricsRegistry

        r = MetricsRegistry()
        r.counter("x_events_total", "h").inc(k="v")
        r.counter("x_removed_count", "h").inc()
        om = r.expose(openmetrics=True)
        assert "# TYPE x_events counter" in om
        assert 'x_events_total{k="v"} 1' in om
        assert "# TYPE x_removed_count counter" in om
        assert "x_removed_count_total 1" in om
        # the classic dialect is untouched
        classic = r.expose()
        assert "# TYPE x_events_total counter" in classic
        assert "x_removed_count 1" in classic

    def test_recorder_keeps_adopted_id_collisions(self):
        """A serving recorder holds one adopted trace per served RPC —
        several can share one (client) trace id and ALL must be listed."""
        t = trace.Tracer(recorder=trace.FlightRecorder(capacity=8))
        for method in ("Estimate", "BatchEstimate"):
            with t.tick("main", parent_context="5:1", method=method):
                pass
        traces = t.recorder.traces()
        assert [tr.trace_id for tr in traces] == [5, 5]
        assert [tr.root.attrs["method"] for tr in traces] == [
            "Estimate", "BatchEstimate",
        ]
        # detail lookup resolves to the most recent match
        found = t.recorder.get(5)
        assert found is not None
        assert found.root.attrs["method"] == "BatchEstimate"


# ------------------------------------------------------------- SloSpec
class TestSloSpec:
    def test_default_catalogs(self):
        from autoscaler_tpu.slo import control_loop_slos

        names = {s.name for s in default_slos()}
        assert names == {SLI_FLEET_E2E, SLI_TICK_DURATION, SLI_PENDING_POD}
        assert {s.name for s in fleet_slos()} == {SLI_FLEET_E2E}
        # the control loop runs no coalescer: its catalog must not declare
        # an objective that can never receive events
        assert {s.name for s in control_loop_slos()} == {
            SLI_TICK_DURATION, SLI_PENDING_POD,
        }
        for s in default_slos():
            s.validate()

    @pytest.mark.parametrize("kw", [
        dict(target=1.0), dict(target=0.0), dict(threshold_s=0.0),
        dict(windows_s=()), dict(windows_s=(0.0,)), dict(burn_alert=0.0),
        dict(name=""),
    ])
    def test_rejects_bad_specs(self, kw):
        with pytest.raises(SloError):
            _spec(**kw).validate()

    def test_engine_rejects_duplicates_and_empty(self):
        with pytest.raises(ValueError):
            SloEngine(specs=[_spec(), _spec()])
        with pytest.raises(ValueError):
            SloEngine(specs=[])


# ------------------------------------------------------------ SloEngine
class TestSloEngine:
    def test_burn_rate_arithmetic(self):
        e = SloEngine(specs=[_spec()])
        for i in range(8):
            e.observe("s", 0.5, now=float(i))       # good
        e.observe("s", 2.0, now=8.0)                # bad
        e.observe("s", 3.0, now=9.0)                # bad
        rec = e.tick(9.0, 0)
        w = rec["slos"]["s"]["windows"]["100"]
        assert w["total"] == 10 and w["bad"] == 2
        assert w["error_rate"] == pytest.approx(0.2)
        # burn = error_rate / (1 - target) = 0.2 / 0.1 = 2.0
        assert w["burn_rate"] == pytest.approx(2.0)
        assert validate_records([rec]) == []

    def test_window_filtering_ages_events_out(self):
        e = SloEngine(specs=[_spec(windows_s=(10.0, 1000.0))])
        e.observe("s", 9.0, now=0.0)   # bad, old
        e.observe("s", 0.1, now=99.0)  # good, recent
        rec = e.tick(100.0, 0)
        short = rec["slos"]["s"]["windows"]["10"]
        long_ = rec["slos"]["s"]["windows"]["1000"]
        assert (short["total"], short["bad"]) == (1, 0)
        assert (long_["total"], long_["bad"]) == (2, 1)
        # lifetime counters are never windowed
        assert rec["slos"]["s"]["events_total"] == 2
        assert rec["slos"]["s"]["events_bad"] == 1

    def test_alerting_needs_every_window_burning(self):
        e = SloEngine(specs=[_spec(windows_s=(10.0, 1000.0), burn_alert=5.0)])
        # one bad event at now=99: short window sees only it (burn 10),
        # long window sees it diluted below the alert factor
        for i in range(50):
            e.observe("s", 0.1, now=float(i))
        e.observe("s", 9.0, now=99.0)
        rec = e.tick(100.0, 0)
        slo = rec["slos"]["s"]
        assert slo["windows"]["10"]["burn_rate"] >= 5.0
        assert slo["windows"]["1000"]["burn_rate"] < 5.0
        assert slo["alerting"] is False
        # saturate both windows → alert
        e2 = SloEngine(specs=[_spec(windows_s=(10.0, 1000.0), burn_alert=5.0)])
        for i in range(10):
            e2.observe("s", 9.0, now=90.0 + i)
        rec2 = e2.tick(100.0, 0)
        assert rec2["slos"]["s"]["alerting"] is True
        assert validate_records([rec2]) == []

    def test_empty_window_never_alerts(self):
        e = SloEngine(specs=[_spec(burn_alert=0.001)])
        rec = e.tick(0.0, 0)
        assert rec["slos"]["s"]["alerting"] is False

    def test_unknown_slo_dropped_and_failures_are_bad(self):
        e = SloEngine(specs=[_spec()])
        e.observe("nope", 1.0, now=0.0)     # silently dropped
        e.observe_event("s", bad=True, now=0.0)
        rec = e.tick(0.0, 0)
        assert rec["slos"]["s"]["events_bad"] == 1

    def test_metrics_published(self):
        m = AutoscalerMetrics()
        e = SloEngine(specs=[_spec()], metrics=m)
        e.observe("s", 0.5, now=0.0)
        e.observe("s", 5.0, now=1.0)
        e.tick(1.0, 0)
        assert m.slo_events_total.get(slo="s", verdict="good") == 1.0
        assert m.slo_events_total.get(slo="s", verdict="bad") == 1.0
        assert m.slo_burn_rate.get(slo="s", window="10") == pytest.approx(5.0)

    def test_ring_bounded(self):
        e = SloEngine(specs=[_spec()], ring_capacity=2)
        for i in range(5):
            e.tick(float(i), i)
        recs = e.records()
        assert [r["tick"] for r in recs] == [3, 4]
        assert e.last_record()["tick"] == 4


class TestPendingPodSli:
    def _engine(self, threshold=30.0):
        return SloEngine(specs=[
            SloSpec(name=SLI_PENDING_POD, description="d", target=0.5,
                    threshold_s=threshold, windows_s=(1000.0,)),
        ])

    def _explain(self, now, pods):
        return {"now_ts": now, "pods": {k: "cpu" for k in pods}}

    def test_pod_resolving_inside_threshold_is_good(self):
        e = self._engine()
        e.observe_explain(self._explain(0.0, ["p1"]))
        e.observe_explain(self._explain(10.0, []))
        rec = e.tick(10.0, 0)
        slo = rec["slos"][SLI_PENDING_POD]
        assert (slo["events_total"], slo["events_bad"]) == (1, 0)

    def test_overstayer_charged_once_and_not_again_on_resolve(self):
        e = self._engine(threshold=15.0)
        e.observe_explain(self._explain(0.0, ["p1"]))
        e.observe_explain(self._explain(20.0, ["p1"]))   # overstay → bad
        e.observe_explain(self._explain(30.0, ["p1"]))   # still: no re-charge
        e.observe_explain(self._explain(40.0, []))       # resolve: no event
        rec = e.tick(40.0, 0)
        slo = rec["slos"][SLI_PENDING_POD]
        assert (slo["events_total"], slo["events_bad"]) == (1, 1)

    def test_malformed_record_ignored(self):
        e = self._engine()
        e.observe_explain(None)
        e.observe_explain({"pods": {"p": "cpu"}})   # no now_ts
        # no pods AND no pending split: a crashed tick — established nothing
        e.observe_explain({"now_ts": 1.0})
        assert e.tick(1.0, 0)["slos"][SLI_PENDING_POD]["events_total"] == 0

    def test_cleared_pending_set_resolves_tracked_pods(self):
        """A healthy tick with ZERO pending pods notes the 'pending' split
        but no per-pod section — the tracker must read that as the empty
        set and resolve its pods NOW, not freeze until the next pending
        episode (which charged false bad events with inflated durations)."""
        e = self._engine(threshold=60.0)
        e.observe_explain(self._explain(0.0, ["p1"]))
        # pod scheduled: pending cleared — record carries the split only
        e.observe_explain({"now_ts": 30.0, "pending": {"pending": 0}})
        rec = e.tick(30.0, 0)
        slo = rec["slos"][SLI_PENDING_POD]
        assert (slo["events_total"], slo["events_bad"]) == (1, 0)
        # a much later pending episode must NOT resurrect p1
        e.observe_explain(self._explain(300.0, ["p2"]))
        e.observe_explain({"now_ts": 310.0, "pending": {"pending": 0}})
        slo = e.tick(310.0, 1)["slos"][SLI_PENDING_POD]
        assert (slo["events_total"], slo["events_bad"]) == (2, 0)

    def test_crashed_tick_does_not_resolve_tracked_pods(self):
        """Crash-shaped records must leave the tracker untouched — the pod
        is still pending as far as anyone knows: no sections at all (crash
        before the pending note), AND a pending split still reporting
        pending pods with no per-pod section (crash between the pending
        note and the scale-up explain — falsely resolving here would reset
        the pending clock every crash of a crash-looping tick, the exact
        outage where budget must keep burning)."""
        e = self._engine(threshold=60.0)
        e.observe_explain(self._explain(0.0, ["p1"]))
        e.observe_explain({"now_ts": 10.0})   # crash before the split
        e.observe_explain(
            {"now_ts": 15.0, "pending": {"pending": 1}}   # crash after it
        )
        e.observe_explain(self._explain(20.0, ["p1"]))   # still tracked
        e.observe_explain({"now_ts": 30.0, "pending": {"pending": 0}})
        slo = e.tick(30.0, 0)["slos"][SLI_PENDING_POD]
        assert (slo["events_total"], slo["events_bad"]) == (1, 0)

    def test_crash_loop_still_burns_budget(self):
        """A pod pending through repeated crash-shaped ticks accumulates
        pending time and is charged its bad event on the first healthy
        overstaying tick."""
        e = self._engine(threshold=15.0)
        e.observe_explain(self._explain(0.0, ["p1"]))
        for t in (10.0, 20.0, 30.0):
            e.observe_explain({"now_ts": t, "pending": {"pending": 1}})
        e.observe_explain(self._explain(40.0, ["p1"]))   # healthy, overstayed
        slo = e.tick(40.0, 0)["slos"][SLI_PENDING_POD]
        assert (slo["events_total"], slo["events_bad"]) == (1, 1)


# ------------------------------------------------------------ the ledger
def _valid_records():
    e = SloEngine(specs=[_spec()])
    e.observe("s", 0.1, now=0.0)
    e.observe("s", 2.0, now=1.0)
    r0 = e.tick(1.0, 0)
    e.observe("s", 0.1, now=2.0)
    r1 = e.tick(2.0, 1)
    return [r0, r1]


class TestLedger:
    def test_valid_ledger_and_summary(self):
        recs = _valid_records()
        assert validate_records(recs) == []
        agg = summarize(recs)
        assert agg["ticks"] == 2
        assert agg["slos"]["s"]["events_total"] == 3
        assert agg["slos"]["s"]["worst_burn_rate"]["10"] == pytest.approx(5.0)

    def test_tight_budget_tolerance(self):
        """A target-0.9999 SLO's burn is the error rate amplified 10_000x,
        so the validator's tolerance must scale with 1/budget — a correct
        engine record must not fail the arithmetic cross-check on the
        9-digit rounding of error_rate."""
        spec = SloSpec(name="tight", description="d", target=0.9999,
                       threshold_s=1.0, windows_s=(10_000.0,))
        e = SloEngine(specs=[spec])
        for i in range(8191):
            e.observe("tight", 0.1, now=float(i % 100))
        e.observe("tight", 9.0, now=99.0)
        rec = e.tick(100.0, 0)
        assert validate_records([rec]) == [], validate_records([rec])

    def test_record_line_is_sorted_strict_json(self):
        line = record_line(_valid_records()[0])
        doc = json.loads(line)
        assert doc["schema"] == SCHEMA
        assert line == json.dumps(
            doc, sort_keys=True, separators=(",", ":")
        ) + "\n"

    @pytest.mark.parametrize("mutate,needle", [
        (lambda r: r[0].update(schema="bogus"), "schema"),
        (lambda r: r[1].update(tick=0), "not increasing"),
        (lambda r: r[1].update(now_ts=0.0), "went backwards"),
        (lambda r: r[0]["slos"]["s"]["windows"]["10"].update(
            error_rate=0.9), "error_rate"),
        (lambda r: r[0]["slos"]["s"]["windows"]["10"].update(
            burn_rate=0.123), "burn_rate"),
        (lambda r: r[0]["slos"]["s"].update(alerting=True), "alerting"),
        (lambda r: r[0]["slos"]["s"].update(target=1.5), "target"),
        (lambda r: r[1]["slos"]["s"].update(events_total=0), "decreased"),
        (lambda r: r[0]["slos"]["s"]["windows"]["10"].update(
            bad=99), "exceeds"),
        (lambda r: r[0].update(slos={}), "non-empty"),
    ])
    def test_corruptions_caught(self, mutate, needle):
        recs = _valid_records()
        mutate(recs)
        errors = validate_records(recs)
        assert errors and any(needle in e for e in errors), errors


class TestBenchGate:
    def _run(self, path):
        return subprocess.run(
            [sys.executable, str(REPO / "bench.py"), "--slo-ledger", str(path)],
            capture_output=True, text=True, timeout=300, cwd=str(REPO),
        )

    def test_exit_code_contract(self, tmp_path):
        good = tmp_path / "good.jsonl"
        good.write_text("".join(record_line(r) for r in _valid_records()))
        proc = self._run(good)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        report = json.loads(proc.stdout)
        assert report["valid"] and report["slos"]["s"]["events_total"] == 3

        bad = tmp_path / "bad.jsonl"
        recs = _valid_records()
        recs[0]["slos"]["s"]["windows"]["10"]["burn_rate"] = 99.0
        bad.write_text("".join(record_line(r) for r in recs))
        proc = self._run(bad)
        assert proc.returncode == 1
        assert not json.loads(proc.stdout)["valid"]

        proc = self._run(tmp_path / "missing.jsonl")
        assert proc.returncode == 2


# ----------------------------------------- fleet ticket lifecycle + SLIs
class TestTicketLifecycle:
    def _req(self, rng, tenant="t", P=8, G=3):
        return FleetRequest(
            tenant_id=tenant,
            pod_req=rng.integers(0, 100, (P, 6)).astype(np.float32),
            pod_masks=rng.random((G, P)) > 0.3,
            template_allocs=rng.integers(50, 400, (G, 6)).astype(np.float32),
            node_caps=rng.integers(1, 8, G).astype(np.int32),
            max_nodes=16,
        )

    def test_stamps_ordered_and_metrics_move(self):
        rng = np.random.default_rng(5)
        m = AutoscalerMetrics()
        co = FleetCoalescer(buckets="16x4x8", metrics=m)
        tracer = trace.Tracer(recorder=trace.FlightRecorder(capacity=2))
        with tracer.tick("main"):
            tk = co.submit(self._req(rng))
            co.flush()
        tk.result(1.0)
        assert 0.0 < tk.t_submit <= tk.t_admit <= tk.t_dispatch
        assert tk.t_dispatch <= tk.t_demux <= tk.t_resolve
        assert tk.trace_context and trace.parse_context(tk.trace_context)
        assert m.fleet_queue_wait_seconds.count(
            tenant="t", bucket="16x4x8"
        ) == 1
        assert m.fleet_service_seconds.count(tenant="t", bucket="16x4x8") == 1
        assert m.fleet_e2e_seconds.count(tenant="t", bucket="16x4x8") == 1
        # exemplar on some bucket carries the origin trace id — in the
        # OpenMetrics dialect ONLY: the classic 0.0.4 exposition must stay
        # exemplar-free (a '#' after a sample value is a parse error that
        # would take down every scrape of a classic Prometheus)
        expo = m.registry.expose(openmetrics=True)
        assert '# {trace_id="0"}' in expo
        assert expo.endswith("# EOF\n")
        classic = m.registry.expose()
        assert "# {trace_id=" not in classic
        assert "# EOF" not in classic

    def test_window_thread_stamps_share_submitter_clock_domain(self):
        """A ticket submitted inside a synthetic-clock trace but dispatched
        by the (untraced) window thread must stamp EVERY lifecycle point
        from the submitter's captured clock — mixing the synthetic timeline
        with the bare monotonic fallback recorded system-uptime-sized
        garbage as queue_wait/e2e."""
        from autoscaler_tpu.loadgen.driver import _TraceClock

        rng = np.random.default_rng(21)
        m = AutoscalerMetrics()
        co = FleetCoalescer(buckets="16x4x8", window_s=0.002, metrics=m)
        co.start()
        try:
            tracer = trace.Tracer(
                clock=_TraceClock(),
                recorder=trace.FlightRecorder(capacity=2),
            )
            with tracer.tick("main"):
                tk = co.submit(self._req(rng))
            tk.result(10.0)
        finally:
            co.stop()
        # synthetic clock: 1ms per reading — every stamp lives near zero,
        # and the deltas are a handful of milliseconds, not system uptime
        assert tk.t_submit <= tk.t_dispatch <= tk.t_resolve
        assert tk.t_resolve < 1.0, (tk.t_submit, tk.t_dispatch, tk.t_resolve)
        e2e = max(tk.t_resolve - tk.t_submit, 0.0)
        assert e2e < 1.0

    def test_slo_fed_per_resolved_ticket_and_failed_batch(self, monkeypatch):
        rng = np.random.default_rng(6)
        engine = SloEngine(specs=fleet_slos())
        co = FleetCoalescer(buckets="16x4x8", slo=engine)
        co.submit(self._req(rng))
        co.flush()
        assert engine.tick(0.0, 0)["slos"][SLI_FLEET_E2E]["events_total"] == 1
        # every rung failing charges one BAD event per ticket
        monkeypatch.setattr(
            co, "_walk_ladder",
            lambda *a, **k: (_ for _ in ()).throw(RuntimeError("boom")),
        )
        tk = co.submit(self._req(rng))
        co.flush()
        with pytest.raises(Exception):
            tk.result(1.0)
        slo = engine.tick(1.0, 1)["slos"][SLI_FLEET_E2E]
        assert (slo["events_total"], slo["events_bad"]) == (2, 1)

    def test_tenant_label_cardinality_bound(self):
        rng = np.random.default_rng(7)
        m = AutoscalerMetrics()
        co = FleetCoalescer(buckets="16x4x8", metrics=m, max_tenant_labels=2)
        for name in ("a", "b", "noisy-1", "noisy-2"):
            co.submit(self._req(rng, tenant=name))
        co.flush()
        assert co.tenant_label("a") == "a"
        assert co.tenant_label("b") == "b"
        assert co.tenant_label("noisy-1") == OVERFLOW_TENANT
        assert co.tenant_label("never-seen") == OVERFLOW_TENANT
        assert m.fleet_e2e_seconds.count(
            tenant=OVERFLOW_TENANT, bucket="16x4x8"
        ) == 2
        # overflow tenants are NOT memoized — the guard itself must stay
        # bounded under an abusive tenant-id generator
        for i in range(100):
            assert co.tenant_label(f"abuse-{i}") == OVERFLOW_TENANT
        assert len(co._tenant_labels) == 2
        # 0 = unbounded
        co2 = FleetCoalescer(buckets="16x4x8", max_tenant_labels=0)
        for i in range(100):
            assert co2.tenant_label(f"t{i}") == f"t{i}"

    def test_dispatch_span_links_every_cobatched_ticket(self):
        rng = np.random.default_rng(8)
        co = FleetCoalescer(buckets="16x4x8", batch_scenarios=4)
        tracer = trace.Tracer(recorder=trace.FlightRecorder(capacity=2))
        contexts = []
        with tracer.tick("main"):
            for name in ("a", "b"):
                with trace.span("fleetSubmit", tenant=name):
                    tk = co.submit(self._req(rng, tenant=name))
                    contexts.append(tk.trace_context)
            co.flush()
        assert len(set(contexts)) == 2
        dispatch = [
            s for t in tracer.recorder.traces() for s in t.spans
            if s.name == "fleetDispatch" and s.attrs.get("outcome") == "ok"
        ]
        assert dispatch
        assert dispatch[-1].attrs["links"] == ",".join(contexts)


# ------------------------------------------------------- RPC propagation
@pytest.fixture()
def rpc_pair():
    pytest.importorskip("grpc")
    from autoscaler_tpu.rpc.service import TpuSimulationClient, serve

    side_tracer = trace.Tracer(recorder=trace.FlightRecorder(capacity=16))
    co = FleetCoalescer(buckets="16x4x8", window_s=0.002, batch_scenarios=4)
    server, port = serve(fleet=co, tracer=side_tracer)
    client = TpuSimulationClient(f"127.0.0.1:{port}", default_timeout_s=30.0)
    yield client, side_tracer
    client.close()
    server.stop(0)
    co.stop()


def test_rpc_serving_spans_share_client_trace_id(rpc_pair):
    """The cross-process acceptance: client and sidecar spans for the same
    request share ONE trace id, and each serving root names the exact
    rpcCall parent span."""
    client, side_tracer = rpc_pair
    rng = np.random.default_rng(9)
    req = rng.integers(1, 100, (9, 6)).astype(np.float32)
    masks = rng.random((3, 9)) > 0.2
    allocs = rng.integers(100, 500, (3, 6)).astype(np.float32)
    caps = rng.integers(1, 16, 3).astype(np.int32)
    gids = ["g0", "g1", "g2"]
    client_tracer = trace.Tracer(recorder=trace.FlightRecorder(capacity=4))
    with client_tracer.tick("main"):
        client.estimate(req, masks, allocs, gids, caps, max_nodes=16)
        client.batch_estimate(
            req, masks, allocs, gids, caps, max_nodes=16, tenant_id="alpha",
        )
    client_trace = client_tracer.recorder.traces()[-1]
    rpc_span_ids = {
        s.span_id for s in client_trace.spans if s.name == "rpcCall"
    }
    served = side_tracer.recorder.traces()
    assert len(served) == 2
    assert {t.root.attrs["method"] for t in served} == {
        "Estimate", "BatchEstimate",
    }
    for t in served:
        assert t.trace_id == client_trace.trace_id
        assert t.root.attrs["parent_trace_id"] == client_trace.trace_id
        assert t.root.attrs["parent_span_id"] in rpc_span_ids


def test_rpc_without_client_trace_serves_local_trace(rpc_pair):
    client, side_tracer = rpc_pair
    rng = np.random.default_rng(10)
    req = rng.integers(1, 100, (6, 6)).astype(np.float32)
    client.estimate(
        req, rng.random((2, 6)) > 0.2,
        rng.integers(100, 500, (2, 6)).astype(np.float32),
        ["g0", "g1"], rng.integers(1, 16, 2).astype(np.int32), max_nodes=8,
    )
    served = side_tracer.recorder.traces()[-1]
    assert "parent_trace_id" not in served.root.attrs


def test_fleet_proto_carries_trace_context():
    from autoscaler_tpu.rpc import fleet_pb2

    fields = {f.name for f in fleet_pb2.BatchEstimateRequest.DESCRIPTOR.fields}
    assert "trace_context" in fields
    msg = fleet_pb2.BatchEstimateRequest(trace_context="4:2")
    assert fleet_pb2.BatchEstimateRequest.FromString(
        msg.SerializeToString()
    ).trace_context == "4:2"


# ----------------------------------------------------- run_once + /sloz
class TestRunOnceIntegration:
    def test_window_record_per_tick_with_tick_duration_events(self):
        pods = [build_test_pod(f"p{i}", cpu_m=600, mem=GB) for i in range(3)]
        a = make_autoscaler(pods=pods)
        a.run_once(now_ts=0.0)
        a.run_once(now_ts=10.0)
        recs = a.slo.records()
        assert len(recs) == 2
        assert validate_records(recs) == []
        last = recs[-1]
        assert last["slos"][SLI_TICK_DURATION]["events_total"] == 2
        # the window record shares the perf/trace tick id
        assert last["tick"] == a.observatory.last_record()["tick"]

    def test_pending_pods_feed_pending_sli(self):
        # an unschedulable pod (too big for any group) stays pending long
        # enough to overstay the 60s threshold → one bad event
        pods = [build_test_pod("giant", cpu_m=50_000, mem=GB)]
        a = make_autoscaler(pods=pods)
        for i in range(9):
            a.run_once(now_ts=float(i * 10))
        slo = a.slo.records()[-1]["slos"][SLI_PENDING_POD]
        assert slo["events_total"] >= 1
        assert slo["events_bad"] >= 1

    def test_crashed_tick_still_writes_window_record(self, monkeypatch):
        a = make_autoscaler()
        monkeypatch.setattr(
            a, "_run_once_traced",
            lambda *ar, **kw: (_ for _ in ()).throw(RuntimeError("boom")),
        )
        with pytest.raises(RuntimeError):
            a.run_once(now_ts=0.0)
        assert len(a.slo.records()) == 1


class TestSlozEndpoint:
    def _get(self, port, path):
        with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}") as r:
            return r.status, r.read().decode()

    def test_list_and_drilldown(self):
        a = make_autoscaler()
        a.run_once(now_ts=0.0)
        server = ObservabilityServer(a, "127.0.0.1:0")
        port = server.start()
        try:
            code, body = self._get(port, "/sloz")
            assert code == 200
            listing = json.loads(body)
            assert listing["schema"] == SCHEMA
            # the control-loop catalog only — no permanently-silent fleet
            # objective on a process that serves no fleet traffic
            assert set(listing["slos"]) == {
                SLI_TICK_DURATION, SLI_PENDING_POD,
            }
            code, body = self._get(port, f"/sloz?slo={SLI_TICK_DURATION}")
            assert code == 200
            detail = json.loads(body)
            assert detail["slo"] == SLI_TICK_DURATION
            assert len(detail["history"]) == 1
            with pytest.raises(urllib.error.HTTPError) as ei:
                self._get(port, "/sloz?slo=bogus")
            assert ei.value.code == 400
            with pytest.raises(urllib.error.HTTPError) as ei:
                self._get(port, "/sloz/extra")
            assert ei.value.code == 404
        finally:
            server.stop()

    def test_metrics_content_negotiation(self):
        """/metrics serves the classic (exemplar-free) exposition by
        default and the OpenMetrics dialect — exemplars + # EOF — only
        when the scraper's Accept header asks for it."""
        a = make_autoscaler()
        a.run_once(now_ts=0.0)
        # seat an exemplar on a fleet histogram
        a.metrics.fleet_e2e_seconds.observe_with_exemplar(
            0.02, "7", tenant="t", bucket="16x4x8"
        )
        server = ObservabilityServer(a, "127.0.0.1:0")
        port = server.start()
        try:
            code, body = self._get(port, "/metrics")
            assert code == 200
            assert "# {trace_id=" not in body and "# EOF" not in body
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/metrics",
                headers={"Accept": "application/openmetrics-text"},
            )
            with urllib.request.urlopen(req) as r:
                assert "openmetrics-text" in r.headers["Content-Type"]
                om = r.read().decode()
            assert '# {trace_id="7"}' in om
            assert om.endswith("# EOF\n")
        finally:
            server.stop()

    def test_gated_behind_slo_enabled(self):
        a = make_autoscaler(slo_enabled=False)
        a.run_once(now_ts=0.0)
        server = ObservabilityServer(a, "127.0.0.1:0")
        port = server.start()
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                self._get(port, "/sloz")
            assert ei.value.code == 404
        finally:
            server.stop()

    def test_sloz_race_ring_eviction(self):
        """The /tracez+/perfz race-suite shape: /sloz racing a writer that
        churns the window ring — every response well-formed JSON, never a
        torn record."""
        a = make_autoscaler()
        a.run_once(now_ts=0.0)
        stop = threading.Event()
        errors = []

        def writer():
            i = 0
            while not stop.is_set():
                i += 1
                a.slo.observe(SLI_TICK_DURATION, 0.01 * (i % 3), now=float(i))
                a.slo.tick(float(i), i)

        server = ObservabilityServer(a, "127.0.0.1:0")
        port = server.start()
        t = threading.Thread(target=writer, daemon=True)
        t.start()
        try:
            for _ in range(60):
                for path in ("/sloz", f"/sloz?slo={SLI_TICK_DURATION}"):
                    with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}{path}"
                    ) as r:
                        body = r.read().decode()
                    try:
                        json.loads(body)
                    except json.JSONDecodeError as e:  # pragma: no cover
                        errors.append(f"{path}: torn response: {e}")
        finally:
            stop.set()
            t.join(timeout=5)
            server.stop()
        assert not errors


# ------------------------------------------- loadgen byte-determinism
def _fleet_spec_doc():
    return {
        "name": "slo_fleet", "seed": 2, "ticks": 3,
        "fleet": {"tenants": [
            {"name": "a", "pods": 6, "groups": 2, "max_nodes": 8},
            {"name": "b", "pods": 12, "groups": 4, "max_nodes": 8,
             "whatif": True},
        ]},
        "options": {"fleet_shape_buckets": "16x4x8",
                    "fleet_batch_scenarios": 4, "fleet_prewarm": False,
                    "perf_cost_model": False},
    }


def test_fleet_slo_ledger_replays_byte_identically():
    from autoscaler_tpu.loadgen.fleetdrive import run_fleet_scenario
    from autoscaler_tpu.loadgen.spec import ScenarioSpec

    r1 = run_fleet_scenario(ScenarioSpec.from_dict(_fleet_spec_doc()))
    r2 = run_fleet_scenario(ScenarioSpec.from_dict(_fleet_spec_doc()))
    assert r1.all_match()
    lines = r1.slo_ledger_lines()
    assert lines and lines == r2.slo_ledger_lines()
    recs = [json.loads(line) for line in lines.splitlines()]
    assert validate_records(recs) == []
    # every round's answers feed the fleet objective
    assert recs[-1]["slos"][SLI_FLEET_E2E]["events_total"] == 6


def test_fleet_report_gains_split_columns_and_slo_section():
    from autoscaler_tpu.loadgen.fleetdrive import run_fleet_scenario
    from autoscaler_tpu.loadgen.score import build_fleet_report
    from autoscaler_tpu.loadgen.spec import ScenarioSpec

    result = run_fleet_scenario(ScenarioSpec.from_dict(_fleet_spec_doc()))
    report = build_fleet_report(result)
    for tenant, row in report["fleet"]["per_tenant_latency_s"].items():
        assert {
            "queue_wait_p50_s", "queue_wait_p99_s", "service_p50_s",
            "service_p99_s", "p50_s", "p99_s",
        } <= set(row), (tenant, row)
        # the split decomposes the e2e figure
        assert row["queue_wait_p99_s"] <= row["p99_s"]
        assert row["service_p99_s"] <= row["p99_s"]
    assert report["slo"]["slos"][SLI_FLEET_E2E]["events_total"] == 6
    # exemplar trace ids resolve in the run's flight recorder
    expo = result.metrics.registry.expose(openmetrics=True)
    trace_ids = {t.trace_id for t in result.recorder.traces()}
    import re

    ex_ids = {
        int(x) for x in re.findall(r'# \{trace_id="(\d+)"\}', expo)
    }
    assert ex_ids and ex_ids <= trace_ids


def test_tick_driver_writes_slo_ledger(tmp_path):
    """The control-loop scenario path: --slo-ledger on a tiny run writes a
    schema-valid, replay-stable ledger."""
    from autoscaler_tpu.loadgen.driver import run_scenario
    from autoscaler_tpu.loadgen.spec import ScenarioSpec

    doc = {
        "name": "slo_ticks", "seed": 3, "ticks": 4, "tick_interval_s": 10.0,
        "node_groups": [
            {"name": "g", "cpu_m": 4000, "mem_mb": 16384, "max_size": 6,
             "initial_size": 1},
        ],
        "events": [
            {"at_tick": 0, "kind": "pod_burst", "count": 6, "cpu_m": 500,
             "mem_mb": 256},
        ],
    }
    r1 = run_scenario(ScenarioSpec.from_dict(doc))
    r2 = run_scenario(ScenarioSpec.from_dict(doc))
    lines = r1.slo_ledger_lines()
    assert lines == r2.slo_ledger_lines()
    recs = [json.loads(line) for line in lines.splitlines()]
    assert len(recs) == 4
    assert validate_records(recs) == []
    assert recs[-1]["slos"][SLI_TICK_DURATION]["events_total"] == 4
