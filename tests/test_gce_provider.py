"""GCE/TPU provider tests: MIG url parsing, template→node construction (TPU
labels/taint/allocatable), size mutations with min/max guards, cache
invalidation, price model, stockout error surfacing, and a control-loop
integration scaling a TPU node pool (modeled on the reference's
gce_cloud_provider_test.go + templates_test.go)."""
import pytest

from autoscaler_tpu.cloudprovider.gce import (
    GceMig,
    GcePriceModel,
    InMemoryGceApi,
    MigTemplate,
    TPU_RESOURCE_LABEL,
    TPU_TAINT_KEY,
    TPU_TOPOLOGY_LABEL,
    build_gce_provider,
    build_node_from_template,
    parse_mig_url,
)
from autoscaler_tpu.cloudprovider.interface import (
    InstanceErrorClass,
    InstanceState,
    NodeGroupError,
)
from autoscaler_tpu.config.options import AutoscalingOptions
from autoscaler_tpu.core.static_autoscaler import StaticAutoscaler
from autoscaler_tpu.kube.api import FakeClusterAPI
from autoscaler_tpu.kube.objects import Node, Resources, Toleration
from autoscaler_tpu.utils.test_utils import GB, build_test_pod

MIG_URL = "projects/proj/zones/us-central2-b/instanceGroups/tpu-pool"


def make_provider(quota=None, machine_type="ct5lp-hightpu-4t", target=1):
    api = InMemoryGceApi()
    api.add_mig(
        "proj",
        "us-central2-b",
        "tpu-pool",
        MigTemplate(machine_type=machine_type, tpu_topology="2x2"),
        target_size=target,
        quota=quota,
    )
    provider = build_gce_provider([f"0:10:{MIG_URL}"], api)
    return api, provider


class TestUrlAndTemplates:
    def test_parse_mig_url(self):
        assert parse_mig_url(MIG_URL) == ("proj", "us-central2-b", "tpu-pool")
        assert parse_mig_url(
            "https://www.googleapis.com/compute/v1/" + MIG_URL
        ) == ("proj", "us-central2-b", "tpu-pool")
        with pytest.raises(ValueError):
            parse_mig_url("projects/p/instanceGroups/x")

    def test_tpu_template_node(self):
        tmpl = MigTemplate(machine_type="ct5lp-hightpu-4t", tpu_topology="2x2")
        node = build_node_from_template("n", "us-central2-b", tmpl)
        assert node.allocatable.tpu == 4
        assert node.labels[TPU_RESOURCE_LABEL] == "tpu-v5-lite-podslice"
        assert node.labels[TPU_TOPOLOGY_LABEL] == "2x2"
        assert any(t.key == TPU_TAINT_KEY for t in node.taints)
        assert node.labels["topology.kubernetes.io/zone"] == "us-central2-b"

    def test_plain_template_node_has_no_tpu_artifacts(self):
        node = build_node_from_template(
            "n", "z", MigTemplate(machine_type="e2-standard-4")
        )
        assert node.allocatable.tpu == 0
        assert TPU_RESOURCE_LABEL not in node.labels
        assert not node.taints

    def test_unknown_machine_type_raises(self):
        with pytest.raises(NodeGroupError):
            build_node_from_template("n", "z", MigTemplate(machine_type="zz-99"))


class TestMigOperations:
    def test_increase_and_max_guard(self):
        api, provider = make_provider()
        (mig,) = provider.node_groups()
        mig.increase_size(2)
        assert mig.target_size() == 3
        assert ("resize", "tpu-pool", 3) in api.calls
        with pytest.raises(NodeGroupError):
            mig.increase_size(100)

    def test_delete_nodes_ownership_and_min(self):
        api, provider = make_provider(target=2)
        (mig,) = provider.node_groups()
        stranger = Node(name="other-node")
        with pytest.raises(NodeGroupError):
            mig.delete_nodes([stranger])
        mine = Node(name="tpu-pool-0")
        mig.delete_nodes([mine])
        assert mig.target_size() == 1
        assert ("delete", "tpu-pool", ("tpu-pool-0",)) in api.calls

    def test_decrease_target_size_never_deletes_running(self):
        api, provider = make_provider(target=2)
        (mig,) = provider.node_groups()
        with pytest.raises(NodeGroupError):
            mig.decrease_target_size(1)  # both instances are RUNNING
        mig.increase_size(1)  # adds a CREATING instance
        mig.decrease_target_size(1)
        assert mig.target_size() == 2

    def test_cache_invalidation_on_mutation(self):
        api, provider = make_provider()
        (mig,) = provider.node_groups()
        assert mig.target_size() == 1
        # direct API change is hidden by the cache...
        api.resize("proj", "us-central2-b", "tpu-pool", 5)
        assert mig.target_size() == 1
        # ...but our own mutation invalidates, so the next read is fresh
        # (increase computes from the cached value, like the reference)
        mig.increase_size(1)
        assert mig.target_size() == 2

    def test_resize_down_cancels_creating_instances(self):
        api, provider = make_provider(target=2)
        (mig,) = provider.node_groups()
        mig.increase_size(1)  # CREATING tpu-pool-2
        mig.decrease_target_size(1)
        instances = api.list_instances("proj", "us-central2-b", "tpu-pool")
        assert len(instances) == 2
        assert all(i.state == InstanceState.RUNNING for i in instances)
        api.settle()  # must not resurrect the canceled instance
        assert len(api.list_instances("proj", "us-central2-b", "tpu-pool")) == 2

    def test_delete_unknown_name_does_not_shrink_target(self):
        api, provider = make_provider(target=2)
        api.delete_instances("proj", "us-central2-b", "tpu-pool", ["ghost"])
        assert api.get_target_size("proj", "us-central2-b", "tpu-pool") == 2
        assert len(api.list_instances("proj", "us-central2-b", "tpu-pool")) == 2

    def test_stockout_surfaces_error_instances(self):
        api, provider = make_provider(quota=1)
        (mig,) = provider.node_groups()
        mig.increase_size(2)
        instances = mig.nodes()
        errored = [i for i in instances if i.error_info is not None]
        assert errored
        assert (
            errored[0].error_info.error_class
            == InstanceErrorClass.OUT_OF_RESOURCES
        )

    def test_node_group_for_node_via_provider_id(self):
        api, provider = make_provider()
        node = Node(
            name="tpu-pool-0",
            provider_id="gce://proj/us-central2-b/tpu-pool-0",
        )
        group = provider.node_group_for_node(node)
        assert group is not None and group.id().endswith("tpu-pool")


class TestPricing:
    def test_tpu_and_spot_prices(self):
        model = GcePriceModel()
        tmpl = MigTemplate(machine_type="ct5lp-hightpu-4t")
        node = build_node_from_template("n", "z", tmpl)
        hour = model.node_price(node, 0, 3600)
        assert hour == pytest.approx(4.80)
        spot_node = build_node_from_template(
            "n", "z", MigTemplate(machine_type="ct5lp-hightpu-4t", spot=True)
        )
        assert model.node_price(spot_node, 0, 3600) < hour

    def test_unknown_type_estimates_from_resources(self):
        model = GcePriceModel()
        node = Node(
            name="n",
            allocatable=Resources(cpu_m=4000, memory=16 * GB),
            labels={"node.kubernetes.io/instance-type": "custom-4-16384"},
        )
        assert model.node_price(node, 0, 3600) > 0

    def test_pod_price(self):
        model = GcePriceModel()
        pod = build_test_pod("p", cpu_m=1000, mem=1 * GB)
        assert model.pod_price(pod, 0, 3600) == pytest.approx(0.033 + 0.0044, rel=1e-3)


class TestControlLoopIntegration:
    def test_tpu_pod_scales_up_tpu_pool(self):
        api, provider = make_provider(target=0)
        k8s_api = FakeClusterAPI()
        pod = build_test_pod("trainer", cpu_m=1000, mem=1 * GB)
        pod.requests = Resources(cpu_m=1000, memory=1 * GB, tpu=4, pods=1)
        pod.tolerations = [Toleration(key=TPU_TAINT_KEY, operator="Exists")]
        k8s_api.add_pod(pod)
        autoscaler = StaticAutoscaler(provider, k8s_api, AutoscalingOptions())
        result = autoscaler.run_once(now_ts=0.0)
        assert result.scale_up is not None and result.scale_up.scaled_up
        (mig,) = provider.node_groups()
        assert mig.target_size() >= 1
        assert any(c[0] == "resize" for c in api.calls)

    def test_non_tolerating_pod_does_not_scale_tpu_pool(self):
        api, provider = make_provider(target=0)
        k8s_api = FakeClusterAPI()
        k8s_api.add_pod(build_test_pod("web", cpu_m=100))
        autoscaler = StaticAutoscaler(provider, k8s_api, AutoscalingOptions())
        result = autoscaler.run_once(now_ts=0.0)
        assert result.scale_up is None or not result.scale_up.scaled_up
        (mig,) = provider.node_groups()
        assert mig.target_size() == 0


class TestAutoDiscovery:
    """--node-group-auto-discovery (reference GCE MIG auto-discovery by
    name prefix): MIGs matching a prefix join the provider with the spec's
    bounds; explicit specs win on overlap."""

    def test_prefix_discovery(self):
        from autoscaler_tpu.cloudprovider.gce import (
            MigTemplate,
            build_gce_provider,
            parse_auto_discovery_spec,
        )

        spec = parse_auto_discovery_spec("mig:namePrefix=tpu-,min=1,max=7")
        assert spec == {"prefix": "tpu-", "min": 1, "max": 7}

        api = InMemoryGceApi()
        tmpl = MigTemplate(machine_type="ct5lp-hightpu-4t", tpu_topology="2x2")
        api.add_mig("proj", "z", "tpu-a", tmpl, target_size=1)
        api.add_mig("proj", "z", "tpu-b", tmpl, target_size=2)
        api.add_mig("proj", "z", "cpu-pool", tmpl, target_size=1)
        provider = build_gce_provider(
            ["0:10:projects/proj/zones/z/instanceGroups/tpu-a"],
            api,
            auto_discovery=["mig:namePrefix=tpu-,min=1,max=7"],
        )
        by_name = {g.name: g for g in provider.node_groups()}
        assert set(by_name) == {"tpu-a", "tpu-b"}     # cpu-pool not matched
        assert by_name["tpu-a"].min_size() == 0        # explicit spec wins
        assert by_name["tpu-a"].max_size() == 10
        assert by_name["tpu-b"].min_size() == 1        # discovered bounds
        assert by_name["tpu-b"].max_size() == 7

    def test_bad_specs_rejected(self):
        from autoscaler_tpu.cloudprovider.gce import parse_auto_discovery_spec

        with pytest.raises(ValueError):
            parse_auto_discovery_spec("asg:namePrefix=x")
        with pytest.raises(ValueError):
            parse_auto_discovery_spec("mig:min=1")
        with pytest.raises(ValueError):
            parse_auto_discovery_spec("mig:namePrefix=x,bogus=1")


class TestConcurrentRefresh:
    def test_parallel_refresh_maps_all_migs(self):
        """--gce-concurrent-refreshes analog: MIG listings fetch on a worker
        pool; the node→MIG map must be complete and the pool actually used."""
        import threading
        import time

        api = InMemoryGceApi()
        urls = []
        for i in range(6):
            api.add_mig(
                "proj", "us-central2-b", f"pool-{i}",
                MigTemplate(machine_type="ct5lp-hightpu-4t", tpu_topology="2x2"),
                target_size=2,
            )
            urls.append(
                f"0:10:projects/proj/zones/us-central2-b/instanceGroups/pool-{i}"
            )
        provider = build_gce_provider(urls, api, concurrent_refreshes=4)
        threads = set()
        orig = provider._manager.instances

        def slow_listing(mig):
            threads.add(threading.get_ident())
            time.sleep(0.1)  # a realistic HTTP round-trip
            return orig(mig)

        provider._manager.instances = slow_listing
        provider.refresh()
        # concurrency proven by thread identity, not wall clock (which
        # flakes on loaded workers): slow listings spread across workers
        assert len(threads) > 1
        # every MIG's instances resolve (providerID form, reference
        # gce_cloud_provider.go NodeGroupForNode)
        from autoscaler_tpu.kube.objects import Node

        for i in range(6):
            for j in range(2):
                node = Node(
                    name=f"pool-{i}-{j}",
                    provider_id=f"gce://proj/us-central2-b/pool-{i}-{j}",
                )
                g = provider.node_group_for_node(node)
                assert g is not None and f"pool-{i}" in g.id()
