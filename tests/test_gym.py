"""Policy gym (autoscaler_tpu/gym): env determinism + decision parity,
PolicySpec bounds, tuner byte-identity and the improvement invariant,
ledger validation exit codes, fleet-coalesced score parity, CLI e2e."""
import json
import time

import pytest

from autoscaler_tpu.gym import (
    BASELINE_ID,
    DEFAULT_POLICY,
    KNOB_SPACE,
    GymError,
    PolicyError,
    PolicyGymEnv,
    PolicySpec,
    SuiteSpec,
    is_suite_doc,
    load_jsonl,
    record_line,
    summarize,
    validate_records,
)
from autoscaler_tpu.gym.tune import (
    PolicyRng,
    TuneConfig,
    _window_sleep,
    tune_suite,
)
from autoscaler_tpu.loadgen.spec import (
    Event,
    NodeGroupSpec,
    ScenarioSpec,
    SpecError,
    WorkloadSpec,
)


def tiny_spec(name="gymtest", seed=5, **kw):
    base = dict(
        name=name,
        seed=seed,
        ticks=8,
        tick_interval_s=10.0,
        node_groups=[
            NodeGroupSpec(name="g", min_size=0, max_size=10, initial_size=2),
        ],
        events=[
            Event(at_tick=1, kind="pod_burst", count=6, cpu_m=1500.0,
                  mem_mb=1024.0, prefix="burst"),
            Event(at_tick=4, kind="pod_complete", count=4, prefix="burst"),
        ],
    )
    base.update(kw)
    return ScenarioSpec(**base)


def tiny_suite(**kw):
    return SuiteSpec(name="tiny", scenarios=[
        tiny_spec(),
        tiny_spec(name="gymtest2", seed=6, workloads=[
            WorkloadSpec(kind="spike", rate=5.0, period_ticks=4,
                         completion_rate=0.5),
        ], events=[]),
    ], **kw)


class TestPolicySpec:
    def test_bounds_rejected_loudly(self):
        with pytest.raises(PolicyError, match="scale_down_utilization_threshold"):
            PolicySpec(scale_down_utilization_threshold=2.0)
        with pytest.raises(PolicyError, match="never clamp"):
            PolicySpec(kernel_breaker_cooldown_s=-5.0)
        with pytest.raises(PolicyError, match="expander"):
            PolicySpec(expander="cheapest")
        with pytest.raises(PolicyError, match="integer"):
            PolicySpec(kernel_breaker_failure_threshold=2.5)

    def test_unknown_knob_rejected(self):
        with pytest.raises(PolicyError, match="no_such"):
            PolicySpec.from_dict({"no_such": 1})

    def test_round_trip_and_overrides(self):
        pol = PolicySpec(expander="most-pods", scale_down_unneeded_time_s=30.0,
                         kernel_breaker_failure_threshold=2)
        assert PolicySpec.from_dict(pol.to_dict()) == pol
        ov = pol.to_overrides()
        assert ov["expander"] == "most-pods"
        assert isinstance(ov["kernel_breaker_failure_threshold"], int)
        # the override dict passes the AutoscalingOptions schema gate
        from autoscaler_tpu.config.options import validate_overrides

        validate_overrides(ov)

    def test_every_knob_matches_an_options_field(self):
        from autoscaler_tpu.config.options import validate_overrides

        full = PolicySpec(**{
            k.name: (k.choices[0] if k.kind == "choice"
                     else (int(k.lo) if k.kind == "int" else float(k.lo)))
            for k in KNOB_SPACE
        })
        validate_overrides(full.to_overrides())

    def test_renderers(self):
        pol = PolicySpec(expander="price", scale_down_unneeded_time_s=117.6293)
        assert "--expander=price" in pol.render_flags()
        # full precision survives rendering (a rounded flag would name a
        # policy nobody evaluated)
        assert "117.6293" in pol.render_flags()
        assert "--set expander=price" in pol.render_set_args()
        yaml = pol.render_values_yaml()
        assert yaml.startswith("autoscaling:")
        assert "scaleDownUnneededTime: 117.6293" in yaml
        assert DEFAULT_POLICY.render_flags() == ""


class TestEnv:
    def test_step_before_reset_raises(self):
        with pytest.raises(GymError, match="reset"):
            PolicyGymEnv(tiny_spec()).step()

    def test_reset_step_deterministic(self):
        def episode():
            env = PolicyGymEnv(tiny_spec())
            obs = [env.reset(seed=5)]
            rewards = []
            done = False
            while not done:
                o, r, done, _ = env.step()
                obs.append(o)
                rewards.append(r)
            return obs, rewards

        a_obs, a_rewards = episode()
        b_obs, b_rewards = episode()
        assert a_obs == b_obs
        assert a_rewards == b_rewards
        assert len(a_rewards) == tiny_spec().ticks

    def test_rollout_matches_direct_driver_for_identity_policy(self):
        from autoscaler_tpu.loadgen.driver import run_scenario

        rollout = PolicyGymEnv(tiny_spec()).rollout()
        direct = run_scenario(tiny_spec())
        assert rollout.decision_log == direct.decision_log()

    def test_step_rewards_sum_to_objective(self):
        rollout = PolicyGymEnv(tiny_spec()).rollout()
        assert sum(rollout.step_rewards) == pytest.approx(
            -rollout.objective, abs=1e-5
        )
        assert rollout.score == pytest.approx(-rollout.objective, abs=1e-5)

    def test_policy_changes_decisions(self):
        # a policy that forbids scale-down entirely must change the log
        lazy = PolicySpec(scale_down_unneeded_time_s=3600.0,
                          scale_down_delay_after_add_s=3600.0)
        a = PolicyGymEnv(tiny_spec()).rollout()
        b = PolicyGymEnv(tiny_spec()).rollout(policy=lazy)
        assert a.decision_log != b.decision_log

    def test_step_past_done_raises(self):
        env = PolicyGymEnv(tiny_spec())
        env.reset()
        done = False
        while not done:
            _, _, done, _ = env.step()
        with pytest.raises(GymError, match="done"):
            env.step()
        # the episode stayed exactly spec.ticks long
        assert len(env._driver.finish().records) == tiny_spec().ticks

    def test_mid_episode_policy_change_rejected(self):
        env = PolicyGymEnv(tiny_spec())
        env.reset()
        env.step()
        with pytest.raises(PolicyError, match="mid-episode"):
            env.step(PolicySpec(expander="most-pods"))

    def test_first_step_action_rebinds(self):
        pol = PolicySpec(scale_down_unneeded_time_s=3600.0,
                         scale_down_delay_after_add_s=3600.0)
        env = PolicyGymEnv(tiny_spec())
        env.reset()
        env.step(pol)            # tick 0: rebind through the options seam
        done = False
        while not done:
            _, _, done, _ = env.step()
        direct = PolicyGymEnv(tiny_spec()).rollout(policy=pol)
        assert env._driver.finish().decision_log() == direct.decision_log

    def test_fleet_scenario_rejected(self):
        doc = tiny_spec().to_dict()
        doc.pop("node_groups")
        doc["fleet"] = {"tenants": [{"name": "t0"}]}
        with pytest.raises(GymError, match="fleet"):
            PolicyGymEnv(ScenarioSpec.from_dict(doc))


class TestFleetCoalescedRollouts:
    def test_fleet_vs_solo_score_parity(self):
        from autoscaler_tpu.fleet.coalescer import FleetCoalescer

        spec = tiny_spec()
        solo = PolicyGymEnv(spec).rollout()
        co = FleetCoalescer(window_s=0.002, clock=time.perf_counter,
                            sleep=_window_sleep)
        co.start()
        try:
            fleet = PolicyGymEnv(spec, coalescer=co).rollout()
        finally:
            co.stop()
        assert fleet.objective == solo.objective
        assert fleet.score == solo.score
        # no dynamic affinity in this world: decisions match byte-for-byte
        assert fleet.decision_log == solo.decision_log

    def test_stopped_coalescer_falls_back_to_solo(self):
        from autoscaler_tpu.fleet.coalescer import FleetCoalescer

        spec = tiny_spec()
        co = FleetCoalescer(window_s=0.002, clock=time.perf_counter,
                            sleep=_window_sleep)
        # never started: tickets would hang, so give the env a tiny
        # timeout — every dispatch must degrade to the solo ladder and the
        # rollout still matches the solo answer
        env = PolicyGymEnv(spec, coalescer=co, rollout_timeout_s=0.05)
        fleet = env.rollout()
        solo = PolicyGymEnv(spec).rollout()
        assert fleet.objective == solo.objective


class TestTuner:
    def test_double_tune_byte_identical(self):
        suite = tiny_suite()
        cfg = TuneConfig(generations=2, population=3, seed=3, workers=3)
        a = tune_suite(suite, cfg)
        b = tune_suite(suite, cfg)
        assert a.ledger_lines() == b.ledger_lines()
        assert validate_records(a.records) == []

    def test_solo_and_worker_count_invariance(self):
        suite = tiny_suite()
        base = tune_suite(
            suite, TuneConfig(generations=1, population=3, seed=3, workers=3)
        )
        solo = tune_suite(
            suite, TuneConfig(generations=1, population=3, seed=3, workers=1,
                              fleet_coalesce=False)
        )
        # candidate scores are identical; only the recorded lane flag and
        # per-run wall time may differ
        strip = lambda recs: [
            {k: v for k, v in r.items() if k != "fleet_coalesced"}
            for r in recs
        ]
        assert strip(base.records) == strip(solo.records)

    def test_baseline_present_and_invariant(self):
        result = tune_suite(
            tiny_suite(),
            TuneConfig(generations=2, population=3, seed=3, workers=2),
        )
        gen0 = result.records[0]
        ids = [c["id"] for c in gen0["candidates"]]
        assert BASELINE_ID in ids
        bests = [r["best_so_far"]["total"] for r in result.records]
        assert bests == sorted(bests)
        assert result.best_total >= result.baseline_total

    def test_policy_rng_deterministic(self):
        a, b = PolicyRng(7), PolicyRng(7)
        seq_a = [a.uniform(0, 1), a.gauss(0, 1), a.choice(("x", "y", "z")),
                 a.coin(0.5)]
        seq_b = [b.uniform(0, 1), b.gauss(0, 1), b.choice(("x", "y", "z")),
                 b.coin(0.5)]
        assert seq_a == seq_b
        assert PolicyRng(8).uniform(0, 1) != a.uniform(0, 1)


class TestLedger:
    def _tune(self):
        return tune_suite(
            tiny_suite(),
            TuneConfig(generations=2, population=3, seed=3, workers=3),
        )

    def test_validate_clean_and_summarize(self, tmp_path):
        result = self._tune()
        path = tmp_path / "tune.jsonl"
        path.write_text(result.ledger_lines())
        records = load_jsonl(str(path))
        assert validate_records(records) == []
        agg = summarize(records)
        assert agg["generations"] == 2
        assert agg["baseline_total"] == result.baseline_total
        assert agg["winner"]["total"] == result.best_total
        assert "beats_baseline" in agg

    def test_validation_catches_corruption(self):
        result = self._tune()
        records = [json.loads(record_line(r)) for r in result.records]
        # decreasing best_so_far = improvement invariant violation
        records[-1]["best_so_far"]["total"] = records[0]["best_so_far"]["total"] - 99
        assert any("improvement invariant" in e
                   for e in validate_records(records))
        # missing baseline
        records2 = [json.loads(record_line(r)) for r in result.records]
        records2[0]["candidates"] = [
            c for c in records2[0]["candidates"] if c["id"] != BASELINE_ID
        ]
        assert any(BASELINE_ID in e for e in validate_records(records2))
        # a truncated ledger (records < declared generations) is invalid:
        # its mid-tune best would masquerade as the winner
        truncated = [json.loads(record_line(result.records[0]))]
        assert any("truncated" in e for e in validate_records(truncated))
        # wrong schema
        records3 = [json.loads(record_line(r)) for r in result.records]
        records3[0]["schema"] = "nope/9"
        assert any("schema" in e for e in validate_records(records3))
        # out-of-space policy
        records4 = [json.loads(record_line(r)) for r in result.records]
        records4[0]["candidates"][0]["policy"] = {"surprise_knob": 1}
        assert any("knob" in e for e in validate_records(records4))

    def test_validation_covers_every_declared_field(self):
        """Regression (graftlint GL017): suite, fleet_coalesced and
        pruned are declared in SCHEMA_FIELDS but the validator never read
        them — drift on any of them passed validation silently."""
        result = self._tune()

        def fresh():
            return [json.loads(record_line(r)) for r in result.records]

        records = fresh()
        records[0]["suite"] = ""
        assert any("suite" in e for e in validate_records(records))
        records = fresh()
        records[0]["fleet_coalesced"] = "yes"
        assert any(
            "fleet_coalesced" in e for e in validate_records(records)
        )
        records = fresh()
        records[0]["pruned"] = -1
        assert any("pruned" in e for e in validate_records(records))
        # pruned must AGREE with the eliminated_after annotations, not
        # merely be a well-typed int
        records = fresh()
        records[-1]["pruned"] = records[-1]["pruned"] + 1
        assert any("disagrees" in e for e in validate_records(records))

    def test_bench_exit_codes(self, tmp_path, capsys):
        import bench

        result = self._tune()
        good = tmp_path / "good.jsonl"
        good.write_text(result.ledger_lines())
        assert bench._gym_ledger_main(str(good)) == 0
        report = json.loads(capsys.readouterr().out)
        assert report["valid"] and report["metric"] == "gym_ledger"

        bad = tmp_path / "bad.jsonl"
        records = [json.loads(record_line(r)) for r in result.records]
        records[0]["generation"] = 7
        bad.write_text("".join(record_line(r) for r in records))
        assert bench._gym_ledger_main(str(bad)) == 1
        capsys.readouterr()

        assert bench._gym_ledger_main(str(tmp_path / "missing.jsonl")) == 2
        capsys.readouterr()


class TestSuiteSpec:
    def test_round_trip_and_validation(self):
        suite = tiny_suite()
        again = SuiteSpec.from_dict(suite.to_dict())
        assert again.to_dict() == suite.to_dict()
        with pytest.raises(SpecError, match="at least one"):
            SuiteSpec(name="empty", scenarios=[])
        with pytest.raises(SpecError, match="duplicate"):
            SuiteSpec(name="dup", scenarios=[tiny_spec(), tiny_spec()])

    def test_is_suite_doc(self):
        assert is_suite_doc(tiny_suite().to_dict())
        assert not is_suite_doc(tiny_spec().to_dict())

    def test_canned_suite_parses(self):
        suite = SuiteSpec.load("benchmarks/scenarios/gym_suite.json")
        names = suite.scenario_names()
        assert len(names) == 5
        # coverage: diurnal + spike + drain-heavy + kernel-fault + a
        # preemption storm (priority-carrying bursts under churn tuning)
        kinds = {w.kind for s in suite.scenarios for w in s.workloads}
        assert {"diurnal", "spike", "drain_heavy", "steady"} <= kinds
        assert any(
            e.priority > 0
            for s in suite.scenarios for e in s.events
        )
        assert any(
            e.fault is not None and e.fault.kind == "kernel_fault"
            for s in suite.scenarios for e in s.events
        )

    def test_loadgen_validate_accepts_suite(self, capsys):
        from autoscaler_tpu.loadgen.cli import main as loadgen_main

        rc = loadgen_main(["validate", "benchmarks/scenarios/gym_suite.json"])
        assert rc == 0
        assert "suite gym_suite" in capsys.readouterr().out


@pytest.mark.slow
class TestCliEndToEnd:
    def test_tune_validate_apply_cycle(self, tmp_path, capsys):
        from autoscaler_tpu.gym.cli import main as gym_main

        suite_path = tmp_path / "suite.json"
        tiny_suite().save(str(suite_path))
        ledger = tmp_path / "tune.jsonl"
        rc = gym_main([
            "tune", str(suite_path), "--generations", "2", "--population",
            "3", "--seed", "3", "--ledger", str(ledger),
        ])
        assert rc == 0
        report = json.loads(capsys.readouterr().out)
        assert report["metric"] == "gym_tune_tiny"
        assert report["winner"]["total"] >= report["baseline_total"]

        assert gym_main(["validate", str(ledger)]) == 0
        capsys.readouterr()

        assert gym_main(["apply", str(ledger)]) == 0
        out = capsys.readouterr().out
        assert "values.yaml fragment" in out

        # replay must reproduce the ledger byte-for-byte
        assert gym_main(["replay", str(suite_path), "--ledger",
                         str(ledger)]) == 0
        assert "byte-identical" in capsys.readouterr().out

        # a mismatched suite is a usage error (exit 2 BEFORE burning a
        # tune), never a false determinism violation
        other = tmp_path / "other.json"
        renamed = tiny_suite()
        renamed.name = "other"
        renamed.save(str(other))
        assert gym_main(["replay", str(other), "--ledger", str(ledger)]) == 2
        assert "does not match" in capsys.readouterr().err

    def test_replay_preserves_high_precision_weights(self, tmp_path, capsys):
        # the recorded weights must reach the re-tune VERBATIM: a %g-style
        # string round-trip would replay a tune nobody ran and report a
        # false divergence
        from autoscaler_tpu.gym.cli import main as gym_main

        suite_path = tmp_path / "suite.json"
        tiny_suite().save(str(suite_path))
        ledger = tmp_path / "tune.jsonl"
        assert gym_main([
            "tune", str(suite_path), "--generations", "1", "--population",
            "2", "--seed", "4", "--weights", "cost=0.0123456789",
            "--ledger", str(ledger),
        ]) == 0
        capsys.readouterr()
        assert gym_main(["replay", str(suite_path), "--ledger",
                         str(ledger)]) == 0
        assert "byte-identical" in capsys.readouterr().out

    def test_missing_suite_exits_2(self, capsys):
        from autoscaler_tpu.gym.cli import main as gym_main

        assert gym_main(["tune", "/nonexistent/suite.json"]) == 2
        assert "error:" in capsys.readouterr().err
