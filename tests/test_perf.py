"""Perf-observatory tests (autoscaler_tpu/perf): compile telemetry, the
XLA cost ledger, residency accounting, the per-tick ledger schema +
regression gate, /perfz, and the loadgen byte-determinism acceptance."""
import json
import subprocess
import sys
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from autoscaler_tpu import trace
from autoscaler_tpu.cloudprovider.test_provider import TestCloudProvider
from autoscaler_tpu.config.options import AutoscalingOptions
from autoscaler_tpu.core.static_autoscaler import StaticAutoscaler
from autoscaler_tpu.kube.api import FakeClusterAPI
from autoscaler_tpu.main import ObservabilityServer
from autoscaler_tpu.metrics.metrics import (
    DURATION_BUCKETS,
    AutoscalerMetrics,
    MetricsRegistry,
    PERF_RECORD,
)
from autoscaler_tpu.perf import (
    POOL_KERNEL_OPERANDS,
    POOL_SNAPSHOT,
    PerfObservatory,
    ResidencyLedger,
    SCHEMA,
    analyze_cost,
    array_bytes,
    default_peak_flops,
    operand_bytes,
    record_line,
    shape_signature,
    summarize,
    validate_records,
)
from autoscaler_tpu.utils.test_utils import GB, build_test_node, build_test_pod


# ---------------------------------------------------------------- helpers
class _FakeSpan:
    def __init__(self):
        self.attrs = {}

    def set_attrs(self, **kw):
        self.attrs.update(kw)


def _dispatch_once(obs, fn, args, route="xla_scan", wall=0.01, span=None):
    obs.clear_pending()
    obs.note_kernel(fn, args, {})
    obs.on_dispatch(route, wall, span=span)


def make_autoscaler(pods=(), **opt_kw):
    provider = TestCloudProvider()
    api = FakeClusterAPI()
    provider.add_node_group(
        "g", 0, 10, 1, build_test_node("t", cpu_m=1000, mem=2 * GB)
    )
    node = build_test_node("g-0", cpu_m=1000, mem=2 * GB)
    provider.add_node("g", node)
    api.add_node(node)
    for p in pods:
        api.add_pod(p)
    return StaticAutoscaler(provider, api, AutoscalingOptions(**opt_kw))


@pytest.fixture(scope="module")
def ladder_replays():
    """The acceptance workload: the canned kernel-fault scenario run twice."""
    from autoscaler_tpu.loadgen.driver import run_scenario
    from autoscaler_tpu.loadgen.spec import ScenarioSpec

    path = "benchmarks/scenarios/kernel_fault_ladder.json"
    r1 = run_scenario(ScenarioSpec.load(path))
    r2 = run_scenario(ScenarioSpec.load(path))
    return r1, r2


# ------------------------------------------------- duration bucket ladder
class TestDurationBuckets:
    def test_bucket_boundaries_pinned(self):
        """The ladder is dashboard history: a silent change corrupts every
        recorded series. Extends DOWN to 1e-4 s so sub-millisecond device
        dispatches resolve instead of piling into the bottom bucket."""
        assert DURATION_BUCKETS == (
            1e-4, 2.5e-4, 5e-4,
            1e-3, 2.5e-3, 5e-3,
            1e-2, 2.5e-2, 5e-2,
            0.1, 0.25, 0.5,
            1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0,
        )
        assert DURATION_BUCKETS[0] == 1e-4

    def test_sub_ms_dispatches_resolve(self):
        r = MetricsRegistry()
        h = r.histogram("d", "")
        h.observe(2e-4, function="deviceDispatch")
        h.observe(3e-3, function="deviceDispatch")
        counts = h.bucket_counts(function="deviceDispatch")
        # 2e-4 lands at le=2.5e-4 (index 1), NOT the bottom bucket
        assert counts[0] == 0 and counts[1] == 1
        # cumulative le-semantics: both observations admitted at le=5e-3
        assert counts[DURATION_BUCKETS.index(5e-3)] == 2

    def test_histogram_exposition_and_quantile_api(self):
        r = MetricsRegistry()
        h = r.histogram("cluster_autoscaler_function_duration_seconds", "x")
        for v in (5e-5, 2e-4, 0.02, 4.0):
            h.observe(v, function="estimate")
        text = r.expose()
        assert (
            'function_duration_seconds_bucket{function="estimate",le="0.0001"} 1'
            in text
        )
        assert (
            'function_duration_seconds_bucket{function="estimate",le="+Inf"} 4'
            in text
        )
        assert "# TYPE cluster_autoscaler_function_duration_seconds histogram" in text
        assert 'function_duration_seconds_count{function="estimate"} 4' in text
        # the Summary quantile surface (scorer p50/p99 columns) still works
        assert h.quantile(0.5, function="estimate") == 0.02
        assert h.count(function="estimate") == 4

    def test_autoscaler_function_duration_is_histogram(self):
        m = AutoscalerMetrics()
        m.observe_duration_value("estimate", 3e-4)
        assert m.function_duration.bucket_counts(function="estimate")[2] == 1
        assert m.function_duration.kind == "histogram"


# --------------------------------------------------------------- costmodel
class TestCostModel:
    def test_shape_signature_deterministic_and_kwargs_sorted(self):
        a = np.zeros((8, 6), np.float32)
        b = np.zeros((1, 8), bool)
        s1 = shape_signature((a, b), {"max_nodes": 16, "caps": a})
        s2 = shape_signature((a, b), {"caps": a, "max_nodes": 16})
        assert s1 == s2
        assert "8x6:float32" in s1 and "max_nodes=16" in s1

    def test_signature_distinguishes_shapes_and_statics(self):
        a = np.zeros((8, 6), np.float32)
        base = shape_signature((a,), {"max_nodes": 16})
        assert base != shape_signature((a,), {"max_nodes": 32})
        assert base != shape_signature(
            (np.zeros((16, 6), np.float32),), {"max_nodes": 16}
        )

    def test_operand_bytes_counts_nested_leaves(self):
        a = np.zeros((4, 4), np.float32)   # 64 B
        b = np.zeros((8,), np.int32)       # 32 B
        assert operand_bytes((a, (b, b)), {"k": a, "s": 3}) == 64 + 32 + 32 + 64

    def test_analyze_cost_answers_on_cpu(self):
        @jax.jit
        def mm(x, y):
            return x @ y

        x = jnp.ones((32, 32), jnp.float32)
        cost = analyze_cost(mm, (x, x), {})
        assert cost is not None
        assert cost.get("flops", 0) > 0
        assert cost.get("peak_bytes", 0) > 0

    def test_analyze_cost_caches_failures(self):
        calls = []

        class NoLower:
            __name__ = "no_lower_kernel"

        assert analyze_cost(NoLower(), (), {}, sig="s") is None

        class Raises:
            __name__ = "raising_kernel_perc"

            def lower(self, *a, **k):
                calls.append(1)
                raise RuntimeError("backend cannot answer")

        r = Raises()
        assert analyze_cost(r, (), {}, sig="t") is None
        assert analyze_cost(r, (), {}, sig="t") is None
        assert len(calls) == 1  # the failure is cached — asked exactly once

    def test_default_peak_flops_positive(self):
        assert default_peak_flops() > 0


# --------------------------------------------------------------- residency
class TestResidency:
    def test_set_drop_and_pool_sums(self):
        led = ResidencyLedger()
        led.set("snapshot", "packer", 720)
        led.set("snapshot", "extra", 80)
        led.set("kernel_operands", "dispatch", 228)
        assert led.pool_bytes("snapshot") == 800
        led.drop("snapshot", "extra")
        assert led.snapshot() == {"kernel_operands": 228, "snapshot": 720}

    def test_gauge_feed(self):
        m = AutoscalerMetrics()
        led = ResidencyLedger(metrics=m)
        led.set(POOL_SNAPSHOT, "packer", 1024)
        assert m.device_resident_bytes.get(pool=POOL_SNAPSHOT) == 1024.0
        led.drop(POOL_SNAPSHOT, "packer")
        assert m.device_resident_bytes.get(pool=POOL_SNAPSHOT) == 0.0

    def test_array_bytes_nested(self):
        a = np.zeros((4,), np.float32)
        assert array_bytes([a, {"x": a}, (a,)]) == 48
        assert array_bytes(None) == 0

    def test_rpc_servicer_accounts_scenario_batches(self):
        from autoscaler_tpu.perf import POOL_SCENARIO_BATCHES
        from autoscaler_tpu.rpc.service import TpuSimulationServicer

        led = ResidencyLedger()
        servicer = TpuSimulationServicer(residency=led)
        with servicer._account(
            "Estimate",
            np.zeros((8, 6), np.float32),   # 192 B
            np.zeros((2, 8), np.uint8),     # 16 B
        ):
            assert led.pool_bytes(POOL_SCENARIO_BATCHES) == 208
        # released when the RPC returns: the batch is garbage once the
        # response is serialized, and must not read as live after it
        assert led.pool_bytes(POOL_SCENARIO_BATCHES) == 0
        assert POOL_SCENARIO_BATCHES not in led.snapshot()
        # a residency-less servicer (the default) stays inert
        with TpuSimulationServicer()._account("Estimate", np.zeros((4,))):
            pass


# ------------------------------------------------------------------ ledger
def _tick_rec(tick, dispatches=()):
    return {
        "schema": SCHEMA,
        "tick": tick,
        "now_ts": 1000.0 + tick,
        "dispatches": list(dispatches),
        "resident_bytes": {"snapshot": 720},
    }


def _disp(route="xla_scan", sig="8x6:f32", cache="hit", s=0.001):
    return {
        "route": route,
        "sig": sig,
        "cache": cache,
        "cold": cache == "miss",
        "dispatch_s": s,
        "operand_bytes": 128,
    }


class TestLedger:
    def test_valid_ledger_passes(self):
        recs = [
            _tick_rec(0, [_disp(cache="miss")]),
            _tick_rec(1, [_disp(cache="hit")]),
        ]
        assert validate_records(recs) == []

    def test_schema_and_monotonicity_errors(self):
        bad = [_tick_rec(3), {**_tick_rec(3), "schema": "nope"}]
        errors = validate_records(bad)
        assert any("not increasing" in e for e in errors)
        assert any("schema" in e for e in errors)

    def test_steady_state_compile_regression_detected(self):
        recs = [
            _tick_rec(0, [_disp(cache="miss")]),
            _tick_rec(1, [_disp(cache="hit")]),
            _tick_rec(2, [_disp(cache="miss")]),  # the executable was lost
        ]
        errors = validate_records(recs)
        assert len(errors) == 1
        assert "compile-on-steady-state-tick" in errors[0]

    def test_truncated_ledger_hits_without_miss_are_legal(self):
        # a ring-evicted prefix can hide the original miss — hits alone
        # must validate (the gate is truncation-safe)
        recs = [_tick_rec(5, [_disp(cache="hit")])]
        assert validate_records(recs) == []

    def test_distinct_signatures_may_each_miss(self):
        recs = [
            _tick_rec(0, [_disp(sig="a", cache="miss")]),
            _tick_rec(1, [_disp(sig="b", cache="miss")]),
        ]
        assert validate_records(recs) == []

    def test_cold_cache_disagreement_flagged(self):
        d = _disp(cache="miss")
        d["cold"] = False
        errors = validate_records([_tick_rec(0, [d])])
        assert any("disagrees" in e for e in errors)

    def test_record_line_byte_stable(self):
        rec = _tick_rec(0, [_disp()])
        assert record_line(rec) == record_line(json.loads(record_line(rec)))

    def test_summarize_per_route_split(self):
        recs = [
            _tick_rec(0, [_disp(cache="miss", s=0.5)]),
            _tick_rec(1, [_disp(cache="hit", s=0.001),
                          _disp(route="native", sig="", cache="miss", s=0.002)]),
        ]
        agg = summarize(recs)
        assert agg["ticks"] == 2
        xs = agg["routes"]["xla_scan"]
        assert xs["compiles"] == 1 and xs["dispatches"] == 2
        assert xs["compile_s"] == 0.5 and xs["execute_s"] == 0.001
        assert xs["signatures"] == 1  # both xla_scan dispatches share one sig
        assert agg["resident_bytes_peak"]["snapshot"] == 720

    def test_summarize_byte_stable_across_hash_seeds(self):
        """GL010 regression lock: the signature sets summarize accumulates
        must never leak iteration order into the serialized summary —
        the JSON must be byte-identical under different PYTHONHASHSEEDs
        (set iteration order over strings varies per process)."""
        import os
        from pathlib import Path

        prog = (
            "import json\n"
            "from autoscaler_tpu.perf.ledger import summarize\n"
            "recs = [{'tick': t, 'resident_bytes': {},\n"
            "         'dispatches': [\n"
            "             {'route': 'xla_scan', 'sig': f'sig{i}',\n"
            "              'cache': 'hit', 'dispatch_s': 0.001}\n"
            "             for i in range(12)]}\n"
            "        for t in range(3)]\n"
            "print(json.dumps(summarize(recs), sort_keys=True))\n"
        )
        outs = set()
        for seed in ("0", "1", "4242"):
            env = dict(os.environ, PYTHONHASHSEED=seed, JAX_PLATFORMS="cpu")
            proc = subprocess.run(
                [sys.executable, "-c", prog],
                capture_output=True, text=True, env=env,
                cwd=str(Path(__file__).resolve().parent.parent),
            )
            assert proc.returncode == 0, proc.stderr
            outs.add(proc.stdout)
        assert len(outs) == 1, f"summary bytes vary with hash seed: {outs}"


# ------------------------------------------------------------- observatory
class TestObservatory:
    def _fn(self):
        def kernel(*a, **k):
            return None

        kernel.__name__ = "fake_kernel"
        return kernel

    def test_cold_then_warm_split_and_span_attrs(self):
        m = AutoscalerMetrics()
        obs = PerfObservatory(metrics=m)
        obs.begin_tick(0, 1000.0)
        fn = self._fn()
        args = (np.zeros((8, 6), np.float32),)
        cold_span = _FakeSpan()
        _dispatch_once(obs, fn, args, wall=0.5, span=cold_span)
        assert cold_span.attrs["cache"] == "miss" and cold_span.attrs["cold"]
        assert cold_span.attrs["shape_sig"] == "8x6:float32"
        assert cold_span.attrs["operand_bytes"] == 192
        warm_span = _FakeSpan()
        _dispatch_once(obs, fn, args, wall=0.01, span=warm_span)
        assert warm_span.attrs["cache"] == "hit"
        assert warm_span.attrs["execute_est_s"] == 0.01
        assert warm_span.attrs["compile_est_s"] == pytest.approx(0.49)
        rec = obs.end_tick()
        assert [d["cache"] for d in rec["dispatches"]] == ["miss", "hit"]
        assert rec["resident_bytes"][POOL_KERNEL_OPERANDS] == 192
        assert m.kernel_compile_cache_total.get(
            route="xla_scan", outcome="miss"
        ) == 1
        assert m.kernel_compile_cache_total.get(
            route="xla_scan", outcome="hit"
        ) == 1
        assert m.kernel_compile_seconds.count(route="xla_scan") == 1
        assert m.kernel_execute_seconds.count(route="xla_scan") == 1

    def test_cold_is_per_signature_not_per_route(self):
        obs = PerfObservatory()
        obs.begin_tick(0, 0.0)
        fn = self._fn()
        _dispatch_once(obs, fn, (np.zeros((8, 6), np.float32),))
        _dispatch_once(obs, fn, (np.zeros((16, 6), np.float32),))
        rec = obs.end_tick()
        assert [d["cache"] for d in rec["dispatches"]] == ["miss", "miss"]

    def test_stale_pending_cannot_leak_across_rungs(self):
        obs = PerfObservatory()
        obs.begin_tick(0, 0.0)
        # a rung observed its kernel entry then faulted: on_dispatch never
        # ran. The next rung (host — no observed entry) must not inherit it.
        obs.note_kernel(self._fn(), (np.zeros((8, 6), np.float32),), {})
        obs.clear_pending()
        obs.on_dispatch("native", 0.001)
        rec = obs.end_tick()
        assert rec["dispatches"][0]["sig"] == ""
        assert rec["dispatches"][0]["operand_bytes"] == 0
        # the faulted rung's operand bytes were released with the parked
        # call — a host-served tick must not report a dead dispatch's
        # arrays as resident
        assert POOL_KERNEL_OPERANDS not in rec["resident_bytes"]

    def test_clear_pending_preserves_served_dispatch_residency(self):
        # clear_pending before a FOLLOWING estimate() call must not release
        # the operands of the dispatch that already served this tick
        obs = PerfObservatory()
        obs.begin_tick(0, 0.0)
        _dispatch_once(obs, self._fn(), (np.zeros((8, 6), np.float32),))
        obs.clear_pending()  # next estimate's rung walk starts
        rec = obs.end_tick()
        assert rec["resident_bytes"][POOL_KERNEL_OPERANDS] == 192

    def test_ring_bounded_and_queries(self):
        obs = PerfObservatory(ring_capacity=2)
        for i in range(4):
            obs.begin_tick(i, float(i))
            obs.end_tick()
        assert [r["tick"] for r in obs.records()] == [2, 3]
        listing = json.loads(obs.list_json())
        assert listing["schema"] == SCHEMA
        assert [t["tick"] for t in listing["ticks"]] == [2, 3]
        assert json.loads(obs.detail_json(3))["tick"] == 3
        assert obs.detail_json(0) is None

    def test_idle_tick_does_not_inherit_operand_bytes(self):
        # the kernel_operands pool accounts the in-flight dispatch; a tick
        # with no dispatch must not report the last tick's operands as
        # live (end_tick releases the slot after snapshotting)
        obs = PerfObservatory()
        obs.begin_tick(0, 0.0)
        _dispatch_once(obs, self._fn(), (np.zeros((8, 6), np.float32),))
        rec0 = obs.end_tick()
        assert rec0["resident_bytes"][POOL_KERNEL_OPERANDS] == 192
        obs.begin_tick(1, 1.0)
        rec1 = obs.end_tick()
        assert POOL_KERNEL_OPERANDS not in rec1["resident_bytes"]

    def test_end_tick_without_begin_is_noop(self):
        obs = PerfObservatory()
        assert obs.end_tick() is None
        assert obs.last_record() is None

    def test_dispatch_outside_tick_still_feeds_stats(self):
        obs = PerfObservatory()
        _dispatch_once(obs, self._fn(), (np.zeros((2,), np.float32),))
        assert obs.records() == []  # no open tick — nothing ringed
        obs.begin_tick(0, 0.0)
        _dispatch_once(obs, self._fn(), (np.zeros((2,), np.float32),))
        rec = obs.end_tick()
        # the pre-tick dispatch was that signature's cold one
        assert rec["dispatches"][0]["cache"] == "hit"


# ---------------------------------------------- run_once + estimator wiring
class TestRunOnceIntegration:
    def test_tick_record_per_run_once_with_dispatches(self):
        pods = [
            build_test_pod(f"p{i}", cpu_m=600, mem=GB) for i in range(4)
        ]
        a = make_autoscaler(pods=pods, perf_cost_model=True)
        a.run_once(now_ts=0.0)
        rec = a.observatory.last_record()
        assert rec is not None and rec["schema"] == SCHEMA
        assert rec["dispatches"], "scale-up tick recorded no dispatches"
        d = rec["dispatches"][0]
        assert d["cache"] == "miss" and d["sig"]
        assert d.get("cost", {}).get("flops", 0) > 0
        assert rec["resident_bytes"][POOL_SNAPSHOT] > 0
        # the estimator's deviceDispatch span carries the telemetry attrs
        spans = [
            s
            for t in a.tracer.recorder.traces()
            for s in t.spans
            if s.name == "deviceDispatch" and s.attrs.get("outcome") == "ok"
        ]
        assert spans and all("cache" in s.attrs for s in spans)
        assert any("model_flops" in s.attrs for s in spans)

    def test_crashed_tick_still_closes_its_record(self, monkeypatch):
        a = make_autoscaler()
        monkeypatch.setattr(
            a, "_run_once_traced",
            lambda *ar, **kw: (_ for _ in ()).throw(RuntimeError("boom")),
        )
        with pytest.raises(RuntimeError):
            a.run_once(now_ts=0.0)
        assert a.observatory.last_record() is not None

    def test_perf_record_span_in_tick_tree(self):
        a = make_autoscaler()
        a.run_once(now_ts=0.0)
        names = {s.name for t in a.tracer.recorder.traces() for s in t.spans}
        assert PERF_RECORD in names


# ----------------------------------------------------------------- /perfz
class TestPerfzEndpoint:
    def _get(self, port, path):
        with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}") as r:
            return r.status, r.read().decode()

    def test_perfz_list_and_detail(self):
        a = make_autoscaler()
        a.run_once(now_ts=0.0)
        a.run_once(now_ts=10.0)
        server = ObservabilityServer(a, "127.0.0.1:0")
        port = server.start()
        try:
            code, body = self._get(port, "/perfz")
            assert code == 200
            listing = json.loads(body)
            assert listing["schema"] == SCHEMA and len(listing["ticks"]) == 2
            tick = listing["ticks"][-1]["tick"]
            code, body = self._get(port, f"/perfz?tick={tick}")
            assert code == 200 and json.loads(body)["tick"] == tick
            with pytest.raises(urllib.error.HTTPError) as ei:
                self._get(port, "/perfz?tick=99999")
            assert ei.value.code == 404
            with pytest.raises(urllib.error.HTTPError) as ei:
                self._get(port, "/perfz?tick=bogus")
            assert ei.value.code == 400
        finally:
            server.stop()

    def test_perfz_gated_like_tracez(self):
        a = make_autoscaler(perf_enabled=False)
        a.run_once(now_ts=0.0)
        server = ObservabilityServer(a, "127.0.0.1:0")
        port = server.start()
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                self._get(port, "/perfz")
            assert ei.value.code == 404
        finally:
            server.stop()


class TestConcurrentRingEviction:
    """Satellite: /tracez and /perfz racing a writer that overflows both
    rings — every response must be well-formed JSON, never a torn trace."""

    def test_endpoints_race_ring_overflow(self):
        a = make_autoscaler(trace_ring_size=2, perf_ring_size=2)
        a.run_once(now_ts=0.0)  # warm compile so writer iterations are fast
        stop = threading.Event()
        errors = []

        def writer():
            i = 0
            while not stop.is_set():
                i += 1
                # the cheap tick analog: tracer ring + perf ring both roll
                with a.tracer.tick("main", now_ts=float(i)):
                    a.observatory.begin_tick(i, float(i))
                    with trace.span("estimate"):
                        pass
                a.observatory.end_tick()

        server = ObservabilityServer(a, "127.0.0.1:0")
        port = server.start()
        t = threading.Thread(target=writer, daemon=True)
        t.start()
        try:
            for _ in range(60):
                for path in ("/tracez", "/perfz", "/tracez?format=chrome"):
                    with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}{path}"
                    ) as r:
                        body = r.read().decode()
                    try:
                        json.loads(body)
                    except json.JSONDecodeError as e:  # pragma: no cover
                        errors.append(f"{path}: torn response: {e}")
        finally:
            stop.set()
            t.join(timeout=5)
            server.stop()
        assert not errors


# -------------------------------------------------- chrome track metadata
class TestChromeMetadata:
    def test_metadata_events_name_tracks(self):
        from autoscaler_tpu.trace.recorder import chrome_trace_doc

        tracer = trace.Tracer(recorder=trace.FlightRecorder(capacity=4))
        for i in range(2):
            with tracer.tick("main", now_ts=float(i)):
                with trace.span("estimate"):
                    pass
        doc = chrome_trace_doc(tracer.recorder.traces())
        meta = [e for e in doc["traceEvents"] if e["ph"] == "M"]
        pids = {e["pid"] for e in doc["traceEvents"] if e["ph"] == "X"}
        proc = {e["pid"]: e for e in meta if e["name"] == "process_name"}
        thr = {e["pid"]: e for e in meta if e["name"] == "thread_name"}
        assert set(proc) == pids and set(thr) == pids
        for pid in pids:
            assert proc[pid]["args"]["name"] == f"autoscaler/tick {pid}"
            assert thr[pid]["args"]["name"] == "autoscaler/tick"


# ------------------------------------- loadgen determinism + scorer + CLI
class TestLoadgenPerfDeterminism:
    def test_two_replays_write_byte_identical_perf_ledgers(
        self, ladder_replays
    ):
        r1, r2 = ladder_replays
        l1, l2 = r1.perf_ledger_lines(), r2.perf_ledger_lines()
        assert l1 and l1 == l2
        records = [json.loads(line) for line in l1.splitlines()]
        assert validate_records(records) == []
        assert len(records) == r1.spec.ticks

    def test_replayed_dispatch_spans_carry_perf_attrs(self, ladder_replays):
        """Acceptance: each served deviceDispatch span in the replayed
        trace carries the compile/execute split and cost-model attrs for
        its route."""
        r1, _ = ladder_replays
        served = [
            s
            for t in r1.recorder.traces()
            for s in t.spans
            if s.name == "deviceDispatch" and s.attrs.get("outcome") == "ok"
        ]
        assert served
        for s in served:
            assert "cache" in s.attrs and "dispatch_s" in s.attrs
        warm = [s for s in served if s.attrs.get("cache") == "hit"]
        assert warm
        for s in warm:
            assert "compile_est_s" in s.attrs and "execute_est_s" in s.attrs
        assert any("model_flops" in s.attrs for s in served)

    def test_scorer_perf_columns(self, ladder_replays):
        from autoscaler_tpu.loadgen.score import build_report

        r1, _ = ladder_replays
        report = build_report(r1)
        perf = report["perf"]
        assert perf["ticks"] == r1.spec.ticks
        route = next(iter(perf["routes"].values()))
        for col in ("dispatches", "compiles", "compile_s", "execute_s"):
            assert col in route
        pool = next(iter(perf["resident_bytes"].values()))
        assert set(pool) == {"p50", "p99", "peak"}

    def test_bench_perf_ledger_gate(self, ladder_replays, tmp_path):
        r1, _ = ladder_replays
        good = tmp_path / "good.jsonl"
        good.write_text(r1.perf_ledger_lines())
        proc = subprocess.run(
            [sys.executable, "bench.py", "--perf-ledger", str(good)],
            capture_output=True, text=True,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        report = json.loads(proc.stdout)
        assert report["valid"] and report["routes"]
        # seed a steady-state compile regression: replay the first miss
        records = [json.loads(line) for line in good.read_text().splitlines()]
        first_miss = next(
            d for r in records for d in r["dispatches"] if d["cache"] == "miss"
        )
        records[-1]["dispatches"].append(dict(first_miss))
        bad = tmp_path / "bad.jsonl"
        bad.write_text("".join(record_line(r) for r in records))
        proc = subprocess.run(
            [sys.executable, "bench.py", "--perf-ledger", str(bad)],
            capture_output=True, text=True,
        )
        assert proc.returncode == 1
        assert "compile-on-steady-state-tick" in proc.stdout
        # unreadable ledger → exit 2
        proc = subprocess.run(
            [sys.executable, "bench.py", "--perf-ledger",
             str(tmp_path / "missing.jsonl")],
            capture_output=True, text=True,
        )
        assert proc.returncode == 2
        # malformed-but-parseable ledger → the bounded JSON error report
        # and exit 1, never a traceback
        mangled = tmp_path / "mangled.jsonl"
        mangled.write_text("[1,2,3]\n" + json.dumps({"schema": SCHEMA}) + "\n")
        proc = subprocess.run(
            [sys.executable, "bench.py", "--perf-ledger", str(mangled)],
            capture_output=True, text=True,
        )
        assert proc.returncode == 1, proc.stdout + proc.stderr
        report = json.loads(proc.stdout)
        assert not report["valid"] and report["errors_total"] > 0

    def test_cli_perf_ledger_flag(self, tmp_path):
        from autoscaler_tpu.loadgen.cli import main as loadgen_main

        out = tmp_path / "ledger.jsonl"
        rc = loadgen_main([
            "run", "benchmarks/scenarios/burst_small.json",
            "--perf-ledger", str(out),
        ])
        assert rc == 0
        records = [
            json.loads(line) for line in out.read_text().splitlines()
        ]
        assert records and validate_records(records) == []
