"""Node autoprovisioning tests: candidate creation from pod shapes, budget
caps, dedup, orchestrator integration (candidate wins → group created for
real → scale-up lands), and empty-group cleanup (modeled on the reference's
processors/nodegroups behavior + orchestrator.go:217)."""
import pytest

from autoscaler_tpu.cloudprovider.test_provider import TestCloudProvider
from autoscaler_tpu.clusterstate.registry import ClusterStateRegistry
from autoscaler_tpu.config.options import AutoscalingOptions
from autoscaler_tpu.core.scaleup.orchestrator import ScaleUpOrchestrator
from autoscaler_tpu.kube.objects import Resources
from autoscaler_tpu.processors.nodegroups import (
    AutoprovisioningNodeGroupListProcessor,
    CandidateNodeGroup,
    MachineShape,
)
from autoscaler_tpu.processors.pipeline import NodeGroupManager
from autoscaler_tpu.utils.test_utils import GB, build_test_node, build_test_pod

SHAPES = [
    MachineShape("small", 2000, 8 * GB, price_per_hour=0.07),
    MachineShape("big", 16000, 64 * GB, price_per_hour=0.54),
    MachineShape("tpu4", 112000, 192 * GB, tpu=4, price_per_hour=4.8),
]


def make_factory(provider):
    def factory(candidate: CandidateNodeGroup):
        return provider.add_node_group(
            candidate.id(),
            0,
            candidate.max_size(),
            0,
            candidate.template_node_info(),
            price_per_hour=candidate.price_per_hour,
            autoprovisioned=True,
        )

    return factory


def processor_for(provider, **kw):
    return AutoprovisioningNodeGroupListProcessor(
        make_factory(provider), SHAPES, **kw
    )


class TestCandidateCreation:
    def test_unfittable_pod_gets_cheapest_fitting_shape(self):
        provider = TestCloudProvider()
        provider.add_node_group("g", 0, 10, 0, build_test_node("t", cpu_m=1000))
        proc = processor_for(provider)
        # needs 4 cores: no existing template fits; "big" is the cheapest fit
        pod = build_test_pod("p", cpu_m=4000, mem=1 * GB)
        cands = proc.process(provider, [pod], provider.node_groups())
        assert len(cands) == 1
        assert cands[0].id().startswith("nap-big-")
        assert not cands[0].exist()
        assert cands[0].autoprovisioned()

    def test_fittable_pod_creates_nothing(self):
        provider = TestCloudProvider()
        provider.add_node_group(
            "g", 0, 10, 0, build_test_node("t", cpu_m=8000, mem=32 * GB)
        )
        proc = processor_for(provider)
        pod = build_test_pod("p", cpu_m=4000, mem=1 * GB)
        assert proc.process(provider, [pod], provider.node_groups()) == []

    def test_tpu_pod_selects_tpu_shape_and_selector_labels(self):
        provider = TestCloudProvider()
        proc = processor_for(provider)
        pod = build_test_pod("p", cpu_m=1000, node_selector={"pool": "train"})
        pod.requests = Resources(cpu_m=1000, memory=1 * GB, tpu=4, pods=1)
        cands = proc.process(provider, [pod], [])
        assert len(cands) == 1
        tmpl = cands[0].template_node_info()
        assert tmpl.allocatable.tpu == 4
        assert tmpl.labels["pool"] == "train"

    def test_identical_pods_dedupe_oversized_never_fit(self):
        provider = TestCloudProvider()
        proc = processor_for(provider)
        pods = [build_test_pod(f"p{i}", cpu_m=4000) for i in range(5)]
        pods.append(build_test_pod("huge", cpu_m=999000))  # no shape fits
        cands = proc.process(provider, pods, [])
        assert len(cands) == 1

    def test_budget_counts_existing_autoprovisioned(self):
        provider = TestCloudProvider()
        provider.add_node_group(
            "nap-old", 0, 10, 0, build_test_node("t", cpu_m=100),
            autoprovisioned=True,
        )
        proc = processor_for(provider, max_autoprovisioned_groups=1)
        pod = build_test_pod("p", cpu_m=4000)
        assert proc.process(provider, [pod], provider.node_groups()) == []


class TestOrchestratorIntegration:
    def test_candidate_win_creates_group_and_scales(self):
        provider = TestCloudProvider()
        provider.add_node_group("g", 0, 10, 0, build_test_node("t", cpu_m=1000))
        csr = ClusterStateRegistry(provider, AutoscalingOptions())
        orch = ScaleUpOrchestrator(
            provider,
            AutoscalingOptions(),
            csr,
            node_group_list_processor=processor_for(provider),
        )
        pod = build_test_pod("p", cpu_m=4000, mem=1 * GB)
        result = orch.scale_up([pod], [], now_ts=0.0)
        assert result.scaled_up
        assert result.chosen_group.startswith("nap-big-")
        created = [g for g in provider.node_groups() if g.id() == result.chosen_group]
        assert created and created[0].exist()
        assert created[0].target_size() >= 1
        assert created[0].autoprovisioned()

    def test_existing_group_preferred_when_it_fits(self):
        provider = TestCloudProvider()
        provider.add_node_group(
            "g", 0, 10, 0, build_test_node("t", cpu_m=8000, mem=32 * GB)
        )
        csr = ClusterStateRegistry(provider, AutoscalingOptions())
        orch = ScaleUpOrchestrator(
            provider,
            AutoscalingOptions(),
            csr,
            node_group_list_processor=processor_for(provider),
        )
        result = orch.scale_up([build_test_pod("p", cpu_m=4000)], [], now_ts=0.0)
        assert result.scaled_up and result.chosen_group == "g"
        assert all(not g.id().startswith("nap-") for g in provider.node_groups())


class TestFailureHandling:
    def test_failed_creation_backs_off(self):
        provider = TestCloudProvider()

        def exploding_factory(candidate):
            raise RuntimeError("cloud quota exceeded")

        proc = AutoprovisioningNodeGroupListProcessor(exploding_factory, SHAPES)
        csr = ClusterStateRegistry(provider, AutoscalingOptions())
        orch = ScaleUpOrchestrator(
            provider, AutoscalingOptions(), csr, node_group_list_processor=proc
        )
        pod = build_test_pod("p", cpu_m=4000, mem=1 * GB)
        r1 = orch.scale_up([pod], [], now_ts=0.0)
        assert not r1.scaled_up and r1.error
        # same candidate id regenerates next loop but is now backed off —
        # no second create() attempt (no error, just no viable option)
        r2 = orch.scale_up([pod], [], now_ts=1.0)
        assert not r2.scaled_up and r2.error is None
        assert any(g.startswith("nap-") for g in r2.skipped_groups)

    def test_collision_with_live_group_skipped(self):
        provider = TestCloudProvider()
        proc = processor_for(provider)
        pod = build_test_pod("p", cpu_m=4000, mem=1 * GB)
        (cand,) = proc.process(provider, [pod], [])
        live = cand.create()
        live.increase_size(3)
        # existing group's template fetch failing must not let a duplicate
        # candidate overwrite the live group

        class BrokenTemplate:
            def __getattr__(self, item):
                return getattr(live, item)

            def template_node_info(self):
                raise RuntimeError("template fetch failed")

        cands = proc.process(provider, [pod], [BrokenTemplate()])
        assert cands == []
        assert provider._groups[cand.id()].target_size() == 3


class TestCleanup:
    def test_empty_autoprovisioned_group_removed(self):
        provider = TestCloudProvider()
        provider.add_node_group(
            "nap-x", 0, 10, 0, build_test_node("t"), autoprovisioned=True
        )
        provider.add_node_group("keep", 0, 10, 0, build_test_node("t2"))
        removed = NodeGroupManager().remove_unneeded_node_groups(provider)
        assert removed == ["nap-x"]
        assert [g.id() for g in provider.node_groups()] == ["keep"]


class TestAffinityCandidates:
    def test_affinity_only_pod_gets_labeled_candidate(self):
        """A pod placing itself via required node affinity (no nodeSelector)
        must get a candidate template carrying the affinity labels, and the
        pod must fit its own candidate."""
        from autoscaler_tpu.kube.objects import (
            Affinity,
            LabelSelector,
            LabelSelectorRequirement,
        )
        from autoscaler_tpu.processors.nodegroups import _pod_fits_template

        provider = TestCloudProvider()
        proc = processor_for(provider)
        aff = Affinity(
            node_selector_terms=(
                LabelSelector(
                    match_expressions=(
                        LabelSelectorRequirement("pool", "In", ("train",)),
                    )
                ),
            )
        )
        pod = build_test_pod("p", cpu_m=1000, affinity=aff)
        cands = proc.process(provider, [pod], [])
        assert len(cands) == 1
        template = cands[0].template_node_info()
        assert template.labels.get("pool") == "train"
        assert _pod_fits_template(pod, template)

    def test_unsynthesizable_affinity_skipped(self):
        """Gt/Lt expressions can't be satisfied by a guessed label — no dead
        candidate should be produced."""
        from autoscaler_tpu.kube.objects import (
            Affinity,
            LabelSelector,
            LabelSelectorRequirement,
        )

        provider = TestCloudProvider()
        proc = processor_for(provider)
        aff = Affinity(
            node_selector_terms=(
                LabelSelector(
                    match_expressions=(
                        LabelSelectorRequirement("zone-rank", "Gt", ("5",)),
                    )
                ),
            )
        )
        pod = build_test_pod("p", cpu_m=1000, affinity=aff)
        assert proc.process(provider, [pod], []) == []

    def test_distinct_affinity_distinct_groups(self):
        from autoscaler_tpu.kube.objects import (
            Affinity,
            LabelSelector,
            LabelSelectorRequirement,
        )

        provider = TestCloudProvider()
        proc = processor_for(provider)

        def aff(v):
            return Affinity(
                node_selector_terms=(
                    LabelSelector(
                        match_expressions=(
                            LabelSelectorRequirement("pool", "In", (v,)),
                        )
                    ),
                )
            )

        pods = [
            build_test_pod("a", cpu_m=1000, affinity=aff("train")),
            build_test_pod("b", cpu_m=1000, affinity=aff("serve")),
        ]
        cands = proc.process(provider, pods, [])
        assert len(cands) == 2
        assert cands[0].id() != cands[1].id()
