"""PodTopologySpread hard-filter parity.

Reference: the scheduler framework's PodTopologySpread filter plugin, run by
cluster-autoscaler/simulator/predicatechecker/schedulerbased.go:129 per
(pod, node). Coverage and divergences are documented in PREDICATES.md; the
oracle below implements the filter rule directly (count per domain of
matching placed pods; placing must keep count+1-min <= maxSkew; nodes
without the topology label never satisfy the constraint).
"""
import numpy as np
import pytest

from autoscaler_tpu.kube.objects import LabelSelector, TopologySpreadConstraint
from autoscaler_tpu.snapshot.packer import (
    compute_factored_mask,
    compute_sched_mask,
)
from autoscaler_tpu.utils.test_utils import build_test_node, build_test_pod
from tests.test_factored_mask import expand

ZONE = "topology.kubernetes.io/zone"


def spread(max_skew=1, key=ZONE, match=None, when="DoNotSchedule"):
    return TopologySpreadConstraint(
        max_skew=max_skew,
        topology_key=key,
        selector=LabelSelector.from_dict(match or {"app": "web"}),
        when_unsatisfiable=when,
    )


def zone_world(placed_per_zone=(1, 1, 0)):
    """One node per zone a/b/c; `placed_per_zone` app=web pods pinned on each."""
    nodes, pods, node_of = [], [], []
    for z, count in zip("abc", placed_per_zone):
        node = build_test_node(f"n-{z}", cpu_m=10_000)
        node.labels[ZONE] = f"zone-{z}"
        nodes.append(node)
        for k in range(count):
            p = build_test_pod(f"placed-{z}-{k}", cpu_m=100, labels={"app": "web"})
            pods.append(p)
            node_of.append(len(nodes) - 1)
    return nodes, pods, node_of


class TestSpreadFilter:
    def test_skew_forces_empty_zone(self):
        nodes, pods, node_of = zone_world((1, 1, 0))
        new = build_test_pod("new", cpu_m=100, labels={"app": "web"})
        new.topology_spread = (spread(max_skew=1),)
        pods.append(new)
        node_of.append(-1)
        mask = compute_sched_mask(nodes, pods, node_of)
        # counts a=1 b=1 c=0, min=0: only zone-c keeps skew <= 1
        assert list(mask[-1]) == [False, False, True]

    def test_larger_skew_allows_all(self):
        nodes, pods, node_of = zone_world((1, 1, 0))
        new = build_test_pod("new", cpu_m=100, labels={"app": "web"})
        new.topology_spread = (spread(max_skew=2),)
        pods.append(new)
        node_of.append(-1)
        mask = compute_sched_mask(nodes, pods, node_of)
        assert list(mask[-1]) == [True, True, True]

    def test_node_without_label_excluded(self):
        nodes, pods, node_of = zone_world((0, 0, 0))
        bare = build_test_node("bare", cpu_m=10_000)  # no zone label
        nodes.append(bare)
        new = build_test_pod("new", cpu_m=100, labels={"app": "web"})
        new.topology_spread = (spread(max_skew=1),)
        pods.append(new)
        node_of.append(-1)
        mask = compute_sched_mask(nodes, pods, node_of)
        assert list(mask[-1]) == [True, True, True, False]

    def test_schedule_anyway_is_soft(self):
        nodes, pods, node_of = zone_world((3, 0, 0))
        new = build_test_pod("new", cpu_m=100, labels={"app": "web"})
        new.topology_spread = (spread(max_skew=1, when="ScheduleAnyway"),)
        pods.append(new)
        node_of.append(-1)
        mask = compute_sched_mask(nodes, pods, node_of)
        assert mask[-1].all()

    def test_namespace_isolation(self):
        nodes, pods, node_of = zone_world((0, 0, 0))
        other = build_test_pod("other-ns", cpu_m=100, labels={"app": "web"},
                               namespace="prod")
        pods.append(other)
        node_of.append(0)  # zone-a, but different namespace
        new = build_test_pod("new", cpu_m=100, labels={"app": "web"})
        new.topology_spread = (spread(max_skew=1),)
        pods.append(new)
        node_of.append(-1)
        mask = compute_sched_mask(nodes, pods, node_of)
        assert mask[-1].all()  # prod pod never counts toward default/ skew

    def test_selector_mismatch_ignored(self):
        nodes, pods, node_of = zone_world((2, 0, 0))
        new = build_test_pod("new", cpu_m=100, labels={"app": "db"})
        new.topology_spread = (spread(max_skew=1, match={"app": "db"}),)
        pods.append(new)
        node_of.append(-1)
        mask = compute_sched_mask(nodes, pods, node_of)
        assert mask[-1].all()  # the web pods don't match app=db

    def test_hostname_spread(self):
        # kubernetes.io/hostname: every node its own domain — one web pod per
        # node max at skew 1 once any node has one
        nodes, pods, node_of = [], [], []
        for i in range(3):
            n = build_test_node(f"h{i}", cpu_m=10_000)
            n.labels["kubernetes.io/hostname"] = f"h{i}"
            nodes.append(n)
        placed = build_test_pod("placed", cpu_m=100, labels={"app": "web"})
        pods.append(placed)
        node_of.append(0)
        new = build_test_pod("new", cpu_m=100, labels={"app": "web"})
        new.topology_spread = (spread(max_skew=1, key="kubernetes.io/hostname"),)
        pods.append(new)
        node_of.append(-1)
        mask = compute_sched_mask(nodes, pods, node_of)
        # h0 would give counts (2,0,0): skew 2 > 1; h1/h2 give (1,1,0)
        assert list(mask[-1]) == [False, True, True]


def oracle_row(nodes, pods, node_of, i):
    """Direct implementation of the FULL plugin filter rule for pod i (the
    serial oracle the kernels are parity-locked against, SURVEY.md §7 #2):
    domain eligibility via nodeLabelsMatchSpreadConstraints + node inclusion
    policies (common.go:46,289), matchLabelKeys selector extension
    (common.go:99), minDomains (filtering.go:53), selfMatch (filtering.go:367)."""
    from autoscaler_tpu.kube import objects as k8s

    pod = pods[i]
    hard = [c for c in pod.topology_spread if c.when_unsatisfiable == "DoNotSchedule"]
    allowed = np.ones(len(nodes), bool)
    all_keys = {c.topology_key for c in hard}
    for c in hard:
        sel_labels = dict(c.selector.match_labels)
        for k in c.match_label_keys:
            if k in pod.labels:
                sel_labels[k] = pod.labels[k]
        from autoscaler_tpu.kube.objects import LabelSelector as LS

        sel = LS(
            match_labels=tuple(sorted(sel_labels.items())),
            match_expressions=c.selector.match_expressions,
        )

        def eligible(n):
            if not all(k in n.labels for k in all_keys):
                return False
            if c.node_affinity_policy != "Ignore" and not k8s.node_matches_selector(pod, n):
                return False
            if c.node_taints_policy == "Honor" and not k8s.pod_tolerates_taints(pod, n.taints):
                return False
            return True

        values = {}
        for n in nodes:
            if eligible(n):
                values.setdefault(n.labels[c.topology_key], 0)
        for q, j in zip(pods, node_of):
            if q is pod or j < 0 or not eligible(nodes[j]):
                continue
            v = nodes[j].labels.get(c.topology_key)
            if (
                v in values
                and q.namespace == pod.namespace
                and q.deletion_ts is None
                and sel.matches(q.labels)
            ):
                values[v] += 1
        min_count = min(values.values()) if values else 0
        if (c.min_domains or 1) > len(values):
            min_count = 0
        self_match = 1 if sel.matches(pod.labels) else 0
        for j, n in enumerate(nodes):
            v = n.labels.get(c.topology_key)
            if v is None:
                allowed[j] = False
            elif values.get(v, 0) + self_match - min_count > c.max_skew:
                allowed[j] = False
    return allowed


class TestFullPluginSemantics:
    """The details of PREDICATES.md divergence 2, now closed: minDomains,
    node inclusion policies, matchLabelKeys, selfMatch."""

    def test_min_domains_treats_min_as_zero(self):
        # zones a=2, b=2 placed; 2 domains exist but minDomains=3 → global
        # min is 0, so even the balanced domains fail maxSkew=1 at count 2
        nodes, pods, node_of = zone_world((2, 2))
        new = build_test_pod("new", cpu_m=100, labels={"app": "web"})
        new.topology_spread = (
            TopologySpreadConstraint(
                max_skew=1, topology_key=ZONE,
                selector=LabelSelector.from_dict({"app": "web"}),
                min_domains=3,
            ),
        )
        pods.append(new)
        node_of.append(-1)
        mask = compute_sched_mask(nodes, pods, node_of)
        # counts+1-0 = 3 > 1 everywhere
        assert list(mask[-1]) == [False, False]
        np.testing.assert_array_equal(
            mask[-1], oracle_row(nodes, pods, node_of, len(pods) - 1)
        )

    def test_min_domains_satisfied_restores_normal_min(self):
        nodes, pods, node_of = zone_world((2, 2))
        new = build_test_pod("new", cpu_m=100, labels={"app": "web"})
        new.topology_spread = (
            TopologySpreadConstraint(
                max_skew=1, topology_key=ZONE,
                selector=LabelSelector.from_dict({"app": "web"}),
                min_domains=2,
            ),
        )
        pods.append(new)
        node_of.append(-1)
        mask = compute_sched_mask(nodes, pods, node_of)
        assert list(mask[-1]) == [True, True]  # 2+1-2 = 1 <= 1

    def test_node_affinity_policy_honor_excludes_domains(self):
        # zone-b node doesn't match the pod's nodeSelector → with the
        # default Honor policy its domain doesn't exist for min/counts: the
        # pod sees a single domain (a, count 1), min=1 → a allowed. The
        # node itself is still unschedulable via the selector mask.
        nodes, pods, node_of = zone_world((1, 0))
        nodes[0].labels["disk"] = "ssd"
        new = build_test_pod("new", cpu_m=100, labels={"app": "web"})
        new.node_selector = {"disk": "ssd"}
        new.topology_spread = (spread(max_skew=1),)
        pods.append(new)
        node_of.append(-1)
        mask = compute_sched_mask(nodes, pods, node_of)
        assert list(mask[-1]) == [True, False]
        np.testing.assert_array_equal(
            mask[-1],
            oracle_row(nodes, pods, node_of, len(pods) - 1)
            & np.array([True, False]),  # selector mask composes
        )

    def test_node_affinity_policy_ignore_keeps_domains(self):
        # same world, policy Ignore: zone-b's empty domain counts → min=0,
        # zone-a (count 1) now fails maxSkew=1... 1+1-0=2>1
        nodes, pods, node_of = zone_world((1, 0))
        nodes[0].labels["disk"] = "ssd"
        new = build_test_pod("new", cpu_m=100, labels={"app": "web"})
        new.node_selector = {"disk": "ssd"}
        new.topology_spread = (
            TopologySpreadConstraint(
                max_skew=1, topology_key=ZONE,
                selector=LabelSelector.from_dict({"app": "web"}),
                node_affinity_policy="Ignore",
            ),
        )
        pods.append(new)
        node_of.append(-1)
        mask = compute_sched_mask(nodes, pods, node_of)
        assert list(mask[-1]) == [False, False]

    def test_node_taints_policy_honor(self):
        from autoscaler_tpu.kube.objects import Taint

        nodes, pods, node_of = zone_world((1, 0))
        nodes[1].taints.append(Taint("dedicated", "x", "NoSchedule"))
        new = build_test_pod("new", cpu_m=100, labels={"app": "web"})
        new.topology_spread = (
            TopologySpreadConstraint(
                max_skew=1, topology_key=ZONE,
                selector=LabelSelector.from_dict({"app": "web"}),
                node_taints_policy="Honor",
            ),
        )
        pods.append(new)
        node_of.append(-1)
        mask = compute_sched_mask(nodes, pods, node_of)
        # tainted zone-b excluded from domains → only a (count 1), min=1:
        # a passes spread (taint mask blocks b independently)
        assert mask[-1][0]
        # default Ignore policy: b's empty domain registers, min=0 → a fails
        new.topology_spread = (spread(max_skew=1),)
        mask2 = compute_sched_mask(nodes, pods, node_of)
        assert not mask2[-1][0]

    def test_match_label_keys_scopes_to_own_revision(self):
        # old-revision pods fill zone-a; a new-revision pod with
        # matchLabelKeys=["rev"] ignores them (selector gains rev=v2)
        nodes, pods, node_of = zone_world((0, 0))
        for k in range(3):
            p = build_test_pod(
                f"old-{k}", cpu_m=100, labels={"app": "web", "rev": "v1"}
            )
            pods.append(p)
            node_of.append(0)
        new = build_test_pod(
            "new", cpu_m=100, labels={"app": "web", "rev": "v2"}
        )
        new.topology_spread = (
            TopologySpreadConstraint(
                max_skew=1, topology_key=ZONE,
                selector=LabelSelector.from_dict({"app": "web"}),
                match_label_keys=("rev",),
            ),
        )
        pods.append(new)
        node_of.append(-1)
        mask = compute_sched_mask(nodes, pods, node_of)
        assert list(mask[-1]) == [True, True]  # v1 pods don't count
        # without matchLabelKeys the v1 pile blocks zone-a
        new.topology_spread = (spread(max_skew=1),)
        mask2 = compute_sched_mask(nodes, pods, node_of)
        assert list(mask2[-1]) == [False, True]

    def test_self_match_zero_when_pod_misses_own_selector(self):
        # a pod whose labels don't match its own constraint selector adds
        # selfMatch=0 (filtering.go:367): balanced counts stay balanced
        nodes, pods, node_of = zone_world((1, 1))
        new = build_test_pod("new", cpu_m=100, labels={"app": "other"})
        new.topology_spread = (spread(max_skew=1),)
        pods.append(new)
        node_of.append(-1)
        mask = compute_sched_mask(nodes, pods, node_of)
        assert list(mask[-1]) == [True, True]
        np.testing.assert_array_equal(
            mask[-1], oracle_row(nodes, pods, node_of, len(pods) - 1)
        )

    def test_terminating_pods_do_not_count(self):
        nodes, pods, node_of = zone_world((2, 0))
        pods[0].deletion_ts = 123.0  # one zone-a pod is terminating
        new = build_test_pod("new", cpu_m=100, labels={"app": "web"})
        new.topology_spread = (spread(max_skew=1),)
        pods.append(new)
        node_of.append(-1)
        mask = compute_sched_mask(nodes, pods, node_of)
        # effective counts a=1 b=0 → a fails (1+1-0=2), b ok
        assert list(mask[-1]) == [False, True]


class TestFullSemanticsOracleParity:
    @pytest.mark.parametrize("seed", range(6))
    def test_random_worlds_with_all_knobs(self, seed):
        from autoscaler_tpu.kube.objects import Taint

        rng = np.random.default_rng(1000 + seed)
        zones = [f"zone-{z}" for z in "abcd"[: rng.integers(2, 5)]]
        nodes = []
        for j in range(int(rng.integers(4, 10))):
            n = build_test_node(f"n{j}", cpu_m=100_000)
            if rng.random() < 0.85:
                n.labels[ZONE] = str(rng.choice(zones))
            if rng.random() < 0.3:
                n.labels["disk"] = str(rng.choice(["ssd", "hdd"]))
            if rng.random() < 0.25:
                n.taints.append(Taint("dedicated", "x", "NoSchedule"))
            nodes.append(n)
        pods, node_of = [], []
        apps = ["web", "db"]
        for i in range(int(rng.integers(8, 20))):
            app = str(rng.choice(apps))
            labels = {"app": app, "rev": str(rng.choice(["v1", "v2"]))}
            p = build_test_pod(f"p{i}", cpu_m=10, labels=labels)
            if rng.random() < 0.3:
                p.node_selector = {"disk": "ssd"}
            if rng.random() < 0.2:
                p.deletion_ts = 1.0
            if rng.random() < 0.6:
                p.topology_spread = (
                    TopologySpreadConstraint(
                        max_skew=int(rng.integers(1, 3)),
                        topology_key=ZONE,
                        selector=LabelSelector.from_dict({"app": app}),
                        min_domains=(
                            int(rng.integers(1, 5)) if rng.random() < 0.5 else None
                        ),
                        node_affinity_policy=str(
                            rng.choice(["Honor", "Ignore"])
                        ),
                        node_taints_policy=str(
                            rng.choice(["Honor", "Ignore"])
                        ),
                        match_label_keys=(
                            ("rev",) if rng.random() < 0.5 else ()
                        ),
                    ),
                )
            pods.append(p)
            node_of.append(
                int(rng.integers(0, len(nodes))) if rng.random() < 0.6 else -1
            )

        mask = compute_sched_mask(nodes, pods, node_of)
        fm = expand(
            compute_factored_mask(nodes, pods, node_of), len(pods), len(nodes)
        )
        from autoscaler_tpu.kube import objects as k8s

        for i, p in enumerate(pods):
            if not p.topology_spread or node_of[i] >= 0:
                continue
            # spread oracle composes with the independent static predicates
            static = np.array(
                [
                    k8s.node_matches_selector(p, n)
                    and k8s.pod_tolerates_taints(p, n.taints)
                    for n in nodes
                ],
                bool,
            )
            expected = oracle_row(nodes, pods, node_of, i) & static
            np.testing.assert_array_equal(
                mask[i], expected, err_msg=f"pod {i} dense seed {seed}"
            )
            np.testing.assert_array_equal(
                fm[i], expected, err_msg=f"pod {i} factored seed {seed}"
            )


class TestOracleParity:
    @pytest.mark.parametrize("seed", range(4))
    def test_random_worlds(self, seed):
        rng = np.random.default_rng(seed)
        zones = [f"zone-{z}" for z in "abcd"[: rng.integers(2, 5)]]
        nodes = []
        for j in range(int(rng.integers(4, 10))):
            n = build_test_node(f"n{j}", cpu_m=100_000)
            if rng.random() < 0.85:
                n.labels[ZONE] = str(rng.choice(zones))
            nodes.append(n)
        pods, node_of = [], []
        apps = ["web", "db", "cache"]
        for i in range(int(rng.integers(8, 25))):
            app = str(rng.choice(apps))
            p = build_test_pod(f"p{i}", cpu_m=10, labels={"app": app})
            if rng.random() < 0.5:
                p.topology_spread = (
                    spread(
                        max_skew=int(rng.integers(1, 3)),
                        match={"app": app},
                    ),
                )
            pods.append(p)
            node_of.append(int(rng.integers(0, len(nodes))) if rng.random() < 0.6 else -1)

        mask = compute_sched_mask(nodes, pods, node_of)
        fm = expand(compute_factored_mask(nodes, pods, node_of), len(pods), len(nodes))
        for i, p in enumerate(pods):
            if not p.topology_spread or node_of[i] >= 0:
                continue
            expected = oracle_row(nodes, pods, node_of, i)
            np.testing.assert_array_equal(mask[i], expected, err_msg=f"pod {i} dense")
            np.testing.assert_array_equal(fm[i], expected, err_msg=f"pod {i} factored")


class TestProfileEpochAtomicity:
    """ADVICE r5 medium — a capped profile registry resetting MID-PASS must
    not collide distinct profiles in the row rules: profile_id() returns the
    (epoch, id) pair atomically, pod_profile_value reads under the lock, and
    the packer snapshots the epoch, rebuilding (or falling back to tuple
    interning) when it moved."""

    def _world(self, n_profiles=12):
        nodes, pods, node_of = [], [], []
        for z in "ab":
            node = build_test_node(f"n-{z}", cpu_m=100_000)
            node.labels[ZONE] = f"zone-{z}"
            nodes.append(node)
        # distinct per-pod label profiles (the churn shape that trips the
        # cap) placed alternately across zones
        for i in range(n_profiles):
            p = build_test_pod(
                f"placed-{i}", cpu_m=10,
                labels={"app": "web", "pod-hash": f"h{i}"},
            )
            pods.append(p)
            node_of.append(i % 2)
        new = build_test_pod("new", cpu_m=10, labels={"app": "web"})
        new.topology_spread = (spread(max_skew=1, match={"app": "web"}),)
        pods.append(new)
        node_of.append(-1)
        return nodes, pods, node_of

    def test_mask_correct_under_tiny_cap(self, monkeypatch):
        """Force a registry reset every few interns: every profile_id() pass
        over the placed pods spans several epochs. The row rules must still
        count all 12 placed matchers (6 per zone, balanced → both zones
        admit the new pod; a collision under-counts or mismatches)."""
        import autoscaler_tpu.kube.objects as k8s

        nodes, pods, node_of = self._world()
        expected = compute_sched_mask(nodes, pods, node_of)[-1]
        monkeypatch.setattr(k8s, "_POD_PROFILE_CAP", 3)
        # fresh instances so nothing rides the per-instance memo
        nodes2, pods2, node_of2 = self._world()
        got = compute_sched_mask(nodes2, pods2, node_of2)[-1]
        np.testing.assert_array_equal(got, expected)

    def test_concurrent_churn_does_not_corrupt_pass(self, monkeypatch):
        """A writer thread interning unique profiles (the RPC-worker shape)
        while the packer pass runs: with the tiny cap the registry resets
        continuously, and every pass must still produce the oracle mask."""
        import threading

        import autoscaler_tpu.kube.objects as k8s

        nodes, pods, node_of = self._world()
        expected = compute_sched_mask(nodes, pods, node_of)[-1]
        monkeypatch.setattr(k8s, "_POD_PROFILE_CAP", 4)
        stop = threading.Event()

        def churn():
            i = 0
            while not stop.is_set():
                build_test_pod(
                    f"churn-{i}", labels={"job": f"j{i}"}
                ).profile_id()
                i += 1

        t = threading.Thread(target=churn, daemon=True)
        t.start()
        try:
            for trial in range(8):
                nodes2, pods2, node_of2 = self._world()
                got = compute_sched_mask(nodes2, pods2, node_of2)[-1]
                np.testing.assert_array_equal(
                    got, expected, err_msg=f"trial {trial}"
                )
        finally:
            stop.set()
            t.join(timeout=5)

    def test_profile_value_epoch_api(self):
        from autoscaler_tpu.kube.objects import (
            pod_profile_epoch,
            pod_profile_value,
        )

        p = build_test_pod("api-check", labels={"app": "x"})
        pid = p.profile_id()
        ns, labels = pod_profile_value(pid)
        assert ns == p.namespace and labels == p.labels
        assert isinstance(pod_profile_epoch(), int)
