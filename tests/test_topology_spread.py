"""PodTopologySpread hard-filter parity.

Reference: the scheduler framework's PodTopologySpread filter plugin, run by
cluster-autoscaler/simulator/predicatechecker/schedulerbased.go:129 per
(pod, node). Coverage and divergences are documented in PREDICATES.md; the
oracle below implements the filter rule directly (count per domain of
matching placed pods; placing must keep count+1-min <= maxSkew; nodes
without the topology label never satisfy the constraint).
"""
import numpy as np
import pytest

from autoscaler_tpu.kube.objects import LabelSelector, TopologySpreadConstraint
from autoscaler_tpu.snapshot.packer import (
    compute_factored_mask,
    compute_sched_mask,
)
from autoscaler_tpu.utils.test_utils import build_test_node, build_test_pod
from tests.test_factored_mask import expand

ZONE = "topology.kubernetes.io/zone"


def spread(max_skew=1, key=ZONE, match=None, when="DoNotSchedule"):
    return TopologySpreadConstraint(
        max_skew=max_skew,
        topology_key=key,
        selector=LabelSelector.from_dict(match or {"app": "web"}),
        when_unsatisfiable=when,
    )


def zone_world(placed_per_zone=(1, 1, 0)):
    """One node per zone a/b/c; `placed_per_zone` app=web pods pinned on each."""
    nodes, pods, node_of = [], [], []
    for z, count in zip("abc", placed_per_zone):
        node = build_test_node(f"n-{z}", cpu_m=10_000)
        node.labels[ZONE] = f"zone-{z}"
        nodes.append(node)
        for k in range(count):
            p = build_test_pod(f"placed-{z}-{k}", cpu_m=100, labels={"app": "web"})
            pods.append(p)
            node_of.append(len(nodes) - 1)
    return nodes, pods, node_of


class TestSpreadFilter:
    def test_skew_forces_empty_zone(self):
        nodes, pods, node_of = zone_world((1, 1, 0))
        new = build_test_pod("new", cpu_m=100, labels={"app": "web"})
        new.topology_spread = (spread(max_skew=1),)
        pods.append(new)
        node_of.append(-1)
        mask = compute_sched_mask(nodes, pods, node_of)
        # counts a=1 b=1 c=0, min=0: only zone-c keeps skew <= 1
        assert list(mask[-1]) == [False, False, True]

    def test_larger_skew_allows_all(self):
        nodes, pods, node_of = zone_world((1, 1, 0))
        new = build_test_pod("new", cpu_m=100, labels={"app": "web"})
        new.topology_spread = (spread(max_skew=2),)
        pods.append(new)
        node_of.append(-1)
        mask = compute_sched_mask(nodes, pods, node_of)
        assert list(mask[-1]) == [True, True, True]

    def test_node_without_label_excluded(self):
        nodes, pods, node_of = zone_world((0, 0, 0))
        bare = build_test_node("bare", cpu_m=10_000)  # no zone label
        nodes.append(bare)
        new = build_test_pod("new", cpu_m=100, labels={"app": "web"})
        new.topology_spread = (spread(max_skew=1),)
        pods.append(new)
        node_of.append(-1)
        mask = compute_sched_mask(nodes, pods, node_of)
        assert list(mask[-1]) == [True, True, True, False]

    def test_schedule_anyway_is_soft(self):
        nodes, pods, node_of = zone_world((3, 0, 0))
        new = build_test_pod("new", cpu_m=100, labels={"app": "web"})
        new.topology_spread = (spread(max_skew=1, when="ScheduleAnyway"),)
        pods.append(new)
        node_of.append(-1)
        mask = compute_sched_mask(nodes, pods, node_of)
        assert mask[-1].all()

    def test_namespace_isolation(self):
        nodes, pods, node_of = zone_world((0, 0, 0))
        other = build_test_pod("other-ns", cpu_m=100, labels={"app": "web"},
                               namespace="prod")
        pods.append(other)
        node_of.append(0)  # zone-a, but different namespace
        new = build_test_pod("new", cpu_m=100, labels={"app": "web"})
        new.topology_spread = (spread(max_skew=1),)
        pods.append(new)
        node_of.append(-1)
        mask = compute_sched_mask(nodes, pods, node_of)
        assert mask[-1].all()  # prod pod never counts toward default/ skew

    def test_selector_mismatch_ignored(self):
        nodes, pods, node_of = zone_world((2, 0, 0))
        new = build_test_pod("new", cpu_m=100, labels={"app": "db"})
        new.topology_spread = (spread(max_skew=1, match={"app": "db"}),)
        pods.append(new)
        node_of.append(-1)
        mask = compute_sched_mask(nodes, pods, node_of)
        assert mask[-1].all()  # the web pods don't match app=db

    def test_hostname_spread(self):
        # kubernetes.io/hostname: every node its own domain — one web pod per
        # node max at skew 1 once any node has one
        nodes, pods, node_of = [], [], []
        for i in range(3):
            n = build_test_node(f"h{i}", cpu_m=10_000)
            n.labels["kubernetes.io/hostname"] = f"h{i}"
            nodes.append(n)
        placed = build_test_pod("placed", cpu_m=100, labels={"app": "web"})
        pods.append(placed)
        node_of.append(0)
        new = build_test_pod("new", cpu_m=100, labels={"app": "web"})
        new.topology_spread = (spread(max_skew=1, key="kubernetes.io/hostname"),)
        pods.append(new)
        node_of.append(-1)
        mask = compute_sched_mask(nodes, pods, node_of)
        # h0 would give counts (2,0,0): skew 2 > 1; h1/h2 give (1,1,0)
        assert list(mask[-1]) == [False, True, True]


def oracle_row(nodes, pods, node_of, i):
    """Direct implementation of the filter rule for pod i (the serial
    oracle the kernels are parity-locked against, SURVEY.md §7 #2)."""
    pod = pods[i]
    allowed = np.ones(len(nodes), bool)
    for c in pod.topology_spread:
        if c.when_unsatisfiable != "DoNotSchedule":
            continue
        values = {}
        for n in nodes:
            v = n.labels.get(c.topology_key)
            if v is not None:
                values.setdefault(v, 0)
        for q, j in zip(pods, node_of):
            if q is pod or j < 0:
                continue
            v = nodes[j].labels.get(c.topology_key)
            if (
                v is not None
                and q.namespace == pod.namespace
                and c.selector.matches(q.labels)
            ):
                values[v] += 1
        min_count = min(values.values()) if values else 0
        for j, n in enumerate(nodes):
            v = n.labels.get(c.topology_key)
            if v is None:
                allowed[j] = False
            elif values[v] + 1 - min_count > c.max_skew:
                allowed[j] = False
    return allowed


class TestOracleParity:
    @pytest.mark.parametrize("seed", range(4))
    def test_random_worlds(self, seed):
        rng = np.random.default_rng(seed)
        zones = [f"zone-{z}" for z in "abcd"[: rng.integers(2, 5)]]
        nodes = []
        for j in range(int(rng.integers(4, 10))):
            n = build_test_node(f"n{j}", cpu_m=100_000)
            if rng.random() < 0.85:
                n.labels[ZONE] = str(rng.choice(zones))
            nodes.append(n)
        pods, node_of = [], []
        apps = ["web", "db", "cache"]
        for i in range(int(rng.integers(8, 25))):
            app = str(rng.choice(apps))
            p = build_test_pod(f"p{i}", cpu_m=10, labels={"app": app})
            if rng.random() < 0.5:
                p.topology_spread = (
                    spread(
                        max_skew=int(rng.integers(1, 3)),
                        match={"app": app},
                    ),
                )
            pods.append(p)
            node_of.append(int(rng.integers(0, len(nodes))) if rng.random() < 0.6 else -1)

        mask = compute_sched_mask(nodes, pods, node_of)
        fm = expand(compute_factored_mask(nodes, pods, node_of), len(pods), len(nodes))
        for i, p in enumerate(pods):
            if not p.topology_spread or node_of[i] >= 0:
                continue
            expected = oracle_row(nodes, pods, node_of, i)
            np.testing.assert_array_equal(mask[i], expected, err_msg=f"pod {i} dense")
            np.testing.assert_array_equal(fm[i], expected, err_msg=f"pod {i} factored")
