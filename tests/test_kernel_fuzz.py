"""Differential fuzz: randomized worlds through all three FFD binpack
implementations — the XLA scan (production), the serial numpy oracle
(mirrors the reference algorithm, binpacking_estimator.go:65-141), and the
Pallas kernel (interpret mode on CPU; Mosaic on TPU) — asserting exact
agreement on node counts and scheduled sets.

This widens the fixed-seed parity tests with varied shapes: degenerate
resources (zero-request pods, pods-count-only binding), tight caps, all-
masked groups, single-pod groups, huge pods that never fit, non-multiple-
of-chunk pod counts, and duplicate pod specs (the equivalence-dedup path).
"""
import numpy as np
import pytest

from autoscaler_tpu.estimator.reference_impl import ffd_binpack_reference
from autoscaler_tpu.kube.objects import CPU, GPU, MEMORY, PODS
from autoscaler_tpu.ops.binpack import ffd_binpack_groups, ffd_binpack_groups_runs
from autoscaler_tpu.ops.pallas_binpack import ffd_binpack_groups_pallas

import jax.numpy as jnp


def random_world(rng, P, G):
    pod_req = np.zeros((P, 6), np.float32)
    pod_req[:, CPU] = rng.integers(0, 2000, P)        # incl. zero-cpu pods
    pod_req[:, MEMORY] = rng.integers(0, 8192, P)
    if rng.random() < 0.3:
        gpu_pods = rng.random(P) < 0.2
        pod_req[gpu_pods, GPU] = rng.integers(1, 4, int(gpu_pods.sum()))
    pod_req[:, PODS] = 1
    if rng.random() < 0.2:
        # duplicate specs: the dedup path must agree with per-pod scans
        idx = rng.integers(0, P, P)
        pod_req = pod_req[idx]

    allocs = np.zeros((G, 6), np.float32)
    allocs[:, CPU] = rng.choice([1000, 4000, 16000], G)
    allocs[:, MEMORY] = rng.choice([2048, 8192, 65536], G)
    if rng.random() < 0.3:
        allocs[rng.random(G) < 0.3, GPU] = 8
    # tiny pods-per-node caps sometimes dominate
    allocs[:, PODS] = rng.choice([2, 16, 110], G)

    masks = rng.random((G, P)) > rng.uniform(0.0, 0.4)
    if rng.random() < 0.2:
        masks[rng.integers(0, G)] = False              # fully-masked group
    caps = rng.integers(1, 40, G).astype(np.int32)
    return pod_req, masks, allocs, caps


@pytest.mark.parametrize("case", range(24))
def test_differential_fuzz(case):
    rng = np.random.default_rng(1000 + case)
    P = int(rng.choice([1, 7, 33, 96, 200, 517]))     # incl. non-tile sizes
    G = int(rng.choice([1, 3, 8, 17]))
    pod_req, masks, allocs, caps = random_world(rng, P, G)
    # static across cases: caps are drawn from [1, 40) and both kernel and
    # oracle clamp via min(cap, max_nodes), so results are identical — but a
    # per-case max_nodes would defeat the jit cache and recompile all three
    # kernels for every case (~8s each)
    max_nodes = 40

    out = ffd_binpack_groups(
        jnp.asarray(pod_req), jnp.asarray(masks), jnp.asarray(allocs),
        max_nodes=max_nodes, node_caps=jnp.asarray(caps),
    )
    counts = np.asarray(out.node_count)
    sched = np.asarray(out.scheduled)

    # serial oracle, group by group (caps clamp like the kernel)
    for g in range(G):
        ref_count, ref_sched = ffd_binpack_reference(
            pod_req, masks[g], allocs[g], int(min(caps[g], max_nodes))
        )
        assert ref_count == int(counts[g]), f"case {case} group {g} count"
        np.testing.assert_array_equal(
            sched[g], ref_sched, err_msg=f"case {case} group {g} scheduled"
        )

    # equivalence-runs dedup twin: collapse identical (requests, mask-column)
    # pods into runs (the host equivalence grouping, groups.go:61), then the
    # per-run placed counts must match the per-pod kernel's scheduled sets.
    key = np.concatenate([pod_req, masks.T.astype(np.float32)], axis=1)
    uniq, inverse, counts_u = np.unique(
        key, axis=0, return_inverse=True, return_counts=True
    )
    run_req = np.ascontiguousarray(uniq[:, :6], dtype=np.float32)
    run_masks = np.ascontiguousarray(uniq[:, 6:].astype(bool).T)  # [G, U]
    runs = ffd_binpack_groups_runs(
        jnp.asarray(run_req), jnp.asarray(counts_u.astype(np.int32)),
        jnp.asarray(run_masks), jnp.asarray(allocs),
        max_nodes=max_nodes, node_caps=jnp.asarray(caps),
    )
    np.testing.assert_array_equal(np.asarray(runs.node_count), counts,
                                  err_msg=f"case {case} runs count")
    placed = np.asarray(runs.placed_counts)                       # [G, U]
    for g in range(G):
        per_run_sched = np.bincount(
            inverse[sched[g]], minlength=len(uniq)
        )
        np.testing.assert_array_equal(
            placed[g], per_run_sched, err_msg=f"case {case} group {g} run counts"
        )

    # Pallas twin (interpret mode on CPU; exercises pad/chunk edges)
    pal = ffd_binpack_groups_pallas(
        jnp.asarray(pod_req), jnp.asarray(masks), jnp.asarray(allocs),
        max_nodes=max_nodes, node_caps=jnp.asarray(caps), chunk=64,
    )
    np.testing.assert_array_equal(np.asarray(pal.node_count), counts,
                                  err_msg=f"case {case} pallas count")
    np.testing.assert_array_equal(np.asarray(pal.scheduled), sched,
                                  err_msg=f"case {case} pallas scheduled")


def random_terms(rng, P, G, T):
    match = rng.random((T, P)) < rng.uniform(0.1, 0.6)
    aff_of = (rng.random((T, P)) < 0.2) & match
    anti_of = (rng.random((T, P)) < 0.2) & ~aff_of
    node_level = rng.random(T) < 0.5
    has_label = rng.random((G, T)) < rng.uniform(0.5, 1.0)
    return match, aff_of, anti_of, node_level, has_label


@pytest.mark.parametrize("case", range(12))
def test_differential_fuzz_affinity_pallas(case):
    """Randomized degenerate worlds through the XLA affinity scan vs the
    Pallas bitset-carry twin (interpret mode) — exact agreement. The XLA
    scan is itself oracle-locked, so this chains to the serial reference."""
    from autoscaler_tpu.ops.binpack import ffd_binpack_groups_affinity
    from autoscaler_tpu.ops.pallas_binpack_affinity import (
        ffd_binpack_groups_affinity_pallas,
    )

    rng = np.random.default_rng(7000 + case)
    P = int(rng.choice([1, 9, 40, 130]))
    G = int(rng.choice([1, 3, 9]))
    T = int(rng.choice([1, 5, 34]))       # incl. multi-plane bitsets
    pod_req, masks, allocs, caps = random_world(rng, P, G)
    match, aff_of, anti_of, node_level, has_label = random_terms(rng, P, G, T)
    max_nodes = 24

    ref = ffd_binpack_groups_affinity(
        jnp.asarray(pod_req), jnp.asarray(masks), jnp.asarray(allocs),
        max_nodes=max_nodes, match=jnp.asarray(match),
        aff_of=jnp.asarray(aff_of), anti_of=jnp.asarray(anti_of),
        node_level=jnp.asarray(node_level), has_label=jnp.asarray(has_label),
        node_caps=jnp.asarray(caps),
    )
    out = ffd_binpack_groups_affinity_pallas(
        pod_req, masks, allocs, max_nodes=max_nodes,
        match=match, aff_of=aff_of, anti_of=anti_of,
        node_level=node_level, has_label=has_label, node_caps=caps,
        chunk=32, interpret=True,
    )
    np.testing.assert_array_equal(
        np.asarray(ref.node_count), np.asarray(out.node_count),
        err_msg=f"case {case} count",
    )
    np.testing.assert_array_equal(
        np.asarray(ref.scheduled), np.asarray(out.scheduled),
        err_msg=f"case {case} scheduled",
    )
