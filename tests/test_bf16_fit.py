"""bf16 fit-compare experiments (ROADMAP Scale #3): the one-sided rounding
guard must make the bf16 verdict conservative — never admitting a pod the
exact f32 compare would reject — and exact on bf16-representable inputs.
"""
import numpy as np
import pytest

import jax.numpy as jnp

from autoscaler_tpu.ops.fit import (
    _bf16_ceil,
    _bf16_floor,
    bf16_compare_operands,
    fit_matrix,
)
from autoscaler_tpu.snapshot.packer import pack
from autoscaler_tpu.utils.test_utils import GB, MB, build_test_node, build_test_pod


class TestRoundingPrimitives:
    def test_ceil_floor_bracket_the_value(self):
        rng = np.random.default_rng(0)
        x = jnp.asarray(
            rng.uniform(0, 1e9, 4096).astype(np.float32)
        )
        ceil = np.asarray(_bf16_ceil(x), np.float32)
        floor = np.asarray(_bf16_floor(x), np.float32)
        xs = np.asarray(x)
        assert (ceil >= xs).all()
        assert (floor <= xs).all()
        # within one bf16 ulp (relative 2^-7 at bf16 precision)
        assert (ceil - xs <= np.maximum(xs, 1.0) * 2**-7 + 1e-30).all()
        assert (xs - floor <= np.maximum(xs, 1.0) * 2**-7 + 1e-30).all()

    def test_exact_values_pass_through(self):
        # bf16-representable values: small ints and power-of-two scales
        exact = jnp.asarray(
            [0.0, 1.0, 2.0, 100.0, 128.0, 250.0, 256.0, 4096.0, 2.0**20],
            jnp.float32,
        )
        np.testing.assert_array_equal(
            np.asarray(_bf16_ceil(exact), np.float32), np.asarray(exact)
        )
        np.testing.assert_array_equal(
            np.asarray(_bf16_floor(exact), np.float32), np.asarray(exact)
        )


class TestOneSidedVerdict:
    def test_never_over_admits(self):
        """Property: bf16 fit ⟹ f32 fit, across random request/free pairs
        engineered to straddle rounding boundaries."""
        rng = np.random.default_rng(1)
        req = rng.uniform(0, 10000, (512, 6)).astype(np.float32)
        free = req * rng.uniform(0.98, 1.02, (512, 6)).astype(np.float32)
        req_b, free_b = bf16_compare_operands(
            jnp.asarray(req), jnp.asarray(free)
        )
        bf16_fits = np.asarray((req_b <= free_b).all(axis=-1))
        f32_fits = (req <= free).all(axis=-1)
        assert (~bf16_fits | f32_fits).all()  # bf16 ⟹ f32

    def test_useful_on_realistic_margins(self):
        """Fits with ≥1% headroom (the normal case — schedulers rarely pack
        to the last byte) all survive bf16 quantization (ulp = 2^-8 rel)."""
        rng = np.random.default_rng(2)
        req = rng.uniform(0, 10000, (512, 6)).astype(np.float32)
        free = req * 1.01
        req_b, free_b = bf16_compare_operands(
            jnp.asarray(req), jnp.asarray(free)
        )
        assert np.asarray((req_b <= free_b).all(axis=-1)).all()

    def test_fit_matrix_parity_on_typical_shapes(self):
        """Typical cluster quantities (power-of-two memory, round
        millicores) are bf16-exact → identical verdicts."""
        nodes = [
            build_test_node(f"n{i}", cpu_m=8000, mem=32 * GB) for i in range(4)
        ]
        pods = [
            build_test_pod(f"p{i}", cpu_m=250 * (1 + i % 3), mem=512 * MB)
            for i in range(16)
        ]
        t, _ = pack(nodes, pods)
        f32 = np.asarray(fit_matrix(t, precision="f32"))
        b16 = np.asarray(fit_matrix(t, precision="bf16"))
        np.testing.assert_array_equal(b16, f32)

    def test_fit_matrix_bf16_is_subset_on_adversarial_shapes(self):
        """Odd quantities (non-representable) may under-admit but never
        over-admit."""
        nodes = [build_test_node(f"n{i}", cpu_m=7777, mem=31 * GB + 123457)
                 for i in range(3)]
        pods = [build_test_pod(f"p{i}", cpu_m=7777 - i, mem=3 * GB + i * 7)
                for i in range(32)]
        t, _ = pack(nodes, pods)
        f32 = np.asarray(fit_matrix(t, precision="f32"))
        b16 = np.asarray(fit_matrix(t, precision="bf16"))
        assert (~b16 | f32).all()

    def test_unknown_precision_rejected(self):
        t, _ = pack([build_test_node("n")], [build_test_pod("p")])
        with pytest.raises(ValueError):
            fit_matrix(t, precision="f16")
