"""IncrementalPacker parity: every update() must be semantically identical
to a full pack() of the same objects — per-(pod key, node name) mask
verdicts, requests, allocatables, used, assignments — across arbitrary
mutation sequences (adds, removes, relists, reassignments, ports/CSI,
affinity/spread), in both dense and factored mask modes.

Reference intent: clustersnapshot/delta.go:26-42 (delta snapshots avoid
O(world) per-loop work); parity discipline mirrors the repo-wide rule that
every kernel/packing variant is pinned to the serial/full oracle.
"""
import numpy as np
import pytest

from autoscaler_tpu.kube.objects import (
    Affinity,
    LabelSelector,
    PodAffinityTerm,
    Taint,
    Toleration,
    TopologySpreadConstraint,
)
from autoscaler_tpu.snapshot.cluster_snapshot import ClusterSnapshot
from autoscaler_tpu.snapshot.incremental import IncrementalPacker
from autoscaler_tpu.snapshot.packer import pack
from autoscaler_tpu.utils.test_utils import GB, MB, build_test_node, build_test_pod


def assert_parity(packer_out, nodes, pods_eff, group_of_node=None, dense=None):
    """Incremental output == full pack of the same (order-free) world."""
    tensors_i, meta_i = packer_out
    tensors_f, meta_f = pack(nodes, pods_eff, group_of_node, dense_mask=dense)
    assert set(meta_i.pod_index) == set(meta_f.pod_index)
    assert set(meta_i.node_index) == set(meta_f.node_index)

    dense_i = np.asarray(tensors_i.dense_sched())
    dense_f = np.asarray(tensors_f.dense_sched())
    alloc_i = np.asarray(tensors_i.node_alloc)
    alloc_f = np.asarray(tensors_f.node_alloc)
    used_i = np.asarray(tensors_i.node_used)
    used_f = np.asarray(tensors_f.node_used)
    group_i = np.asarray(tensors_i.node_group)
    group_f = np.asarray(tensors_f.node_group)
    req_i = np.asarray(tensors_i.pod_req)
    req_f = np.asarray(tensors_f.pod_req)
    pn_i = np.asarray(tensors_i.pod_node)
    pn_f = np.asarray(tensors_f.pod_node)
    pv_i = np.asarray(tensors_i.pod_valid)
    nv_i = np.asarray(tensors_i.node_valid)

    for name, jf in meta_f.node_index.items():
        ji = meta_i.node_index[name]
        assert nv_i[ji], name
        np.testing.assert_array_equal(alloc_i[ji], alloc_f[jf], err_msg=name)
        np.testing.assert_array_equal(used_i[ji], used_f[jf], err_msg=name)
        gi = group_i[ji]
        gf = group_f[jf]
        gname_i = meta_i.group_names[gi] if gi >= 0 else None
        gname_f = meta_f.group_names[gf] if gf >= 0 else None
        assert gname_i == gname_f, name
    # padding rows invalid
    assert nv_i.sum() == len(meta_f.node_index)
    assert pv_i.sum() == len(meta_f.pod_index)

    for key, fi in meta_f.pod_index.items():
        ii = meta_i.pod_index[key]
        assert pv_i[ii], key
        np.testing.assert_array_equal(req_i[ii], req_f[fi], err_msg=key)
        # assignment maps to the same node NAME (row numbering differs)
        name_i = meta_i.nodes[pn_i[ii]].name if pn_i[ii] >= 0 else None
        name_f = meta_f.nodes[pn_f[fi]].name if pn_f[fi] >= 0 else None
        assert name_i == name_f, key
        # effective pod carries the assignment (consumers read node_name)
        assert meta_i.pods[ii].node_name == meta_f.pods[fi].node_name, key
        for name, jf in meta_f.node_index.items():
            ji = meta_i.node_index[name]
            assert dense_i[ii, ji] == dense_f[fi, jf], (key, name)


class World:
    """Twin driver: applies mutations to an object world, feeds the
    IncrementalPacker through a ClusterSnapshot, and checks parity against
    a fresh full pack after every step."""

    def __init__(self, dense=None):
        self.packer = IncrementalPacker(dense_mask=dense)
        self.dense = dense
        self.nodes = {}
        self.pods = {}   # key -> (pod, assign)
        self.groups = {}

    def check(self):
        snap = ClusterSnapshot(packer=self.packer)
        for node in self.nodes.values():
            snap.add_node(node)
        for key, (pod, assign) in self.pods.items():
            if assign and assign in self.nodes:
                snap.add_pod(pod, assign)
            else:
                snap.add_pod(pod)
        out = snap.tensors(self.groups or None)
        # the full-pack oracle wants effective pods (node_name = assignment)
        import copy as _copy

        eff = []
        for key, (pod, assign) in self.pods.items():
            effective = assign if assign in self.nodes else ""
            if pod.node_name != effective:
                pod = _copy.copy(pod)
                pod.node_name = effective
            eff.append(pod)
        assert_parity(out, list(self.nodes.values()), eff, self.groups or None,
                      dense=self.dense)
        return out


def test_steady_state_no_deltas_is_cached_upload_free():
    w = World()
    for i in range(6):
        w.nodes[f"n{i}"] = build_test_node(f"n{i}", cpu_m=4000, mem=8 * GB)
    for i in range(20):
        p = build_test_pod(f"p{i}", cpu_m=100, mem=128 * MB)
        w.pods[p.key()] = (p, f"n{i % 6}")
    t1, _ = w.check()
    full_packs = w.packer.full_packs
    t2, _ = w.check()
    assert w.packer.full_packs == full_packs  # no re-pack
    # unchanged fields reuse the SAME device buffers (no re-upload)
    assert t2.pod_req is t1.pod_req
    assert t2.node_alloc is t1.node_alloc


def test_add_remove_change_pods_and_nodes():
    w = World()
    for i in range(4):
        w.nodes[f"n{i}"] = build_test_node(f"n{i}", cpu_m=4000, mem=8 * GB)
    for i in range(12):
        p = build_test_pod(f"p{i}", cpu_m=200, mem=256 * MB)
        w.pods[p.key()] = (p, f"n{i % 4}" if i % 3 else "")
    w.check()
    # add a node + pods
    w.nodes["n9"] = build_test_node("n9", cpu_m=16000, mem=32 * GB)
    p = build_test_pod("fresh", cpu_m=500, mem=GB)
    w.pods[p.key()] = (p, "n9")
    w.check()
    # remove a middle node (its pods go pending) — exercises column swap
    del w.nodes["n1"]
    w.check()
    # remove some pods — row swaps
    for key in list(w.pods)[2:6]:
        del w.pods[key]
    w.check()
    # "relist": same keys, new objects with different requests
    for key in list(w.pods)[:3]:
        pod, assign = w.pods[key]
        newp = build_test_pod(pod.name, cpu_m=999, mem=333 * MB,
                              namespace=pod.namespace)
        w.pods[key] = (newp, assign)
    w.check()
    # reassign a pod
    key = next(iter(w.pods))
    pod, _ = w.pods[key]
    w.pods[key] = (pod, "n2")
    w.check()


def test_node_relist_with_new_taints_and_labels():
    w = World()
    w.nodes["a"] = build_test_node("a", cpu_m=4000, mem=8 * GB)
    w.nodes["b"] = build_test_node("b", cpu_m=4000, mem=8 * GB)
    tolerant = build_test_pod("tol", cpu_m=100, mem=128 * MB)
    tolerant.tolerations = [Toleration(key="dedicated", value="gpu", effect="NoSchedule")]
    plain = build_test_pod("plain", cpu_m=100, mem=128 * MB)
    sel = build_test_pod("sel", cpu_m=100, mem=128 * MB)
    sel.node_selector = {"zone": "z1"}
    # tolerates the taint node b will grow, so only the selector gates it
    sel.tolerations = [
        Toleration(key="dedicated", value="gpu", effect="NoSchedule")
    ]
    for p in (tolerant, plain, sel):
        w.pods[p.key()] = (p, "")
    w.check()
    # node b gets tainted + labeled (a new object, as a watch would deliver)
    b2 = build_test_node("b", cpu_m=4000, mem=8 * GB)
    b2.taints = [Taint(key="dedicated", value="gpu", effect="NoSchedule")]
    b2.labels = dict(b2.labels, zone="z1")
    w.nodes["b"] = b2
    out = w.check()
    tensors, meta = out
    dense = np.asarray(tensors.dense_sched())
    jb = meta.node_index["b"]
    assert not dense[meta.pod_index[plain.key()], jb]   # blocked by taint
    assert dense[meta.pod_index[tolerant.key()], jb]    # tolerates
    assert not dense[meta.pod_index[sel.key()], meta.node_index["a"]]
    assert dense[meta.pod_index[sel.key()], jb]          # selector satisfied
    # selector pod deleted → 'zone' leaves the relevant key set; parity holds
    del w.pods[sel.key()]
    w.check()


def test_in_place_node_mutation_still_dirties_the_row():
    """Identity diffing alone would miss a caller that mutates a listed Node
    in place (taint/cordon) instead of replacing it; the mut-fingerprint must
    catch the actuator-mutable fields. The real client and FakeClusterAPI
    both replace objects, but the packer must not silently trust that."""
    w = World()
    w.nodes["a"] = build_test_node("a", cpu_m=4000, mem=8 * GB)
    w.nodes["b"] = build_test_node("b", cpu_m=4000, mem=8 * GB)
    p = build_test_pod("p", cpu_m=100, mem=128 * MB)
    w.pods[p.key()] = (p, "")
    tensors, meta = w.check()
    assert np.asarray(tensors.dense_sched())[meta.pod_index[p.key()],
                                             meta.node_index["b"]]
    # SAME object, mutated in place — the forbidden-but-defended pattern
    w.nodes["b"].taints.append(Taint(key="k", value="v", effect="NoSchedule"))
    tensors, meta = w.check()
    assert not np.asarray(tensors.dense_sched())[meta.pod_index[p.key()],
                                                 meta.node_index["b"]]
    w.nodes["a"].unschedulable = True
    tensors, meta = w.check()
    assert not np.asarray(tensors.dense_sched())[meta.pod_index[p.key()],
                                                 meta.node_index["a"]]


def test_churn_at_full_bucket_swaps_without_overflow():
    """Replacing members at EXACTLY the bucket capacity must not transiently
    overflow the slot arrays: additions used to run before stale removals,
    so 8 live + 1 new in an 8-row bucket indexed row 8 (IndexError — found
    by the round-3 chaos-soak marathon, seeds 10106/10128). Removals now
    run first; parity must hold throughout."""
    w = World()
    # exactly one bucket of nodes (bucket_size minimum is 8)
    for i in range(8):
        w.nodes[f"n{i}"] = build_test_node(f"n{i}", cpu_m=4000, mem=8 * GB)
    for i in range(16):
        p = build_test_pod(f"p{i}", cpu_m=100, mem=128 * MB)
        w.pods[p.key()] = (p, f"n{i % 8}")
    w.check()
    # swap one node for a new one at constant count — peak would be 9
    for step in range(4):
        victim = f"n{step}" if step == 0 else f"extra{step - 1}"
        for key, (pod, assign) in list(w.pods.items()):
            if assign == victim:
                w.pods[key] = (pod, "")
        del w.nodes[victim]
        w.nodes[f"extra{step}"] = build_test_node(
            f"extra{step}", cpu_m=4000, mem=8 * GB
        )
        w.check()
    # same discipline for pods: full pod bucket, one swapped per step
    w2 = World()
    w2.nodes["n0"] = build_test_node("n0", cpu_m=100_000, mem=64 * GB)
    for i in range(8):
        p = build_test_pod(f"q{i}", cpu_m=10, mem=16 * MB)
        w2.pods[p.key()] = (p, "n0")
    w2.check()
    for step in range(4):
        old = f"q{step}" if step == 0 else f"fresh{step - 1}"
        del w2.pods[f"default/{old}"]
        p = build_test_pod(f"fresh{step}", cpu_m=10, mem=16 * MB)
        w2.pods[p.key()] = (p, "n0")
        w2.check()


def test_fake_api_taint_cordon_replace_objects():
    """FakeClusterAPI node writes must copy-on-write so identity diffing in
    the incremental packer sees them (kube/api.py contract)."""
    from autoscaler_tpu.kube.api import FakeClusterAPI
    from autoscaler_tpu.kube.objects import Taint as T

    api = FakeClusterAPI()
    node = build_test_node("n1", cpu_m=1000, mem=1 * GB)
    api.nodes[node.name] = node
    api.add_taint("n1", T(key="x", value="y", effect="NoSchedule"))
    assert api.nodes["n1"] is not node
    assert not node.taints  # original untouched
    before = api.nodes["n1"]
    api.cordon_node("n1")
    assert api.nodes["n1"] is not before
    assert api.nodes["n1"].unschedulable and not before.unschedulable
    # idempotent writes don't churn objects
    same = api.nodes["n1"]
    api.cordon_node("n1")
    api.add_taint("n1", T(key="x", value="y", effect="NoSchedule"))
    assert api.nodes["n1"] is same


def test_host_ports_and_csi_across_updates():
    w = World()
    for i in range(3):
        w.nodes[f"n{i}"] = build_test_node(f"n{i}", cpu_m=4000, mem=8 * GB)
    port_pod = build_test_pod("portly", cpu_m=100, mem=128 * MB)
    port_pod.host_ports = (8080,)
    incoming = build_test_pod("incoming", cpu_m=100, mem=128 * MB)
    incoming.host_ports = (8080,)
    w.pods[port_pod.key()] = (port_pod, "n0")
    w.pods[incoming.key()] = (incoming, "")
    out = w.check()
    tensors, meta = out
    dense = np.asarray(tensors.dense_sched())
    assert not dense[meta.pod_index[incoming.key()], meta.node_index["n0"]]
    assert dense[meta.pod_index[incoming.key()], meta.node_index["n1"]]
    # the placed pod keeps its own node (self-cell override)
    assert dense[meta.pod_index[port_pod.key()], meta.node_index["n0"]]
    # move the port pod → occupancy follows
    w.pods[port_pod.key()] = (port_pod, "n2")
    out = w.check()
    tensors, meta = out
    dense = np.asarray(tensors.dense_sched())
    assert dense[meta.pod_index[incoming.key()], meta.node_index["n0"]]
    assert not dense[meta.pod_index[incoming.key()], meta.node_index["n2"]]
    # CSI: node with a 1-volume limit fills up, then drains
    limited = build_test_node("lim", cpu_m=4000, mem=8 * GB)
    limited.csi_attach_limits = {"ebs": 1}
    w.nodes["lim"] = limited
    vol1 = build_test_pod("vol1", cpu_m=50, mem=64 * MB)
    vol1.csi_volumes = (("ebs", "h1"),)
    vol2 = build_test_pod("vol2", cpu_m=50, mem=64 * MB)
    vol2.csi_volumes = (("ebs", "h2"),)
    w.pods[vol1.key()] = (vol1, "lim")
    w.pods[vol2.key()] = (vol2, "")
    out = w.check()
    tensors, meta = out
    dense = np.asarray(tensors.dense_sched())
    assert not dense[meta.pod_index[vol2.key()], meta.node_index["lim"]]
    del w.pods[vol1.key()]
    out = w.check()
    tensors, meta = out
    dense = np.asarray(tensors.dense_sched())
    assert dense[meta.pod_index[vol2.key()], meta.node_index["lim"]]


def test_affinity_and_spread_exceptions_across_updates():
    w = World()
    for i, zone in enumerate(("z1", "z1", "z2")):
        node = build_test_node(f"n{i}", cpu_m=4000, mem=8 * GB)
        node.labels = dict(node.labels, zone=zone)
        w.nodes[f"n{i}"] = node
    anchor = build_test_pod("anchor", cpu_m=100, mem=128 * MB,
                            labels={"app": "db"})
    anti = build_test_pod("anti", cpu_m=100, mem=128 * MB)
    anti.affinity = Affinity(
        pod_anti_affinity=(
            PodAffinityTerm(
                selector=LabelSelector(match_labels=(("app", "db"),)),
                topology_key="zone",
            ),
        )
    )
    w.pods[anchor.key()] = (anchor, "n0")
    w.pods[anti.key()] = (anti, "")
    out = w.check()
    tensors, meta = out
    dense = np.asarray(tensors.dense_sched())
    # anti-affine pod blocked from the anchor's whole zone
    assert not dense[meta.pod_index[anti.key()], meta.node_index["n0"]]
    assert not dense[meta.pod_index[anti.key()], meta.node_index["n1"]]
    assert dense[meta.pod_index[anti.key()], meta.node_index["n2"]]
    # anchor moves to z2 → verdicts flip on the next loop
    w.pods[anchor.key()] = (anchor, "n2")
    out = w.check()
    tensors, meta = out
    dense = np.asarray(tensors.dense_sched())
    assert dense[meta.pod_index[anti.key()], meta.node_index["n0"]]
    assert not dense[meta.pod_index[anti.key()], meta.node_index["n2"]]
    # anchor deleted → no constraint at all
    del w.pods[anchor.key()]
    out = w.check()
    tensors, meta = out
    dense = np.asarray(tensors.dense_sched())
    assert dense[meta.pod_index[anti.key()]][
        [meta.node_index[f"n{i}"] for i in range(3)]
    ].all()

    # hard topology spread joins mid-run
    spready = build_test_pod("spready", cpu_m=100, mem=128 * MB,
                             labels={"app": "web"})
    spready.topology_spread = [
        TopologySpreadConstraint(
            max_skew=1,
            topology_key="zone",
            when_unsatisfiable="DoNotSchedule",
            selector=LabelSelector(match_labels=(("app", "web"),)),
        )
    ]
    placed_web = build_test_pod("web0", cpu_m=100, mem=128 * MB,
                                labels={"app": "web"})
    w.pods[placed_web.key()] = (placed_web, "n0")
    w.pods[spready.key()] = (spready, "")
    w.check()


def test_symmetric_anti_affinity_targets_recomputed():
    """A pod MATCHED by a placed pod's anti-affinity is an exception row;
    when the placed holder vanishes the row must revert to class-only."""
    w = World()
    n0 = build_test_node("n0", cpu_m=4000, mem=8 * GB)
    n0.labels = dict(n0.labels, zone="z1")
    w.nodes["n0"] = n0
    holder = build_test_pod("holder", cpu_m=100, mem=128 * MB)
    holder.affinity = Affinity(
        pod_anti_affinity=(
            PodAffinityTerm(
                selector=LabelSelector(match_labels=(("app", "victim"),)),
                topology_key="zone",
            ),
        )
    )
    victim = build_test_pod("victim", cpu_m=100, mem=128 * MB,
                            labels={"app": "victim"})
    w.pods[holder.key()] = (holder, "n0")
    w.pods[victim.key()] = (victim, "")
    out = w.check()
    tensors, meta = out
    dense = np.asarray(tensors.dense_sched())
    assert not dense[meta.pod_index[victim.key()], meta.node_index["n0"]]
    del w.pods[holder.key()]
    out = w.check()
    tensors, meta = out
    dense = np.asarray(tensors.dense_sched())
    assert dense[meta.pod_index[victim.key()], meta.node_index["n0"]]


@pytest.mark.parametrize("dense", [True, False])
def test_randomized_churn_parity(dense):
    """Property test: random op soup, parity after every step, both mask
    modes (the factored form is what the north-star scale uses)."""
    rng = np.random.default_rng(7)
    w = World(dense=dense)
    zones = ("z1", "z2", "z3")
    serial = [0]

    def new_node():
        name = f"n{serial[0]}"
        serial[0] += 1
        node = build_test_node(name, cpu_m=int(rng.integers(2000, 16000)),
                               mem=8 * GB)
        node.labels = dict(node.labels, zone=str(rng.choice(zones)))
        if rng.random() < 0.2:
            node.taints = [Taint(key="dedicated", value="x",
                                 effect="NoSchedule")]
        w.nodes[name] = node

    def new_pod():
        name = f"p{serial[0]}"
        serial[0] += 1
        pod = build_test_pod(name, cpu_m=int(rng.integers(50, 900)),
                             mem=256 * MB, labels={"app": str(rng.choice(("a", "b")))})
        if rng.random() < 0.2:
            pod.tolerations = [Toleration(key="dedicated", value="x", effect="NoSchedule")]
        if rng.random() < 0.15:
            pod.host_ports = (int(rng.choice((80, 443))),)
        if rng.random() < 0.15:
            pod.affinity = Affinity(
                pod_anti_affinity=(
                    PodAffinityTerm(
                        selector=LabelSelector(
                            match_labels=(("app", str(rng.choice(("a", "b")))),)
                        ),
                        topology_key="zone",
                    ),
                )
            )
        assign = ""
        if w.nodes and rng.random() < 0.6:
            assign = str(rng.choice(list(w.nodes)))
        w.pods[pod.key()] = (pod, assign)

    for _ in range(4):
        new_node()
    for _ in range(10):
        new_pod()
    w.check()

    for step in range(12):
        op = rng.random()
        if op < 0.25:
            new_pod()
        elif op < 0.4 and len(w.pods) > 3:
            del w.pods[str(rng.choice(list(w.pods)))]
        elif op < 0.5:
            new_node()
        elif op < 0.6 and len(w.nodes) > 2:
            del w.nodes[str(rng.choice(list(w.nodes)))]
        elif op < 0.75 and w.pods:
            key = str(rng.choice(list(w.pods)))
            pod, _ = w.pods[key]
            assign = str(rng.choice(list(w.nodes))) if (
                w.nodes and rng.random() < 0.7
            ) else ""
            w.pods[key] = (pod, assign)
        elif op < 0.9 and w.pods:
            # relist: same key, new object
            key = str(rng.choice(list(w.pods)))
            pod, assign = w.pods[key]
            newp = build_test_pod(
                pod.name, cpu_m=int(rng.integers(50, 900)), mem=256 * MB,
                namespace=pod.namespace, labels=dict(pod.labels),
            )
            newp.tolerations = list(pod.tolerations)
            newp.host_ports = tuple(pod.host_ports)
            newp.affinity = pod.affinity
            w.pods[key] = (newp, assign)
        else:
            # group map churn
            w.groups = {
                name: f"g{int(rng.integers(0, 3))}" for name in w.nodes
            }
        w.check()


def test_removal_only_delta_refreshes_device_mask():
    """A loop whose ONLY delta is deletions must re-upload the dense mask:
    the swap-fill rewrites host rows/columns in place, and pods/nodes of
    DIFFERENT predicate classes would otherwise inherit each other's
    verdicts on device (round-3 review finding)."""
    w = World(dense=True)
    tainted = build_test_node("tainted", cpu_m=4000, mem=8 * GB)
    tainted.taints = [Taint(key="dedicated", value="x", effect="NoSchedule")]
    w.nodes["tainted"] = tainted
    w.nodes["open"] = build_test_node("open", cpu_m=4000, mem=8 * GB)
    intolerant = build_test_pod("intolerant", cpu_m=100, mem=128 * MB)
    tolerant = build_test_pod("tolerant", cpu_m=100, mem=128 * MB)
    tolerant.tolerations = [
        Toleration(key="dedicated", value="x", effect="NoSchedule")
    ]
    w.pods[intolerant.key()] = (intolerant, "")
    w.pods[tolerant.key()] = (tolerant, "")
    w.check()
    # pod-removal-only delta: the tolerant pod (added last) swaps into the
    # freed first row — device must show its verdicts, not the intolerant's
    del w.pods[intolerant.key()]
    tensors, meta = w.check()  # assert_parity compares the DEVICE mask
    dense = np.asarray(tensors.dense_sched())
    assert dense[meta.pod_index[tolerant.key()], meta.node_index["tainted"]]
    # node-removal-only delta: removing the FIRST node swaps the open
    # column into its slot; re-add the intolerant pod first so the two
    # columns differ observably
    w.pods[intolerant.key()] = (intolerant, "")
    w.check()
    del w.nodes["tainted"]
    tensors, meta = w.check()
    dense = np.asarray(tensors.dense_sched())
    assert dense[meta.pod_index[intolerant.key()], meta.node_index["open"]]


def test_bucket_growth_triggers_full_rebuild():
    w = World()
    w.nodes["n0"] = build_test_node("n0", cpu_m=4000, mem=8 * GB)
    for i in range(4):
        p = build_test_pod(f"p{i}", cpu_m=100, mem=128 * MB)
        w.pods[p.key()] = (p, "n0")
    w.check()
    before = w.packer.full_packs
    for i in range(4, 40):  # cross the pod bucket
        p = build_test_pod(f"p{i}", cpu_m=100, mem=128 * MB)
        w.pods[p.key()] = (p, "")
    w.check()
    assert w.packer.full_packs == before + 1


def test_autoscaler_shares_packer_across_loops():
    """End-to-end: the StaticAutoscaler's persistent packer sees successive
    loops as deltas (full pack only once), and decisions stay correct."""
    from autoscaler_tpu.cloudprovider.test_provider import TestCloudProvider
    from autoscaler_tpu.config.options import AutoscalingOptions
    from autoscaler_tpu.core.static_autoscaler import StaticAutoscaler
    from autoscaler_tpu.kube.api import FakeClusterAPI

    provider = TestCloudProvider()
    api = FakeClusterAPI()
    provider.add_node_group(
        "g", 0, 10, 1, build_test_node("tmpl", cpu_m=4000, mem=8 * GB)
    )
    node = build_test_node("g-0", cpu_m=4000, mem=8 * GB)
    provider.add_node("g", node)
    api.add_node(node)
    a = StaticAutoscaler(provider, api, AutoscalingOptions())
    a.run_once(now_ts=0.0)
    packs_after_first = a._packer.full_packs
    # two pending 3000m pods: one fits the live empty node, the second
    # needs a new one — the delta loop must still decide the scale-up
    api.add_pod(build_test_pod("p0", cpu_m=3000, mem=GB))
    api.add_pod(build_test_pod("p1", cpu_m=3000, mem=GB))
    a.run_once(now_ts=10.0)
    assert provider._groups["g"].target_size() == 2  # scale-up still works
    assert a._packer.full_packs == packs_after_first  # loop 2 was a delta
    assert a._packer.incremental_updates > 0


def test_swapfill_interleaved_with_same_update_readd():
    """ISSUE 11 satellite regression: removals swap-fill rows while the
    SAME update re-adds a previously-removed key as a new object and a
    fresh key claims a freed slot — the delta-program emitter
    (snapshot/arena.py) depends on this slot bookkeeping staying stable,
    so it is pinned here against the full-pack oracle."""
    w = World()
    for i in range(3):
        w.nodes[f"n{i}"] = build_test_node(f"n{i}", cpu_m=4000, mem=8 * GB)
    for i in range(8):  # full 8-row bucket: any removal must swap-fill
        p = build_test_pod(f"p{i}", cpu_m=100, mem=128 * MB)
        w.pods[p.key()] = (p, f"n{i % 3}")
    w.check()
    # one update: drop p2 (p7 swap-fills into its row) and p5, re-add p2
    # as a NEW object with a new assignment, and a fresh key p8 claims a
    # freed slot — all in the same listing diff
    w.pods.pop("default/p2")
    w.pods.pop("default/p5")
    p2 = build_test_pod("p2", cpu_m=999, mem=256 * MB)
    w.pods[p2.key()] = (p2, "n1")
    p8 = build_test_pod("p8", cpu_m=250, mem=64 * MB)
    w.pods[p8.key()] = (p8, "")
    w.check()
    # and the NEXT update moves the re-added key again (remove a low row,
    # forcing another swap-fill of the re-added pod's row)
    w.pods.pop("default/p0")
    w.check()


def test_removed_key_readded_across_updates_lands_clean():
    """Remove → (swap-fill) → re-add of the same key one update later:
    the re-added pod must get a fresh, fully-derived row (requests, mask,
    assignment), not the stale slot state its key used to own."""
    w = World()
    for i in range(2):
        w.nodes[f"n{i}"] = build_test_node(f"n{i}", cpu_m=4000, mem=8 * GB)
    for i in range(8):
        p = build_test_pod(f"p{i}", cpu_m=100, mem=128 * MB)
        w.pods[p.key()] = (p, f"n{i % 2}")
    w.check()
    removed = w.pods.pop("default/p3")
    w.check()  # p7 swap-filled into p3's row
    # same key returns with DIFFERENT spec and placement
    p3 = build_test_pod("p3", cpu_m=777, mem=512 * MB)
    w.pods[p3.key()] = (p3, "n1")
    w.check()
    # and a reassign of the swap-filled pod in the same world still lands
    w.pods["default/p7"] = (w.pods["default/p7"][0], "n0")
    w.check()
