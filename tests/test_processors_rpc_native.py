"""Processors, native bridge, and gRPC sidecar tests."""
import numpy as np
import pytest

from autoscaler_tpu.cloudprovider.test_provider import TestCloudProvider
from autoscaler_tpu.config.options import AutoscalingOptions
from autoscaler_tpu.estimator.reference_impl import ffd_binpack_reference
from autoscaler_tpu.processors.nodegroupset import BalancingNodeGroupSetProcessor
from autoscaler_tpu.processors.nodeinfos import MixedTemplateNodeInfoProvider
from autoscaler_tpu.processors.pipeline import (
    AutoscalingProcessors,
    CustomResourcesProcessor,
    EventingScaleUpStatusProcessor,
    ScaleDownCandidatesSortingProcessor,
    default_processors,
)
from autoscaler_tpu.utils.test_utils import GB, MB, build_test_node, build_test_pod


class TestBalancingProcessor:
    def _groups(self):
        p = TestCloudProvider()
        t1 = build_test_node("t1", cpu_m=4000, mem=8 * GB)
        t2 = build_test_node("t2", cpu_m=4000, mem=8 * GB)
        t3 = build_test_node("t3", cpu_m=16000, mem=64 * GB)
        p.add_node_group("a", 0, 10, 2, t1)
        p.add_node_group("b", 0, 10, 5, t2)
        p.add_node_group("c", 0, 10, 0, t3)
        gs = {g.id(): g for g in p.node_groups()}
        templates = {"a": t1, "b": t2, "c": t3}
        return p, gs, templates

    def test_find_similar(self):
        p, gs, templates = self._groups()
        proc = BalancingNodeGroupSetProcessor()
        similar = proc.find_similar_node_groups(gs["a"], templates, list(gs.values()))
        assert [g.id() for g in similar] == ["b"]  # c differs in shape

    def test_zone_labels_ignored(self):
        proc = BalancingNodeGroupSetProcessor()
        a = build_test_node("a", labels={"topology.kubernetes.io/zone": "us-a"})
        b = build_test_node("b", labels={"topology.kubernetes.io/zone": "us-b"})
        assert proc.is_similar(a, b)
        c = build_test_node("c", labels={"disk": "ssd"})
        assert not proc.is_similar(a, c)

    def test_balancing_label_keys_mode(self):
        """--balancing-label (GL009 wiring): with label_keys set, similarity
        is decided by those label values ALONE — shape differences and
        other labels are ignored (CreateLabelNodeInfoComparator)."""
        proc = BalancingNodeGroupSetProcessor(label_keys=["pool"])
        small = build_test_node(
            "small", cpu_m=4000, mem=8 * GB, labels={"pool": "x", "disk": "ssd"}
        )
        huge = build_test_node(
            "huge", cpu_m=64000, mem=512 * GB, labels={"pool": "x"}
        )
        other = build_test_node(
            "other", cpu_m=4000, mem=8 * GB, labels={"pool": "y"}
        )
        unlabeled = build_test_node("unlabeled", cpu_m=4000, mem=8 * GB)
        assert proc.is_similar(small, huge)        # same pool: similar
        assert not proc.is_similar(small, other)   # different pool
        assert not proc.is_similar(small, unlabeled)
        assert proc.is_similar(unlabeled, build_test_node("u2", cpu_m=1))

    def test_options_wire_balancing_label_keys(self):
        from autoscaler_tpu.config.options import AutoscalingOptions
        from autoscaler_tpu.processors.pipeline import default_processors

        opts = AutoscalingOptions(balancing_label_keys=["pool"])
        procs = default_processors(opts)
        assert procs.node_group_set.label_keys == ["pool"]

    def test_balance_evens_targets(self):
        p, gs, templates = self._groups()
        proc = BalancingNodeGroupSetProcessor()
        # a=2, b=5; add 5 → a should catch up first
        out = dict(
            (g.id(), n) for g, n in proc.balance_scale_up([gs["a"], gs["b"]], 5)
        )
        assert out["a"] == 4 and out.get("b", 0) == 1  # a:2→6? no: evens to 6/6

    def test_balance_respects_max(self):
        p = TestCloudProvider()
        p.add_node_group("a", 0, 3, 2, build_test_node("t"))
        p.add_node_group("b", 0, 10, 2, build_test_node("t2"))
        gs = {g.id(): g for g in p.node_groups()}
        proc = BalancingNodeGroupSetProcessor()
        out = dict((g.id(), n) for g, n in proc.balance_scale_up(list(gs.values()), 6))
        assert out["a"] <= 1  # capped at max 3
        assert sum(out.values()) <= 6


class TestTemplateProvider:
    def test_prefers_real_node_and_sanitizes(self):
        from autoscaler_tpu.kube.api import to_be_deleted_taint

        p = TestCloudProvider()
        p.add_node_group("g", 0, 10, 1, build_test_node("synthetic", cpu_m=9999))
        real = build_test_node("real-1", cpu_m=4000)
        real.taints.append(to_be_deleted_taint())
        prov = MixedTemplateNodeInfoProvider()
        tmpl = prov.template_for(p.node_groups()[0], [real], now_ts=0.0)
        assert tmpl.allocatable.cpu_m == 4000  # from the real node
        assert tmpl.taints == []               # autoscaler taints stripped
        assert tmpl.name != "real-1"

    def test_falls_back_to_cloud_template(self):
        p = TestCloudProvider()
        p.add_node_group("g", 0, 10, 0, build_test_node("synthetic", cpu_m=1234))
        prov = MixedTemplateNodeInfoProvider()
        tmpl = prov.template_for(p.node_groups()[0], [], now_ts=0.0)
        assert tmpl.allocatable.cpu_m == 1234

    def test_ttl_cache(self):
        p = TestCloudProvider()
        p.add_node_group("g", 0, 10, 0, build_test_node("synthetic", cpu_m=1))
        prov = MixedTemplateNodeInfoProvider(ttl_s=100)
        t1 = prov.template_for(p.node_groups()[0], [], now_ts=0.0)
        real = build_test_node("real", cpu_m=5000)
        t2 = prov.template_for(p.node_groups()[0], [real], now_ts=50.0)
        assert t2 is t1  # cached
        t3 = prov.template_for(p.node_groups()[0], [real], now_ts=200.0)
        assert t3.allocatable.cpu_m == 5000


class TestOtherProcessors:
    def test_custom_resources_readiness(self):
        proc = CustomResourcesProcessor()
        pending_gpu = build_test_node("gpu-init", labels={proc.gpu_label: "a100"})
        ready_gpu = build_test_node("gpu-ok", gpu=8, labels={proc.gpu_label: "a100"})
        plain = build_test_node("cpu")
        ready, not_ready = proc.filter_out_nodes_with_unready_resources(
            [pending_gpu, ready_gpu, plain]
        )
        assert [n.name for n in not_ready] == ["gpu-init"]
        assert len(ready) == 2

    def test_candidate_sorting(self):
        proc = ScaleDownCandidatesSortingProcessor()
        a, b, c = (build_test_node(x) for x in "abc")
        proc.update(["c"])
        assert [n.name for n in proc.sort([a, b, c])] == ["c", "a", "b"]

    def test_eventing_status_processor(self):
        events = []
        proc = EventingScaleUpStatusProcessor(sink=lambda r, m: events.append((r, m)))
        from autoscaler_tpu.core.scaleup.orchestrator import ScaleUpResult

        proc.process(
            ScaleUpResult(
                scaled_up=True,
                chosen_group="g",
                new_nodes=2,
                pods_triggered=[build_test_pod("p")],
                pods_remain_unschedulable=[build_test_pod("q")],
            )
        )
        reasons = [r for r, _ in events]
        assert "TriggeredScaleUp" in reasons and "NotTriggerScaleUp" in reasons

    def test_default_container(self):
        procs = default_processors()
        assert procs.node_group_set is not None
        assert procs.template_node_info_provider is not None


class TestNativeBridge:
    def test_parity_and_availability(self):
        from autoscaler_tpu.native_bridge import available, ffd_binpack_native

        assert available()
        rng = np.random.default_rng(0)
        P = 500
        req = np.zeros((P, 6), np.float32)
        req[:, 0] = rng.integers(50, 1500, P)
        req[:, 1] = rng.integers(64, 4096, P)
        req[:, 5] = 1
        alloc = np.array([4000, 8192, 0, 0, 0, 110], np.float32)
        mask = rng.random(P) > 0.1
        c1, s1 = ffd_binpack_native(req, mask, alloc, 64)
        c2, s2 = ffd_binpack_reference(req, mask, alloc, 64)
        assert c1 == c2
        np.testing.assert_array_equal(s1, s2)

    @pytest.mark.parametrize("seed", range(6))
    def test_affinity_parity_vs_oracle(self, seed):
        from autoscaler_tpu.estimator.reference_impl import (
            ffd_binpack_reference_affinity,
        )
        from autoscaler_tpu.native_bridge import (
            available,
            ffd_binpack_affinity_native,
        )

        assert available()
        rng = np.random.default_rng(seed)
        P, T = 300, 5
        req = np.zeros((P, 6), np.float32)
        req[:, 0] = rng.integers(50, 1500, P)
        req[:, 1] = rng.integers(64, 4096, P)
        req[:, 5] = 1
        alloc = np.array([4000, 8192, 0, 0, 0, 110], np.float32)
        mask = rng.random(P) > 0.1
        match = rng.random((T, P)) < 0.15
        aff_of = (rng.random((T, P)) < 0.05) & match
        anti_of = (rng.random((T, P)) < 0.05) & match
        node_level = rng.random(T) < 0.5
        has_label = rng.random(T) < 0.8
        c1, s1 = ffd_binpack_affinity_native(
            req, mask, alloc, 64, match, aff_of, anti_of, node_level, has_label
        )
        c2, s2 = ffd_binpack_reference_affinity(
            req, mask, alloc, 64, match, aff_of, anti_of, node_level, has_label
        )
        assert c1 == c2
        np.testing.assert_array_equal(s1, s2)

    def test_first_fit_native(self):
        from autoscaler_tpu.native_bridge import first_fit_native

        req = np.array([[100, 0, 0, 0, 0, 1], [9999, 0, 0, 0, 0, 1]], np.float32)
        free = np.array([[50, 0, 0, 0, 0, 10], [500, 0, 0, 0, 0, 10]], np.float32)
        mask = np.ones((2, 2), bool)
        out = first_fit_native(req, free, mask)
        assert list(out) == [1, -1]


class TestGrpcSidecar:
    @pytest.fixture()
    def server(self):
        from autoscaler_tpu.rpc.service import serve

        server, port = serve("127.0.0.1:0")
        yield port
        server.stop(grace=None)

    def test_estimate_rpc(self, server):
        from autoscaler_tpu.rpc.service import TpuSimulationClient

        rng = np.random.default_rng(1)
        P, G = 64, 3
        req = np.zeros((P, 6), np.float32)
        req[:, 0] = rng.integers(100, 1500, P)
        req[:, 1] = rng.integers(64, 2048, P)
        req[:, 5] = 1
        masks = np.ones((G, P), bool)
        allocs = np.tile(np.array([4000, 8192, 0, 0, 0, 110], np.float32), (G, 1))
        caps = np.full(G, 32, np.int32)
        client = TpuSimulationClient(f"127.0.0.1:{server}")
        try:
            counts, scheduled = client.estimate(
                req, masks, allocs, ["a", "b", "c"], caps, max_nodes=32
            )
            ref_c, ref_s = ffd_binpack_reference(req, masks[0], allocs[0], 32)
            assert counts[0] == ref_c
            np.testing.assert_array_equal(scheduled[0], ref_s)
        finally:
            client.close()

    def _widget_world(self):
        """A world where ONLY the named extended resource gates the fit:
        cpu/mem are loose, example.com/widget (1 per pod, 2 per node) caps
        every node at 2 pods. Base-6 truncation would read ~16 pods/node."""
        P, G = 32, 2
        req = np.zeros((P, 7), np.float32)
        req[:, 0] = 100          # cpu loose vs 4000
        req[:, 1] = 128          # mem loose vs 8192
        req[:, 5] = 1            # pods
        req[:, 6] = 1            # example.com/widget — the gating axis
        masks = np.ones((G, P), bool)
        allocs = np.tile(
            np.array([4000, 8192, 0, 0, 0, 110, 2], np.float32), (G, 1)
        )
        caps = np.full(G, 64, np.int32)
        return req, masks, allocs, caps

    def test_estimate_extended_resource_changes_verdict(self, server):
        """r4 verdict missing #1: device-plugin columns must travel over the
        native sidecar RPC and keep their gating power. The widget world's
        verdict (16 nodes for 32 pods) differs from the base-6 truncation
        (2 nodes) — so the wire either carries the column or gets this
        wrong; parity is against the serial reference on the full axis."""
        from autoscaler_tpu.rpc.service import TpuSimulationClient

        req, masks, allocs, caps = self._widget_world()
        client = TpuSimulationClient(f"127.0.0.1:{server}")
        try:
            counts, scheduled = client.estimate(
                req, masks, allocs, ["a", "b"], caps, max_nodes=64,
                extended_resources=("example.com/widget",),
            )
        finally:
            client.close()
        ref_c, ref_s = ffd_binpack_reference(req, masks[0], allocs[0], 64)
        assert counts[0] == ref_c
        np.testing.assert_array_equal(scheduled[0], ref_s)
        # the column is load-bearing: truncating to base-6 changes the verdict
        trunc_c, _ = ffd_binpack_reference(
            req[:, :6], masks[0], allocs[0][:6], 64
        )
        assert trunc_c != ref_c

    def test_estimate_schema_mismatch_aborts(self, server):
        """num_resources must equal 6 + len(extended_resources): a silent
        mismatch would let a device-plugin column shadow a base axis."""
        import grpc

        from autoscaler_tpu.rpc import autoscaler_pb2 as pb
        from autoscaler_tpu.rpc.service import TpuSimulationClient

        req, masks, allocs, caps = self._widget_world()
        client = TpuSimulationClient(f"127.0.0.1:{server}")
        try:
            # client-side validation refuses the bad shape outright
            with pytest.raises(ValueError, match="schema"):
                client.estimate(
                    req, masks, allocs, ["a", "b"], caps, max_nodes=64,
                    extended_resources=("a.example/x", "b.example/y"),
                )
            # a hand-rolled caller skipping the stub hits the server check
            bad = pb.EstimateRequest(
                pods=pb.PackedPods(
                    requests=np.ascontiguousarray(req, "<f4").tobytes(),
                    num_pods=req.shape[0],
                    num_resources=7,
                    extended_resources=["a.example/x", "b.example/y"],
                ),
                pod_masks=np.ascontiguousarray(masks, np.uint8).tobytes(),
                template_allocs=np.ascontiguousarray(allocs, "<f4").tobytes(),
                group_ids=["a", "b"],
                node_caps=np.ascontiguousarray(caps, "<i4").tobytes(),
                max_nodes=64,
            )
            with pytest.raises(grpc.RpcError) as exc:
                client._call("Estimate", bad)
            assert exc.value.code() == grpc.StatusCode.INVALID_ARGUMENT
        finally:
            client.close()

    def test_estimate_extended_cross_process(self):
        """The same widget world against a sidecar in a SEPARATE PROCESS —
        the deployment shape the schema field exists for (host control
        plane → device-owning sidecar)."""
        import subprocess
        import sys

        from autoscaler_tpu.rpc.service import TpuSimulationClient

        proc = subprocess.Popen(
            [
                sys.executable,
                "-c",
                (
                    "import sys; sys.stdout.reconfigure(line_buffering=True)\n"
                    # env JAX_PLATFORMS is NOT enough in a fresh process —
                    # the axon site hook re-pins the platform at import
                    # (same workaround as conftest.py / bench.py)
                    "import jax; jax.config.update('jax_platforms', 'cpu')\n"
                    "from autoscaler_tpu.rpc.service import serve\n"
                    "server, port = serve('127.0.0.1:0')\n"
                    "print(f'PORT={port}')\n"
                    "server.wait_for_termination()\n"
                ),
            ],
            stdout=subprocess.PIPE,
            text=True,
        )
        try:
            # deadline-bounded read: a wedged child (e.g. backend init
            # hanging) must fail the test loudly, not hang the session
            import select

            port = None
            deadline = 60.0
            import time as time_mod

            t_end = time_mod.monotonic() + deadline
            buf = ""
            while time_mod.monotonic() < t_end and port is None:
                ready, _, _ = select.select(
                    [proc.stdout], [], [], min(1.0, t_end - time_mod.monotonic())
                )
                if not ready:
                    continue
                chunk = proc.stdout.readline()
                if not chunk:
                    break
                buf += chunk
                if chunk.startswith("PORT="):
                    port = int(chunk.strip().split("=", 1)[1])
            assert port, (
                f"sidecar subprocess never reported its port within "
                f"{deadline}s; output so far: {buf!r}"
            )
            req, masks, allocs, caps = self._widget_world()
            client = TpuSimulationClient(f"127.0.0.1:{port}")
            try:
                counts, _ = client.estimate(
                    req, masks, allocs, ["a", "b"], caps, max_nodes=64,
                    extended_resources=("example.com/widget",),
                )
            finally:
                client.close()
            ref_c, _ = ffd_binpack_reference(req, masks[0], allocs[0], 64)
            assert list(counts) == [ref_c, ref_c]
        finally:
            proc.kill()
            proc.wait()

    def test_best_options_rpc(self, server):
        from autoscaler_tpu.rpc import autoscaler_pb2 as pb
        from autoscaler_tpu.rpc.service import TpuSimulationClient

        client = TpuSimulationClient(f"127.0.0.1:{server}")
        try:
            best = client.best_options(
                [
                    pb.Option(group_id="few", node_count=1, pod_keys=["a"]),
                    pb.Option(group_id="many", node_count=2, pod_keys=["a", "b", "c"]),
                ]
            )
            assert [b.group_id for b in best] == ["many"]
        finally:
            client.close()

    def test_grpc_expander_filter(self, server):
        from autoscaler_tpu.expander.core import Option
        from autoscaler_tpu.expander.grpc_ import GRPCFilter

        p = TestCloudProvider()
        p.add_node_group("few", 0, 10, 0, build_test_node("t1"))
        p.add_node_group("many", 0, 10, 0, build_test_node("t2"))
        gs = {g.id(): g for g in p.node_groups()}
        options = [
            Option(gs["few"], 1, [build_test_pod("a")]),
            Option(gs["many"], 2, [build_test_pod(f"x{i}") for i in range(3)]),
        ]
        f = GRPCFilter(f"127.0.0.1:{server}")
        best = f.best_options(options)
        assert [o.node_group.id() for o in best] == ["many"]

    def test_grpc_expander_fails_open(self):
        from autoscaler_tpu.expander.core import Option
        from autoscaler_tpu.expander.grpc_ import GRPCFilter

        p = TestCloudProvider()
        p.add_node_group("g", 0, 10, 0, build_test_node("t"))
        options = [Option(p.node_groups()[0], 1, [build_test_pod("a")])]
        f = GRPCFilter("127.0.0.1:1")  # nothing listening
        assert f.best_options(options) == options


class TestNewProcessorSeams:
    """The round-2 seams (reference processors.go:36): actionable-cluster
    gate, scale-down node/set processors, autoscaling status, binpacking
    limiter, candidates observers."""

    def _autoscaler(self, pods=(), procs=None):
        from autoscaler_tpu.config.options import AutoscalingOptions
        from autoscaler_tpu.core.static_autoscaler import StaticAutoscaler
        from autoscaler_tpu.kube.api import FakeClusterAPI
        from autoscaler_tpu.utils.test_utils import GB

        provider = TestCloudProvider()
        api = FakeClusterAPI()
        provider.add_node_group(
            "g", 0, 10, 2, build_test_node("t", cpu_m=2000, mem=4 * GB)
        )
        for i in range(2):
            n = build_test_node(f"g-{i}", cpu_m=2000, mem=4 * GB)
            provider.add_node("g", n)
            api.add_node(n)
        for p in pods:
            api.add_pod(p)
        opts = AutoscalingOptions()
        opts.node_group_defaults.scale_down_unneeded_time_s = 0
        opts.scale_down_delay_after_add_s = 0
        return StaticAutoscaler(provider, api, opts, processors=procs), provider

    def test_actionable_cluster_gate_blocks_loop(self):
        from autoscaler_tpu.processors.pipeline import default_processors
        from autoscaler_tpu.utils.test_utils import GB

        procs = default_processors()

        class Frozen:
            def should_autoscale(self, nodes, now_ts):
                return False

        procs.actionable_cluster = Frozen()
        a, provider = self._autoscaler(
            [build_test_pod("p", cpu_m=1500, mem=1 * GB)], procs
        )
        r = a.run_once(now_ts=0.0)
        assert r.scale_up is None
        assert provider.scale_up_calls == []
        assert any("not actionable" in e for e in r.errors)

    def test_autoscaling_status_processor_sees_every_loop(self):
        from autoscaler_tpu.processors.pipeline import default_processors

        procs = default_processors()
        seen = []
        procs.autoscaling_status = type(
            "Obs", (), {"process": lambda self, result, now_ts: seen.append(now_ts)}
        )()
        a, _ = self._autoscaler(procs=procs)
        a.run_once(now_ts=1.0)
        a.run_once(now_ts=2.0)
        assert seen == [1.0, 2.0]

    def test_scale_down_set_processor_picks_final_set(self):
        from autoscaler_tpu.processors.pipeline import default_processors

        procs = default_processors()

        class OnlyOne:
            def get_nodes_to_remove(self, candidates, max_count):
                return candidates[:1]

        procs.scale_down_set = OnlyOne()
        a, _ = self._autoscaler(procs=procs)  # both nodes empty → removable
        r = a.run_once(now_ts=100.0)
        deleted = r.scale_down.deleted_empty if r.scale_down else []
        assert len(deleted) == 1

    def test_scale_down_node_processor_prefilters(self):
        from autoscaler_tpu.processors.pipeline import default_processors

        procs = default_processors()

        class DropAll:
            def get_scale_down_candidates(self, nodes, all_nodes):
                return []

        procs.scale_down_node = DropAll()
        a, _ = self._autoscaler(procs=procs)
        r = a.run_once(now_ts=100.0)
        assert r.unneeded_nodes == 0 and r.scale_down is None

    def test_binpacking_limiter_bounds_dispatch(self):
        from autoscaler_tpu.processors.pipeline import default_processors
        from autoscaler_tpu.utils.test_utils import GB

        procs = default_processors()

        class NoGroups:
            def limit_groups(self, viable, templates, headrooms, pending):
                return {}, {}, {}

        procs.binpacking_limiter = NoGroups()
        blockers = [
            build_test_pod(f"blocker-{i}", cpu_m=1800, node_name=f"g-{i}")
            for i in range(2)
        ]
        a, provider = self._autoscaler(
            blockers + [build_test_pod("p", cpu_m=1500, mem=1 * GB)], procs
        )
        r = a.run_once(now_ts=0.0)
        assert provider.scale_up_calls == []
        assert r.scale_up is not None and not r.scale_up.scaled_up

    def test_candidates_observers_notified(self):
        from autoscaler_tpu.processors.pipeline import default_processors

        procs = default_processors()
        heard = []
        procs.scale_down_candidates_observers.append(
            type("O", (), {"update": lambda self, names: heard.append(list(names))})()
        )
        a, _ = self._autoscaler(procs=procs)
        a.run_once(now_ts=100.0)
        assert heard and len(heard[-1]) >= 1  # empty nodes became unneeded


class TestDaemonOverheadTemplates:
    """A new node boots the group's daemonsets, so templates built from a
    real node charge its DS/mirror pods against capacity (the reference puts
    those pods INTO the template NodeInfo, simulator/nodes.go:38)."""

    def _group_with_node(self):
        provider = TestCloudProvider()
        provider.add_node_group(
            "g", 0, 10, 1, build_test_node("tmpl", cpu_m=4000, mem=8 * GB)
        )
        node = build_test_node("g-0", cpu_m=4000, mem=8 * GB)
        provider.add_node("g", node)
        return provider, node

    def test_ds_overhead_reduces_template_capacity(self):
        provider, node = self._group_with_node()
        ds = build_test_pod("kube-proxy-x", cpu_m=300, mem=512 * MB,
                            node_name="g-0")
        ds.daemonset = True
        mirror = build_test_pod("static-x", cpu_m=200, mem=256 * MB,
                                node_name="g-0")
        mirror.mirror = True
        plain = build_test_pod("app-x", cpu_m=1000, mem=GB, node_name="g-0")
        pods = {"g-0": [ds, mirror, plain]}
        prov = MixedTemplateNodeInfoProvider()
        (group,) = provider.node_groups()
        tmpl = prov.template_for(group, [node], 0.0, pods_of_node=pods.get)
        # DS + mirror become daemon_overhead; the plain workload pod is NOT
        # charged (it reschedules). allocatable keeps the node's true size so
        # resource limits and group similarity stay correct; only the
        # estimator's packing_capacity shrinks.
        assert tmpl.allocatable.cpu_m == pytest.approx(4000)
        assert tmpl.daemon_overhead.cpu_m == pytest.approx(300 + 200)
        cap = tmpl.packing_capacity()
        assert cap.cpu_m == pytest.approx(4000 - 500)
        assert cap.memory == pytest.approx(8 * GB - 768 * MB)
        assert cap.pods == pytest.approx(110 - 2)
        # cache order-independence: a caller without pods_of_node gets the
        # uncharged base even after the charged call populated the cache
        bare = prov.template_for(group, [node], 0.0)
        assert bare.daemon_overhead.cpu_m == 0.0

    def test_terminating_ds_pod_not_charged(self):
        """A DS/mirror pod with a DeletionTimestamp won't exist on a NEW
        node: charging it double-counts mid-replacement daemons, and its
        membership in running_ds_names would suppress the --force-ds
        recharge (reference skips deleted pods, simulator/nodes.go:41)."""
        from autoscaler_tpu.kube.objects import DaemonSet, OwnerRef, Resources

        provider, node = self._group_with_node()
        dying = build_test_pod("logging-agent-old", cpu_m=300, mem=256 * MB,
                               node_name="g-0", namespace="kube-system")
        dying.daemonset = True
        dying.owner_ref = OwnerRef(kind="DaemonSet", name="logging-agent")
        dying.deletion_ts = 10.0
        live = build_test_pod("kube-proxy-x", cpu_m=200, mem=128 * MB,
                              node_name="g-0")
        live.daemonset = True
        prov = MixedTemplateNodeInfoProvider()
        (group,) = provider.node_groups()
        pending = DaemonSet(
            name="logging-agent", namespace="kube-system",
            requests=Resources(cpu_m=400, memory=256 * MB),
        )
        tmpl = prov.template_for(
            group, [node], 0.0,
            pods_of_node={"g-0": [dying, live]}.get,
            pending_daemonsets=[pending],
        )
        # the dying replica is NOT charged, and it does NOT mask the
        # --force-ds recharge of its own DaemonSet (charged at 400m)
        assert tmpl.daemon_overhead.cpu_m == pytest.approx(200 + 400)

    def test_no_lookup_keeps_full_capacity(self):
        provider, node = self._group_with_node()
        prov = MixedTemplateNodeInfoProvider()
        (group,) = provider.node_groups()
        tmpl = prov.template_for(group, [node], 0.0)
        assert tmpl.allocatable.cpu_m == pytest.approx(4000)

    def test_estimator_sees_reduced_capacity_end_to_end(self):
        """RunOnce: with a fat daemonset on the group's node, fewer pending
        pods fit per new node, so the scale-up asks for more nodes."""
        from autoscaler_tpu.config.options import AutoscalingOptions
        from autoscaler_tpu.core.static_autoscaler import StaticAutoscaler
        from autoscaler_tpu.kube.api import FakeClusterAPI

        def world(with_ds):
            provider = TestCloudProvider()
            api = FakeClusterAPI()
            provider.add_node_group(
                "g", 0, 20, 1, build_test_node("t", cpu_m=4000, mem=8 * GB)
            )
            node = build_test_node("g-0", cpu_m=4000, mem=8 * GB)
            provider.add_node("g", node)
            api.add_node(node)
            if with_ds:
                ds = build_test_pod("ds-0", cpu_m=2200, mem=GB, node_name="g-0")
                ds.daemonset = True
                api.add_pod(ds)
            for i in range(8):
                api.add_pod(build_test_pod(f"p{i}", cpu_m=1500, mem=GB))
            a = StaticAutoscaler(provider, api, AutoscalingOptions())
            a.run_once(now_ts=0.0)
            return provider._groups["g"].target_size()

        lean = world(with_ds=False)   # 2 × 1500m per 4000m node
        fat = world(with_ds=True)     # DS leaves 1800m → 1 pod per node
        assert fat > lean


class TestForceDaemonSets:
    """--force-ds (simulator/nodes.go:56): DaemonSets suitable for the
    template but not yet running on its source node charge new-node
    capacity too."""

    def _pending_ds(self, name="logging-agent", cpu_m=500, selector=None,
                    tolerations=()):
        from autoscaler_tpu.kube.objects import DaemonSet, Resources

        return DaemonSet(
            name=name, namespace="kube-system",
            node_selector=dict(selector or {}),
            tolerations=list(tolerations),
            requests=Resources(cpu_m=cpu_m, memory=256 * MB),
        )

    def test_pending_ds_charged(self):
        provider = TestCloudProvider()
        provider.add_node_group(
            "g", 0, 10, 1, build_test_node("tmpl", cpu_m=4000, mem=8 * GB)
        )
        node = build_test_node("g-0", cpu_m=4000, mem=8 * GB)
        provider.add_node("g", node)
        prov = MixedTemplateNodeInfoProvider()
        (group,) = provider.node_groups()
        tmpl = prov.template_for(
            group, [node], 0.0, pods_of_node=lambda n: [],
            pending_daemonsets=[self._pending_ds()],
        )
        assert tmpl.daemon_overhead.cpu_m == pytest.approx(500)
        assert tmpl.daemon_overhead.pods == pytest.approx(1)

    def test_running_ds_not_double_charged(self):
        from autoscaler_tpu.kube.objects import OwnerRef

        provider = TestCloudProvider()
        provider.add_node_group(
            "g", 0, 10, 1, build_test_node("tmpl", cpu_m=4000, mem=8 * GB)
        )
        node = build_test_node("g-0", cpu_m=4000, mem=8 * GB)
        provider.add_node("g", node)
        running = build_test_pod("logging-agent-x", cpu_m=500,
                                 mem=256 * MB, node_name="g-0",
                                 namespace="kube-system")
        running.daemonset = True
        running.owner_ref = OwnerRef(kind="DaemonSet", name="logging-agent")
        prov = MixedTemplateNodeInfoProvider()
        (group,) = provider.node_groups()
        tmpl = prov.template_for(
            group, [node], 0.0,
            pods_of_node={"g-0": [running]}.get,
            pending_daemonsets=[self._pending_ds()],
        )
        # charged ONCE via the running pod, not again as pending
        assert tmpl.daemon_overhead.cpu_m == pytest.approx(500)

    def test_unsuitable_ds_not_charged(self):
        provider = TestCloudProvider()
        provider.add_node_group(
            "g", 0, 10, 1, build_test_node("tmpl", cpu_m=4000, mem=8 * GB)
        )
        node = build_test_node("g-0", cpu_m=4000, mem=8 * GB)
        provider.add_node("g", node)
        prov = MixedTemplateNodeInfoProvider()
        (group,) = provider.node_groups()
        tmpl = prov.template_for(
            group, [node], 0.0, pods_of_node=lambda n: [],
            pending_daemonsets=[
                self._pending_ds(selector={"accel": "gpu"})  # label absent
            ],
        )
        assert tmpl.daemon_overhead.cpu_m == pytest.approx(0)

    def test_tainted_template_needs_toleration(self):
        from autoscaler_tpu.kube.objects import Taint, Toleration

        provider = TestCloudProvider()
        tainted_tmpl = build_test_node("tmpl", cpu_m=4000, mem=8 * GB)
        tainted_tmpl.taints.append(Taint("dedicated", "tpu"))
        provider.add_node_group("g", 0, 10, 0, tainted_tmpl)
        prov = MixedTemplateNodeInfoProvider()
        (group,) = provider.node_groups()
        no_tol = prov.template_for(
            group, [], 0.0, pods_of_node=lambda n: [],
            pending_daemonsets=[self._pending_ds()],
        )
        # synthetic templates keep their taints; intolerant DS is unsuitable
        assert no_tol.daemon_overhead.cpu_m == pytest.approx(0)
        prov.invalidate()
        tol = prov.template_for(
            group, [], 0.0, pods_of_node=lambda n: [],
            pending_daemonsets=[
                self._pending_ds(tolerations=[Toleration(operator="Exists")])
            ],
        )
        assert tol.daemon_overhead.cpu_m == pytest.approx(500)

    def test_kube_client_lists_daemonsets(self):
        import sys
        sys.path.insert(0, "tests")
        from test_kube_client import FakeApiServer

        from autoscaler_tpu.kube.client import KubeClusterAPI, KubeRestClient

        srv = FakeApiServer()
        try:
            srv.daemonsets = [{
                "metadata": {"name": "fluentd", "namespace": "kube-system"},
                "spec": {"template": {"spec": {
                    "nodeSelector": {"pool": "logs"},
                    "tolerations": [{"operator": "Exists"}],
                    "containers": [{"name": "c", "resources": {
                        "requests": {"cpu": "150m", "memory": "200Mi"}}}],
                }}},
            }]
            api = KubeClusterAPI(KubeRestClient(srv.url))
            (ds,) = api.list_daemonsets()
            assert ds.key() == "kube-system/fluentd"
            assert ds.node_selector == {"pool": "logs"}
            assert ds.requests.cpu_m == pytest.approx(150)
            assert ds.tolerations[0].operator == "Exists"
        finally:
            srv.close()

    def test_idle_loop_issues_no_daemonset_list(self):
        """--force-ds on an idle cluster (nothing pending, nothing upcoming)
        must not LIST daemonsets every scan interval."""
        from autoscaler_tpu.config.options import AutoscalingOptions
        from autoscaler_tpu.core.static_autoscaler import StaticAutoscaler
        from autoscaler_tpu.kube.api import FakeClusterAPI

        calls = []

        class CountingApi(FakeClusterAPI):
            def list_daemonsets(self):
                calls.append(1)
                return super().list_daemonsets()

        provider = TestCloudProvider()
        api = CountingApi()
        provider.add_node_group("g", 0, 10, 1,
                                build_test_node("t", cpu_m=4000, mem=8 * GB))
        node = build_test_node("g-0", cpu_m=4000, mem=8 * GB)
        provider.add_node("g", node)
        api.add_node(node)
        a = StaticAutoscaler(provider, api,
                             AutoscalingOptions(force_daemonsets=True))
        a.run_once(now_ts=0.0)   # idle: no pending pods, no upcoming nodes
        assert calls == []
        # demand appears (pod too big for existing free capacity, so it
        # stays pending into scale-up) → exactly one LIST this loop
        api.add_pod(build_test_pod("p", cpu_m=4500, mem=GB))
        a.run_once(now_ts=700.0)
        assert len(calls) == 1
