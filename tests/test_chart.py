"""Helm chart sanity: every template renders to valid YAML with the default
values, the flags the deployment passes exist in the CLI, and the RBAC rules
cover what the control loop touches (modeled on the reference chart's CI lint
gate, .github/workflows/pr.yaml chart job)."""
import pathlib
import re

import yaml

CHART = pathlib.Path(__file__).parent.parent / "deploy" / "chart" / "tpu-autoscaler"


def load_values():
    return yaml.safe_load((CHART / "values.yaml").read_text())


def render(text, values, namespace="kube-system"):
    """Minimal helm renderer: ``{{ .Values.x.y }}`` / ``{{ $.Values.x.y }}``
    / ``{{ .Release.Namespace }}`` substitution, whole-line
    ``{{- if .Values.x }} … {{- end }}`` guards, and whole-line
    ``{{- range $i := until (int .Values.x) }} … {{- end }}`` loops with
    ``{{ $i }}`` in the body (the fleet-HA per-replica endpoint wiring) —
    the chart deliberately sticks to these forms so it stays testable
    without a helm binary."""

    def lookup(path):
        cur = values
        for part in path.split(".")[2:]:
            cur = cur[part]
        return cur

    IF_RE = re.compile(r"^\s*\{\{-?\s*if\s+(\.Values\.[\w.]+)\s*-?\}\}\s*$")
    RANGE_RE = re.compile(
        r"^\s*\{\{-?\s*range\s+\$(\w+)\s*:=\s*until\s+"
        r"\(int\s+(\.Values\.[\w.]+)\)\s*-?\}\}\s*$"
    )
    END_RE = re.compile(r"^\s*\{\{-?\s*end\s*-?\}\}\s*$")

    def parse(lines, i):
        """→ (block nodes, index past the closing end, end-seen). Nodes:
        ("line", text) | ("if", path, body) | ("range", var, path, body)."""
        nodes = []
        while i < len(lines):
            line = lines[i]
            if END_RE.match(line):
                return nodes, i + 1, True
            m = IF_RE.match(line)
            if m:
                body, i, closed = parse(lines, i + 1)
                assert closed, "unclosed {{- if }}"
                nodes.append(("if", m.group(1), body))
                continue
            m = RANGE_RE.match(line)
            if m:
                body, i, closed = parse(lines, i + 1)
                assert closed, "unclosed {{- range }}"
                nodes.append(("range", m.group(1), m.group(2), body))
                continue
            nodes.append(("line", line))
            i += 1
        return nodes, i, False

    def sub_line(line, env):
        def sub(m):
            expr = m.group(1).strip()
            if expr in (".Release.Namespace", "$.Release.Namespace"):
                return namespace
            if expr.startswith(".Values.") or expr.startswith("$.Values."):
                return str(lookup(expr.lstrip("$")))
            if expr.startswith("$") and expr[1:] in env:
                return str(env[expr[1:]])
            raise AssertionError(f"unsupported template expr {expr!r}")

        return re.sub(r"\{\{([^}]+)\}\}", sub, line)

    out = []

    def emit(nodes, env):
        for node in nodes:
            if node[0] == "line":
                out.append(sub_line(node[1], env))
            elif node[0] == "if":
                # helm truthiness for our value types: empty string /
                # false / 0 / None are falsy
                if lookup(node[1]):
                    emit(node[2], env)
            else:
                _, var, path, body = node
                for k in range(int(lookup(path))):
                    emit(body, {**env, var: k})

    lines = text.splitlines()
    nodes, _, closed = parse(lines, 0)
    assert not closed, "unbalanced {{- end }}"
    emit(nodes, {})
    return "\n".join(out) + "\n"


def test_chart_and_values_parse():
    chart = yaml.safe_load((CHART / "Chart.yaml").read_text())
    assert chart["name"] == "tpu-autoscaler"
    values = load_values()
    assert values["rbac"]["serviceAccountName"]


def test_all_templates_render_to_valid_yaml():
    values = load_values()
    rendered = {}
    for tpl in sorted((CHART / "templates").glob("*.yaml")):
        out = render(tpl.read_text(), values)
        docs = list(yaml.safe_load_all(out))
        assert docs and all(d for d in docs), tpl.name
        rendered[tpl.name] = docs
    kinds = {d["kind"] for docs in rendered.values() for d in docs}
    assert {
        "Deployment",
        "ServiceAccount",
        "ClusterRole",
        "ClusterRoleBinding",
        "Service",
        "PodDisruptionBudget",
    } <= kinds


def test_deployment_flags_exist_in_cli():
    values = load_values()
    out = render((CHART / "templates" / "deployment.yaml").read_text(), values)
    dep = yaml.safe_load(out)
    args = dep["spec"]["template"]["spec"]["containers"][0]["args"]
    cli = (CHART.parent.parent.parent / "autoscaler_tpu" / "main.py").read_text()
    for arg in args:
        flag = arg.split("=")[0]
        assert f'"{flag}"' in cli, f"chart passes unknown flag {flag}"


def test_rbac_covers_loop_needs():
    values = load_values()
    out = render((CHART / "templates" / "clusterrole.yaml").read_text(), values)
    role = yaml.safe_load(out)
    granted = set()
    for rule in role["rules"]:
        for res in rule["resources"]:
            for verb in rule["verbs"]:
                granted.add((res, verb))
    # the loop's write surface: taints, evictions, status configmap, lease
    for need in [
        ("nodes", "update"),
        ("pods/eviction", "create"),
        ("configmaps", "update"),
        ("leases", "update"),
        ("poddisruptionbudgets", "list"),
    ]:
        assert need in granted, need


def test_sidecar_drain_wiring():
    """Fleet overload armor (ISSUE 14): the sidecar must pass the armor
    flags, expose the health port, probe readiness on /healthz, and wire
    preStop to /drain — the drain bit IS the readiness signal."""
    values = load_values()
    out = render((CHART / "templates" / "deployment.yaml").read_text(), values)
    dep = yaml.safe_load(out)
    containers = dep["spec"]["template"]["spec"]["containers"]
    sidecar = next(c for c in containers if c["name"] == "tpu-sidecar")
    cmd = sidecar["command"]
    for flag, value in [
        ("--fleet-max-queue-depth", str(values["fleet"]["maxQueueDepth"])),
        ("--fleet-tenant-qps", str(values["fleet"]["tenantQps"])),
        ("--fleet-tenant-burst", str(values["fleet"]["tenantBurst"])),
        ("--fleet-drain-grace-s", str(values["fleet"]["drainGraceS"])),
        ("--health-port", str(values["sidecar"]["healthPort"])),
    ]:
        assert flag in cmd, f"sidecar missing {flag}"
        assert cmd[cmd.index(flag) + 1] == value
    health_port = values["sidecar"]["healthPort"]
    probe = sidecar["readinessProbe"]["httpGet"]
    assert probe["path"] == "/healthz" and probe["port"] == health_port
    pre_stop = sidecar["lifecycle"]["preStop"]["httpGet"]
    assert pre_stop["path"] == "/drain" and pre_stop["port"] == health_port
    ports = {p["containerPort"] for p in sidecar["ports"]}
    assert health_port in ports


def test_sidecar_flags_exist_in_launcher_cli():
    """Every flag the chart passes to the sidecar must exist in the
    launcher's parser (the sidecar analog of the control-plane flag
    check) — a chart flag the launcher doesn't parse crashes the pod."""
    values = load_values()
    out = render((CHART / "templates" / "deployment.yaml").read_text(), values)
    dep = yaml.safe_load(out)
    containers = dep["spec"]["template"]["spec"]["containers"]
    sidecar = next(c for c in containers if c["name"] == "tpu-sidecar")
    launcher = (
        CHART.parent.parent.parent / "autoscaler_tpu" / "rpc" / "__main__.py"
    ).read_text()
    for arg in sidecar["command"]:
        if arg.startswith("--"):
            assert f'"{arg}"' in launcher, f"sidecar passes unknown flag {arg}"


def test_fleet_ha_replica_and_tier_wiring():
    """Fleet HA (ISSUE 15): `sidecar.replicas` must drive BOTH the
    replica StatefulSet's size and the control plane's --rpc-address
    failover list (in-pod endpoint + one stable DNS name per replica),
    and `fleet.tenantTiers` must reach EVERY sidecar launcher as
    --fleet-tenant-tiers with JSON that actually parses."""
    import json

    values = load_values()
    values["sidecar"]["replicas"] = 3
    out = render((CHART / "templates" / "deployment.yaml").read_text(), values)
    dep = yaml.safe_load(out)
    control = dep["spec"]["template"]["spec"]["containers"][0]
    addrs = [a.split("=", 1)[1] for a in control["args"]
             if a.startswith("--rpc-address=")]
    assert addrs[0] == values["sidecar"]["grpcAddress"]
    assert addrs[1:] == [
        f"tpu-autoscaler-sidecar-{i}.tpu-autoscaler-sidecar."
        f"kube-system.svc:9090"
        for i in range(3)
    ]
    assert any(a.startswith("--rpc-hedge=") for a in control["args"])
    # the replica pool: StatefulSet sized by the same value, headless
    # Service for the per-replica DNS the address list enumerates
    ha = render(
        (CHART / "templates" / "sidecar-fleet.yaml").read_text(), values
    )
    sts, svc = list(yaml.safe_load_all(ha))
    assert sts["kind"] == "StatefulSet" and sts["spec"]["replicas"] == 3
    assert sts["spec"]["serviceName"] == "tpu-autoscaler-sidecar"
    # k8s headless marker is the literal string "None" (YAML null would
    # mean "allocate a ClusterIP")
    assert svc["kind"] == "Service" and svc["spec"]["clusterIP"] == "None"
    # tenant tiers reach both launchers, and the JSON is real JSON with
    # the mandatory default tier
    for sidecar in (
        dep["spec"]["template"]["spec"]["containers"][1],
        sts["spec"]["template"]["spec"]["containers"][0],
    ):
        cmd = sidecar["command"]
        assert "--fleet-tenant-tiers" in cmd, sidecar["name"]
        tiers = json.loads(cmd[cmd.index("--fleet-tenant-tiers") + 1])
        assert "default" in tiers
        # readiness/drain wiring on the replicas too
        assert sidecar["readinessProbe"]["httpGet"]["path"] == "/healthz"
        assert sidecar["lifecycle"]["preStop"]["httpGet"]["path"] == "/drain"
    # every StatefulSet launcher flag exists in the launcher CLI
    launcher = (
        CHART.parent.parent.parent / "autoscaler_tpu" / "rpc" / "__main__.py"
    ).read_text()
    for arg in sts["spec"]["template"]["spec"]["containers"][0]["command"]:
        if arg.startswith("--"):
            assert f'"{arg}"' in launcher, f"replica passes unknown flag {arg}"


def test_empty_compile_cache_dir_renders_valid_deployment():
    """arena.compileCacheDir: \"\" (cache disabled) must drop the flag,
    the volumeMount, AND the volume — a bare `mountPath:` is an invalid
    manifest the API server rejects."""
    values = load_values()
    values["arena"]["compileCacheDir"] = ""
    out = render((CHART / "templates" / "deployment.yaml").read_text(), values)
    dep = yaml.safe_load(out)
    spec = dep["spec"]["template"]["spec"]
    control = spec["containers"][0]
    assert not any("--compile-cache-dir" in a for a in control["args"])
    assert "volumeMounts" not in control
    assert "volumes" not in spec
