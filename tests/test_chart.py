"""Helm chart sanity: every template renders to valid YAML with the default
values, the flags the deployment passes exist in the CLI, and the RBAC rules
cover what the control loop touches (modeled on the reference chart's CI lint
gate, .github/workflows/pr.yaml chart job)."""
import pathlib
import re

import yaml

CHART = pathlib.Path(__file__).parent.parent / "deploy" / "chart" / "tpu-autoscaler"


def load_values():
    return yaml.safe_load((CHART / "values.yaml").read_text())


def render(text, values, namespace="kube-system"):
    """Minimal {{ .Values.x.y }} / {{ .Release.Namespace }} renderer plus
    whole-line ``{{- if .Values.x }} … {{- end }}`` guards — the chart
    deliberately sticks to these two forms so it stays testable without a
    helm binary."""

    def lookup(path):
        cur = values
        for part in path.split(".")[2:]:
            cur = cur[part]
        return cur

    # line-based conditional blocks: include the body iff every enclosing
    # guard's value is truthy (helm truthiness for our value types:
    # empty string / false / 0 / None are falsy)
    out_lines = []
    stack = []
    for line in text.splitlines():
        m_if = re.match(r"^\s*\{\{-?\s*if\s+(\.Values\.[\w.]+)\s*-?\}\}\s*$", line)
        m_end = re.match(r"^\s*\{\{-?\s*end\s*-?\}\}\s*$", line)
        if m_if:
            stack.append(bool(lookup(m_if.group(1))))
            continue
        if m_end:
            assert stack, "unbalanced {{- end }}"
            stack.pop()
            continue
        if all(stack):
            out_lines.append(line)
    assert not stack, "unclosed {{- if }}"
    text = "\n".join(out_lines) + "\n"

    def sub(m):
        expr = m.group(1).strip()
        if expr == ".Release.Namespace":
            return namespace
        if expr.startswith(".Values."):
            return str(lookup(expr))
        raise AssertionError(f"unsupported template expr {expr!r}")

    return re.sub(r"\{\{([^}]+)\}\}", sub, text)


def test_chart_and_values_parse():
    chart = yaml.safe_load((CHART / "Chart.yaml").read_text())
    assert chart["name"] == "tpu-autoscaler"
    values = load_values()
    assert values["rbac"]["serviceAccountName"]


def test_all_templates_render_to_valid_yaml():
    values = load_values()
    rendered = {}
    for tpl in sorted((CHART / "templates").glob("*.yaml")):
        out = render(tpl.read_text(), values)
        docs = list(yaml.safe_load_all(out))
        assert docs and all(d for d in docs), tpl.name
        rendered[tpl.name] = docs
    kinds = {d["kind"] for docs in rendered.values() for d in docs}
    assert {
        "Deployment",
        "ServiceAccount",
        "ClusterRole",
        "ClusterRoleBinding",
        "Service",
        "PodDisruptionBudget",
    } <= kinds


def test_deployment_flags_exist_in_cli():
    values = load_values()
    out = render((CHART / "templates" / "deployment.yaml").read_text(), values)
    dep = yaml.safe_load(out)
    args = dep["spec"]["template"]["spec"]["containers"][0]["args"]
    cli = (CHART.parent.parent.parent / "autoscaler_tpu" / "main.py").read_text()
    for arg in args:
        flag = arg.split("=")[0]
        assert f'"{flag}"' in cli, f"chart passes unknown flag {flag}"


def test_rbac_covers_loop_needs():
    values = load_values()
    out = render((CHART / "templates" / "clusterrole.yaml").read_text(), values)
    role = yaml.safe_load(out)
    granted = set()
    for rule in role["rules"]:
        for res in rule["resources"]:
            for verb in rule["verbs"]:
                granted.add((res, verb))
    # the loop's write surface: taints, evictions, status configmap, lease
    for need in [
        ("nodes", "update"),
        ("pods/eviction", "create"),
        ("configmaps", "update"),
        ("leases", "update"),
        ("poddisruptionbudgets", "list"),
    ]:
        assert need in granted, need


def test_sidecar_drain_wiring():
    """Fleet overload armor (ISSUE 14): the sidecar must pass the armor
    flags, expose the health port, probe readiness on /healthz, and wire
    preStop to /drain — the drain bit IS the readiness signal."""
    values = load_values()
    out = render((CHART / "templates" / "deployment.yaml").read_text(), values)
    dep = yaml.safe_load(out)
    containers = dep["spec"]["template"]["spec"]["containers"]
    sidecar = next(c for c in containers if c["name"] == "tpu-sidecar")
    cmd = sidecar["command"]
    for flag, value in [
        ("--fleet-max-queue-depth", str(values["fleet"]["maxQueueDepth"])),
        ("--fleet-tenant-qps", str(values["fleet"]["tenantQps"])),
        ("--fleet-tenant-burst", str(values["fleet"]["tenantBurst"])),
        ("--fleet-drain-grace-s", str(values["fleet"]["drainGraceS"])),
        ("--health-port", str(values["sidecar"]["healthPort"])),
    ]:
        assert flag in cmd, f"sidecar missing {flag}"
        assert cmd[cmd.index(flag) + 1] == value
    health_port = values["sidecar"]["healthPort"]
    probe = sidecar["readinessProbe"]["httpGet"]
    assert probe["path"] == "/healthz" and probe["port"] == health_port
    pre_stop = sidecar["lifecycle"]["preStop"]["httpGet"]
    assert pre_stop["path"] == "/drain" and pre_stop["port"] == health_port
    ports = {p["containerPort"] for p in sidecar["ports"]}
    assert health_port in ports


def test_sidecar_flags_exist_in_launcher_cli():
    """Every flag the chart passes to the sidecar must exist in the
    launcher's parser (the sidecar analog of the control-plane flag
    check) — a chart flag the launcher doesn't parse crashes the pod."""
    values = load_values()
    out = render((CHART / "templates" / "deployment.yaml").read_text(), values)
    dep = yaml.safe_load(out)
    containers = dep["spec"]["template"]["spec"]["containers"]
    sidecar = next(c for c in containers if c["name"] == "tpu-sidecar")
    launcher = (
        CHART.parent.parent.parent / "autoscaler_tpu" / "rpc" / "__main__.py"
    ).read_text()
    for arg in sidecar["command"]:
        if arg.startswith("--"):
            assert f'"{arg}"' in launcher, f"sidecar passes unknown flag {arg}"


def test_empty_compile_cache_dir_renders_valid_deployment():
    """arena.compileCacheDir: \"\" (cache disabled) must drop the flag,
    the volumeMount, AND the volume — a bare `mountPath:` is an invalid
    manifest the API server rejects."""
    values = load_values()
    values["arena"]["compileCacheDir"] = ""
    out = render((CHART / "templates" / "deployment.yaml").read_text(), values)
    dep = yaml.safe_load(out)
    spec = dep["spec"]["template"]["spec"]
    control = spec["containers"][0]
    assert not any("--compile-cache-dir" in a for a in control["args"])
    assert "volumeMounts" not in control
    assert "volumes" not in spec
