"""Snapshot tests: fork/revert/commit semantics (modeled on the reference's
cluster-autoscaler/simulator/clustersnapshot/clustersnapshot_test.go) plus
packer/mask correctness for taints, selectors, and (anti-)affinity."""
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from autoscaler_tpu.snapshot.tensors import empty_snapshot

from autoscaler_tpu.kube.objects import (
    CPU,
    MEMORY,
    PODS,
    Taint,
    Toleration,
)
from autoscaler_tpu.snapshot.cluster_snapshot import ClusterSnapshot, SnapshotError
from autoscaler_tpu.snapshot.packer import pack
from autoscaler_tpu.utils.test_utils import (
    MB,
    anti_affinity,
    build_test_node,
    build_test_pod,
    pod_affinity,
)


def test_pack_shapes_and_padding():
    nodes = [build_test_node(f"n{i}") for i in range(3)]
    pods = [build_test_pod(f"p{i}") for i in range(5)]
    t, meta = pack(nodes, pods)
    assert t.num_nodes >= 3 and t.num_pods >= 5
    assert int(t.node_valid.sum()) == 3
    assert int(t.pod_valid.sum()) == 5
    # power-of-two bucketing
    assert t.num_nodes == 8 and t.num_pods == 8


def test_pack_used_accounting():
    nodes = [build_test_node("n0", cpu_m=1000)]
    pods = [
        build_test_pod("p0", cpu_m=300, node_name="n0"),
        build_test_pod("p1", cpu_m=200, node_name="n0"),
        build_test_pod("p2", cpu_m=100),  # pending
    ]
    t, meta = pack(nodes, pods)
    j = meta.node_index["n0"]
    assert t.node_used[j, CPU] == pytest.approx(500)
    assert t.node_used[j, PODS] == pytest.approx(2)
    free = np.asarray(t.free())
    assert free[j, CPU] == pytest.approx(500)
    assert int(t.pod_node[meta.pod_index["default/p2"]]) == -1


def test_mask_taints_and_tolerations():
    tainted = build_test_node("tainted", taints=[Taint("dedicated", "gpu")])
    clean = build_test_node("clean")
    tol = build_test_pod("tol", tolerations=[Toleration(key="dedicated", value="gpu")])
    plain = build_test_pod("plain")
    t, meta = pack([tainted, clean], [tol, plain])
    m = np.asarray(t.sched_mask)
    ti, ci = meta.node_index["tainted"], meta.node_index["clean"]
    assert m[meta.pod_index["default/tol"], ti]
    assert not m[meta.pod_index["default/plain"], ti]
    assert m[meta.pod_index["default/plain"], ci]


def test_mask_node_selector():
    gpu_node = build_test_node("gpu", labels={"accel": "tpu"})
    cpu_node = build_test_node("cpu")
    pod = build_test_pod("p", node_selector={"accel": "tpu"})
    t, meta = pack([gpu_node, cpu_node], [pod])
    m = np.asarray(t.sched_mask)
    assert m[0, meta.node_index["gpu"]]
    assert not m[0, meta.node_index["cpu"]]


def test_mask_anti_affinity_against_placed():
    n0, n1 = build_test_node("n0"), build_test_node("n1")
    placed = build_test_pod("placed", labels={"app": "db"}, node_name="n0")
    incoming = build_test_pod("in", affinity=anti_affinity({"app": "db"}))
    t, meta = pack([n0, n1], [placed, incoming])
    m = np.asarray(t.sched_mask)
    i = meta.pod_index["default/in"]
    assert not m[i, meta.node_index["n0"]]
    assert m[i, meta.node_index["n1"]]


def test_mask_symmetric_anti_affinity():
    # the *placed* pod declares anti-affinity; the incoming pod matches it
    n0, n1 = build_test_node("n0"), build_test_node("n1")
    placed = build_test_pod(
        "placed", node_name="n0", affinity=anti_affinity({"app": "web"})
    )
    incoming = build_test_pod("in", labels={"app": "web"})
    t, meta = pack([n0, n1], [placed, incoming])
    m = np.asarray(t.sched_mask)
    i = meta.pod_index["default/in"]
    assert not m[i, meta.node_index["n0"]]
    assert m[i, meta.node_index["n1"]]


def test_mask_pod_affinity():
    n0, n1 = build_test_node("n0"), build_test_node("n1")
    placed = build_test_pod("placed", labels={"app": "cache"}, node_name="n1")
    incoming = build_test_pod("in", affinity=pod_affinity({"app": "cache"}))
    t, meta = pack([n0, n1], [placed, incoming])
    m = np.asarray(t.sched_mask)
    i = meta.pod_index["default/in"]
    assert m[i, meta.node_index["n1"]]
    assert not m[i, meta.node_index["n0"]]


def test_mask_unschedulable_node():
    n = build_test_node("n0")
    n.unschedulable = True
    t, meta = pack([n], [build_test_pod("p")])
    assert not np.asarray(t.sched_mask)[0, 0]


def test_pod_profile_interning():
    """profile_key/profile_id: equal (namespace, labels) share one global
    id; the id is instance-memoized and survives dataclasses.replace of
    unrelated fields; pod_profile_value round-trips."""
    import dataclasses

    from autoscaler_tpu.kube.objects import pod_profile_value

    a = build_test_pod("a", labels={"app": "web", "tier": "fe"})
    b = build_test_pod("b", labels={"tier": "fe", "app": "web"})  # other order
    c = build_test_pod("c", labels={"app": "web"})
    assert a.profile_key() == b.profile_key()
    assert a.profile_id() == b.profile_id()
    assert a.profile_id() != c.profile_id()
    ns, labels = pod_profile_value(a.profile_id())
    assert ns == a.namespace and labels == a.labels
    a2 = dataclasses.replace(a, priority=7)
    assert a2.profile_id() == a.profile_id()


def test_pod_profile_registry_epoch_reset(monkeypatch):
    """Past the cap the registry resets (long-lived leaders see per-pod-
    unique labels — controller-revision-hash etc. — and must not grow
    without bound); memoized ids from the old epoch lazily re-intern and
    pod_profile_value stays consistent."""
    from autoscaler_tpu.kube import objects as o

    monkeypatch.setattr(o, "_POD_PROFILE_CAP", 2)
    old = build_test_pod("old", labels={"k": "old"})
    old_id = old.profile_id()
    # mint fresh profiles until a reset happens
    fresh = [
        build_test_pod(f"f{i}", labels={"rev": f"r{i}-{id(object())}"})
        for i in range(4)
    ]
    for p in fresh:
        p.profile_id()
    # old pod's memo is from a previous epoch: re-intern, stay consistent
    nid = old.profile_id()
    ns, labels = o.pod_profile_value(nid)
    assert ns == old.namespace and labels == old.labels
    assert old.profile_id() == nid  # stable within the new epoch
    del old_id


class TestClusterSnapshot:
    def test_add_and_list(self):
        s = ClusterSnapshot()
        s.add_node(build_test_node("n0"))
        s.add_pod(build_test_pod("p0"), "n0")
        assert [n.name for n in s.nodes()] == ["n0"]
        assert s.assignment("default/p0") == "n0"

    def test_fork_revert(self):
        s = ClusterSnapshot()
        s.add_node(build_test_node("n0"))
        s.fork()
        s.add_node(build_test_node("n1"))
        s.add_pod(build_test_pod("p0"), "n1")
        assert len(s.nodes()) == 2
        s.revert()
        assert [n.name for n in s.nodes()] == ["n0"]
        assert s.pods() == []

    def test_fork_commit(self):
        s = ClusterSnapshot()
        s.add_node(build_test_node("n0"))
        s.fork()
        s.add_node(build_test_node("n1"))
        s.commit()
        assert len(s.nodes()) == 2
        assert s.fork_depth == 0

    def test_nested_forks(self):
        s = ClusterSnapshot()
        s.add_node(build_test_node("n0"))
        s.fork()
        s.add_node(build_test_node("n1"))
        s.fork()
        s.add_node(build_test_node("n2"))
        assert len(s.nodes()) == 3
        s.revert()
        assert len(s.nodes()) == 2
        s.commit()
        assert len(s.nodes()) == 2
        assert s.get_node("n1") is not None

    def test_remove_in_fork_then_revert(self):
        s = ClusterSnapshot()
        s.add_node(build_test_node("n0"))
        s.add_pod(build_test_pod("p0"), "n0")
        s.fork()
        s.remove_node("n0")
        assert s.nodes() == [] and s.pods() == []
        s.revert()
        assert len(s.nodes()) == 1 and len(s.pods()) == 1

    def test_duplicate_add_raises(self):
        s = ClusterSnapshot()
        s.add_node(build_test_node("n0"))
        with pytest.raises(SnapshotError):
            s.add_node(build_test_node("n0"))

    def test_schedule_pending_pod(self):
        s = ClusterSnapshot()
        s.add_node(build_test_node("n0"))
        s.add_pod(build_test_pod("p0"))
        assert len(s.pending_pods()) == 1
        s.schedule_pod("default/p0", "n0")
        assert s.pending_pods() == []
        assert s.pods_on_node("n0")[0].name == "p0"

    def test_tensor_cache_invalidation(self):
        s = ClusterSnapshot()
        s.add_node(build_test_node("n0", cpu_m=1000))
        t1, m1 = s.tensors()
        t2, m2 = s.tensors()
        assert t1 is t2  # cached
        s.add_pod(build_test_pod("p0", cpu_m=100), "n0")
        t3, m3 = s.tensors()
        assert t3 is not t1
        assert float(t3.node_used[m3.node_index["n0"], CPU]) == pytest.approx(100)

    def test_tensors_reflect_fork_assignment(self):
        s = ClusterSnapshot()
        s.add_node(build_test_node("n0", cpu_m=1000))
        s.add_pod(build_test_pod("p0", cpu_m=250))
        s.fork()
        s.schedule_pod("default/p0", "n0")
        t, meta = s.tensors()
        assert float(t.node_used[meta.node_index["n0"], CPU]) == pytest.approx(250)
        s.revert()
        t, meta = s.tensors()
        assert float(t.node_used[meta.node_index["n0"], CPU]) == pytest.approx(0)


def test_mask_host_port_conflict_for_placed_pod():
    # a placed hostPort pod must see conflicts on OTHER nodes (drain refit
    # path) but never conflict with itself on its own node
    n0, n1 = build_test_node("n0"), build_test_node("n1")
    p1 = build_test_pod("p1", node_name="n0")
    p1.host_ports = (80,)
    p2 = build_test_pod("p2", node_name="n1")
    p2.host_ports = (80,)
    t, meta = pack([n0, n1], [p1, p2])
    m = np.asarray(t.sched_mask)
    i1, i2 = meta.pod_index["default/p1"], meta.pod_index["default/p2"]
    j0, j1 = meta.node_index["n0"], meta.node_index["n1"]
    assert m[i1, j0] and m[i2, j1]      # each fine where it runs
    assert not m[i1, j1] and not m[i2, j0]  # conflict across


def test_mask_pod_affinity_self_match():
    # first pod of a self-affine group must be schedulable (k8s self-match rule)
    n0 = build_test_node("n0")
    p = build_test_pod("p", labels={"app": "db"}, affinity=pod_affinity({"app": "db"}))
    t, meta = pack([n0], [p])
    assert np.asarray(t.sched_mask)[0, meta.node_index["n0"]]


def test_mask_symmetric_anti_affinity_not_self():
    # a placed pod whose anti-affinity matches its own labels stays valid on
    # its own node
    n0 = build_test_node("n0")
    p = build_test_pod(
        "p", labels={"app": "web"}, node_name="n0",
        affinity=anti_affinity({"app": "web"}),
    )
    t, meta = pack([n0], [p])
    assert np.asarray(t.sched_mask)[0, meta.node_index["n0"]]


class TestUndoLogDifferential:
    """Randomized differential test: the undo-log snapshot must match a naive
    copy-on-fork model over arbitrary op sequences (the contract the
    reference locks in clustersnapshot_test.go's fork/revert/commit grid)."""

    class _Naive:
        def __init__(self):
            self.stack = [({}, {}, {})]  # (nodes, pods, assign)

        def _top(self):
            return self.stack[-1]

        def fork(self):
            n, p, a = self.stack[-1]
            self.stack.append((dict(n), dict(p), dict(a)))

        def revert(self):
            self.stack.pop()

        def commit(self):
            top = self.stack.pop()
            self.stack[-1] = top

        def add_node(self, node):
            self._top()[0][node.name] = node

        def remove_node(self, name):
            n, p, a = self._top()
            del n[name]
            for k in [k for k, v in a.items() if v == name]:
                del p[k]
                del a[k]

        def add_pod(self, pod, node_name=""):
            n, p, a = self._top()
            p[pod.key()] = pod
            assign = node_name or pod.node_name
            if assign:
                a[pod.key()] = assign

        def remove_pod(self, key):
            n, p, a = self._top()
            del p[key]
            a.pop(key, None)

        def schedule_pod(self, key, node):
            self._top()[2][key] = node

        def state(self):
            n, p, a = self._top()
            return (
                sorted(n),
                sorted(p),
                sorted(a.items()),
            )

    @pytest.mark.parametrize("seed", range(6))
    def test_random_ops(self, seed):
        import random

        rng = random.Random(seed)
        snap = ClusterSnapshot()
        naive = self._Naive()
        node_names = [f"n{i}" for i in range(12)]
        pod_names = [f"p{i}" for i in range(30)]

        for _ in range(400):
            op = rng.random()
            if op < 0.2:
                name = rng.choice(node_names)
                if snap.get_node(name) is None:
                    snap.add_node(build_test_node(name))
                    naive.add_node(build_test_node(name))
            elif op < 0.3:
                live = snap.nodes()
                if live:
                    name = rng.choice(live).name
                    snap.remove_node(name)
                    naive.remove_node(name)
            elif op < 0.5:
                pn = rng.choice(pod_names)
                pod = build_test_pod(pn)
                if snap.get_pod(pod.key()) is None:
                    live = snap.nodes()
                    target = rng.choice(live).name if live and rng.random() < 0.5 else ""
                    snap.add_pod(pod, target)
                    naive.add_pod(pod, target)
            elif op < 0.6:
                live = snap.pods()
                if live:
                    key = rng.choice(live).key()
                    snap.remove_pod(key)
                    naive.remove_pod(key)
            elif op < 0.7:
                livep, liven = snap.pods(), snap.nodes()
                if livep and liven:
                    key = rng.choice(livep).key()
                    node = rng.choice(liven).name
                    snap.schedule_pod(key, node)
                    naive.schedule_pod(key, node)
            elif op < 0.8:
                snap.fork()
                naive.fork()
            elif op < 0.9:
                if snap.fork_depth > 0:
                    snap.revert()
                    naive.revert()
            else:
                if snap.fork_depth > 0:
                    snap.commit()
                    naive.commit()

            n, p, a = naive.state()
            assert sorted(x.name for x in snap.nodes()) == n
            assert sorted(x.key() for x in snap.pods()) == p
            got_assign = sorted(
                (x.key(), snap.assignment(x.key()))
                for x in snap.pods()
                if snap.assignment(x.key())
            )
            assert got_assign == a
            # index consistency
            for node in snap.nodes():
                for pod in snap.pods_on_node(node.name):
                    assert snap.assignment(pod.key()) == node.name


def test_ghost_assignment_survives_add_node_revert():
    """A pod whose node_name references a not-yet-present node keeps its
    index membership when an add_node of that node is reverted (the bucket
    must not be destroyed with the node)."""
    snap = ClusterSnapshot()
    snap.add_pod(build_test_pod("p", node_name="n1"))
    snap.fork()
    snap.add_node(build_test_node("n1"))
    assert [p.name for p in snap.pods_on_node("n1")] == ["p"]
    snap.revert()
    assert snap.assignment("default/p") == "n1"
    assert [p.name for p in snap.pods_on_node("n1")] == ["p"]
    snap.add_node(build_test_node("n1"))
    assert [p.name for p in snap.pods_on_node("n1")] == ["p"]


def test_base_level_mutations_not_logged():
    snap = ClusterSnapshot()
    snap.add_node(build_test_node("n"))
    snap.add_pod(build_test_pod("p", node_name="n"))
    snap.remove_pod("default/p")
    assert snap._undo == [[]]
    snap.fork()
    snap.add_node(build_test_node("m"))
    assert len(snap._undo[1]) == 1
    snap.commit()  # splice into base -> dropped
    assert snap._undo == [[]]


def test_tensors_cache_survives_fork_revert():
    """The fork→mutate→revert pattern restores the exact pre-fork state, so a
    tensors() cache built before the fork must still be served after revert
    (no re-pack), while a cache built inside the fork must not leak out."""
    snap = ClusterSnapshot()
    snap.add_node(build_test_node("n"))
    snap.add_pod(build_test_pod("p", node_name="n"))
    t0, _ = snap.tensors()
    snap.fork()
    snap.add_pod(build_test_pod("q"))
    snap.revert()
    t1, _ = snap.tensors()
    assert t1 is t0  # same cached object, no re-pack

    snap.fork()
    snap.add_pod(build_test_pod("q2"))
    t_fork, _ = snap.tensors()
    snap.revert()
    snap.add_pod(build_test_pod("r"))
    t2, _ = snap.tensors()
    assert t2 is not t_fork
    assert int(t2.pod_valid.sum()) == 2  # p + r, not the reverted q2


def test_no_bucket_leak_on_node_churn():
    snap = ClusterSnapshot()
    for i in range(50):
        snap.add_node(build_test_node(f"churn-{i}"))
        snap.remove_node(f"churn-{i}")
    assert len(snap._by_node) == 0


def test_resources_rows_matches_resources_row():
    """The vectorized flatten must stay bit-identical to the scalar one —
    the MiB-scaling invariant lives in both (packer.resources_row docstring)."""
    import numpy as np

    from autoscaler_tpu.kube.objects import Resources
    from autoscaler_tpu.snapshot.packer import resources_row, resources_rows

    rng = np.random.default_rng(3)
    items = [
        Resources(
            cpu_m=float(rng.integers(0, 10**5)),
            memory=float(rng.integers(0, 2**38)),     # incl. non-MiB-aligned
            ephemeral=float(rng.integers(0, 2**33)),
            gpu=float(rng.integers(0, 8)),
            tpu=float(rng.integers(0, 8)),
            pods=float(rng.integers(0, 256)),
        )
        for _ in range(64)
    ]
    out = np.zeros((64, 6), np.float32)
    resources_rows(items, 1.0, out)
    for i, r in enumerate(items):
        np.testing.assert_array_equal(out[i], resources_row(r, 1.0))
    out2 = np.zeros((64, 6), np.float32)
    resources_rows(items, None, out2)
    for i, r in enumerate(items):
        np.testing.assert_array_equal(out2[i], resources_row(r, r.pods))


class TestTensorScheduleOps:
    """The device twin of ClusterSnapshot AddPod/RemovePod: schedule_pod /
    unschedule_pod as traceable updates (clustersnapshot.go:29 surface)."""

    def test_schedule_unschedule_roundtrip(self):
        t = empty_snapshot(num_pods=8, num_nodes=4)
        t = dataclasses.replace(
            t,
            pod_req=t.pod_req.at[0].set(jnp.ones(t.pod_req.shape[1])),
            pod_valid=t.pod_valid.at[0].set(True),
            node_valid=t.node_valid.at[:2].set(True),
        )

        @jax.jit
        def roundtrip(t):
            t1 = t.schedule_pod(0, 1)
            t2 = t1.unschedule_pod(0)
            return t1, t2

        t1, t2 = roundtrip(t)
        assert int(t1.pod_node[0]) == 1
        # exact accounting: node 1 carries exactly the pod's request
        np.testing.assert_array_equal(
            np.asarray(t1.node_used[1]), np.ones(t.pod_req.shape[1])
        )
        np.testing.assert_array_equal(
            np.asarray(t1.node_used[0]), np.zeros(t.pod_req.shape[1])
        )
        # unschedule restores exactly
        assert int(t2.pod_node[0]) == -1
        np.testing.assert_array_equal(
            np.asarray(t2.node_used), np.asarray(t.node_used)
        )

    def test_unschedule_unassigned_is_noop(self):
        t = empty_snapshot(num_pods=4, num_nodes=2)
        t = dataclasses.replace(
            t, pod_req=t.pod_req.at[0].set(jnp.ones(t.pod_req.shape[1]))
        )
        t2 = t.unschedule_pod(0)  # pod 0 was never scheduled
        np.testing.assert_array_equal(
            np.asarray(t2.node_used), np.asarray(t.node_used)
        )
        assert int(t2.pod_node[0]) == -1
