"""Test harness config: force an 8-device virtual CPU platform so sharding
tests exercise a real Mesh without TPU hardware (multi-chip is validated by
the driver via __graft_entry__.dryrun_multichip the same way)."""
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
