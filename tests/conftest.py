"""Test harness config: force an 8-device virtual CPU platform so sharding
tests exercise a real Mesh without TPU hardware (multi-chip is validated by
the driver via __graft_entry__.dryrun_multichip the same way).

The environment pins JAX_PLATFORMS=axon (the real-TPU tunnel) and pytest
plugins (jaxtyping) import jax before this conftest runs, so mutating
os.environ alone is too late — jax.config.update still works because
backends initialize lazily on first device query.
"""
import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

jax.config.update("jax_platforms", "cpu")

import pytest


@pytest.fixture(scope="session", autouse=True)
def _determinism_sanitizer():
    """Opt-in runtime determinism monitoring for a whole pytest session:
    AUTOSCALER_TPU_SANITIZE=1 installs the analysis/sanitizer.py patches,
    and any ambient wall-clock/rng/environment read trapped inside a
    replay-scoped frame fails the session teardown with the attributed
    file:line report (the pytest half of the hack/verify.sh gate)."""
    if not os.environ.get("AUTOSCALER_TPU_SANITIZE"):
        yield None
        return
    from autoscaler_tpu.analysis.sanitizer import DeterminismSanitizer

    with DeterminismSanitizer() as san:
        yield san
    assert not san.events, (
        "determinism sanitizer trapped ambient reads in replay-scoped "
        "frames:\n" + san.report()
    )

