"""Test harness config: force an 8-device virtual CPU platform so sharding
tests exercise a real Mesh without TPU hardware (multi-chip is validated by
the driver via __graft_entry__.dryrun_multichip the same way).

The environment pins JAX_PLATFORMS=axon (the real-TPU tunnel) and pytest
plugins (jaxtyping) import jax before this conftest runs, so mutating
os.environ alone is too late — jax.config.update still works because
backends initialize lazily on first device query.
"""
import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

jax.config.update("jax_platforms", "cpu")

