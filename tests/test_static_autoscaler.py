"""Integration tests of the full RunOnce loop against the fake provider and
fake cluster API — the analog of the reference's core/static_autoscaler_test.go
scenario tests (scale-up/scale-down event sequences across loop iterations)."""
import numpy as np
import pytest

from autoscaler_tpu.cloudprovider.interface import (
    Instance,
    InstanceErrorClass,
    InstanceErrorInfo,
    InstanceState,
)
from autoscaler_tpu.cloudprovider.test_provider import TestCloudProvider
from autoscaler_tpu.config.options import AutoscalingOptions
from autoscaler_tpu.core.podlistprocessor import FilterOutSchedulablePodListProcessor
from autoscaler_tpu.core.static_autoscaler import StaticAutoscaler
from autoscaler_tpu.kube.api import FakeClusterAPI
from autoscaler_tpu.simulator.hinting import HintingSimulator
from autoscaler_tpu.snapshot.cluster_snapshot import ClusterSnapshot
from autoscaler_tpu.utils.test_utils import GB, MB, build_test_node, build_test_pod


class TestHintingSimulator:
    def test_schedule_and_hints(self):
        s = ClusterSnapshot()
        s.add_node(build_test_node("n0", cpu_m=1000))
        s.add_node(build_test_node("n1", cpu_m=1000))
        pods = [build_test_pod(f"p{i}", cpu_m=400) for i in range(3)]
        for p in pods:
            s.add_pod(p)
        sim = HintingSimulator()
        scheduled, assignments = sim.try_schedule_pods(s, pods, commit=True)
        assert len(scheduled) == 3
        # capacity respected: max 2 per 1000m node with 400m pods
        per_node = {}
        for key, node in assignments.items():
            per_node[node] = per_node.get(node, 0) + 1
        assert all(v <= 2 for v in per_node.values())
        # hints recorded
        assert sim.hints.get("default/p0") is not None

    def test_hint_preferred(self):
        s = ClusterSnapshot()
        s.add_node(build_test_node("n0", cpu_m=2000))
        s.add_node(build_test_node("n1", cpu_m=2000))
        pod = build_test_pod("p", cpu_m=100)
        s.add_pod(pod)
        sim = HintingSimulator()
        sim.hints.set("default/p", "n1")
        _, assignments = sim.try_schedule_pods(s, [pod], commit=False)
        assert assignments["default/p"] == "n1"

    def test_no_capacity(self):
        s = ClusterSnapshot()
        s.add_node(build_test_node("n0", cpu_m=100))
        pod = build_test_pod("p", cpu_m=500)
        s.add_pod(pod)
        sim = HintingSimulator()
        scheduled, _ = sim.try_schedule_pods(s, [pod])
        assert scheduled == []


class TestPodListProcessor:
    def test_filters_schedulable(self):
        s = ClusterSnapshot()
        s.add_node(build_test_node("n0", cpu_m=1000))
        fits = build_test_pod("fits", cpu_m=300)
        too_big = build_test_pod("big", cpu_m=5000)
        s.add_pod(fits)
        s.add_pod(too_big)
        proc = FilterOutSchedulablePodListProcessor()
        still, filtered = proc.process(s, [fits, too_big])
        assert [p.name for p in filtered] == ["fits"]
        assert [p.name for p in still] == ["big"]

    def test_priority_order(self):
        # only one slot: higher priority pod wins it
        s = ClusterSnapshot()
        s.add_node(build_test_node("n0", cpu_m=500))
        low = build_test_pod("low", cpu_m=400, priority=0)
        high = build_test_pod("high", cpu_m=400, priority=10)
        s.add_pod(low)
        s.add_pod(high)
        proc = FilterOutSchedulablePodListProcessor()
        still, filtered = proc.process(s, [low, high])
        assert [p.name for p in filtered] == ["high"]
        assert [p.name for p in still] == ["low"]

    def test_equal_priority_tiebreak_is_order_independent(self):
        """Regression: equal-priority pods used to be packed in caller-list
        order, so the API listing's (non-replayed) order decided which pod
        got the last slot. The pod-key secondary sort makes the outcome a
        pure function of the pod SET."""
        import random

        pods = [
            build_test_pod(f"p{i}", cpu_m=400, priority=7) for i in range(6)
        ]
        outcomes = set()
        for seed in range(8):
            s = ClusterSnapshot()
            s.add_node(build_test_node("n0", cpu_m=900))  # two slots
            shuffled = list(pods)
            random.Random(seed).shuffle(shuffled)
            for p in shuffled:
                s.add_pod(p)
            still, filtered = FilterOutSchedulablePodListProcessor().process(
                s, shuffled
            )
            outcomes.add(
                (
                    tuple(sorted(p.name for p in filtered)),
                    tuple(sorted(p.name for p in still)),
                )
            )
        assert len(outcomes) == 1
        (filtered_names, still_names), = outcomes
        assert len(filtered_names) == 2 and len(still_names) == 4


def build_world(groups, nodes_per_group, pods=(), **opt_kw):
    provider = TestCloudProvider()
    api = FakeClusterAPI()
    for name, lo, hi, cpu, mem in groups:
        n = nodes_per_group.get(name, 0)
        provider.add_node_group(
            name, lo, hi, n, build_test_node(f"{name}-tmpl", cpu_m=cpu, mem=mem)
        )
        for i in range(n):
            node = build_test_node(f"{name}-{i}", cpu_m=cpu, mem=mem)
            provider.add_node(name, node)
            api.add_node(node)
    for pod in pods:
        api.add_pod(pod)
    opts = AutoscalingOptions(expander="least-waste", **opt_kw)
    autoscaler = StaticAutoscaler(provider, api, opts)
    return provider, api, autoscaler


class TestRunOnce:
    def test_scale_up_on_pending_pods(self):
        pods = [build_test_pod(f"p{i}", cpu_m=900, mem=1 * GB) for i in range(4)]
        provider, api, autoscaler = build_world(
            [("g", 0, 10, 1000, 2 * GB)], {"g": 1}, pods
        )
        result = autoscaler.run_once(now_ts=100.0)
        assert result.scale_up is not None and result.scale_up.scaled_up
        assert provider.scale_up_calls == [("g", result.scale_up.new_nodes)]
        assert result.scale_up.new_nodes >= 3

    def test_no_scale_up_when_pods_fit(self):
        pods = [build_test_pod("p", cpu_m=100)]
        provider, api, autoscaler = build_world(
            [("g", 0, 10, 1000, 2 * GB)], {"g": 1}, pods
        )
        result = autoscaler.run_once(now_ts=100.0)
        assert result.filtered_schedulable == 1
        assert result.pending_pods == 0
        assert result.scale_up is None
        assert provider.scale_up_calls == []

    def test_upcoming_nodes_prevent_double_scale_up(self):
        pods = [build_test_pod(f"p{i}", cpu_m=900, mem=1 * GB) for i in range(2)]
        provider, api, autoscaler = build_world(
            [("g", 0, 10, 1000, 2 * GB)], {"g": 0}, pods
        )
        r1 = autoscaler.run_once(now_ts=100.0)
        assert r1.scale_up.scaled_up
        first_calls = len(provider.scale_up_calls)
        # next loop: target raised but nodes not registered yet → upcoming
        # virtual nodes absorb the pods, no second scale-up
        r2 = autoscaler.run_once(now_ts=110.0)
        assert len(provider.scale_up_calls) == first_calls
        assert r2.filtered_schedulable == 2

    def test_scale_down_empty_node_after_unneeded_time(self):
        provider, api, autoscaler = build_world(
            [("g", 0, 10, 1000, 2 * GB)],
            {"g": 3},
            [build_test_pod("p", cpu_m=300, node_name="g-0")],
        )
        autoscaler.options.node_group_defaults.scale_down_unneeded_time_s = 50
        autoscaler.options.scale_down_delay_after_add_s = 0
        r1 = autoscaler.run_once(now_ts=0.0)
        assert r1.unneeded_nodes >= 2  # g-1, g-2 empty
        assert r1.scale_down is None  # unneeded-time not yet reached
        r2 = autoscaler.run_once(now_ts=100.0)
        assert r2.scale_down is not None
        deleted = set(r2.scale_down.deleted_empty)
        assert deleted and deleted <= {"g-1", "g-2"}
        for name in deleted:
            assert name not in api.nodes
        assert provider.scale_down_calls

    def test_scale_down_cooldown_after_scale_up(self):
        pods = [
            build_test_pod("blk0", cpu_m=800, node_name="g-0"),
            build_test_pod("blk1", cpu_m=800, node_name="g-1"),
            build_test_pod("p", cpu_m=900, mem=1 * GB),  # fits no existing node
        ]
        provider, api, autoscaler = build_world(
            [("g", 0, 10, 1000, 2 * GB)], {"g": 2}, pods
        )
        autoscaler.options.node_group_defaults.scale_down_unneeded_time_s = 0
        r1 = autoscaler.run_once(now_ts=0.0)
        assert r1.scale_up.scaled_up
        r2 = autoscaler.run_once(now_ts=10.0)  # within delay_after_add (600s)
        assert r2.scale_down_in_cooldown
        assert r2.scale_down is None

    def test_drain_scale_down_evicts_pods(self):
        provider, api, autoscaler = build_world(
            [("g", 0, 10, 1000, 2 * GB)],
            {"g": 3},
            [build_test_pod("p", cpu_m=100, node_name="g-0")],
        )
        autoscaler.options.node_group_defaults.scale_down_unneeded_time_s = 50
        autoscaler.options.scale_down_delay_after_add_s = 0
        autoscaler.options.max_empty_bulk_delete = 2  # let the drain slot open
        autoscaler.run_once(now_ts=0.0)
        r2 = autoscaler.run_once(now_ts=100.0)
        assert r2.scale_down is not None
        if r2.scale_down.deleted_drain:
            assert "default/p" in api.evicted

    def test_unhealthy_cluster_halts(self):
        provider, api, autoscaler = build_world(
            [("g", 0, 10, 1000, 2 * GB)], {"g": 3}
        )
        for node in api.list_nodes():
            node.ready = False
            node.creation_ts = -10_000
        autoscaler.options.ok_total_unready_count = 0
        result = autoscaler.run_once(now_ts=1000.0)
        assert not result.cluster_healthy
        assert result.scale_up is None and result.scale_down is None

    def test_unregistered_instance_cleanup(self):
        provider, api, autoscaler = build_world(
            [("g", 0, 10, 1000, 2 * GB)], {"g": 1}
        )
        provider.add_instance("g", Instance(id="ghost"))
        g = provider.node_groups()[0]
        g.set_target_size(2)
        # first sighting starts the per-instance provision clock — a booting
        # instance must NOT be deleted immediately (even across restarts)
        r0 = autoscaler.run_once(now_ts=10_000.0)
        assert r0.removed_unregistered == 0
        # still unregistered past max_node_provision_time → removed
        timeout = autoscaler.options.max_node_provision_time_s
        result = autoscaler.run_once(now_ts=10_000.0 + timeout + 1)
        assert result.removed_unregistered == 1
        assert ("g", "ghost") in provider.scale_down_calls

    def test_errored_instances_deleted(self):
        provider, api, autoscaler = build_world(
            [("g", 0, 10, 1000, 2 * GB)], {"g": 1}
        )
        provider.add_instance(
            "g",
            Instance(
                id="bad",
                state=InstanceState.CREATING,
                error_info=InstanceErrorInfo(InstanceErrorClass.OUT_OF_RESOURCES),
            ),
        )
        autoscaler.run_once(now_ts=10.0)
        assert ("g", "bad") in provider.scale_down_calls

    def test_expendable_pods_ignored(self):
        pods = [build_test_pod("exp", cpu_m=900, mem=1 * GB, priority=-100)]
        provider, api, autoscaler = build_world(
            [("g", 0, 10, 1000, 2 * GB)], {"g": 0}, pods
        )
        result = autoscaler.run_once(now_ts=0.0)
        assert result.scale_up is None
        assert provider.scale_up_calls == []

    def test_multi_loop_convergence(self):
        # burst of pods → scale up; "cloud" registers nodes; pods get
        # scheduled; extra node scales back down
        pods = [build_test_pod(f"p{i}", cpu_m=800, mem=1 * GB) for i in range(4)]
        provider, api, autoscaler = build_world(
            [("g", 0, 10, 1000, 2 * GB)], {"g": 0}, pods
        )
        autoscaler.options.node_group_defaults.scale_down_unneeded_time_s = 60
        autoscaler.options.scale_down_delay_after_add_s = 120

        r1 = autoscaler.run_once(now_ts=0.0)
        assert r1.scale_up.scaled_up
        n_new = r1.scale_up.new_nodes
        assert n_new == 4  # one 800m pod per 1000m node

        # cloud materializes the nodes, scheduler places the pods
        for i in range(n_new):
            node = build_test_node(f"g-{i}", cpu_m=1000, mem=2 * GB)
            provider.add_node("g", node)
            api.add_node(node)
        for i, pod in enumerate(pods):
            api.pods[pod.key()].node_name = f"g-{i}"

        r2 = autoscaler.run_once(now_ts=30.0)
        assert r2.scale_up is None or not r2.scale_up.scaled_up
        assert len(provider.scale_up_calls) == 1

        # one pod finishes → its node empties → scaled down after unneeded time
        del api.pods["default/p3"]
        r3 = autoscaler.run_once(now_ts=60.0)
        r4 = autoscaler.run_once(now_ts=200.0)  # past cooldown + unneeded time
        deleted = (r4.scale_down.deleted_empty if r4.scale_down else [])
        assert "g-3" in deleted
