"""Full-VPA e2e-style scenarios: feeder → recommender → updater → admission
driven together over simulated days, hermetically.

Models vertical-pod-autoscaler/e2e/v1/full_vpa.go ("Pods under VPA": cpu and
memory requests grow with usage through the full evict-and-readmit loop) and
e2e/v1/{recommender,updater,admission_controller}.go scenario outlines, minus
the live cluster: pods live in-memory, metrics come from InMemoryMetrics,
eviction is the Updater's decision, and re-admission runs through the real
AdmissionServer over HTTPS with in-process generated certs (gencerts.sh
analog) — so the patch path exercised is byte-for-byte the webhook one.
"""
from __future__ import annotations

import base64
import http.client
import json
import ssl

import pytest

from autoscaler_tpu.kube.objects import LabelSelector
from autoscaler_tpu.vpa.admission import AdmissionServer
from autoscaler_tpu.vpa.api import (
    ContainerResourcePolicy,
    UpdateMode,
    Vpa,
)
from autoscaler_tpu.vpa.certs import generate_certs
from autoscaler_tpu.vpa.feeder import (
    ClusterStateFeeder,
    ContainerUsage,
    InMemoryMetrics,
)
from autoscaler_tpu.vpa.recommender import (
    CheckpointManager,
    ClusterStateModel,
    ContainerKey,
    PercentileRecommender,
    instance_key,
)
from autoscaler_tpu.vpa.updater import Updater

MB = 1024**2
GB = 1024**3
DAY = 86400.0
T0 = 1_700_000_000.0  # fixed epoch so runs are deterministic

CONTAINER = "hamster"
WORKLOAD = "hamster"
VPA_NAME = "hamster-vpa"
LABELS = {"app": "hamster"}


def apply_json_patch(doc: dict, patch_ops: list) -> dict:
    """Minimal RFC 6902 'add' applier — the only op the webhook emits."""
    import copy

    doc = copy.deepcopy(doc)
    for op in patch_ops:
        assert op["op"] == "add"
        parts = [p.replace("~1", "/").replace("~0", "~") for p in op["path"].strip("/").split("/")]
        target = doc
        for part in parts[:-1]:
            target = target[int(part)] if isinstance(target, list) else target[part]
        last = parts[-1]
        if isinstance(target, list):
            target.insert(int(last), op["value"])
        else:
            target[last] = op["value"]
    return doc


def parse_cpu(s: str) -> float:
    return float(s[:-1]) / 1000.0 if s.endswith("m") else float(s)


class HamsterCluster:
    """The e2e harness: a replicated workload under one VPA, with live pod
    requests as the observable state (what full_vpa.go polls on the real
    deployment)."""

    def __init__(self, replicas=4, update_mode=UpdateMode.AUTO, policies=()):
        self.vpa = Vpa(
            name=VPA_NAME,
            target_selector=LabelSelector.from_dict(LABELS),
            update_mode=update_mode,
            resource_policies=list(policies),
        )
        self.model = ClusterStateModel()
        self.feeder = ClusterStateFeeder(self.model, [self.vpa])
        self.recommender = PercentileRecommender(self.model)
        self.updater = Updater()
        self.metrics = InMemoryMetrics()
        self.recommendations = {}
        self.oom_ts = {}
        # pod state: name -> {"cpu": cores, "memory": bytes}
        self.requests = {
            f"{WORKLOAD}-{i}": {"cpu": 0.1, "memory": 200 * MB}
            for i in range(replicas)
        }
        self.evictions = []
        bundle = generate_certs()
        self._client_ctx = bundle.client_ssl_context()
        self.server = AdmissionServer([self.vpa], self.recommendations, tls=bundle)
        self.server.start()

    def close(self):
        self.server.stop()

    # -- one simulated control-loop pass ------------------------------------
    def scrape(self, now, cpu_cores, memory_bytes):
        self.metrics.set_usage(
            [
                ContainerUsage(
                    namespace="default",
                    pod_name=name,
                    container=CONTAINER,
                    pod_labels=LABELS,
                    cpu_cores=cpu_cores,
                    memory_bytes=memory_bytes,
                )
                for name in self.requests
            ]
        )
        self.feeder.feed_once(self.metrics, now)

    def recommend(self, now):
        # keep the dict identity the admission server reads from
        self.recommendations.clear()
        self.recommendations.update(self.recommender.recommend(now))

    def update_and_readmit(self, now):
        """Updater evicts drifted pods; each eviction is followed by the
        replacement pod going through the webhook (the Recreate loop)."""
        from autoscaler_tpu.utils.test_utils import build_test_pod

        pods = [
            build_test_pod(
                name,
                cpu_m=req["cpu"] * 1000.0,
                mem=req["memory"],
                labels=LABELS,
            )
            for name, req in self.requests.items()
        ]
        evicted = self.updater.run_once(
            {WORKLOAD: pods},
            self.recommendations,
            {WORKLOAD: VPA_NAME},
            now,
            oom_ts=self.oom_ts,
            recommendation_age_s=0.0,
            vpas={VPA_NAME: self.vpa},
        )
        for pod in evicted:
            self.evictions.append((now, pod.name))
            self.requests[pod.name] = self._admit_replacement(pod.name)
        return evicted

    def _admit_replacement(self, name):
        """POST the replacement pod's AdmissionReview to the HTTPS webhook
        and return the patched requests."""
        pod_json = {
            "metadata": {"name": name, "labels": dict(LABELS)},
            "spec": {
                "containers": [
                    {
                        "name": CONTAINER,
                        "resources": {"requests": {"cpu": "100m", "memory": str(200 * MB)}},
                    }
                ]
            },
        }
        review = {
            "apiVersion": "admission.k8s.io/v1",
            "kind": "AdmissionReview",
            "request": {"uid": "u", "namespace": "default", "object": pod_json},
        }
        host, port = self.server.address
        conn = http.client.HTTPSConnection(host, port, timeout=5, context=self._client_ctx)
        try:
            conn.request(
                "POST", "/mutate", json.dumps(review), {"Content-Type": "application/json"}
            )
            resp = json.loads(conn.getresponse().read())
        finally:
            conn.close()
        assert resp["response"]["allowed"] is True
        if "patch" not in resp["response"]:
            return {"cpu": 0.1, "memory": 200 * MB}
        ops = json.loads(base64.b64decode(resp["response"]["patch"]))
        patched = apply_json_patch(pod_json, ops)
        reqs = patched["spec"]["containers"][0]["resources"]["requests"]
        return {"cpu": parse_cpu(reqs["cpu"]), "memory": float(reqs["memory"])}

    def run_days(self, days, cpu_cores, memory_bytes, scrape_every_s=1200.0):
        now = getattr(self, "_now", T0)
        end = now + days * DAY
        while now < end:
            self.scrape(now, cpu_cores, memory_bytes)
            if int(now) % 3600 < scrape_every_s:  # hourly decision pass
                self.recommend(now)
                self.update_and_readmit(now)
            now += scrape_every_s
        self._now = now
        return now


@pytest.fixture
def cluster():
    c = HamsterCluster()
    yield c
    c.close()


class TestFullVpa:
    def test_cpu_requests_grow_with_usage(self, cluster):
        """full_vpa.go:96 — steady 350m usage vs 100m initial requests: every
        pod converges up through evict+readmit, close to target (p90 * 1.15
        margin ~ 0.40 cores)."""
        cluster.run_days(3, cpu_cores=0.35, memory_bytes=250 * MB)
        for req in cluster.requests.values():
            assert 0.30 <= req["cpu"] <= 0.60, cluster.requests
        assert len(cluster.evictions) >= len(cluster.requests)

    def test_memory_requests_grow_with_usage(self, cluster):
        """full_vpa.go:111 — memory working set 1GB vs 200MB initial."""
        cluster.run_days(3, cpu_cores=0.1, memory_bytes=1 * GB)
        for req in cluster.requests.values():
            assert req["memory"] >= 0.9 * GB, cluster.requests

    def test_requests_shrink_after_usage_drops(self, cluster):
        """Decaying histograms let recommendations follow usage down — the
        recommender side of e2e 'recommendations respect usage decrease'."""
        cluster.run_days(2, cpu_cores=1.0, memory_bytes=400 * MB)
        high = {k: dict(v) for k, v in cluster.requests.items()}
        cluster.run_days(8, cpu_cores=0.15, memory_bytes=400 * MB)
        for name, req in cluster.requests.items():
            assert req["cpu"] < high[name]["cpu"] * 0.7, (req, high[name])

    def test_oom_quick_path_bumps_memory(self, cluster):
        """updater.go OOM quick path + recommender OOM bump: after an OOM
        observation the pod is evicted promptly and readmitted with memory
        at least the OOM level."""
        now = cluster.run_days(1, cpu_cores=0.2, memory_bytes=300 * MB)
        key = ContainerKey(VPA_NAME, CONTAINER, "default")
        victim = next(iter(cluster.requests))
        cluster.model.observe_oom(key, 800 * MB, now, pod=instance_key("default", victim))
        cluster.oom_ts[f"default/{victim}"] = now
        cluster.recommend(now)
        evicted = cluster.update_and_readmit(now + 60.0)
        assert victim in {p.name for p in evicted}
        assert cluster.requests[victim]["memory"] >= 800 * MB

    def test_update_mode_off_only_recommends(self):
        """e2e admission/updater 'Off' mode: recommendations exist but no pod
        is ever evicted or patched."""
        c = HamsterCluster(update_mode=UpdateMode.OFF)
        try:
            c.run_days(2, cpu_cores=0.5, memory_bytes=600 * MB)
            assert c.evictions == []
            key = ContainerKey(VPA_NAME, CONTAINER, "default")
            assert key in c.recommendations  # recommender still works
            for req in c.requests.values():
                assert req["cpu"] == 0.1 and req["memory"] == 200 * MB
        finally:
            c.close()

    def test_resource_policy_caps_admitted_requests(self):
        """e2e admission 'caps to max allowed': maxAllowed clamps what the
        webhook writes even when usage wants more."""
        cap = ContainerResourcePolicy(
            container_name=CONTAINER, max_cpu=0.25, max_memory=400 * MB
        )
        c = HamsterCluster(policies=[cap])
        try:
            c.run_days(3, cpu_cores=1.5, memory_bytes=2 * GB)
            for req in c.requests.values():
                assert req["cpu"] <= 0.25 + 1e-9
                assert req["memory"] <= 400 * MB + 1
        finally:
            c.close()

    def test_eviction_rate_limited_per_pass(self, cluster):
        """No pass evicts every replica at once (updater.go eviction
        tolerance): with 4 replicas and default 0.5 tolerance, each decision
        pass evicts at most 2."""
        cluster.run_days(2, cpu_cores=0.6, memory_bytes=500 * MB)
        by_pass = {}
        for ts, name in cluster.evictions:
            by_pass.setdefault(ts, []).append(name)
        assert by_pass, "expected evictions"
        assert max(len(v) for v in by_pass.values()) <= 2

    def test_checkpoint_restart_preserves_recommendations(self, cluster):
        """recommender e2e checkpoint scenario: serialize mid-run, rebuild a
        fresh model from checkpoints, recommendations survive the restart."""
        now = cluster.run_days(2, cpu_cores=0.4, memory_bytes=700 * MB)
        cluster.recommend(now)
        key = ContainerKey(VPA_NAME, CONTAINER, "default")
        before = cluster.recommendations[key]

        checkpoints = CheckpointManager(cluster.model).store()
        fresh = ClusterStateModel()
        CheckpointManager(fresh).load(checkpoints)
        after = PercentileRecommender(fresh).recommend(now)[key]
        assert after.target_cpu == pytest.approx(before.target_cpu, rel=0.05)
        assert after.target_memory == pytest.approx(before.target_memory, rel=0.05)

        # Restored history must SURVIVE subsequent live feeding: the bank
        # adopts the checkpoint's decay reference, so the first post-restart
        # sample at a real epoch must not trip a re-reference that zeroes
        # the restored mass.
        feeder = ClusterStateFeeder(fresh, [cluster.vpa])
        metrics = InMemoryMetrics()
        metrics.set_usage(
            [
                ContainerUsage(
                    "default", "hamster-9", CONTAINER, LABELS,
                    cpu_cores=0.05, memory_bytes=100 * MB,
                )
            ]
        )
        feeder.feed_once(metrics, now + 600.0)
        still = PercentileRecommender(fresh).recommend(now + 600.0)[key]
        # one tiny sample against two days of history must barely move it
        assert still.target_cpu == pytest.approx(before.target_cpu, rel=0.10)
        assert still.target_memory == pytest.approx(before.target_memory, rel=0.10)
