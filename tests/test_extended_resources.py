"""Named extended resources as first-class fit dimensions (PREDICATES
divergence 4 closure) and DaemonSet affinity-based targeting in template
overhead (divergence 6 closure).

Reference: NodeResourcesFit evaluates EVERY resource name in a pod's
requests against the node's allocatable (schedulerbased.go:109-163 →
noderesources/fit.go) — two device plugins on one node are distinct
dimensions; and simulator/nodes.go:38-56 places DaemonSet pods via the full
filter chain, including required node affinity (how the default scheduler
targets DS pods since k8s 1.12)."""
from __future__ import annotations

import numpy as np
import pytest

from autoscaler_tpu.estimator.binpacking import BinpackingNodeEstimator
from autoscaler_tpu.kube.convert import daemonset_from_json, resources_from_map
from autoscaler_tpu.kube.objects import (
    NUM_RESOURCES,
    DaemonSet,
    LabelSelector,
    Resources,
)
from autoscaler_tpu.snapshot.packer import extended_schema, pack, resources_row
from autoscaler_tpu.utils.test_utils import GB, MB, build_test_node, build_test_pod

FPGA = "example.com/fpga"
NIC = "example.com/nic"


def fpga_pod(name, fpga=1.0, cpu=100):
    p = build_test_pod(name, cpu_m=cpu)
    p.requests = Resources(
        cpu_m=cpu, memory=100 * MB, pods=0, extended=((FPGA, fpga),)
    )
    return p


def device_node(name, fpga=2.0, nic=0.0, cpu=8000):
    n = build_test_node(name, cpu_m=cpu, mem=16 * GB)
    ext = tuple(
        x for x in ((FPGA, fpga), (NIC, nic)) if x[1] > 0
    )
    n.allocatable = Resources(
        cpu_m=cpu, memory=16 * GB, pods=110, extended=ext
    )
    return n


class TestResourcesArithmetic:
    def test_add_merges_by_name(self):
        a = Resources(cpu_m=100, extended=((FPGA, 1.0),))
        b = Resources(cpu_m=200, extended=((FPGA, 2.0), (NIC, 1.0)))
        s = a + b
        assert s.cpu_m == 300
        assert s.extended_map() == {FPGA: 3.0, NIC: 1.0}

    def test_sub_drops_zeroed_names(self):
        a = Resources(extended=((FPGA, 2.0), (NIC, 1.0)))
        b = Resources(extended=((FPGA, 2.0),))
        assert (a - b).extended_map() == {NIC: 1.0}

    def test_convert_collects_unknown_names(self):
        r = resources_from_map({
            "cpu": "500m", "memory": "1Gi", "nvidia.com/gpu": "1",
            FPGA: "2", "hugepages-2Mi": "512Mi",
        })
        assert r.cpu_m == 500 and r.gpu == 1
        em = r.extended_map()
        assert em[FPGA] == 2
        assert em["hugepages-2Mi"] == 512 * MB


class TestPackedSchema:
    def test_schema_and_columns(self):
        nodes = [device_node("n0", fpga=2, nic=4)]
        pods = [fpga_pod("p0")]
        tensors, meta = pack(nodes, pods)
        # schema = pod-requested names ONLY: the node's nic allocatable
        # widens nothing (a name no pod requests can never gate a fit)
        assert meta.extended_resources == (FPGA,)
        R = NUM_RESOURCES + 1
        assert tensors.node_alloc.shape[1] == R
        assert tensors.pod_req.shape[1] == R
        col = NUM_RESOURCES
        assert float(tensors.node_alloc[0, col]) == 2.0
        assert float(tensors.pod_req[0, col]) == 1.0

    def test_node_only_names_do_not_widen(self):
        """Real cloud nodes report allocatable like attachable-volumes-*:
        with no pod requesting them the snapshot must stay base-width."""
        n = build_test_node("n0", cpu_m=4000)
        n.allocatable = Resources(
            cpu_m=4000, memory=8 * GB, pods=110,
            extended=(("attachable-volumes-aws-ebs", 25.0),),
        )
        tensors, meta = pack([n], [build_test_pod("p0")])
        assert meta.extended_resources == ()
        assert tensors.node_alloc.shape[1] == NUM_RESOURCES

    def test_no_extended_keeps_base_width(self):
        tensors, meta = pack(
            [build_test_node("n0")], [build_test_pod("p0")]
        )
        assert meta.extended_resources == ()
        assert tensors.node_alloc.shape[1] == NUM_RESOURCES

    def test_row_and_rows_agree(self):
        r = Resources(cpu_m=100, memory=GB, extended=((NIC, 3.0),))
        ext = (FPGA, NIC)
        row = resources_row(r, 1.0, ext)
        assert row.shape == (NUM_RESOURCES + 2,)
        assert row[NUM_RESOURCES] == 0.0 and row[NUM_RESOURCES + 1] == 3.0


class TestEstimatorDistinguishesDevices:
    def test_fpga_capacity_bounds_packing(self):
        """5 one-fpga pods on a 2-fpga template need 3 nodes, even though
        cpu alone would fit all 5 on one node. The old collapse (unknown
        names dropped) estimated 1 node — an under-provision the scheduler
        then strands as Pending."""
        template = device_node("tmpl", fpga=2)
        pods = [fpga_pod(f"p{i}") for i in range(5)]
        count, scheduled = BinpackingNodeEstimator().estimate(pods, template)
        assert count == 3
        assert len(scheduled) == 5

    def test_two_plugin_resources_stay_distinct(self):
        """A pod requesting nic must not consume fpga capacity: 2 fpga pods
        + 2 nic pods on a (fpga=1, nic=8) template → fpga forces 2 nodes,
        nic rides along."""
        template = device_node("tmpl", fpga=1, nic=8)
        pods = [fpga_pod("f0"), fpga_pod("f1")]
        for i in range(2):
            p = build_test_pod(f"n{i}", cpu_m=100)
            p.requests = Resources(
                cpu_m=100, memory=100 * MB, pods=0, extended=((NIC, 1.0),)
            )
            pods.append(p)
        count, scheduled = BinpackingNodeEstimator().estimate(pods, template)
        assert count == 2
        assert len(scheduled) == 4

    def test_pod_requesting_absent_resource_never_schedules(self):
        template = build_test_node("tmpl", cpu_m=8000)
        pods = [fpga_pod("p0")]
        count, scheduled = BinpackingNodeEstimator().estimate(pods, template)
        assert count == 0 and scheduled == []

    def test_estimate_many_mixed_groups(self):
        """Group A has fpga nodes, group B does not: the fpga pod fits only
        in A; plain pods fit in either."""
        templates = {
            "a": device_node("tmpl-a", fpga=1),
            "b": build_test_node("tmpl-b", cpu_m=8000),
        }
        pods = [fpga_pod("p0"), build_test_pod("plain", cpu_m=100)]
        res = BinpackingNodeEstimator().estimate_many(pods, templates)
        count_a, sched_a = res["a"]
        count_b, sched_b = res["b"]
        assert count_a == 1 and len(sched_a) == 2
        assert count_b == 1 and [p.name for p in sched_b] == ["plain"]


class TestDRAClaims:
    """Minimal DRA model (PREDICATES divergence 4): claims are counted
    per-node resources under the reserved dra.k8s.io/ namespace."""

    def test_claims_fold_into_requests(self):
        import dataclasses

        from autoscaler_tpu.kube.objects import DRA_CLAIM_PREFIX

        p = build_test_pod("p0", cpu_m=100)
        p2 = dataclasses.replace(
            p, resource_claims=(("gpu.nvidia.com", 2.0), ("gpu.nvidia.com", 1.0))
        )
        assert p2.requests.extended_map() == {
            DRA_CLAIM_PREFIX + "gpu.nvidia.com": 3.0
        }

    def test_fold_is_idempotent_under_replace(self):
        """dataclasses.replace re-runs __post_init__; the claim axis must
        not double (utils/tpu.py and vpa/updater.py replace pods)."""
        import dataclasses

        from autoscaler_tpu.kube.objects import DRA_CLAIM_PREFIX, Pod

        p = Pod("p0", resource_claims=(("net.example/vf", 1.0),))
        for _ in range(3):
            p = dataclasses.replace(p, priority=p.priority + 1)
        assert p.requests.extended_map() == {
            DRA_CLAIM_PREFIX + "net.example/vf": 1.0
        }

    def test_claim_gates_estimate(self):
        """4 pods claiming one class-device each on a 2-device template need
        2 nodes; without the claim model cpu alone would fit all on one."""
        from autoscaler_tpu.kube.objects import DRA_CLAIM_PREFIX

        template = build_test_node("tmpl", cpu_m=8000, mem=16 * GB)
        template.allocatable = Resources(
            cpu_m=8000, memory=16 * GB, pods=110,
            extended=((DRA_CLAIM_PREFIX + "gpu.nvidia.com", 2.0),),
        )
        import dataclasses

        pods = [
            dataclasses.replace(
                build_test_pod(f"p{i}", cpu_m=100),
                resource_claims=(("gpu.nvidia.com", 1.0),),
            )
            for i in range(4)
        ]
        count, scheduled = BinpackingNodeEstimator().estimate(pods, template)
        assert count == 2
        assert len(scheduled) == 4

    def test_unclaimable_class_never_schedules(self):
        template = build_test_node("tmpl", cpu_m=8000)
        from autoscaler_tpu.kube.objects import Pod

        p = Pod("p0", resource_claims=(("fpga.example", 1.0),))
        count, scheduled = BinpackingNodeEstimator().estimate([p], template)
        assert count == 0 and scheduled == []


class TestIncrementalSchemaChange:
    def test_new_extended_name_forces_rebuild_with_parity(self):
        from autoscaler_tpu.snapshot.incremental import IncrementalPacker

        nodes = [device_node("n0", fpga=2), build_test_node("n1", cpu_m=4000)]
        plain = build_test_pod("plain", cpu_m=200, node_name="n1")
        packer = IncrementalPacker()
        t1, m1 = packer.update(
            nodes, [(plain.key(), plain)], {plain.key(): "n1"}
        )
        # fpga capacity exists but no pod requests it → base schema
        assert m1.extended_resources == ()
        full_packs_before = packer.full_packs

        nic_pod = build_test_pod("nicpod", cpu_m=100)
        nic_pod.requests = Resources(
            cpu_m=100, memory=50 * MB, pods=0, extended=((NIC, 1.0),)
        )
        items = [(plain.key(), plain), (nic_pod.key(), nic_pod)]
        t2, m2 = packer.update(nodes, items, {plain.key(): "n1"})
        assert m2.extended_resources == (NIC,)
        assert packer.full_packs == full_packs_before + 1  # schema rebuild
        # parity vs a fresh full pack on the same world
        ref_t, ref_m = pack(nodes, [plain, nic_pod])
        for key in (plain.key(), nic_pod.key()):
            i, j = m2.pod_index[key], ref_m.pod_index[key]
            np.testing.assert_array_equal(
                np.asarray(t2.pod_req[i]), np.asarray(ref_t.pod_req[j])
            )

    def test_stable_schema_stays_incremental(self):
        from autoscaler_tpu.snapshot.incremental import IncrementalPacker

        nodes = [device_node("n0", fpga=2)]
        pod = fpga_pod("p0")
        packer = IncrementalPacker()
        packer.update(nodes, [(pod.key(), pod)], {})
        before = packer.incremental_updates
        packer.update(nodes, [(pod.key(), pod)], {})
        assert packer.incremental_updates == before + 1


class TestDaemonSetAffinityTargeting:
    def _ds_with_affinity(self, key="pool", value="gpu"):
        return DaemonSet(
            name="device-plugin", namespace="kube-system",
            requests=Resources(cpu_m=300, memory=256 * MB),
            node_selector_terms=(
                LabelSelector.from_dict({key: value}),
            ),
        )

    def test_suitable_only_on_matching_nodes(self):
        ds = self._ds_with_affinity()
        target = build_test_node("gpu-node", cpu_m=4000)
        target.labels["pool"] = "gpu"
        other = build_test_node("cpu-node", cpu_m=4000)
        assert ds.suitable_for(target)
        assert not ds.suitable_for(other)

    def test_parse_from_apps_v1_json(self):
        ds = daemonset_from_json({
            "metadata": {"name": "nvidia-plugin", "namespace": "kube-system"},
            "spec": {"template": {"spec": {
                "affinity": {"nodeAffinity": {
                    "requiredDuringSchedulingIgnoredDuringExecution": {
                        "nodeSelectorTerms": [
                            {"matchExpressions": [
                                {"key": "pool", "operator": "In",
                                 "values": ["gpu"]},
                            ]},
                        ],
                    },
                }},
                "containers": [
                    {"resources": {"requests": {"cpu": "300m"}}},
                ],
            }}},
        })
        assert len(ds.node_selector_terms) == 1
        node = build_test_node("n", cpu_m=4000)
        node.labels["pool"] = "gpu"
        assert ds.suitable_for(node)
        assert not ds.suitable_for(build_test_node("m", cpu_m=4000))

    def test_match_fields_pin_to_named_node(self):
        """matchFields metadata.name must pin, not widen: a matchFields-only
        term used to parse into an empty LabelSelector that matched EVERY
        node, charging the DS into every template's overhead."""
        ds = daemonset_from_json({
            "metadata": {"name": "pinned", "namespace": "kube-system"},
            "spec": {"template": {"spec": {
                "affinity": {"nodeAffinity": {
                    "requiredDuringSchedulingIgnoredDuringExecution": {
                        "nodeSelectorTerms": [
                            {"matchFields": [
                                {"key": "metadata.name", "operator": "In",
                                 "values": ["node-x"]},
                            ]},
                        ],
                    },
                }},
                "containers": [
                    {"resources": {"requests": {"cpu": "300m"}}},
                ],
            }}},
        })
        assert ds.suitable_for(build_test_node("node-x", cpu_m=4000))
        assert not ds.suitable_for(build_test_node("node-y", cpu_m=4000))

    def test_empty_term_matches_no_nodes(self):
        """An empty nodeSelectorTerm matches NO objects in Kubernetes."""
        ds = daemonset_from_json({
            "metadata": {"name": "broken", "namespace": "kube-system"},
            "spec": {"template": {"spec": {
                "affinity": {"nodeAffinity": {
                    "requiredDuringSchedulingIgnoredDuringExecution": {
                        "nodeSelectorTerms": [{}],
                    },
                }},
                "containers": [
                    {"resources": {"requests": {"cpu": "300m"}}},
                ],
            }}},
        })
        assert not ds.suitable_for(build_test_node("any", cpu_m=4000))

    def test_pod_node_affinity_match_fields(self):
        """The same matchFields handling flows through pod parsing into
        node_matches_selector (the packer's class predicate)."""
        from autoscaler_tpu.kube.convert import pod_from_json
        from autoscaler_tpu.kube.objects import node_matches_selector

        pod = pod_from_json({
            "metadata": {"name": "p", "namespace": "default"},
            "spec": {
                "containers": [],
                "affinity": {"nodeAffinity": {
                    "requiredDuringSchedulingIgnoredDuringExecution": {
                        "nodeSelectorTerms": [
                            {"matchFields": [
                                {"key": "metadata.name", "operator": "In",
                                 "values": ["node-x"]},
                            ]},
                        ],
                    },
                }},
            },
        })
        assert node_matches_selector(pod, build_test_node("node-x", cpu_m=4000))
        assert not node_matches_selector(pod, build_test_node("node-y", cpu_m=4000))

    def test_force_ds_charges_only_affinity_matched_templates(self):
        """--force-ds through the template provider: a DS affinity-targeting
        pool=gpu charges the gpu group's template and not the cpu group's
        (reference simulator/nodes.go:56 runs the full filter chain)."""
        from autoscaler_tpu.cloudprovider.test_provider import TestCloudProvider
        from autoscaler_tpu.processors.nodeinfos import (
            MixedTemplateNodeInfoProvider,
        )

        provider = TestCloudProvider()
        gpu_tmpl = build_test_node("gpu-tmpl", cpu_m=4000, mem=8 * GB)
        gpu_tmpl.labels["pool"] = "gpu"
        cpu_tmpl = build_test_node("cpu-tmpl", cpu_m=4000, mem=8 * GB)
        provider.add_node_group("gpu", 0, 10, 1, gpu_tmpl)
        provider.add_node_group("cpu", 0, 10, 1, cpu_tmpl)
        gpu_node = build_test_node("gpu-0", cpu_m=4000, mem=8 * GB)
        gpu_node.labels["pool"] = "gpu"
        cpu_node = build_test_node("cpu-0", cpu_m=4000, mem=8 * GB)
        provider.add_node("gpu", gpu_node)
        provider.add_node("cpu", cpu_node)

        prov = MixedTemplateNodeInfoProvider()
        ds = self._ds_with_affinity()
        groups = {g.id(): g for g in provider.node_groups()}
        tmpl_gpu = prov.template_for(
            groups["gpu"], [gpu_node], 0.0,
            pending_daemonsets=[ds],
        )
        tmpl_cpu = prov.template_for(
            groups["cpu"], [cpu_node], 0.0,
            pending_daemonsets=[ds],
        )
        assert tmpl_gpu.daemon_overhead.cpu_m == pytest.approx(300)
        assert tmpl_cpu.daemon_overhead.cpu_m == 0.0
