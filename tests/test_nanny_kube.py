"""Addon-resizer binary against the recorded HTTP API server.

Reference: addon-resizer/nanny/nanny_lib.go:103 (PollAPIServer) — count
nodes, read the dependent container, resize when outside the deadband.
"""
import pytest

from test_kube_client import FakeApiServer, node_json

from autoscaler_tpu.addonresizer.main import NannyRunner, main
from autoscaler_tpu.addonresizer.nanny import LinearEstimator
from autoscaler_tpu.kube.client import KubeRestClient

MB = 1024 * 1024


def dep_json(name="metrics-server", ns="kube-system", cpu="300m", mem="200Mi",
             limits=True):
    qty = {"cpu": cpu, "memory": mem}
    resources = {"requests": dict(qty)}
    if limits:
        resources["limits"] = dict(qty)
    return {
        "metadata": {"name": name, "namespace": ns},
        "spec": {
            "template": {
                "spec": {
                    "containers": [
                        {"name": name, "resources": resources}
                    ]
                }
            }
        },
    }


@pytest.fixture()
def srv():
    s = FakeApiServer()
    yield s
    s.close()


def make_runner(srv):
    return NannyRunner(
        KubeRestClient(srv.url),
        "kube-system",
        "metrics-server",
        "metrics-server",
        LinearEstimator(
            base_cpu_m=300.0, cpu_per_node_m=2.0,
            base_memory=200 * MB, memory_per_node=1 * MB,
        ),
    )


class TestNannyRunner:
    def test_resizes_on_node_count_growth(self, srv):
        srv.deployments["kube-system/metrics-server"] = dep_json()
        for i in range(200):
            srv.nodes[f"n{i}"] = node_json(f"n{i}")
        runner = make_runner(srv)
        assert runner.run_once() is True  # 300m base → 700m at 200 nodes
        req = srv.deployments["kube-system/metrics-server"]["spec"]["template"][
            "spec"
        ]["containers"][0]["resources"]
        assert req["requests"]["cpu"] == "700m"
        assert req["requests"] == req["limits"]  # nanny writes both
        # steady state: within deadband → no further writes
        writes_before = len(srv.writes)
        assert runner.run_once() is False
        assert len(srv.writes) == writes_before

    def test_deadband_swallows_small_changes(self, srv):
        srv.deployments["kube-system/metrics-server"] = dep_json(
            cpu="320m", mem="210Mi"
        )
        for i in range(5):
            srv.nodes[f"n{i}"] = node_json(f"n{i}")
        # want 310m vs current 320m: ~3% < 10% deadband
        assert make_runner(srv).run_once() is False

    def test_drifted_limits_reconciled(self, srv):
        """checkResource compares limits too (nanny_lib.go:125): in-band
        requests with missing or drifted limits still get reconciled to
        requests == limits."""
        srv.deployments["kube-system/metrics-server"] = dep_json(
            cpu="310m", mem="205Mi", limits=False
        )
        for i in range(5):
            srv.nodes[f"n{i}"] = node_json(f"n{i}")
        runner = make_runner(srv)
        assert runner.run_once() is True  # requests in band, limits absent
        req = srv.deployments["kube-system/metrics-server"]["spec"]["template"][
            "spec"
        ]["containers"][0]["resources"]
        assert req["requests"] == req["limits"]
        assert runner.run_once() is False  # now fully converged

    def test_cli_binary(self, srv):
        srv.deployments["kube-system/metrics-server"] = dep_json()
        for i in range(100):
            srv.nodes[f"n{i}"] = node_json(f"n{i}")
        rc = main([
            "--kube-api", srv.url,
            "--deployment", "metrics-server",
            "--poll-period", "0",
            "--max-iterations", "2",
        ])
        assert rc == 0
        req = srv.deployments["kube-system/metrics-server"]["spec"]["template"][
            "spec"
        ]["containers"][0]["resources"]["requests"]
        assert req["cpu"] == "500m"  # 300m + 2m * 100 nodes
