"""Cluster API provider — annotation-driven discovery, scale semantics,
node→machine→group resolution, scale subresource wire path.

Reference behaviors pinned: clusterapi_nodegroup.go (IncreaseSize,
DeleteNodes mark+shrink with rollback, DecreaseTargetSize bounds,
TemplateNodeInfo gated on CanScaleFromZero), clusterapi_controller.go
(nodeGroupForNode via machine ownership), clusterapi_utils.go (annotation
keys, capacity parsing).
"""
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from autoscaler_tpu.cloudprovider.clusterapi import (
    CPU_KEY,
    GPU_COUNT_KEY,
    LABELS_KEY,
    MAX_PODS_KEY,
    MEMORY_KEY,
    TAINTS_KEY,
    ClusterAPIProvider,
    InMemoryCapiApi,
    RestCapiApi,
    delete_machine_key,
    machine_annotation_key,
    max_size_key,
    min_size_key,
)
from autoscaler_tpu.cloudprovider.interface import InstanceState, NodeGroupError
from autoscaler_tpu.utils.test_utils import build_test_node


def md(name, ns="default", replicas=3, min_size=1, max_size=10, ann=None):
    a = {min_size_key(): str(min_size), max_size_key(): str(max_size)}
    a.update(ann or {})
    return {
        "kind": "MachineDeployment",
        "metadata": {"name": name, "namespace": ns, "annotations": a},
        "spec": {
            "replicas": replicas,
            "selector": {"matchLabels": {"md": name}},
        },
    }


def ms(name, ns="default", owner_md=None, replicas=3, annotated=False):
    meta = {"name": name, "namespace": ns, "annotations": {}}
    if owner_md:
        meta["ownerReferences"] = [
            {"kind": "MachineDeployment", "name": owner_md, "controller": True}
        ]
    if annotated:
        meta["annotations"] = {min_size_key(): "0", max_size_key(): "5"}
    sel = {"md": owner_md} if owner_md else {"ms": name}
    return {
        "kind": "MachineSet",
        "metadata": meta,
        "spec": {"replicas": replicas, "selector": {"matchLabels": sel}},
    }


def machine(name, ns="default", owner_ms=None, labels=None, provider_id=None,
            phase="Running", deleting=False):
    meta = {"name": name, "namespace": ns, "labels": labels or {}}
    if owner_ms:
        meta["ownerReferences"] = [
            {"kind": "MachineSet", "name": owner_ms, "controller": True}
        ]
    if deleting:
        meta["deletionTimestamp"] = "2026-07-31T00:00:00Z"
    spec = {}
    if provider_id:
        spec["providerID"] = provider_id
    return {
        "kind": "Machine",
        "metadata": meta,
        "spec": spec,
        "status": {"phase": phase},
    }


def capi_node(name, machine_ref, provider_id=""):
    n = build_test_node(name, cpu_m=4000)
    n.annotations[machine_annotation_key()] = machine_ref
    n.provider_id = provider_id
    return n


def world():
    """One MD-managed group (MD annotated, its MS not) + one standalone
    annotated MS + one unmanaged MS."""
    api = InMemoryCapiApi()
    api.add(md("web", replicas=2))
    api.add(ms("web-abc", owner_md="web", replicas=2))
    api.add(ms("solo", annotated=True, replicas=1))
    api.add(ms("plain", replicas=4))  # no annotations → not managed
    for i in range(2):
        api.add(machine(f"web-abc-{i}", owner_ms="web-abc",
                        labels={"md": "web"},
                        provider_id=f"capi:////web-{i}"))
    api.add(machine("solo-0", owner_ms="solo", labels={"ms": "solo"},
                    provider_id="capi:////solo-0"))
    return api


class TestDiscovery:
    def test_annotated_resources_become_groups(self):
        p = ClusterAPIProvider(world())
        ids = sorted(g.id() for g in p.node_groups())
        assert ids == [
            "MachineDeployment/default/web",
            "MachineSet/default/solo",
        ]

    def test_zero_replica_group_needs_capacity_annotations(self):
        api = InMemoryCapiApi()
        api.add(md("cold", replicas=0))
        assert ClusterAPIProvider(api).node_groups() == []
        api.add(md("warm", replicas=0, ann={CPU_KEY: "4", MEMORY_KEY: "16Gi"}))
        p = ClusterAPIProvider(api)
        assert [g.id() for g in p.node_groups()] == [
            "MachineDeployment/default/warm"
        ]

    def test_sizes_and_target(self):
        p = ClusterAPIProvider(world())
        g = {x.id(): x for x in p.node_groups()}["MachineDeployment/default/web"]
        assert (g.min_size(), g.max_size(), g.target_size()) == (1, 10, 2)


class TestNodeGroupForNode:
    def test_via_machine_annotation_md_owns(self):
        p = ClusterAPIProvider(world())
        node = capi_node("web-0", "default/web-abc-0")
        g = p.node_group_for_node(node)
        assert g is not None and g.id() == "MachineDeployment/default/web"

    def test_via_provider_id_fallback(self):
        p = ClusterAPIProvider(world())
        node = build_test_node("solo-0", cpu_m=4000)
        node.provider_id = "capi:////solo-0"
        g = p.node_group_for_node(node)
        assert g is not None and g.id() == "MachineSet/default/solo"

    def test_unknown_node(self):
        p = ClusterAPIProvider(world())
        assert p.node_group_for_node(build_test_node("stray")) is None


class TestScaling:
    def test_increase_size_writes_scale(self):
        api = world()
        p = ClusterAPIProvider(api)
        g = {x.id(): x for x in p.node_groups()}["MachineDeployment/default/web"]
        g.increase_size(3)
        assert api.get_scale("MachineDeployment", "default", "web") == 5
        assert ("scale", "MachineDeployment", "default", "web", 5) in api.writes

    def test_increase_past_max_refused(self):
        p = ClusterAPIProvider(world())
        g = {x.id(): x for x in p.node_groups()}["MachineDeployment/default/web"]
        with pytest.raises(NodeGroupError, match="too large"):
            g.increase_size(100)

    def test_delete_nodes_marks_and_shrinks(self):
        api = world()
        p = ClusterAPIProvider(api)
        g = {x.id(): x for x in p.node_groups()}["MachineDeployment/default/web"]
        node = capi_node("web-0", "default/web-abc-0")
        g.delete_nodes([node])
        assert api.get_scale("MachineDeployment", "default", "web") == 1
        m = api.objects[("Machine", "default", "web-abc-0")]
        assert delete_machine_key() in m["metadata"]["annotations"]

    def test_delete_foreign_node_refused(self):
        p = ClusterAPIProvider(world())
        g = {x.id(): x for x in p.node_groups()}["MachineDeployment/default/web"]
        foreign = capi_node("solo-0", "default/solo-0")
        with pytest.raises(NodeGroupError, match="doesn't belong"):
            g.delete_nodes([foreign])

    def test_delete_below_min_refused(self):
        api = InMemoryCapiApi()
        api.add(md("tight", replicas=1, min_size=1))
        api.add(ms("tight-1", owner_md="tight", replicas=1))
        api.add(machine("tight-m", owner_ms="tight-1", labels={"md": "tight"},
                        provider_id="capi:////t0"))
        p = ClusterAPIProvider(api)
        g = p.node_groups()[0]
        with pytest.raises(NodeGroupError, match="min size"):
            g.delete_nodes([capi_node("t", "default/tight-m")])

    def test_decrease_target_cannot_delete_existing(self):
        api = world()
        api.set_scale("MachineDeployment", "default", "web", 4)
        p = ClusterAPIProvider(api)
        g = {x.id(): x for x in p.node_groups()}["MachineDeployment/default/web"]
        g.decrease_target_size(-2)  # 4 -> 2 == provisioned machines: fine
        assert g.target_size() == 2
        with pytest.raises(NodeGroupError, match="existing"):
            g.decrease_target_size(-1)  # would dip below the 2 machines


class TestInstancesAndTemplate:
    def test_instance_states(self):
        api = world()
        api.add(machine("web-abc-new", owner_ms="web-abc", labels={"md": "web"},
                        phase="Provisioning"))
        api.add(machine("web-abc-dying", owner_ms="web-abc", labels={"md": "web"},
                        provider_id="capi:////dying", deleting=True))
        p = ClusterAPIProvider(api)
        g = {x.id(): x for x in p.node_groups()}["MachineDeployment/default/web"]
        by_id = {i.id: i.state for i in g.nodes()}
        assert by_id["capi:////web-0"] == InstanceState.RUNNING
        assert by_id["capi://default/web-abc-new"] == InstanceState.CREATING
        assert by_id["capi:////dying"] == InstanceState.DELETING

    def test_template_from_capacity_annotations(self):
        api = InMemoryCapiApi()
        api.add(md("gpu", replicas=0, ann={
            CPU_KEY: "8", MEMORY_KEY: "32Gi", GPU_COUNT_KEY: "2",
            MAX_PODS_KEY: "58",
            LABELS_KEY: "pool=gpu,zone=z1",
            TAINTS_KEY: "nvidia.com/gpu=present:NoSchedule",
        }))
        p = ClusterAPIProvider(api)
        t = p.node_groups()[0].template_node_info()
        assert t.allocatable.cpu_m == 8000
        assert t.allocatable.memory == 32 * 1024**3
        assert t.allocatable.gpu == 2
        assert t.allocatable.pods == 58
        assert t.labels["pool"] == "gpu" and t.labels["zone"] == "z1"
        assert t.taints[0].key == "nvidia.com/gpu"
        assert t.taints[0].effect == "NoSchedule"

    def test_template_without_capacity_refused(self):
        p = ClusterAPIProvider(world())
        g = {x.id(): x for x in p.node_groups()}["MachineDeployment/default/web"]
        with pytest.raises(NodeGroupError, match="scale from zero"):
            g.template_node_info()


class TestAutoDiscovery:
    """--node-group-auto-discovery=clusterapi:... filtering
    (clusterapi_autodiscovery.go: namespace / clusterName / exact-match
    label requirements; multiple specs OR together)."""

    def _api(self):
        from autoscaler_tpu.cloudprovider.clusterapi import cluster_name_label

        api = InMemoryCapiApi()
        a = md("web-a", ns="team-a")
        a["metadata"]["labels"] = {cluster_name_label(): "prod"}
        api.add(a)
        b = md("web-b", ns="team-b")
        b["spec"]["clusterName"] = "staging"
        api.add(b)
        c = md("web-c", ns="team-a")
        c["metadata"]["labels"] = {"tier": "gpu"}
        api.add(c)
        return api

    def test_namespace_filter(self):
        from autoscaler_tpu.cloudprovider.clusterapi import AutoDiscoverySpec

        p = ClusterAPIProvider(
            self._api(), [AutoDiscoverySpec("clusterapi:namespace=team-a")]
        )
        assert sorted(g.id() for g in p.node_groups()) == [
            "MachineDeployment/team-a/web-a",
            "MachineDeployment/team-a/web-c",
        ]

    def test_cluster_name_filter_spec_and_label(self):
        from autoscaler_tpu.cloudprovider.clusterapi import AutoDiscoverySpec

        p = ClusterAPIProvider(
            self._api(), [AutoDiscoverySpec("clusterapi:clusterName=prod")]
        )
        assert [g.id() for g in p.node_groups()] == [
            "MachineDeployment/team-a/web-a"
        ]
        p = ClusterAPIProvider(
            self._api(), [AutoDiscoverySpec("clusterapi:clusterName=staging")]
        )
        assert [g.id() for g in p.node_groups()] == [
            "MachineDeployment/team-b/web-b"
        ]

    def test_label_requirement_and_or_of_specs(self):
        from autoscaler_tpu.cloudprovider.clusterapi import AutoDiscoverySpec

        p = ClusterAPIProvider(
            self._api(),
            [
                AutoDiscoverySpec("clusterapi:tier=gpu"),
                AutoDiscoverySpec("clusterapi:clusterName=staging"),
            ],
        )
        assert sorted(g.id() for g in p.node_groups()) == [
            "MachineDeployment/team-a/web-c",
            "MachineDeployment/team-b/web-b",
        ]

    def test_bad_spec_rejected(self):
        from autoscaler_tpu.cloudprovider.clusterapi import AutoDiscoverySpec

        with pytest.raises(ValueError, match="should be clusterapi:"):
            AutoDiscoverySpec("mig:zone=us")
        with pytest.raises(ValueError, match="key=value"):
            AutoDiscoverySpec("clusterapi:namespaceonly")


class TestResilience:
    def test_malformed_annotation_skips_one_resource(self, caplog):
        """A typo'd max-size on ONE resource must not disable autoscaling
        for the whole cluster (the reference logs and skips too)."""
        import logging

        api = world()
        api.add(md("broken", ann={max_size_key(): "ten"}))
        with caplog.at_level(logging.WARNING, logger="clusterapi"):
            p = ClusterAPIProvider(api)
        ids = sorted(g.id() for g in p.node_groups())
        assert ids == [
            "MachineDeployment/default/web",
            "MachineSet/default/solo",
        ]
        assert any("broken" in r.message for r in caplog.records)

    def test_delete_rollback_on_transport_failure(self):
        """A shrink that dies in transport (not a bound check) must unmark
        the machine — otherwise the CAPI controller reaps it on the next
        unrelated scale-down (clusterapi_nodegroup.go:160-163)."""

        class FlakyApi(InMemoryCapiApi):
            def set_scale(self, kind, ns, name, replicas):
                raise ConnectionError("api server hiccup")

        api = FlakyApi()
        api.add(md("web", replicas=2))
        api.add(ms("web-abc", owner_md="web", replicas=2))
        for i in range(2):
            api.add(machine(f"web-abc-{i}", owner_ms="web-abc",
                            labels={"md": "web"},
                            provider_id=f"capi:////web-{i}"))
        p = ClusterAPIProvider(api)
        g = p.node_groups()[0]
        with pytest.raises(ConnectionError):
            g.delete_nodes([capi_node("web-0", "default/web-abc-0")])
        m = api.objects[("Machine", "default", "web-abc-0")]
        assert delete_machine_key() not in (
            m["metadata"].get("annotations") or {}
        )

    def test_lookups_use_refresh_snapshot_not_per_call_lists(self):
        """node_group_for_node for N nodes must not issue N cluster-wide
        LISTs — lookups read the refresh-scoped memo."""

        class CountingApi(InMemoryCapiApi):
            def __init__(self):
                super().__init__()
                self.list_calls = 0

            def list_machines(self, namespace):
                self.list_calls += 1
                return super().list_machines(namespace)

        api = CountingApi()
        api.add(md("web", replicas=2))
        api.add(ms("web-abc", owner_md="web", replicas=2))
        for i in range(2):
            api.add(machine(f"web-abc-{i}", owner_ms="web-abc",
                            labels={"md": "web"},
                            provider_id=f"capi:////web-{i}"))
        p = ClusterAPIProvider(api)
        api.list_calls = 0
        for i in range(10):
            g = p.node_group_for_node(
                capi_node(f"n{i}", f"default/web-abc-{i % 2}")
            )
            assert g is not None
        assert api.list_calls <= 1  # one memo fill, not one per call


class FakeCapiServer:
    """Minimal CRD API server: cluster-wide lists, the scale subresource,
    and machine merge-patches — what RestCapiApi actually speaks."""

    def __init__(self, api: InMemoryCapiApi):
        self.api = api
        self.requests = []
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _send(self, code, payload):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _route(self):
                # /apis/cluster.x-k8s.io/v1beta1/...
                parts = self.path.split("?")[0].strip("/").split("/")
                outer.requests.append((self.command, self.path))
                return parts

            def do_GET(self):
                parts = self._route()
                plural_kind = {
                    "machinedeployments": "MachineDeployment",
                    "machinesets": "MachineSet",
                    "machines": "Machine",
                }
                if parts[-1] in plural_kind:  # cluster or ns list
                    kind = plural_kind[parts[-1]]
                    ns = parts[parts.index("namespaces") + 1] \
                        if "namespaces" in parts else None
                    items = [
                        o for (k, n, _), o in sorted(outer.api.objects.items())
                        if k == kind and (ns is None or n == ns)
                    ]
                    self._send(200, {"items": items})
                elif parts[-1] == "scale":
                    kind = plural_kind[parts[-3]]
                    ns, name = parts[parts.index("namespaces") + 1], parts[-2]
                    self._send(200, {
                        "kind": "Scale",
                        "metadata": {"name": name, "namespace": ns},
                        "spec": {"replicas": outer.api.get_scale(kind, ns, name)},
                    })
                else:
                    self._send(404, {})

            def do_PUT(self):
                parts = self._route()
                length = int(self.headers.get("Content-Length") or 0)
                body = json.loads(self.rfile.read(length))
                if parts[-1] == "scale":
                    plural_kind = {
                        "machinedeployments": "MachineDeployment",
                        "machinesets": "MachineSet",
                    }
                    kind = plural_kind[parts[-3]]
                    ns, name = parts[parts.index("namespaces") + 1], parts[-2]
                    outer.api.set_scale(
                        kind, ns, name, body["spec"]["replicas"]
                    )
                    self._send(200, body)
                else:
                    self._send(404, {})

            def do_PATCH(self):
                parts = self._route()
                length = int(self.headers.get("Content-Length") or 0)
                body = json.loads(self.rfile.read(length))
                if parts[-2] == "machines" or parts[-3] == "machines":
                    ns = parts[parts.index("namespaces") + 1]
                    name = parts[-1]
                    for key, value in (
                        body.get("metadata", {}).get("annotations", {}) or {}
                    ).items():
                        outer.api.annotate_machine(ns, name, key, value)
                    self._send(200, {})
                else:
                    self._send(404, {})

        self.server = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.port = self.server.server_address[1]
        threading.Thread(target=self.server.serve_forever, daemon=True).start()

    def close(self):
        self.server.shutdown()


class TestRestWirePath:
    def test_rest_api_end_to_end(self):
        from autoscaler_tpu.kube.client import KubeRestClient

        backing = world()
        srv = FakeCapiServer(backing)
        try:
            rest = KubeRestClient(f"http://127.0.0.1:{srv.port}")
            p = ClusterAPIProvider(RestCapiApi(rest))
            ids = sorted(g.id() for g in p.node_groups())
            assert ids == [
                "MachineDeployment/default/web",
                "MachineSet/default/solo",
            ]
            g = {x.id(): x for x in p.node_groups()}[
                "MachineDeployment/default/web"
            ]
            g.increase_size(2)
            assert backing.get_scale("MachineDeployment", "default", "web") == 4
            # delete over the wire: scale PUT + machine PATCH
            node = capi_node("web-0", "default/web-abc-0")
            g.delete_nodes([node])
            assert backing.get_scale("MachineDeployment", "default", "web") == 3
            m = backing.objects[("Machine", "default", "web-abc-0")]
            assert delete_machine_key() in m["metadata"]["annotations"]
            methods = {c for c, _ in srv.requests}
            assert {"GET", "PUT", "PATCH"} <= methods
        finally:
            srv.close()


class TestControlLoopIntegration:
    def test_scale_up_through_run_once(self):
        """A pending pod no existing node absorbs drives run_once to
        increase the MachineDeployment's scale — the provider inside the
        real decision path (scale-from-zero template via capacity
        annotations)."""
        from autoscaler_tpu.config.options import AutoscalingOptions
        from autoscaler_tpu.core.static_autoscaler import StaticAutoscaler
        from autoscaler_tpu.kube.api import FakeClusterAPI
        from autoscaler_tpu.utils.test_utils import build_test_pod

        api = InMemoryCapiApi()
        api.add(md("workers", replicas=0, min_size=0, max_size=5, ann={
            CPU_KEY: "8", MEMORY_KEY: "32Gi",
        }))
        provider = ClusterAPIProvider(api)
        kube = FakeClusterAPI()
        pod = build_test_pod("pending-1", cpu_m=2000)
        kube.add_pod(pod)
        opts = AutoscalingOptions()
        autoscaler = StaticAutoscaler(provider, kube, opts)
        autoscaler.run_once(now_ts=1000.0)
        assert api.get_scale("MachineDeployment", "default", "workers") >= 1


class TestFailedMachines:
    """ADVICE r5 — status.failureMessage / Failed phase must surface as
    InstanceErrorInfo on a stable capi:// id so the core's fast
    deleteCreatedNodesWithErrors + failed-scale-up path reacts, instead of
    waiting out maxNodeProvisionTime."""

    def _world_with_failed(self, failure_message="quota exhausted", phase="Failed"):
        api = InMemoryCapiApi()
        api.add(md("web", replicas=3))
        api.add(ms("web-abc", owner_md="web", replicas=3))
        for i in range(2):
            api.add(machine(f"web-abc-{i}", owner_ms="web-abc",
                            labels={"md": "web"},
                            provider_id=f"capi:////web-{i}"))
        failed = machine("web-abc-2", owner_ms="web-abc",
                         labels={"md": "web"}, phase=phase)
        if failure_message:
            failed["status"]["failureMessage"] = failure_message
        api.add(failed)
        p = ClusterAPIProvider(api)
        (group,) = p.node_groups()
        return api, p, group

    def test_failure_message_surfaces_error_info(self):
        from autoscaler_tpu.cloudprovider.interface import InstanceErrorClass

        _, _, group = self._world_with_failed()
        errored = [i for i in group.nodes() if i.error_info is not None]
        assert len(errored) == 1
        inst = errored[0]
        assert inst.id == "capi://default/web-abc-2"
        assert inst.state == InstanceState.CREATING
        assert inst.error_info.error_class == InstanceErrorClass.OTHER
        assert "quota exhausted" in inst.error_info.error_message

    def test_failed_phase_without_message_still_errors(self):
        _, _, group = self._world_with_failed(failure_message="")
        errored = [i for i in group.nodes() if i.error_info is not None]
        assert len(errored) == 1
        assert "failed" in errored[0].error_info.error_message

    def test_healthy_machines_carry_no_error_info(self):
        _, _, group = self._world_with_failed()
        healthy = [i for i in group.nodes() if i.error_info is None]
        assert len(healthy) == 2
        assert all(i.state == InstanceState.RUNNING for i in healthy)

    def test_errored_instance_deletable_by_capi_id(self):
        """The core deletes errored instances as Node(name=id,
        provider_id=id) — the capi:// id must resolve back to the machine
        (static_autoscaler._delete_created_nodes_with_errors)."""
        from autoscaler_tpu.kube.objects import Node

        api, _, group = self._world_with_failed()
        (inst,) = [i for i in group.nodes() if i.error_info is not None]
        group.delete_nodes([Node(name=inst.id, provider_id=inst.id)])
        assert group.target_size() == 2
        m = [x for x in api.list_machines("default")
             if x["metadata"]["name"] == "web-abc-2"][0]
        assert delete_machine_key() in m["metadata"].get("annotations", {})
