"""Parity tests for the Pallas dynamic-affinity FFD kernel
(ops/pallas_binpack_affinity) against the XLA scan twin
(ops/binpack.ffd_binpack_groups_affinity), which is itself locked to the
serial oracle in tests/test_affinity_binpack.py — so exact agreement here
chains to oracle parity. Runs in interpret mode on the CPU test platform;
the real-TPU path is exercised by benchmarks/affinity_bench.py.
"""
import numpy as np
import pytest

import jax.numpy as jnp

from autoscaler_tpu.kube.objects import CPU, MEMORY, PODS
from autoscaler_tpu.ops.binpack import ffd_binpack_groups_affinity
from autoscaler_tpu.ops.pallas_binpack_affinity import (
    _pack_term_bits,
    ffd_binpack_groups_affinity_pallas,
)


def rand_world(seed, P=40, G=3, T=5, max_nodes=16):
    rng = np.random.default_rng(seed)
    pod_req = np.zeros((P, 6), np.float32)
    pod_req[:, CPU] = rng.integers(200, 2500, P)
    pod_req[:, MEMORY] = rng.integers(128, 4096, P)
    pod_req[:, PODS] = 1
    allocs = np.zeros((G, 6), np.float32)
    allocs[:, CPU] = rng.integers(3000, 9000, G)
    allocs[:, MEMORY] = rng.integers(6000, 16000, G)
    allocs[:, PODS] = 32
    masks = rng.random((G, P)) > 0.1
    match = rng.random((T, P)) < 0.4
    aff_of = (rng.random((T, P)) < 0.15) & match  # realistic: self-matching
    anti_of = (rng.random((T, P)) < 0.15) & ~aff_of
    node_level = rng.random(T) < 0.5
    has_label = rng.random((G, T)) < 0.8
    caps = rng.integers(2, max_nodes, G).astype(np.int32)
    return pod_req, masks, allocs, match, aff_of, anti_of, node_level, has_label, caps


def assert_twin_parity(pod_req, masks, allocs, max_nodes, match, aff_of,
                       anti_of, node_level, has_label, caps=None):
    jcaps = None if caps is None else jnp.asarray(caps)
    ref = ffd_binpack_groups_affinity(
        jnp.asarray(pod_req), jnp.asarray(masks), jnp.asarray(allocs),
        max_nodes=max_nodes,
        match=jnp.asarray(match), aff_of=jnp.asarray(aff_of),
        anti_of=jnp.asarray(anti_of), node_level=jnp.asarray(node_level),
        has_label=jnp.asarray(has_label), node_caps=jcaps,
    )
    out = ffd_binpack_groups_affinity_pallas(
        pod_req, masks, allocs, max_nodes=max_nodes,
        match=match, aff_of=aff_of, anti_of=anti_of,
        node_level=node_level, has_label=has_label, node_caps=caps,
        interpret=True,
    )
    np.testing.assert_array_equal(
        np.asarray(ref.node_count), np.asarray(out.node_count)
    )
    np.testing.assert_array_equal(
        np.asarray(ref.scheduled), np.asarray(out.scheduled)
    )
    np.testing.assert_array_equal(
        np.asarray(ref.node_used), np.asarray(out.node_used)
    )


class TestPackBits:
    def test_roundtrip_layout(self):
        rng = np.random.default_rng(0)
        T, N = 37, 11                     # spills into a second plane
        rows = rng.random((T, N)) < 0.5
        planes = np.asarray(_pack_term_bits(jnp.asarray(rows), 2))
        for t in range(T):
            for n in range(N):
                bit = (planes[t // 32, n] >> (t % 32)) & 1
                assert bool(bit) == rows[t, n]


class TestParity:
    @pytest.mark.parametrize("seed", range(6))
    def test_randomized_worlds(self, seed):
        assert_twin_parity(*rand_world(seed)[:3], 16, *rand_world(seed)[3:])

    def test_many_terms_multi_plane(self):
        """T > 32 exercises multi-plane bitsets."""
        w = rand_world(11, P=48, G=2, T=40)
        assert_twin_parity(*w[:3], 12, *w[3:])

    def test_anti_affinity_one_per_node(self):
        """4 mutually anti-affine pods need 4 nodes despite resource room."""
        P = 4
        pod_req = np.zeros((P, 6), np.float32)
        pod_req[:, CPU] = 500
        pod_req[:, PODS] = 1
        allocs = np.zeros((1, 6), np.float32)
        allocs[0, CPU] = 4000
        allocs[0, PODS] = 110
        masks = np.ones((1, P), bool)
        match = np.ones((1, P), bool)
        aff_of = np.zeros((1, P), bool)
        anti_of = np.ones((1, P), bool)
        node_level = np.array([True])
        has_label = np.ones((1, 1), bool)
        out = ffd_binpack_groups_affinity_pallas(
            pod_req, masks, allocs, max_nodes=8,
            match=match, aff_of=aff_of, anti_of=anti_of,
            node_level=node_level, has_label=has_label, interpret=True,
        )
        assert int(out.node_count[0]) == 4
        assert_twin_parity(pod_req, masks, allocs, 8, match, aff_of,
                           anti_of, node_level, has_label)

    def test_affinity_colocation_with_seeding(self):
        """Affinity-requiring pods that match their own term co-locate on
        one node via the self-match seeding rule."""
        P = 3
        pod_req = np.zeros((P, 6), np.float32)
        pod_req[:, CPU] = 500
        pod_req[:, PODS] = 1
        allocs = np.zeros((1, 6), np.float32)
        allocs[0, CPU] = 4000
        allocs[0, PODS] = 110
        masks = np.ones((1, P), bool)
        match = np.ones((1, P), bool)
        aff_of = np.ones((1, P), bool)
        anti_of = np.zeros((1, P), bool)
        node_level = np.array([True])
        has_label = np.ones((1, 1), bool)
        out = ffd_binpack_groups_affinity_pallas(
            pod_req, masks, allocs, max_nodes=8,
            match=match, aff_of=aff_of, anti_of=anti_of,
            node_level=node_level, has_label=has_label, interpret=True,
        )
        assert int(out.node_count[0]) == 1
        assert np.asarray(out.scheduled)[0].all()
        assert_twin_parity(pod_req, masks, allocs, 8, match, aff_of,
                           anti_of, node_level, has_label)

    def test_group_level_no_label_never_blocks(self):
        """A template lacking the topology label: anti terms over it cannot
        be violated, affinity terms over it cannot be satisfied."""
        w = list(rand_world(3))
        w[7] = np.zeros_like(w[7])  # has_label all False
        assert_twin_parity(*w[:3], 16, *w[3:])

    def test_zero_terms_degenerates_to_plain(self):
        from autoscaler_tpu.ops.binpack import ffd_binpack_groups

        rng = np.random.default_rng(9)
        P, G = 30, 2
        pod_req = np.zeros((P, 6), np.float32)
        pod_req[:, CPU] = rng.integers(100, 2000, P)
        pod_req[:, PODS] = 1
        allocs = np.zeros((G, 6), np.float32)
        allocs[:, CPU] = rng.integers(2000, 8000, G)
        allocs[:, PODS] = 110
        masks = np.ones((G, P), bool)
        plain = ffd_binpack_groups(
            jnp.asarray(pod_req), jnp.asarray(masks), jnp.asarray(allocs),
            max_nodes=16,
        )
        out = ffd_binpack_groups_affinity_pallas(
            pod_req, masks, allocs, max_nodes=16,
            match=np.zeros((0, P), bool), aff_of=np.zeros((0, P), bool),
            anti_of=np.zeros((0, P), bool), node_level=np.zeros(0, bool),
            has_label=np.zeros((G, 0), bool), interpret=True,
        )
        np.testing.assert_array_equal(
            np.asarray(plain.node_count), np.asarray(out.node_count)
        )
        np.testing.assert_array_equal(
            np.asarray(plain.scheduled), np.asarray(out.scheduled)
        )

    def test_multi_chunk_carry(self):
        """Terms and capacity carry across pod-chunk boundaries."""
        w = rand_world(17, P=70, G=2, T=3)
        pod_req, masks, allocs = w[:3]
        out_small = ffd_binpack_groups_affinity_pallas(
            pod_req, masks, allocs, max_nodes=12,
            match=w[3], aff_of=w[4], anti_of=w[5],
            node_level=w[6], has_label=w[7], node_caps=w[8],
            chunk=16, interpret=True,
        )
        ref = ffd_binpack_groups_affinity(
            jnp.asarray(pod_req), jnp.asarray(masks), jnp.asarray(allocs),
            max_nodes=12,
            match=jnp.asarray(w[3]), aff_of=jnp.asarray(w[4]),
            anti_of=jnp.asarray(w[5]), node_level=jnp.asarray(w[6]),
            has_label=jnp.asarray(w[7]), node_caps=jnp.asarray(w[8]),
        )
        np.testing.assert_array_equal(
            np.asarray(ref.node_count), np.asarray(out_small.node_count)
        )
        np.testing.assert_array_equal(
            np.asarray(ref.scheduled), np.asarray(out_small.scheduled)
        )


class TestEstimatorRouting:
    def test_estimate_many_routes_affinity_to_pallas_on_tpu(self, monkeypatch):
        """On a TPU backend, estimate_many's dynamic-affinity dispatch (no
        hard spread) takes the Pallas twin; results must equal the XLA
        route. The backend is spoofed and the kernel pinned to interpret
        mode so the route itself is exercised on the CPU test platform."""
        import autoscaler_tpu.estimator.binpacking as bp
        import autoscaler_tpu.ops.pallas_binpack_affinity as pba
        from autoscaler_tpu.utils.test_utils import (
            anti_affinity,
            build_test_node,
            build_test_pod,
        )

        pods = []
        for i in range(8):
            p = build_test_pod(f"p{i}", cpu_m=400, labels={"app": "web"})
            if i < 4:
                p.affinity = anti_affinity({"app": "web"})
            pods.append(p)
        tmpl = build_test_node("tmpl", cpu_m=4000)
        est = bp.BinpackingNodeEstimator()
        want = est.estimate_many(pods, {"g": tmpl})   # XLA route (cpu)

        calls = []
        real = pba.ffd_binpack_groups_affinity_pallas

        def spy(*args, **kw):
            calls.append(1)
            kw["interpret"] = True      # spoofed backend, still on CPU
            return real(*args, **kw)

        monkeypatch.setattr(pba, "ffd_binpack_groups_affinity_pallas", spy)
        monkeypatch.setattr(bp.jax, "default_backend", lambda: "tpu")
        got = est.estimate_many(pods, {"g": tmpl})
        assert calls, "pallas affinity route was not taken"
        assert got.keys() == want.keys()
        for g in want:
            assert got[g][0] == want[g][0]
            assert [p.name for p in got[g][1]] == [p.name for p in want[g][1]]


class TestRouteObservability:
    """r4 verdict weak #6: losing the VMEM fast path must be observable —
    a route metric on every dispatch, one log line on real cliffs."""

    def _world(self):
        from autoscaler_tpu.utils.test_utils import (
            anti_affinity,
            build_test_node,
            build_test_pod,
        )

        pods = []
        for i in range(8):
            p = build_test_pod(f"p{i}", cpu_m=400, labels={"app": "web"})
            if i < 4:
                p.affinity = anti_affinity({"app": "web"})
            pods.append(p)
        return pods, build_test_node("tmpl", cpu_m=4000)

    def test_pallas_route_counts_ok(self, monkeypatch):
        import autoscaler_tpu.estimator.binpacking as bp
        import autoscaler_tpu.ops.pallas_binpack_affinity as pba
        from autoscaler_tpu.metrics.metrics import AutoscalerMetrics

        pods, tmpl = self._world()
        real = pba.ffd_binpack_groups_affinity_pallas
        monkeypatch.setattr(
            pba, "ffd_binpack_groups_affinity_pallas",
            lambda *a, **kw: real(*a, **{**kw, "interpret": True}),
        )
        monkeypatch.setattr(bp.jax, "default_backend", lambda: "tpu")
        m = AutoscalerMetrics()
        est = bp.BinpackingNodeEstimator(metrics=m)
        est.estimate_many(pods, {"g": tmpl})
        assert m.estimator_kernel_route_total.get(
            route="pallas_affinity", reason="ok"
        ) == 1

    def test_vmem_cliff_falls_back_with_metric_and_log(
        self, monkeypatch, caplog
    ):
        import logging as logging_mod

        import autoscaler_tpu.estimator.binpacking as bp
        import autoscaler_tpu.ops.pallas_binpack_affinity as pba
        from autoscaler_tpu.metrics.metrics import AutoscalerMetrics

        pods, tmpl = self._world()
        monkeypatch.setattr(bp.jax, "default_backend", lambda: "tpu")
        monkeypatch.setattr(
            pba, "affinity_vmem_estimate",
            lambda *a, **kw: pba.VMEM_BUDGET + 1,
        )
        m = AutoscalerMetrics()
        est = bp.BinpackingNodeEstimator(metrics=m)
        with caplog.at_level(logging_mod.INFO, logger="estimator"):
            est.estimate_many(pods, {"g": tmpl})
        assert m.estimator_kernel_route_total.get(
            route="xla_scan", reason="vmem"
        ) == 1
        assert any(
            "fell back to xla_scan (vmem)" in r.message for r in caplog.records
        ), caplog.records

    def test_cpu_route_counts_without_log_noise(self, caplog):
        import logging as logging_mod

        import autoscaler_tpu.estimator.binpacking as bp
        from autoscaler_tpu.metrics.metrics import AutoscalerMetrics

        pods, tmpl = self._world()
        m = AutoscalerMetrics()
        est = bp.BinpackingNodeEstimator(metrics=m)
        with caplog.at_level(logging_mod.INFO, logger="estimator"):
            est.estimate_many(pods, {"g": tmpl})
        assert m.estimator_kernel_route_total.get(
            route="xla_scan", reason="not_tpu"
        ) == 1
        assert not any(
            "fell back" in r.message for r in caplog.records
        ), "environmental (not_tpu) routing must not log per dispatch"


class TestEdgeGuards:
    def test_inf_alloc_clamps_like_plain_twin(self):
        """Unlimited CSI-attach virtual planes (+inf allocs) must keep
        node_used finite and exact, matching the XLA twin."""
        w = rand_world(23, P=30, G=2, T=3)
        pod_req, masks, allocs = [x.copy() for x in w[:3]]
        allocs = np.concatenate(
            [allocs, np.full((len(allocs), 1), np.inf, np.float32)], axis=1
        )
        pod_req = np.concatenate(
            [pod_req, np.ones((len(pod_req), 1), np.float32)], axis=1
        )
        assert_twin_parity(pod_req, masks, allocs, 12, *w[3:8])

    def test_bad_chunk_rejected(self):
        w = rand_world(1)
        with pytest.raises(ValueError, match="multiple of 8"):
            ffd_binpack_groups_affinity_pallas(
                *w[:3], max_nodes=8,
                match=w[3], aff_of=w[4], anti_of=w[5],
                node_level=w[6], has_label=w[7],
                chunk=20, interpret=True,
            )

    def test_vmem_estimate_shared_with_estimator(self):
        """The estimator's routing gate and the kernel's auto-sizer consume
        the same byte model."""
        from autoscaler_tpu.ops.pallas_binpack_affinity import (
            VMEM_BUDGET,
            affinity_vmem_estimate,
        )

        # the north-star affinity shape fits; a 300-term monster does not
        assert affinity_vmem_estimate(4, 2, 1000, 512) <= VMEM_BUDGET
        assert affinity_vmem_estimate(4, 10, 1000, 256) > VMEM_BUDGET


class TestSpreadParity:
    """Count-plane spread gates vs the XLA kernel (itself locked to the
    serial spread oracle in tests/test_spread_binpack.py)."""

    def _parity(self, kw, spread):
        ref = ffd_binpack_groups_affinity(
            jnp.asarray(kw["pod_req"]), jnp.asarray(kw["pod_masks"]),
            jnp.asarray(kw["template_allocs"]),
            max_nodes=kw["max_nodes"],
            match=jnp.asarray(kw["match"]), aff_of=jnp.asarray(kw["aff_of"]),
            anti_of=jnp.asarray(kw["anti_of"]),
            node_level=jnp.asarray(kw["node_level"]),
            has_label=jnp.asarray(kw["has_label"]),
            node_caps=jnp.asarray(kw["node_caps"]), spread=spread,
        )
        out = ffd_binpack_groups_affinity_pallas(
            kw["pod_req"], kw["pod_masks"], kw["template_allocs"],
            max_nodes=kw["max_nodes"],
            match=kw["match"], aff_of=kw["aff_of"], anti_of=kw["anti_of"],
            node_level=kw["node_level"], has_label=kw["has_label"],
            node_caps=kw["node_caps"], spread=spread, interpret=True,
        )
        np.testing.assert_array_equal(
            np.asarray(ref.node_count), np.asarray(out.node_count)
        )
        np.testing.assert_array_equal(
            np.asarray(ref.scheduled), np.asarray(out.scheduled)
        )
        return ref

    def test_zone_spread_world(self):
        from autoscaler_tpu.utils.sharded_worlds import spread_world

        kw, spread = spread_world(4, 24, 12)
        kw = dict(kw, max_nodes=12)
        ref = self._parity(kw, spread)
        # the gate actually bit: not everything schedules
        assert not np.asarray(ref.scheduled).all()

    def test_hostname_spread_world(self):
        """Hostname-level constraints: each opened node is its own domain;
        the dynamic min over open nodes gates placement."""
        from autoscaler_tpu.estimator.binpacking import _spread_tuple
        from autoscaler_tpu.kube.objects import (
            LabelSelector,
            TopologySpreadConstraint,
        )
        from autoscaler_tpu.snapshot.affinity import build_spread_terms
        from autoscaler_tpu.utils.test_utils import (
            build_test_node,
            build_test_pod,
        )

        HOST = "kubernetes.io/hostname"
        constraint = TopologySpreadConstraint(
            max_skew=1, topology_key=HOST,
            selector=LabelSelector.from_dict({"app": "web"}),
            when_unsatisfiable="DoNotSchedule",
        )
        P, G, M = 12, 2, 8
        pods = []
        for i in range(P):
            p = build_test_pod(f"p{i}", cpu_m=100, labels={"app": "web"})
            p.topology_spread = (constraint,)
            pods.append(p)
        templates = [build_test_node(f"t{g}", cpu_m=4000) for g in range(G)]
        sp = build_spread_terms(pods, templates, pad_pods=P, bucket_terms=True)
        pod_req = np.zeros((P, 6), np.float32)
        pod_req[:, 0] = 100.0
        pod_req[:, 5] = 1.0
        allocs = np.zeros((G, 6), np.float32)
        allocs[:, 0] = 4000.0
        # pods-capacity 3 forces multiple OPEN nodes; once several domains
        # exist, the dynamic min makes the skew gate redirect placements
        # off fuller nodes (a single open node can never violate skew=1)
        allocs[:, 5] = 3.0
        T = 4
        kw = dict(
            pod_req=pod_req, pod_masks=np.ones((G, P), bool),
            template_allocs=allocs, max_nodes=M,
            match=np.zeros((T, P), bool), aff_of=np.zeros((T, P), bool),
            anti_of=np.zeros((T, P), bool), node_level=np.zeros(T, bool),
            has_label=np.zeros((G, T), bool),
            node_caps=np.full(G, M, np.int32),
        )
        ref = self._parity(kw, _spread_tuple(sp))
        # 12 pods at 3-per-node capacity: 4 nodes, spread-balanced
        assert int(np.asarray(ref.node_count)[0]) == 4

    def test_spread_with_affinity_combined(self):
        """Both gate families active in one scan."""
        from autoscaler_tpu.utils.sharded_worlds import spread_world

        kw, spread = spread_world(2, 20, 10)
        kw = dict(kw, max_nodes=10)
        rng = np.random.default_rng(5)
        P = kw["pod_req"].shape[0]
        T = 3
        match = rng.random((T, P)) < 0.4
        kw["match"] = match
        kw["aff_of"] = (rng.random((T, P)) < 0.2) & match
        kw["anti_of"] = (rng.random((T, P)) < 0.2) & ~kw["aff_of"]
        kw["node_level"] = rng.random(T) < 0.5
        kw["has_label"] = np.ones((2, T), bool)
        self._parity(kw, spread)

    def test_too_many_spread_terms_rejected(self):
        from autoscaler_tpu.utils.sharded_worlds import spread_world

        kw, spread = spread_world(2, 8, 6)
        wide = tuple(
            np.zeros((8, 40), bool) if i in (0, 1) else v
            for i, v in enumerate(spread)
        )
        with pytest.raises(ValueError, match="at most 32"):
            ffd_binpack_groups_affinity_pallas(
                kw["pod_req"], kw["pod_masks"], kw["template_allocs"],
                max_nodes=6,
                match=kw["match"], aff_of=kw["aff_of"],
                anti_of=kw["anti_of"], node_level=kw["node_level"],
                has_label=kw["has_label"], spread=wide, interpret=True,
            )


class TestEstimatorSpreadRouting:
    def test_spread_workload_routes_to_pallas_on_tpu(self, monkeypatch):
        """Hard-spread pending pods now take the Pallas twin too (count
        planes), matching the XLA route's results."""
        import autoscaler_tpu.estimator.binpacking as bp
        import autoscaler_tpu.ops.pallas_binpack_affinity as pba
        from autoscaler_tpu.kube.objects import (
            LabelSelector,
            TopologySpreadConstraint,
        )
        from autoscaler_tpu.utils.test_utils import (
            build_test_node,
            build_test_pod,
        )

        constraint = TopologySpreadConstraint(
            max_skew=1, topology_key="kubernetes.io/hostname",
            selector=LabelSelector.from_dict({"app": "web"}),
            when_unsatisfiable="DoNotSchedule",
        )
        pods = []
        for i in range(10):
            p = build_test_pod(f"p{i}", cpu_m=200, labels={"app": "web"})
            p.topology_spread = (constraint,)
            pods.append(p)
        tmpl = build_test_node("tmpl", cpu_m=4000)
        est = bp.BinpackingNodeEstimator()
        want = est.estimate_many(pods, {"g": tmpl})

        calls = []
        real = pba.ffd_binpack_groups_affinity_pallas

        def spy(*args, **kw):
            calls.append(kw.get("spread") is not None)
            kw["interpret"] = True
            return real(*args, **kw)

        monkeypatch.setattr(pba, "ffd_binpack_groups_affinity_pallas", spy)
        monkeypatch.setattr(bp.jax, "default_backend", lambda: "tpu")
        got = est.estimate_many(pods, {"g": tmpl})
        assert calls and calls[0], "pallas spread route was not taken"
        for g in want:
            assert got[g][0] == want[g][0]
            assert [p.name for p in got[g][1]] == [p.name for p in want[g][1]]


class TestSpreadMinDomains:
    def test_min_domains_force_zero_fold(self):
        """minDomains > available domains treats the global min as 0
        (filtering.go:53). The Pallas kernel folds force_zero into
        min_others_eff = 0 (min(0, cnt) == 0 for counts >= 0) — pin that
        fold against the XLA kernel on a world where it changes the
        outcome."""
        from autoscaler_tpu.estimator.binpacking import _spread_tuple
        from autoscaler_tpu.kube.objects import (
            LabelSelector,
            TopologySpreadConstraint,
        )
        from autoscaler_tpu.snapshot.affinity import build_spread_terms
        from autoscaler_tpu.utils.test_utils import (
            build_test_node,
            build_test_pod,
        )

        ZONE = "topology.kubernetes.io/zone"
        constraint = TopologySpreadConstraint(
            max_skew=1, topology_key=ZONE,
            selector=LabelSelector.from_dict({"app": "web"}),
            when_unsatisfiable="DoNotSchedule", min_domains=3,
        )
        P, G, M = 8, 2, 6
        pods = []
        for i in range(P):
            p = build_test_pod(f"p{i}", cpu_m=100, labels={"app": "web"})
            p.topology_spread = (constraint,)
            pods.append(p)
        templates = []
        for g in range(G):
            t = build_test_node(f"t{g}", cpu_m=4000)
            t.labels[ZONE] = f"zone-{g}"
            templates.append(t)
        sp = build_spread_terms(pods, templates, pad_pods=P, bucket_terms=True)
        pod_req = np.zeros((P, 6), np.float32)
        pod_req[:, 0] = 100.0
        pod_req[:, 5] = 1.0
        allocs = np.zeros((G, 6), np.float32)
        allocs[:, 0] = 4000.0
        allocs[:, 5] = 110.0
        T = 4
        kw = dict(
            pod_req=pod_req, pod_masks=np.ones((G, P), bool),
            template_allocs=allocs,
            match=np.zeros((T, P), bool), aff_of=np.zeros((T, P), bool),
            anti_of=np.zeros((T, P), bool), node_level=np.zeros(T, bool),
            has_label=np.zeros((G, T), bool),
            node_caps=np.full(G, M, np.int32),
        )
        spread = _spread_tuple(sp)
        ref = ffd_binpack_groups_affinity(
            jnp.asarray(kw["pod_req"]), jnp.asarray(kw["pod_masks"]),
            jnp.asarray(kw["template_allocs"]), max_nodes=M,
            match=jnp.asarray(kw["match"]), aff_of=jnp.asarray(kw["aff_of"]),
            anti_of=jnp.asarray(kw["anti_of"]),
            node_level=jnp.asarray(kw["node_level"]),
            has_label=jnp.asarray(kw["has_label"]),
            node_caps=jnp.asarray(kw["node_caps"]), spread=spread,
        )
        out = ffd_binpack_groups_affinity_pallas(
            kw["pod_req"], kw["pod_masks"], kw["template_allocs"],
            max_nodes=M,
            match=kw["match"], aff_of=kw["aff_of"], anti_of=kw["anti_of"],
            node_level=kw["node_level"], has_label=kw["has_label"],
            node_caps=kw["node_caps"], spread=spread, interpret=True,
        )
        np.testing.assert_array_equal(
            np.asarray(ref.node_count), np.asarray(out.node_count)
        )
        np.testing.assert_array_equal(
            np.asarray(ref.scheduled), np.asarray(out.scheduled)
        )
        # minDomains=3 over a single-zone group: the effective min is 0,
        # so only maxSkew pods place per group (the gate genuinely bit)
        assert int(np.asarray(ref.node_count).max()) >= 1
        assert not np.asarray(ref.scheduled).all()
