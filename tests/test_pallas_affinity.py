"""Parity tests for the Pallas dynamic-affinity FFD kernel
(ops/pallas_binpack_affinity) against the XLA scan twin
(ops/binpack.ffd_binpack_groups_affinity), which is itself locked to the
serial oracle in tests/test_affinity_binpack.py — so exact agreement here
chains to oracle parity. Runs in interpret mode on the CPU test platform;
the real-TPU path is exercised by benchmarks/affinity_bench.py.
"""
import numpy as np
import pytest

import jax.numpy as jnp

from autoscaler_tpu.kube.objects import CPU, MEMORY, PODS
from autoscaler_tpu.ops.binpack import ffd_binpack_groups_affinity
from autoscaler_tpu.ops.pallas_binpack_affinity import (
    _pack_term_bits,
    ffd_binpack_groups_affinity_pallas,
)


def rand_world(seed, P=40, G=3, T=5, max_nodes=16):
    rng = np.random.default_rng(seed)
    pod_req = np.zeros((P, 6), np.float32)
    pod_req[:, CPU] = rng.integers(200, 2500, P)
    pod_req[:, MEMORY] = rng.integers(128, 4096, P)
    pod_req[:, PODS] = 1
    allocs = np.zeros((G, 6), np.float32)
    allocs[:, CPU] = rng.integers(3000, 9000, G)
    allocs[:, MEMORY] = rng.integers(6000, 16000, G)
    allocs[:, PODS] = 32
    masks = rng.random((G, P)) > 0.1
    match = rng.random((T, P)) < 0.4
    aff_of = (rng.random((T, P)) < 0.15) & match  # realistic: self-matching
    anti_of = (rng.random((T, P)) < 0.15) & ~aff_of
    node_level = rng.random(T) < 0.5
    has_label = rng.random((G, T)) < 0.8
    caps = rng.integers(2, max_nodes, G).astype(np.int32)
    return pod_req, masks, allocs, match, aff_of, anti_of, node_level, has_label, caps


def assert_twin_parity(pod_req, masks, allocs, max_nodes, match, aff_of,
                       anti_of, node_level, has_label, caps=None):
    jcaps = None if caps is None else jnp.asarray(caps)
    ref = ffd_binpack_groups_affinity(
        jnp.asarray(pod_req), jnp.asarray(masks), jnp.asarray(allocs),
        max_nodes=max_nodes,
        match=jnp.asarray(match), aff_of=jnp.asarray(aff_of),
        anti_of=jnp.asarray(anti_of), node_level=jnp.asarray(node_level),
        has_label=jnp.asarray(has_label), node_caps=jcaps,
    )
    out = ffd_binpack_groups_affinity_pallas(
        pod_req, masks, allocs, max_nodes=max_nodes,
        match=match, aff_of=aff_of, anti_of=anti_of,
        node_level=node_level, has_label=has_label, node_caps=caps,
        interpret=True,
    )
    np.testing.assert_array_equal(
        np.asarray(ref.node_count), np.asarray(out.node_count)
    )
    np.testing.assert_array_equal(
        np.asarray(ref.scheduled), np.asarray(out.scheduled)
    )
    np.testing.assert_array_equal(
        np.asarray(ref.node_used), np.asarray(out.node_used)
    )


class TestPackBits:
    def test_roundtrip_layout(self):
        rng = np.random.default_rng(0)
        T, N = 37, 11                     # spills into a second plane
        rows = rng.random((T, N)) < 0.5
        planes = np.asarray(_pack_term_bits(jnp.asarray(rows), 2))
        for t in range(T):
            for n in range(N):
                bit = (planes[t // 32, n] >> (t % 32)) & 1
                assert bool(bit) == rows[t, n]


class TestParity:
    @pytest.mark.parametrize("seed", range(6))
    def test_randomized_worlds(self, seed):
        assert_twin_parity(*rand_world(seed)[:3], 16, *rand_world(seed)[3:])

    def test_many_terms_multi_plane(self):
        """T > 32 exercises multi-plane bitsets."""
        w = rand_world(11, P=48, G=2, T=40)
        assert_twin_parity(*w[:3], 12, *w[3:])

    def test_anti_affinity_one_per_node(self):
        """4 mutually anti-affine pods need 4 nodes despite resource room."""
        P = 4
        pod_req = np.zeros((P, 6), np.float32)
        pod_req[:, CPU] = 500
        pod_req[:, PODS] = 1
        allocs = np.zeros((1, 6), np.float32)
        allocs[0, CPU] = 4000
        allocs[0, PODS] = 110
        masks = np.ones((1, P), bool)
        match = np.ones((1, P), bool)
        aff_of = np.zeros((1, P), bool)
        anti_of = np.ones((1, P), bool)
        node_level = np.array([True])
        has_label = np.ones((1, 1), bool)
        out = ffd_binpack_groups_affinity_pallas(
            pod_req, masks, allocs, max_nodes=8,
            match=match, aff_of=aff_of, anti_of=anti_of,
            node_level=node_level, has_label=has_label, interpret=True,
        )
        assert int(out.node_count[0]) == 4
        assert_twin_parity(pod_req, masks, allocs, 8, match, aff_of,
                           anti_of, node_level, has_label)

    def test_affinity_colocation_with_seeding(self):
        """Affinity-requiring pods that match their own term co-locate on
        one node via the self-match seeding rule."""
        P = 3
        pod_req = np.zeros((P, 6), np.float32)
        pod_req[:, CPU] = 500
        pod_req[:, PODS] = 1
        allocs = np.zeros((1, 6), np.float32)
        allocs[0, CPU] = 4000
        allocs[0, PODS] = 110
        masks = np.ones((1, P), bool)
        match = np.ones((1, P), bool)
        aff_of = np.ones((1, P), bool)
        anti_of = np.zeros((1, P), bool)
        node_level = np.array([True])
        has_label = np.ones((1, 1), bool)
        out = ffd_binpack_groups_affinity_pallas(
            pod_req, masks, allocs, max_nodes=8,
            match=match, aff_of=aff_of, anti_of=anti_of,
            node_level=node_level, has_label=has_label, interpret=True,
        )
        assert int(out.node_count[0]) == 1
        assert np.asarray(out.scheduled)[0].all()
        assert_twin_parity(pod_req, masks, allocs, 8, match, aff_of,
                           anti_of, node_level, has_label)

    def test_group_level_no_label_never_blocks(self):
        """A template lacking the topology label: anti terms over it cannot
        be violated, affinity terms over it cannot be satisfied."""
        w = list(rand_world(3))
        w[7] = np.zeros_like(w[7])  # has_label all False
        assert_twin_parity(*w[:3], 16, *w[3:])

    def test_zero_terms_degenerates_to_plain(self):
        from autoscaler_tpu.ops.binpack import ffd_binpack_groups

        rng = np.random.default_rng(9)
        P, G = 30, 2
        pod_req = np.zeros((P, 6), np.float32)
        pod_req[:, CPU] = rng.integers(100, 2000, P)
        pod_req[:, PODS] = 1
        allocs = np.zeros((G, 6), np.float32)
        allocs[:, CPU] = rng.integers(2000, 8000, G)
        allocs[:, PODS] = 110
        masks = np.ones((G, P), bool)
        plain = ffd_binpack_groups(
            jnp.asarray(pod_req), jnp.asarray(masks), jnp.asarray(allocs),
            max_nodes=16,
        )
        out = ffd_binpack_groups_affinity_pallas(
            pod_req, masks, allocs, max_nodes=16,
            match=np.zeros((0, P), bool), aff_of=np.zeros((0, P), bool),
            anti_of=np.zeros((0, P), bool), node_level=np.zeros(0, bool),
            has_label=np.zeros((G, 0), bool), interpret=True,
        )
        np.testing.assert_array_equal(
            np.asarray(plain.node_count), np.asarray(out.node_count)
        )
        np.testing.assert_array_equal(
            np.asarray(plain.scheduled), np.asarray(out.scheduled)
        )

    def test_multi_chunk_carry(self):
        """Terms and capacity carry across pod-chunk boundaries."""
        w = rand_world(17, P=70, G=2, T=3)
        pod_req, masks, allocs = w[:3]
        out_small = ffd_binpack_groups_affinity_pallas(
            pod_req, masks, allocs, max_nodes=12,
            match=w[3], aff_of=w[4], anti_of=w[5],
            node_level=w[6], has_label=w[7], node_caps=w[8],
            chunk=16, interpret=True,
        )
        ref = ffd_binpack_groups_affinity(
            jnp.asarray(pod_req), jnp.asarray(masks), jnp.asarray(allocs),
            max_nodes=12,
            match=jnp.asarray(w[3]), aff_of=jnp.asarray(w[4]),
            anti_of=jnp.asarray(w[5]), node_level=jnp.asarray(w[6]),
            has_label=jnp.asarray(w[7]), node_caps=jnp.asarray(w[8]),
        )
        np.testing.assert_array_equal(
            np.asarray(ref.node_count), np.asarray(out_small.node_count)
        )
        np.testing.assert_array_equal(
            np.asarray(ref.scheduled), np.asarray(out_small.scheduled)
        )


class TestEstimatorRouting:
    def test_estimate_many_routes_affinity_to_pallas_on_tpu(self, monkeypatch):
        """On a TPU backend, estimate_many's dynamic-affinity dispatch (no
        hard spread) takes the Pallas twin; results must equal the XLA
        route. The backend is spoofed and the kernel pinned to interpret
        mode so the route itself is exercised on the CPU test platform."""
        import autoscaler_tpu.estimator.binpacking as bp
        import autoscaler_tpu.ops.pallas_binpack_affinity as pba
        from autoscaler_tpu.utils.test_utils import (
            anti_affinity,
            build_test_node,
            build_test_pod,
        )

        pods = []
        for i in range(8):
            p = build_test_pod(f"p{i}", cpu_m=400, labels={"app": "web"})
            if i < 4:
                p.affinity = anti_affinity({"app": "web"})
            pods.append(p)
        tmpl = build_test_node("tmpl", cpu_m=4000)
        est = bp.BinpackingNodeEstimator()
        want = est.estimate_many(pods, {"g": tmpl})   # XLA route (cpu)

        calls = []
        real = pba.ffd_binpack_groups_affinity_pallas

        def spy(*args, **kw):
            calls.append(1)
            kw["interpret"] = True      # spoofed backend, still on CPU
            return real(*args, **kw)

        monkeypatch.setattr(pba, "ffd_binpack_groups_affinity_pallas", spy)
        monkeypatch.setattr(bp.jax, "default_backend", lambda: "tpu")
        got = est.estimate_many(pods, {"g": tmpl})
        assert calls, "pallas affinity route was not taken"
        assert got.keys() == want.keys()
        for g in want:
            assert got[g][0] == want[g][0]
            assert [p.name for p in got[g][1]] == [p.name for p in want[g][1]]


class TestEdgeGuards:
    def test_inf_alloc_clamps_like_plain_twin(self):
        """Unlimited CSI-attach virtual planes (+inf allocs) must keep
        node_used finite and exact, matching the XLA twin."""
        w = rand_world(23, P=30, G=2, T=3)
        pod_req, masks, allocs = [x.copy() for x in w[:3]]
        allocs = np.concatenate(
            [allocs, np.full((len(allocs), 1), np.inf, np.float32)], axis=1
        )
        pod_req = np.concatenate(
            [pod_req, np.ones((len(pod_req), 1), np.float32)], axis=1
        )
        assert_twin_parity(pod_req, masks, allocs, 12, *w[3:8])

    def test_bad_chunk_rejected(self):
        w = rand_world(1)
        with pytest.raises(ValueError, match="multiple of 8"):
            ffd_binpack_groups_affinity_pallas(
                *w[:3], max_nodes=8,
                match=w[3], aff_of=w[4], anti_of=w[5],
                node_level=w[6], has_label=w[7],
                chunk=20, interpret=True,
            )

    def test_vmem_estimate_shared_with_estimator(self):
        """The estimator's routing gate and the kernel's auto-sizer consume
        the same byte model."""
        from autoscaler_tpu.ops.pallas_binpack_affinity import (
            VMEM_BUDGET,
            affinity_vmem_estimate,
        )

        # the north-star affinity shape fits; a 300-term monster does not
        assert affinity_vmem_estimate(4, 2, 1000, 512) <= VMEM_BUDGET
        assert affinity_vmem_estimate(4, 10, 1000, 256) > VMEM_BUDGET
