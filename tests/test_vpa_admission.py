"""VPA admission controller + input pipeline tests: JSONPatch construction
with policy clamping and update modes, the HTTP webhook round trip, the
metrics feeder, and history replay (modeled on the reference's
admission-controller logic/server_test.go and input/cluster_feeder_test.go)."""
import base64
import http.client
import json

import pytest

from autoscaler_tpu.kube.objects import LabelSelector
from autoscaler_tpu.vpa.admission import AdmissionServer, review_pod
from autoscaler_tpu.vpa.api import (
    ContainerResourcePolicy,
    ContainerScalingMode,
    UpdateMode,
    Vpa,
    match_vpa,
)
from autoscaler_tpu.vpa.feeder import (
    ClusterStateFeeder,
    ContainerUsage,
    InMemoryMetrics,
)
from autoscaler_tpu.vpa.recommender import (
    ClusterStateModel,
    ContainerKey,
    PercentileRecommender,
    Recommendation,
)

GB = 1024**3
DAY = 86400.0


def make_vpa(**kw):
    return Vpa(
        name="my-vpa",
        target_selector=LabelSelector.from_dict({"app": "web"}),
        **kw,
    )


def make_review(labels=None, containers=None):
    return {
        "apiVersion": "admission.k8s.io/v1",
        "kind": "AdmissionReview",
        "request": {
            "uid": "uid-1",
            "namespace": "default",
            "object": {
                "metadata": {"labels": labels or {"app": "web"}},
                "spec": {
                    "containers": containers
                    or [{"name": "main", "resources": {"requests": {"cpu": "100m"}}}]
                },
            },
        },
    }


REC = Recommendation(
    target_cpu=0.5,
    target_memory=1 * GB,
    lower_cpu=0.25,
    lower_memory=0.5 * GB,
    upper_cpu=1.0,
    upper_memory=2 * GB,
)


def decode_patch(resp):
    return json.loads(base64.b64decode(resp["response"]["patch"]))


class TestReviewPod:
    def test_patches_requests(self):
        out = review_pod(
            make_review(), [make_vpa()], {ContainerKey("my-vpa", "main"): REC}
        )
        assert out["response"]["allowed"] is True
        patch = decode_patch(out)
        cpu = [p for p in patch if p["path"].endswith("/cpu")]
        mem = [p for p in patch if p["path"].endswith("/memory")]
        assert cpu[0]["value"] == "500m"
        assert mem[0]["value"] == str(1 * GB)

    def test_no_matching_vpa_allows_unpatched(self):
        out = review_pod(
            make_review(labels={"app": "db"}),
            [make_vpa()],
            {ContainerKey("my-vpa", "main"): REC},
        )
        assert out["response"]["allowed"] is True
        assert "patch" not in out["response"]

    def test_update_mode_off_never_patches(self):
        out = review_pod(
            make_review(),
            [make_vpa(update_mode=UpdateMode.OFF)],
            {ContainerKey("my-vpa", "main"): REC},
        )
        assert "patch" not in out["response"]

    def test_policy_clamps_target(self):
        vpa = make_vpa(
            resource_policies=[
                ContainerResourcePolicy(container_name="main", max_cpu=0.3)
            ]
        )
        out = review_pod(make_review(), [vpa], {ContainerKey("my-vpa", "main"): REC})
        patch = decode_patch(out)
        cpu = [p for p in patch if p["path"].endswith("/cpu")]
        assert cpu[0]["value"] == "300m"

    def test_container_scaling_off_skips_container(self):
        vpa = make_vpa(
            resource_policies=[
                ContainerResourcePolicy(
                    container_name="main", mode=ContainerScalingMode.OFF
                )
            ]
        )
        out = review_pod(make_review(), [vpa], {ContainerKey("my-vpa", "main"): REC})
        assert "patch" not in out["response"]

    def test_container_without_resources_section(self):
        out = review_pod(
            make_review(containers=[{"name": "main"}]),
            [make_vpa()],
            {ContainerKey("my-vpa", "main"): REC},
        )
        patch = decode_patch(out)
        paths = [p["path"] for p in patch]
        assert "/spec/containers/0/resources" in paths
        assert "/spec/containers/0/resources/requests" in paths

    def test_existing_annotations_preserved(self):
        review = make_review()
        review["request"]["object"]["metadata"]["annotations"] = {
            "prometheus.io/scrape": "true"
        }
        out = review_pod(review, [make_vpa()], {ContainerKey("my-vpa", "main"): REC})
        patch = decode_patch(out)
        # the breadcrumb targets the single key, never the whole map
        assert not any(p["path"] == "/metadata/annotations" for p in patch)
        assert any(p["path"] == "/metadata/annotations/vpaUpdates" for p in patch)

    def test_single_breadcrumb_for_multiple_containers(self):
        containers = [
            {"name": "main", "resources": {"requests": {}}},
            {"name": "sidecar", "resources": {"requests": {}}},
        ]
        recs = {
            ContainerKey("my-vpa", "main"): REC,
            ContainerKey("my-vpa", "sidecar"): REC,
        }
        out = review_pod(make_review(containers=containers), [make_vpa()], recs)
        patch = decode_patch(out)
        crumbs = [p for p in patch if "vpaUpdates" in p["path"]]
        assert len(crumbs) == 1
        # no annotations on the pod → the empty map is added exactly once
        assert [p["path"] for p in patch].count("/metadata/annotations") == 1

    def test_match_vpa_namespace_scoped(self):
        vpa = make_vpa()
        assert match_vpa([vpa], "default", {"app": "web"}) is vpa
        assert match_vpa([vpa], "other", {"app": "web"}) is None


class TestAdmissionServer:
    def test_http_round_trip(self):
        server = AdmissionServer(
            [make_vpa()], {ContainerKey("my-vpa", "main"): REC}
        )
        server.start()
        try:
            host, port = server.address
            conn = http.client.HTTPConnection(host, port, timeout=5)
            body = json.dumps(make_review())
            conn.request(
                "POST", "/mutate", body, {"Content-Type": "application/json"}
            )
            resp = conn.getresponse()
            assert resp.status == 200
            data = json.loads(resp.read())
            assert data["response"]["allowed"] is True
            assert data["response"]["patchType"] == "JSONPatch"
            conn.request("GET", "/health-check")
            assert conn.getresponse().read() == b"ok"
        finally:
            server.stop()

    def test_https_with_generated_certs(self):
        """In-process TLS end to end (certs.go/gencerts.sh analog): the
        server serves the mutate endpoint over HTTPS with a CA-signed cert,
        and a client trusting only the generated caBundle verifies it."""
        from autoscaler_tpu.vpa.certs import generate_certs

        bundle = generate_certs()
        server = AdmissionServer(
            [make_vpa()], {ContainerKey("my-vpa", "main"): REC}, tls=bundle
        )
        server.start()
        try:
            host, port = server.address
            conn = http.client.HTTPSConnection(
                host, port, timeout=5, context=bundle.client_ssl_context()
            )
            body = json.dumps(make_review())
            conn.request(
                "POST", "/mutate", body, {"Content-Type": "application/json"}
            )
            resp = conn.getresponse()
            assert resp.status == 200
            data = json.loads(resp.read())
            assert data["response"]["patchType"] == "JSONPatch"
        finally:
            server.stop()

    def test_stalled_client_does_not_block_webhook(self):
        """A half-open TCP connection that never speaks TLS must not park
        the accept loop: a well-behaved HTTPS client is served while the
        stalled one is still connected."""
        import socket

        from autoscaler_tpu.vpa.certs import generate_certs

        bundle = generate_certs()
        server = AdmissionServer(
            [make_vpa()], {ContainerKey("my-vpa", "main"): REC}, tls=bundle
        )
        server.start()
        try:
            host, port = server.address
            stalled = socket.create_connection((host, port))  # sends nothing
            try:
                conn = http.client.HTTPSConnection(
                    host, port, timeout=5, context=bundle.client_ssl_context()
                )
                conn.request(
                    "POST", "/mutate", json.dumps(make_review()),
                    {"Content-Type": "application/json"},
                )
                assert conn.getresponse().status == 200
            finally:
                stalled.close()
        finally:
            server.stop()

    def test_untrusting_client_rejects_cert(self):
        import ssl

        from autoscaler_tpu.vpa.certs import generate_certs

        bundle = generate_certs()
        other = generate_certs()  # a different CA must NOT be trusted
        server = AdmissionServer([make_vpa()], {}, tls=bundle)
        server.start()
        try:
            host, port = server.address
            conn = http.client.HTTPSConnection(
                host, port, timeout=5, context=other.client_ssl_context()
            )
            with pytest.raises(ssl.SSLError):
                conn.request("GET", "/health-check")
        finally:
            server.stop()

    def test_webhook_configuration_shape(self):
        """config.go:67-99 MutatingWebhookConfiguration parity."""
        from autoscaler_tpu.vpa.certs import generate_certs, webhook_configuration

        bundle = generate_certs()
        cfg = webhook_configuration(bundle)
        hook = cfg["webhooks"][0]
        assert hook["failurePolicy"] == "Ignore"
        assert hook["sideEffects"] == "None"
        assert hook["rules"][0]["operations"] == ["CREATE"]
        assert hook["rules"][0]["resources"] == ["pods"]
        assert base64.b64decode(hook["clientConfig"]["caBundle"]) == bundle.ca_cert_pem
        assert hook["clientConfig"]["service"] == {
            "namespace": "kube-system",
            "name": "vpa-webhook",
            "path": "/mutate",
        }
        by_url = webhook_configuration(bundle, url="https://127.0.0.1:8443/mutate")
        assert by_url["webhooks"][0]["clientConfig"]["url"].endswith("/mutate")


class TestFeederAndHistory:
    def test_feed_once_batches_into_model(self):
        model = ClusterStateModel()
        feeder = ClusterStateFeeder(model, [make_vpa()])
        metrics = InMemoryMetrics()
        metrics.set_usage(
            [
                ContainerUsage(
                    "default", "web-1", "main", {"app": "web"}, 0.4, 1 * GB
                ),
                ContainerUsage(
                    "default", "web-2", "main", {"app": "web"}, 0.6, 1.2 * GB
                ),
                # unmatched pod: ignored
                ContainerUsage("default", "db-1", "pg", {"app": "db"}, 2.0, 4 * GB),
            ]
        )
        n = feeder.feed_once(metrics, now_ts=0.0)
        assert n == 2
        key = ContainerKey("my-vpa", "main")
        assert model.meta(key).sample_count == 4  # 2 cpu + 2 memory

    def test_history_replay_warms_recommendations(self):
        model = ClusterStateModel()
        feeder = ClusterStateFeeder(model, [make_vpa()])
        metrics = InMemoryMetrics()
        cpu_series = [(i * 60.0, 0.5) for i in range(100)]
        mem_series = [(i * 60.0, 1 * GB) for i in range(100)]
        metrics.add_history(
            "default", "web-1", "main", {"app": "web"}, cpu_series, mem_series
        )
        n = feeder.replay_history(metrics)
        assert n == 200
        recs = PercentileRecommender(model).recommend(now_ts=100 * 60.0)
        rec = recs[ContainerKey("my-vpa", "main")]
        # p90 of constant 0.5-core usage, +15% margin → ~0.575
        assert rec.target_cpu == pytest.approx(0.575, rel=0.2)
        assert rec.target_memory >= 1 * GB


class TestProportionalLimits:
    def test_limit_scaled_with_request(self):
        """Raising a 100m request to 500m must scale a 200m limit to 1000m
        (ratio preserved) — otherwise the apiserver rejects the pod."""
        containers = [
            {
                "name": "main",
                "resources": {
                    "requests": {"cpu": "100m", "memory": "256Mi"},
                    "limits": {"cpu": "200m", "memory": "512Mi"},
                },
            }
        ]
        out = review_pod(
            make_review(containers=containers),
            [make_vpa()],
            {ContainerKey("my-vpa", "main"): REC},
        )
        patch = decode_patch(out)
        by_path = {p["path"]: p["value"] for p in patch}
        assert by_path["/spec/containers/0/resources/requests/cpu"] == "500m"
        assert by_path["/spec/containers/0/resources/limits/cpu"] == "1000m"
        # memory: request 256Mi -> 1GB, limit 512Mi -> 2GB (ratio 2)
        assert by_path["/spec/containers/0/resources/limits/memory"] == str(2 * GB)

    def test_limit_without_request_tracks_new_request(self):
        """K8s defaults request := limit, so ratio is 1 and the new limit
        equals the new request."""
        containers = [
            {"name": "main", "resources": {"limits": {"cpu": "200m"}}}
        ]
        out = review_pod(
            make_review(containers=containers),
            [make_vpa()],
            {ContainerKey("my-vpa", "main"): REC},
        )
        by_path = {p["path"]: p["value"] for p in decode_patch(out)}
        assert by_path["/spec/containers/0/resources/limits/cpu"] == "500m"
        assert by_path["/spec/containers/0/resources/requests/cpu"] == "500m"

    def test_no_limits_no_limit_patch(self):
        out = review_pod(
            make_review(), [make_vpa()], {ContainerKey("my-vpa", "main"): REC}
        )
        paths = [p["path"] for p in decode_patch(out)]
        assert not any("limits" in p for p in paths)


class TestNamespaceScoping:
    def test_same_named_vpas_isolated_by_namespace(self):
        """Two VPAs named 'my-vpa' in different namespaces must not share
        recommendations (ContainerKey carries the namespace)."""
        vpa_a = make_vpa()  # namespace default
        vpa_b = Vpa(
            name="my-vpa",
            namespace="team-b",
            target_selector=LabelSelector.from_dict({"app": "web"}),
        )
        recs = {ContainerKey("my-vpa", "main", "team-b"): REC}
        # pod in "default": its VPA has no recommendation -> no patch
        out = review_pod(make_review(), [vpa_a, vpa_b], recs)
        assert "patch" not in out["response"]
        # pod in team-b gets the patch
        review = make_review()
        review["request"]["namespace"] = "team-b"
        out = review_pod(review, [vpa_b], recs)
        assert "patch" in out["response"]

    def test_feeder_keys_namespaced(self):
        model = ClusterStateModel()
        vpa_a = make_vpa()
        vpa_b = Vpa(
            name="my-vpa",
            namespace="team-b",
            target_selector=LabelSelector.from_dict({"app": "web"}),
        )
        feeder = ClusterStateFeeder(model, [vpa_a, vpa_b])
        key_a = feeder._key_for("default", {"app": "web"}, "main")
        key_b = feeder._key_for("team-b", {"app": "web"}, "main")
        assert key_a is not None and key_b is not None
        assert key_a != key_b
