"""Kubemark-style hollow scale test: the reference's scalability procedure
run in-process against the fake provider/API.

Reference: cluster-autoscaler/proposals/scalability_tests.md — the GA scale
claim is 1000 nodes × 30 pods/node (:6), with a loop-duration bound of <30s
target / <10s measured (:14,70), a 30k-pod scale-up burst filling to 1000
nodes (:30-34), and a scale-down scenario removing 300 empty of 1000 nodes
(:44-48). The reference runs this against kubemark hollow nodes on 17 VMs;
here the cluster is simulated in-process (nodes/pods are plain objects, the
decisions run on the device kernels), which is exactly what the reference's
own simulation-first design enables.

These run in CI on the 8-virtual-device CPU platform, so the asserted loop
bound is the reference's *target* (30s) rather than its measured 10s on
dedicated hardware; bench.py tracks the real-TPU numbers.
"""
import os
import time

import pytest

# Wall-clock asserts gate only when explicitly requested (hack/verify.sh sets
# AUTOSCALER_TPU_TIMING_ASSERTS=1, FATALLY — a loop-time regression fails
# CI). To keep the gate meaningful on loaded/shared workers, the bound is
# scaled by a same-run calibration probe: a fixed numpy workload whose
# duration on the reference dev machine is known, so "worker is 3× slower
# today" widens the bound 3× instead of flaking, while a genuine 3× loop
# regression on a healthy worker still fails. Correctness asserts always run.
TIMING_ASSERTS = os.environ.get("AUTOSCALER_TPU_TIMING_ASSERTS") == "1"
_CALIBRATION_REF_S = 0.165  # the probe's duration on the reference machine
_calibration_scale = None


def _machine_scale() -> float:
    """probe_duration / reference_duration, clamped to [1, 10] — never
    tightens the bound below the reference target, never excuses more than
    a 10×-loaded worker."""
    global _calibration_scale
    if _calibration_scale is None:
        import numpy as np

        a = np.random.default_rng(0).random((1024, 1024)).astype(np.float32)
        for _ in range(2):
            (a @ a).sum()  # warm the BLAS path
        t0 = time.perf_counter()
        for _ in range(8):
            (a @ a).sum()
        probe = time.perf_counter() - t0
        _calibration_scale = min(10.0, max(1.0, probe / _CALIBRATION_REF_S))
    return _calibration_scale


def assert_loop_bound(loop_s, bound_s=30.0):
    if TIMING_ASSERTS:
        bound = bound_s * _machine_scale()
        assert loop_s < bound, (
            f"loop took {loop_s:.1f}s (reference target {bound_s:.0f}s, "
            f"calibrated bound {bound:.0f}s at machine scale "
            f"{_machine_scale():.2f}) — a real loop-time regression"
        )

from autoscaler_tpu.cloudprovider.test_provider import TestCloudProvider
from autoscaler_tpu.config.options import AutoscalingOptions
from autoscaler_tpu.core.static_autoscaler import StaticAutoscaler
from autoscaler_tpu.kube.api import FakeClusterAPI
from autoscaler_tpu.kube.objects import OwnerRef
from autoscaler_tpu.utils.test_utils import GB, MB, build_test_node, build_test_pod

NODES = 1000
PODS_PER_NODE = 30
# node shape: 8 cores / 32GB, 110-pod kubelet default — 30 × (250m, 1GB)
# pods fill 7.5 cores / 30GB, the kubemark-ish "full node"
NODE_CPU = 8000
NODE_MEM = 32 * GB
POD_CPU = 250
POD_MEM = 1 * GB


def burst_pods(n, start=0):
    pods = []
    for i in range(start, start + n):
        p = build_test_pod(f"burst-{i}", cpu_m=POD_CPU, mem=POD_MEM)
        # one controller → one equivalence run → one scan step on device
        p.owner_ref = OwnerRef(kind="ReplicaSet", name="burst-rs")
        pods.append(p)
    return pods


def build_world(started_nodes, pods=()):
    provider = TestCloudProvider()
    api = FakeClusterAPI()
    provider.add_node_group(
        "g", 0, NODES, started_nodes,
        build_test_node("g-tmpl", cpu_m=NODE_CPU, mem=NODE_MEM),
    )
    for i in range(started_nodes):
        node = build_test_node(f"g-{i}", cpu_m=NODE_CPU, mem=NODE_MEM)
        provider.add_node("g", node)
        api.add_node(node)
    for pod in pods:
        api.add_pod(pod)
    opts = AutoscalingOptions(expander="least-waste")
    return provider, api, StaticAutoscaler(provider, api, opts)


class TestScaleUpBurst:
    def test_30k_pod_burst_fills_1000_nodes(self):
        """scalability_tests.md:30-34 — 30k pending pods on an empty group
        must produce one scale-up request to (max) 1000 nodes, within the
        reference's 30s loop target."""
        pods = burst_pods(NODES * PODS_PER_NODE)
        provider, api, autoscaler = build_world(started_nodes=1, pods=pods)
        t0 = time.perf_counter()
        result = autoscaler.run_once(now_ts=100.0)
        loop_s = time.perf_counter() - t0
        assert result.scale_up is not None and result.scale_up.scaled_up
        # 30k pods × 250m = 7500 cores → needs ~938 full nodes; the group
        # fills to its 1000-node max or the exact estimate, whichever is less
        assert result.scale_up.new_nodes >= 900
        assert result.scale_up.new_nodes <= NODES
        assert provider.scale_up_calls and provider.scale_up_calls[0][0] == "g"
        assert_loop_bound(loop_s)

    def test_second_loop_no_double_request(self):
        """Upcoming (requested-but-unregistered) nodes must absorb the pending
        pods — the next loop may not re-request the same capacity
        (static_autoscaler.go:484-519 upcoming-node injection)."""
        pods = burst_pods(5000)
        provider, api, autoscaler = build_world(started_nodes=1, pods=pods)
        r1 = autoscaler.run_once(now_ts=100.0)
        assert r1.scale_up is not None and r1.scale_up.scaled_up
        first = r1.scale_up.new_nodes
        r2 = autoscaler.run_once(now_ts=110.0)
        second = r2.scale_up.new_nodes if (r2.scale_up and r2.scale_up.scaled_up) else 0
        assert second <= first * 0.1, (
            f"second loop re-requested {second} nodes on top of {first}"
        )


class TestScaleDown300:
    def test_300_empty_of_1000_removed(self):
        """scalability_tests.md:44-48 — 300 empty nodes among 1000 are found
        unneeded and deleted after the unneeded-time, bounded per loop by the
        empty-bulk-delete budget."""
        pods = []
        for i in range(300, NODES):  # nodes 300..999 carry load, 0..299 empty
            for j in range(3):
                pods.append(
                    build_test_pod(
                        f"w-{i}-{j}", cpu_m=2000, mem=8 * GB, node_name=f"g-{i}"
                    )
                )
        provider, api, autoscaler = build_world(started_nodes=NODES, pods=pods)
        autoscaler.options.node_group_defaults.scale_down_unneeded_time_s = 60
        autoscaler.options.scale_down_delay_after_add_s = 0
        # raise the per-loop deletion budgets like the reference's scale test
        # config does (both default to 10, actuator budget-crop)
        autoscaler.options.max_empty_bulk_delete = 300
        autoscaler.options.max_scale_down_parallelism = 300

        t0 = time.perf_counter()
        r1 = autoscaler.run_once(now_ts=100.0)
        loop_s = time.perf_counter() - t0
        assert r1.unneeded_nodes >= 300
        assert r1.scale_down is None  # unneeded-time not yet reached
        assert_loop_bound(loop_s)

        r2 = autoscaler.run_once(now_ts=200.0)
        assert r2.scale_down is not None
        deleted = set(r2.scale_down.deleted_empty)
        assert len(deleted) == 300
        assert deleted == {f"g-{i}" for i in range(300)}

    def test_loaded_nodes_stay(self):
        pods = []
        for i in range(NODES):
            for j in range(6):
                pods.append(
                    build_test_pod(
                        f"w-{i}-{j}", cpu_m=1200, mem=5 * GB, node_name=f"g-{i}"
                    )
                )
        provider, api, autoscaler = build_world(started_nodes=NODES, pods=pods)
        autoscaler.options.node_group_defaults.scale_down_unneeded_time_s = 0
        autoscaler.options.scale_down_delay_after_add_s = 0
        r = autoscaler.run_once(now_ts=100.0)
        assert r.unneeded_nodes == 0
        assert r.scale_down is None or not r.scale_down.deleted_empty
