"""Decision-provenance tests (autoscaler_tpu/explain): kernel constraint
attribution vs the serial oracle twin, the DecisionExplainer ring,
run_once DecisionRecords, /explainz, the decision-ledger gate, and the
loadgen byte-determinism acceptance on the skip_reasons scenario."""
import json
import subprocess
import sys
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

import jax.numpy as jnp

from autoscaler_tpu.cloudprovider.test_provider import TestCloudProvider
from autoscaler_tpu.config.options import AutoscalingOptions
from autoscaler_tpu.core.static_autoscaler import StaticAutoscaler
from autoscaler_tpu.explain import (
    DecisionExplainer,
    LEDGER_POD_REASONS,
    REASON_AFFINITY_SPREAD,
    REASON_CPU,
    REASON_MEMORY,
    REASON_NAMES,
    REASON_NODE_CAP,
    REASON_NONE,
    REASON_POD_SLOT,
    REASON_RESOURCE,
    REASON_TOPOLOGY,
    SCHEMA,
    SkipReason,
    reason_histogram,
    reason_name,
    record_line,
    summarize,
    validate_records,
)
from autoscaler_tpu.estimator.reference_impl import (
    attribute_unschedulable_reference,
    ffd_binpack_reference_groups,
)
from autoscaler_tpu.kube.api import FakeClusterAPI
from autoscaler_tpu.kube.objects import CPU, MEMORY, NUM_RESOURCES, PODS
from autoscaler_tpu.main import ObservabilityServer
from autoscaler_tpu.metrics.metrics import EXPLAIN_RECORD
from autoscaler_tpu.ops.binpack import (
    attribute_unschedulable,
    attribution_summary,
    ffd_binpack_groups,
)
from autoscaler_tpu.utils.test_utils import GB, build_test_node, build_test_pod

MB = 1024 * 1024


# ---------------------------------------------------------------- helpers
def make_autoscaler(pods=(), second_group=False, **opt_kw):
    provider = TestCloudProvider()
    api = FakeClusterAPI()
    provider.add_node_group(
        "g", 0, 10, 1, build_test_node("t", cpu_m=1000, mem=2 * GB)
    )
    node = build_test_node("g-0", cpu_m=1000, mem=2 * GB)
    provider.add_node("g", node)
    api.add_node(node)
    if second_group:
        # a group pinned at max size → SkipReason.MAX_SIZE_REACHED
        provider.add_node_group(
            "capped", 0, 1, 1, build_test_node("t2", cpu_m=1000, mem=2 * GB)
        )
        n2 = build_test_node("capped-0", cpu_m=1000, mem=2 * GB)
        provider.add_node("capped", n2)
        api.add_node(n2)
    for p in pods:
        api.add_pod(p)
    return StaticAutoscaler(provider, api, AutoscalingOptions(**opt_kw))


def _attr(req, masks, allocs, scheduled, involved):
    return np.asarray(
        attribute_unschedulable(
            jnp.asarray(req), jnp.asarray(masks), jnp.asarray(allocs),
            jnp.asarray(scheduled), jnp.asarray(involved),
        )
    )


# ------------------------------------------------------ reason vocabulary
class TestReasonVocabulary:
    def test_codes_ordered_by_severity(self):
        # min-across-groups semantics depend on this exact ordering
        assert REASON_NONE < REASON_NODE_CAP < REASON_AFFINITY_SPREAD
        assert REASON_AFFINITY_SPREAD < REASON_POD_SLOT < REASON_RESOURCE
        assert REASON_RESOURCE < REASON_MEMORY < REASON_CPU < REASON_TOPOLOGY
        assert len(REASON_NAMES) == 8

    def test_reason_name_bounds(self):
        assert reason_name(REASON_CPU) == "cpu"
        assert reason_name(99).startswith("unknown_")

    def test_histogram_drops_zero_and_scheduled(self):
        counts = [5, 0, 0, 0, 0, 2, 0, 1]
        assert reason_histogram(counts) == {"memory": 2, "topology": 1}

    def test_ledger_vocabulary_closed(self):
        assert "scheduled" not in LEDGER_POD_REASONS
        assert "not_chosen" in LEDGER_POD_REASONS
        assert "no_viable_group" in LEDGER_POD_REASONS
        assert {r.value for r in SkipReason} == {
            "unhealthy_or_backed_off", "max_size_reached", "no_template",
        }


# ------------------------------------------------- attribution kernel
class TestAttributionKernel:
    def _crafted_world(self):
        """One pod per reason against one group; R = base + 1 ext column."""
        R = NUM_RESOURCES + 1
        alloc = np.zeros((R,), np.float32)
        alloc[CPU], alloc[MEMORY], alloc[PODS] = 1000, 4 * GB, 2
        alloc[NUM_RESOURCES] = 1.0          # one ext unit per node
        pods = {
            "fits": (500, 1 * GB, 0.0),
            "cpu": (2000, 1 * GB, 0.0),
            "mem": (500, 8 * GB, 0.0),
            "ext": (500, 1 * GB, 2.0),
            "masked": (500, 1 * GB, 0.0),
        }
        order = list(pods)
        req = np.zeros((len(order), R), np.float32)
        for i, k in enumerate(order):
            cpu, mem, ext = pods[k]
            req[i, CPU], req[i, MEMORY], req[i, PODS] = cpu, mem, 1.0
            req[i, NUM_RESOURCES] = ext
        masks = np.ones((1, len(order)), bool)
        masks[0, order.index("masked")] = False
        return req, masks, alloc[None, :], order

    def test_priority_chain_per_reason(self):
        req, masks, allocs, order = self._crafted_world()
        scheduled = np.zeros((1, len(order)), bool)
        scheduled[0, order.index("fits")] = True
        involved = np.zeros((len(order),), bool)
        codes = _attr(req, masks, allocs, scheduled, involved)[0]
        assert codes[order.index("fits")] == REASON_NONE
        assert codes[order.index("cpu")] == REASON_CPU
        assert codes[order.index("mem")] == REASON_MEMORY
        assert codes[order.index("ext")] == REASON_RESOURCE
        assert codes[order.index("masked")] == REASON_TOPOLOGY

    def test_node_cap_vs_affinity_involvement(self):
        req = np.zeros((2, NUM_RESOURCES), np.float32)
        req[:, CPU], req[:, MEMORY], req[:, PODS] = 100, 1 * MB, 1
        alloc = np.zeros((1, NUM_RESOURCES), np.float32)
        alloc[0, CPU], alloc[0, MEMORY], alloc[0, PODS] = 1000, 1 * GB, 10
        masks = np.ones((1, 2), bool)
        scheduled = np.zeros((1, 2), bool)
        involved = np.array([False, True])
        codes = _attr(req, masks, alloc, scheduled, involved)[0]
        assert codes[0] == REASON_NODE_CAP
        assert codes[1] == REASON_AFFINITY_SPREAD

    def test_pod_slot_reason(self):
        req = np.zeros((1, NUM_RESOURCES), np.float32)
        req[0, CPU], req[0, PODS] = 100, 1.0
        alloc = np.zeros((1, NUM_RESOURCES), np.float32)
        alloc[0, CPU], alloc[0, MEMORY] = 1000, 1 * GB   # pods capacity 0
        codes = _attr(
            req, np.ones((1, 1), bool), alloc, np.zeros((1, 1), bool),
            np.zeros((1,), bool),
        )[0]
        assert codes[0] == REASON_POD_SLOT

    def test_mask_beats_resource_violations(self):
        req = np.full((1, NUM_RESOURCES), 1e9, np.float32)
        alloc = np.ones((1, NUM_RESOURCES), np.float32)
        codes = _attr(
            req, np.zeros((1, 1), bool), alloc, np.zeros((1, 1), bool),
            np.zeros((1,), bool),
        )[0]
        assert codes[0] == REASON_TOPOLOGY

    def test_summary_hist_weights_and_dominant_min(self):
        reasons = np.array(
            [[REASON_CPU, REASON_NONE], [REASON_NODE_CAP, REASON_TOPOLOGY]],
            np.int32,
        )
        weights = np.array([[3, 1], [2, 1]], np.int32)
        hist, dom = attribution_summary(
            jnp.asarray(reasons), jnp.asarray(weights)
        )
        hist = np.asarray(hist)
        assert hist[0, REASON_CPU] == 3 and hist[0, REASON_NONE] == 1
        assert hist[1, REASON_NODE_CAP] == 2 and hist[1, REASON_TOPOLOGY] == 1
        # dominant = min across groups: closest-to-schedulable wins
        assert list(np.asarray(dom)) == [REASON_NODE_CAP, REASON_NONE]

    def test_kernel_matches_oracle_on_crafted_world(self):
        req, masks, allocs, order = self._crafted_world()
        scheduled = np.zeros((1, len(order)), bool)
        involved = np.zeros((len(order),), bool)
        kernel = _attr(req, masks, allocs, scheduled, involved)
        oracle = attribute_unschedulable_reference(
            req, masks, allocs, scheduled, involved
        )
        assert (kernel == oracle).all()

    @pytest.mark.slow
    def test_kernel_matches_oracle_randomized(self):
        """Acceptance: reason codes agree with the serial oracle twin on
        randomized shapes, with the scheduled verdict coming from the real
        FFD kernels (not a random mask — attribution must agree on the
        worlds the estimator actually produces)."""
        rng = np.random.default_rng(20260803)
        for trial in range(40):
            P = int(rng.integers(1, 24))
            G = int(rng.integers(1, 6))
            R = int(rng.integers(2, NUM_RESOURCES + 3))
            max_nodes = int(rng.integers(1, 6))
            req = rng.integers(0, 2000, (P, R)).astype(np.float32)
            allocs = rng.integers(1, 3000, (G, R)).astype(np.float32)
            masks = rng.random((G, P)) > 0.25
            involved = rng.random((P,)) > 0.7
            res = ffd_binpack_groups(
                jnp.asarray(req), jnp.asarray(masks), jnp.asarray(allocs),
                max_nodes=max_nodes,
            )
            scheduled = np.asarray(res.scheduled)
            kernel = _attr(req, masks, allocs, scheduled, involved)
            oracle = attribute_unschedulable_reference(
                req, masks, allocs, scheduled, involved
            )
            assert (kernel == oracle).all(), (
                f"trial {trial}: P={P} G={G} R={R} max_nodes={max_nodes}\n"
                f"kernel={kernel}\noracle={oracle}"
            )
            # cross-check against the serial FFD too: a pod the oracle FFD
            # schedules must read REASON_NONE under its own verdict
            counts, sched_ref = ffd_binpack_reference_groups(
                req, masks, allocs, max_nodes
            )
            oracle2 = attribute_unschedulable_reference(
                req, masks, allocs, sched_ref, involved
            )
            assert ((oracle2 == REASON_NONE) == sched_ref).all()

    def test_pallas_attribution_parity(self):
        from autoscaler_tpu.ops.pallas_binpack import ffd_binpack_groups_pallas

        rng = np.random.default_rng(7)
        P, G, R = 12, 3, NUM_RESOURCES
        req = rng.integers(0, 1500, (P, R)).astype(np.float32)
        allocs = rng.integers(500, 4000, (G, R)).astype(np.float32)
        masks = rng.random((G, P)) > 0.2
        result, reasons = ffd_binpack_groups_pallas(
            req, masks, allocs, max_nodes=4, attribution=True
        )
        expected = _attr(
            req, masks, allocs, np.asarray(result.scheduled),
            np.zeros((P,), bool),
        )
        assert (np.asarray(reasons) == expected).all()
        # attribution=False keeps the bare-result contract
        bare = ffd_binpack_groups_pallas(req, masks, allocs, max_nodes=4)
        assert (np.asarray(bare.scheduled) == np.asarray(result.scheduled)).all()

    def test_pallas_affinity_attribution_involvement(self):
        from autoscaler_tpu.ops.pallas_binpack_affinity import (
            ffd_binpack_groups_affinity_pallas,
        )

        P, G, R, T = 4, 1, NUM_RESOURCES, 1
        req = np.zeros((P, R), np.float32)
        req[:, CPU], req[:, MEMORY], req[:, PODS] = 100, 1 * MB, 1
        allocs = np.zeros((G, R), np.float32)
        allocs[0, CPU], allocs[0, MEMORY], allocs[0, PODS] = 1000, 1 * GB, 10
        masks = np.ones((G, P), bool)
        match = np.zeros((T, P), bool)
        match[0, 0] = True              # pod 0 is term-involved
        aff_of = np.zeros((T, P), bool)
        anti_of = np.zeros((T, P), bool)
        node_level = np.zeros((T,), bool)
        has_label = np.ones((G, T), bool)
        result, reasons = ffd_binpack_groups_affinity_pallas(
            req, masks, allocs, max_nodes=1,
            match=match, aff_of=aff_of, anti_of=anti_of,
            node_level=node_level, has_label=has_label,
            node_caps=np.zeros((G,), np.int32),   # nothing places
            attribution=True,
        )
        codes = np.asarray(reasons)[0]
        assert codes[0] == REASON_AFFINITY_SPREAD   # involved via match
        assert (codes[1:] == REASON_NODE_CAP).all()

    def test_pending_fit_reasons_against_live_cluster(self):
        from autoscaler_tpu.ops.fit import pending_fit_reasons
        from autoscaler_tpu.snapshot.cluster_snapshot import ClusterSnapshot

        snap = ClusterSnapshot()
        snap.add_node(build_test_node("n1", cpu_m=1000, mem=2 * GB))
        snap.add_pod(build_test_pod("ok", cpu_m=200, mem=100 * MB))
        snap.add_pod(build_test_pod("cpuhog", cpu_m=5000, mem=100 * MB))
        snap.add_pod(build_test_pod("memhog", cpu_m=200, mem=8 * GB))
        t = snap.tensors()
        if isinstance(t, tuple):
            t = t[0]
        codes = np.asarray(pending_fit_reasons(t))
        keys = [p.name for p in snap.pending_pods()]
        by_name = {k: codes[i] for i, k in enumerate(keys)}
        assert by_name["ok"] == REASON_NONE
        assert by_name["cpuhog"] == REASON_CPU
        assert by_name["memhog"] == REASON_MEMORY


# ------------------------------------------------------ DecisionExplainer
class TestDecisionExplainer:
    def test_ring_bounded_and_queries(self):
        ex = DecisionExplainer(ring_capacity=3)
        for t in range(5):
            ex.begin_tick(t, float(t))
            ex.note("pending", {"pending": t})
            ex.end_tick()
        recs = ex.records()
        assert [r["tick"] for r in recs] == [2, 3, 4]
        assert ex.detail_json(4) is not None
        assert ex.detail_json(0) is None
        assert len(ex.summaries()) == 3

    def test_note_outside_tick_is_noop(self):
        ex = DecisionExplainer()
        ex.note("pending", {"pending": 1})
        assert ex.end_tick() is None
        assert ex.records() == []

    def test_crashed_tick_keeps_partial_record(self):
        ex = DecisionExplainer()
        ex.begin_tick(7, 70.0)
        ex.note("pending", {"pending": 3})
        # the scale-up section never arrives (the tick crashed mid-loop)
        rec = ex.end_tick()
        assert rec["tick"] == 7 and rec["pending"] == {"pending": 3}
        assert "scale_up" not in rec

    def test_pod_and_group_drilldowns(self):
        ex = DecisionExplainer()
        ex.begin_tick(1, 10.0)
        ex.note("pods", {"default/p": "cpu"})
        ex.note("estimator", {"groups": {"g": {"fit_nodes": 1}}})
        ex.note("skipped_groups", {"capped": "max_size_reached"})
        ex.note("expander", {"chosen": "g", "score": 0.5, "options": [
            {"group": "g", "scores": {"least-waste": 0.5}},
        ]})
        ex.end_tick()
        ex.begin_tick(2, 20.0)
        ex.note("scale_up", {"executed": [["g", 1]],
                             "pods_triggered": ["default/p"]})
        ex.end_tick()
        pod_doc = json.loads(ex.pod_json("default/p"))
        assert [row["reason"] for row in pod_doc["ticks"]] == [
            "cpu", "triggered",
        ]
        g_doc = json.loads(ex.group_json("g"))
        assert g_doc["ticks"][0]["chosen"] is True
        assert g_doc["ticks"][0]["estimator"] == {"fit_nodes": 1}
        c_doc = json.loads(ex.group_json("capped"))
        assert c_doc["ticks"][0]["skipped"] == "max_size_reached"

    def test_last_decision_summary(self):
        ex = DecisionExplainer()
        ex.begin_tick(1, 10.0)
        ex.note("expander", {"chosen": "g", "score": 0.25, "options": []})
        ex.note("estimator", {"groups": {
            "g": {"reasons": {"cpu": 2, "memory": 5}},
            "h": {"reasons": {"memory": 1}},
        }})
        ex.end_tick()
        s = ex.last_decision_summary()
        assert s["chosen"] == "g" and s["score"] == 0.25
        assert s["top_rejections"][0] == "memory=6"


# ------------------------------------------------- run_once integration
class TestRunOnceIntegration:
    def test_decision_record_sections_and_gauge(self):
        pods = [build_test_pod(f"p{i}", cpu_m=600, mem=GB) for i in range(3)]
        pods.append(build_test_pod("huge", cpu_m=50000, mem=GB))
        a = make_autoscaler(pods=pods, second_group=True)
        a.run_once(now_ts=0.0)
        rec = a.explainer.last_record()
        assert rec is not None and rec["schema"] == SCHEMA
        assert validate_records([rec]) == []
        assert rec["pending"]["pending"] >= 1
        assert rec["skipped_groups"] == {"capped": "max_size_reached"}
        assert rec["pods"]["default/huge"] == "cpu"
        assert rec["expander"]["chosen"] == "g"
        assert rec["scale_up"]["executed"]
        assert "scale_down" in rec
        g = a.metrics.scaleup_skipped_groups_total
        assert g.get(reason="max_size_reached") == 1.0
        assert g.get(reason="no_template") == 0.0
        # reason-code attrs landed on the estimate span
        spans = {
            s.name: s.attrs
            for t in a.tracer.recorder.traces()
            for s in t.spans
        }
        assert "explain_top_rejection" in spans["estimate"]
        assert spans["scaleUp"]["skipped_groups"] == 1
        assert EXPLAIN_RECORD in spans

    def test_skip_gauge_resets_next_loop(self):
        # more pods than the two existing nodes absorb, so the scale-up
        # pass (and its skip accounting) actually runs
        pods = [build_test_pod(f"p{i}", cpu_m=900, mem=GB) for i in range(3)]
        a = make_autoscaler(pods=pods, second_group=True)
        a.run_once(now_ts=0.0)
        assert a.metrics.scaleup_skipped_groups_total.get(
            reason="max_size_reached"
        ) == 1.0
        # drain the pending pod: no scale-up pass → every reason reads 0
        a.api.pods.clear()
        a.run_once(now_ts=10.0)
        assert a.metrics.scaleup_skipped_groups_total.get(
            reason="max_size_reached"
        ) == 0.0

    def test_crashed_tick_still_closes_its_record(self, monkeypatch):
        a = make_autoscaler()
        monkeypatch.setattr(
            a, "_run_once_traced",
            lambda *ar, **kw: (_ for _ in ()).throw(RuntimeError("boom")),
        )
        with pytest.raises(RuntimeError):
            a.run_once(now_ts=0.0)
        assert a.explainer.last_record() is not None

    def test_status_carries_last_decision(self):
        from autoscaler_tpu.clusterstate.status import build_status

        pods = [build_test_pod(f"p{i}", cpu_m=900, mem=GB) for i in range(2)]
        a = make_autoscaler(pods=pods)
        a.run_once(now_ts=0.0)
        text = build_status(
            a.csr, 0.0,
            last_decision=a.explainer.last_decision_summary(),
        ).render()
        assert "LastDecision" in text and "chosen=g" in text


# ----------------------------------------------------------- /explainz
class TestExplainzEndpoint:
    def _get(self, port, path):
        with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}") as r:
            return r.status, r.read().decode()

    def test_list_detail_pod_group(self):
        pods = [build_test_pod("p", cpu_m=600, mem=GB),
                build_test_pod("huge", cpu_m=50000, mem=GB)]
        a = make_autoscaler(pods=pods, second_group=True)
        a.run_once(now_ts=0.0)
        a.run_once(now_ts=10.0)
        server = ObservabilityServer(a, "127.0.0.1:0")
        port = server.start()
        try:
            code, body = self._get(port, "/explainz")
            listing = json.loads(body)
            assert code == 200 and listing["schema"] == SCHEMA
            assert len(listing["ticks"]) == 2
            tick = listing["ticks"][-1]["tick"]
            code, body = self._get(port, f"/explainz?tick={tick}")
            assert code == 200 and json.loads(body)["tick"] == tick
            code, body = self._get(port, "/explainz?pod=default/huge")
            doc = json.loads(body)
            assert code == 200 and doc["pod"] == "default/huge"
            assert doc["ticks"] and doc["ticks"][0]["reason"] == "cpu"
            code, body = self._get(port, "/explainz?group=capped")
            doc = json.loads(body)
            assert code == 200
            assert doc["ticks"][0]["skipped"] == "max_size_reached"
            with pytest.raises(urllib.error.HTTPError) as ei:
                self._get(port, "/explainz?tick=99999")
            assert ei.value.code == 404
            with pytest.raises(urllib.error.HTTPError) as ei:
                self._get(port, "/explainz?tick=bogus")
            assert ei.value.code == 400
        finally:
            server.stop()

    def test_gated_like_perfz(self):
        a = make_autoscaler(explain_enabled=False)
        a.run_once(now_ts=0.0)
        server = ObservabilityServer(a, "127.0.0.1:0")
        port = server.start()
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                self._get(port, "/explainz")
            assert ei.value.code == 404
        finally:
            server.stop()

    def test_concurrent_ring_eviction_race(self):
        """Satellite: /explainz racing a writer that overflows the ring —
        every response must be well-formed JSON, never a torn record."""
        pods = [build_test_pod("p", cpu_m=600, mem=GB)]
        a = make_autoscaler(pods=pods, explain_ring_size=2)
        a.run_once(now_ts=0.0)   # warm compile so writer iterations are fast
        server = ObservabilityServer(a, "127.0.0.1:0")
        port = server.start()
        stop = threading.Event()
        errors = []

        def writer():
            t = 10.0
            while not stop.is_set():
                a.run_once(now_ts=t)
                t += 10.0

        def reader():
            while not stop.is_set():
                for path in (
                    "/explainz", "/explainz?pod=default/p", "/explainz?group=g",
                ):
                    try:
                        code, body = self._get(port, path)
                        json.loads(body)
                    except urllib.error.HTTPError as e:
                        if e.code != 404:
                            errors.append((path, e))
                    except Exception as e:  # noqa: BLE001 — collected for assert
                        errors.append((path, e))

        threads = [threading.Thread(target=writer)] + [
            threading.Thread(target=reader) for _ in range(2)
        ]
        try:
            for t in threads:
                t.start()
            import time

            time.sleep(1.5)
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=30)
            server.stop()
        assert not errors, errors[:3]


# ------------------------------------------------------------- ledger
class TestLedgerValidation:
    def _record(self, tick=0, **over):
        rec = {
            "schema": SCHEMA,
            "tick": tick,
            "now_ts": float(tick) * 10.0,
            "pending": {"arrived": 1, "filtered_schedulable": 0, "pending": 1},
            "skipped_groups": {},
            "pods": {},
        }
        rec.update(over)
        return rec

    def test_valid_ledger(self):
        recs = [self._record(0), self._record(1)]
        assert validate_records(recs) == []

    def test_schema_and_monotonicity(self):
        errs = validate_records(
            [{"schema": "nope", "tick": 1, "now_ts": 0.0},
             self._record(1), self._record(1)]
        )
        assert any("schema" in e for e in errs)
        assert any("not increasing" in e for e in errs)

    def test_pod_reason_vocabulary_enforced(self):
        errs = validate_records(
            [self._record(0, pods={"default/p": "because reasons"})]
        )
        assert any("closed vocabulary" in e for e in errs)

    def test_skip_reason_vocabulary_enforced(self):
        errs = validate_records(
            [self._record(0, skipped_groups={"g": "felt like it"})]
        )
        assert any("SkipReason" in e for e in errs)

    def test_scaled_up_requires_recorded_score(self):
        rec = self._record(
            0,
            scale_up={"executed": [["g", 2]], "remain_unschedulable": 0},
            expander={"chosen": "g", "options": [{"group": "g"}]},
        )
        errs = validate_records([rec])
        assert any("winning score" in e for e in errs)
        rec["expander"]["score"] = 0.5
        assert validate_records([rec]) == []
        # ...and the chosen group must appear in the scoring table
        rec["expander"]["options"] = [{"group": "other"}]
        errs = validate_records([rec])
        assert any("missing from the expander scoring table" in e for e in errs)

    def test_estimator_section_shape_enforced(self):
        """Regression (graftlint GL017): the estimator section is
        declared in SCHEMA_FIELDS but the validator never read it — a
        malformed estimator document passed validation silently."""
        errs = validate_records(
            [self._record(0, estimator={"groups": "nope"})]
        )
        assert any("estimator" in e for e in errs)
        assert validate_records(
            [self._record(0, estimator={"groups": {}})]
        ) == []

    def test_unexplained_pending_pod_flagged(self):
        rec = self._record(
            0,
            scale_up={"executed": [], "remain_unschedulable": 2},
            pods={"default/p": "cpu"},
        )
        errs = validate_records([rec])
        assert any("unexplained pending pod" in e for e in errs)

    def test_summarize(self):
        recs = [
            self._record(
                0,
                pods={"default/a": "cpu", "default/b": "memory"},
                skipped_groups={"g": "max_size_reached"},
                expander={"chosen": "h", "score": 1.0, "options": []},
                estimator={"groups": {"h": {"reasons": {"cpu": 3}}}},
                scale_up={"executed": [["h", 2]], "remain_unschedulable": 2},
            ),
        ]
        agg = summarize(recs)
        assert agg["pod_reasons"] == {"cpu": 1, "memory": 1}
        assert agg["group_reasons"] == {"cpu": 3}
        assert agg["expander_wins"] == {"h": 1}
        assert agg["skip_reasons"] == {"max_size_reached": 1}
        assert agg["scale_up_nodes"] == 2


# ------------------------------------- loadgen acceptance + scorer + CLI
@pytest.fixture(scope="module")
def skip_replays():
    """The acceptance workload: the skip_reasons scenario run twice."""
    from autoscaler_tpu.loadgen.driver import run_scenario
    from autoscaler_tpu.loadgen.spec import ScenarioSpec

    path = "benchmarks/scenarios/skip_reasons.json"
    r1 = run_scenario(ScenarioSpec.load(path))
    r2 = run_scenario(ScenarioSpec.load(path))
    return r1, r2


class TestLoadgenAcceptance:
    def test_two_replays_write_byte_identical_decision_ledgers(
        self, skip_replays
    ):
        r1, r2 = skip_replays
        l1, l2 = r1.explain_ledger_lines(), r2.explain_ledger_lines()
        assert l1 and l1 == l2
        records = [json.loads(line) for line in l1.splitlines()]
        assert validate_records(records) == []
        assert len(records) == r1.spec.ticks

    def test_every_skip_reason_exercised(self, skip_replays):
        r1, _ = skip_replays
        agg = summarize(r1.explain_records)
        for reason in (
            "unhealthy_or_backed_off", "max_size_reached", "no_template",
        ):
            assert agg["skip_reasons"].get(reason, 0) > 0, agg["skip_reasons"]
        assert r1.injected_faults.get("template_error", 0) > 0
        assert agg["expander_wins"]

    def test_scorer_explain_section(self, skip_replays):
        from autoscaler_tpu.loadgen.score import build_report

        r1, _ = skip_replays
        explain = build_report(r1)["explain"]
        assert explain["ticks"] == r1.spec.ticks
        assert set(explain["skip_reasons"]) >= {
            "unhealthy_or_backed_off", "max_size_reached", "no_template",
        }
        assert explain["expander_wins"]

    def test_bench_explain_ledger_gate(self, skip_replays, tmp_path):
        r1, _ = skip_replays
        good = tmp_path / "good.jsonl"
        good.write_text(r1.explain_ledger_lines())
        proc = subprocess.run(
            [sys.executable, "bench.py", "--explain-ledger", str(good)],
            capture_output=True, text=True,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        report = json.loads(proc.stdout)
        assert report["valid"] and report["skip_reasons"]
        # seed a provenance violation: strip the winning score off an
        # executed scale-up
        records = [json.loads(line) for line in good.read_text().splitlines()]
        executed = next(
            r for r in records if r.get("scale_up", {}).get("executed")
        )
        executed["expander"].pop("score", None)
        bad = tmp_path / "bad.jsonl"
        bad.write_text("".join(record_line(r) for r in records))
        proc = subprocess.run(
            [sys.executable, "bench.py", "--explain-ledger", str(bad)],
            capture_output=True, text=True,
        )
        assert proc.returncode == 1
        assert "winning score" in proc.stdout
        # unreadable ledger → exit 2
        proc = subprocess.run(
            [sys.executable, "bench.py", "--explain-ledger",
             str(tmp_path / "missing.jsonl")],
            capture_output=True, text=True,
        )
        assert proc.returncode == 2

    def test_cli_explain_ledger_flag(self, tmp_path):
        from autoscaler_tpu.loadgen.cli import main as loadgen_main

        out = tmp_path / "ledger.jsonl"
        rc = loadgen_main([
            "run", "benchmarks/scenarios/burst_small.json",
            "--explain-ledger", str(out),
        ])
        assert rc == 0
        records = [json.loads(line) for line in out.read_text().splitlines()]
        assert records and validate_records(records) == []

    def test_decision_records_cover_faulted_rungs(self, skip_replays):
        """Degraded/backoff state is part of every record; the scenario's
        backoff window shows up in the ledger, not just the score."""
        r1, _ = skip_replays
        backed = [r for r in r1.explain_records if r.get("backoff")]
        assert backed, "no tick recorded the tight group's backoff window"
        assert all(b["backoff"] == ["tight"] for b in backed)
