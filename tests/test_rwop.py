"""VolumeRestrictions — ReadWriteOncePod exclusivity.

Reference: the scheduler framework's VolumeRestrictions filter fails a pod
on EVERY node while another live pod uses the same ReadWriteOncePod claim;
CA exercises it via schedulerbased.go:129. Previously a documented
PREDICATES.md divergence (a pending pod with an in-use RWOP claim looked
schedulable → one spurious scale-up per loop); now a mask rule: RWOP
conflict rows are all-False in both the dense and factored paths, shared
with the incremental packer.
"""
import numpy as np

from autoscaler_tpu.kube.convert import pod_from_json, pvc_csi_index
from autoscaler_tpu.snapshot.cluster_snapshot import ClusterSnapshot
from autoscaler_tpu.snapshot.incremental import IncrementalPacker
from autoscaler_tpu.snapshot.packer import compute_factored_mask, compute_sched_mask
from autoscaler_tpu.utils.test_utils import build_test_node, build_test_pod


def rwop_pod(name, handle="claim:default/data", deleting=False):
    p = build_test_pod(name, cpu_m=100)
    p.rwop_handles = (handle,)
    if deleting:
        p.deletion_ts = 9.0
    return p


class TestResolution:
    def test_rwop_claim_resolves(self):
        pvcs = [
            {
                "metadata": {"name": "data", "namespace": "default"},
                "spec": {
                    "volumeName": "pv1",
                    "accessModes": ["ReadWriteOncePod"],
                },
            }
        ]
        pvs = [
            {
                "metadata": {"name": "pv1"},
                "spec": {"csi": {"driver": "d", "volumeHandle": "h1"}},
            }
        ]
        idx = pvc_csi_index(pvcs, pvs)
        driver, handle, terms, rwop = idx[("default", "data")]
        assert (driver, handle) == ("d", "h1")
        assert rwop == "claim:default/data"
        pod = pod_from_json(
            {
                "metadata": {"name": "p", "namespace": "default"},
                "spec": {
                    "containers": [{"name": "c"}],
                    "volumes": [
                        {
                            "name": "v",
                            "persistentVolumeClaim": {"claimName": "data"},
                        }
                    ],
                },
            },
            pvc_resolver=lambda ns, c: idx.get((ns, c)),
        )
        assert pod.rwop_handles == ("claim:default/data",)

    def test_unbound_rwop_claim_still_exclusive(self):
        pvcs = [
            {
                "metadata": {"name": "data", "namespace": "default"},
                "spec": {"accessModes": ["ReadWriteOncePod"]},
            }
        ]
        idx = pvc_csi_index(pvcs, [])
        assert idx[("default", "data")] == (None, None, (), "claim:default/data")


class TestMask:
    def test_in_use_claim_blocks_everywhere(self):
        nodes = [build_test_node(f"n{j}", cpu_m=10_000) for j in range(3)]
        owner = rwop_pod("owner")
        pending = rwop_pod("pending")
        plain = build_test_pod("plain", cpu_m=100)
        mask = compute_sched_mask(nodes, [owner, pending, plain], [0, -1, -1])
        assert not mask[1].any()   # conflict: blocked on every node
        # the sole PLACED user is the legitimate one — movable (its own
        # usage never blocks its own row)
        assert mask[0].all()
        assert mask[2].all()
        from tests.test_factored_mask import expand

        fm = expand(
            compute_factored_mask(nodes, [owner, pending, plain], [0, -1, -1]),
            3, 3,
        )
        np.testing.assert_array_equal(fm, mask)

    def test_two_placed_sharers_both_blocked(self):
        """A config violation (two running pods on one RWOP claim): both are
        unmovable — each sees ANOTHER placed user."""
        nodes = [build_test_node(f"n{j}", cpu_m=10_000) for j in range(2)]
        a, b = rwop_pod("a"), rwop_pod("b")
        mask = compute_sched_mask(nodes, [a, b], [0, 1])
        assert not mask[0].any() and not mask[1].any()

    def test_pending_pair_not_statically_blocked(self):
        """The claim is in use only once a pod RUNS: two pending sharers are
        both admissible statically (the scheduler admits the first; the
        one-wave conservatism note in _rwop_conflict_rows covers the rest)."""
        nodes = [build_test_node("n0", cpu_m=10_000)]
        a, b = rwop_pod("a"), rwop_pod("b")
        mask = compute_sched_mask(nodes, [a, b], [-1, -1])
        assert mask[0].all() and mask[1].all()

    def test_double_mount_of_one_claim_is_one_user(self):
        """One pod mounting the same RWOP claim through two volume entries
        is still a single user — it must not conflict with itself."""
        nodes = [build_test_node("n0", cpu_m=10_000)]
        p = build_test_pod("p", cpu_m=100)
        p.rwop_handles = ("claim:default/data", "claim:default/data")
        mask = compute_sched_mask(nodes, [p], [0])
        assert mask[0].all()

    def test_sole_user_unblocked(self):
        nodes = [build_test_node("n0", cpu_m=10_000)]
        solo = rwop_pod("solo")
        mask = compute_sched_mask(nodes, [solo], [-1])
        assert mask[0].all()

    def test_terminating_sharer_frees_the_claim(self):
        nodes = [build_test_node("n0", cpu_m=10_000)]
        leaving = rwop_pod("leaving", deleting=True)
        pending = rwop_pod("pending")
        mask = compute_sched_mask(nodes, [leaving, pending], [0, -1])
        assert mask[1].all()  # the claim frees when the sharer finishes
        assert mask[0].all()  # the terminating pod is never blocked either

    def test_distinct_claims_do_not_conflict(self):
        nodes = [build_test_node("n0", cpu_m=10_000)]
        a = rwop_pod("a", handle="claim:default/one")
        b = rwop_pod("b", handle="claim:default/two")
        mask = compute_sched_mask(nodes, [a, b], [0, -1])
        assert mask[1].all()


class TestIncrementalParity:
    def test_conflict_appears_and_clears_across_updates(self):
        packer = IncrementalPacker()
        snap = ClusterSnapshot(packer=packer)
        for j in range(2):
            snap.add_node(build_test_node(f"n{j}", cpu_m=10_000))
        owner = rwop_pod("owner")
        snap.add_pod(owner, "n0")
        pending = rwop_pod("pending")
        snap.add_pod(pending)
        t, meta = snap.tensors()
        m = np.asarray(t.dense_sched())
        assert not m[meta.pod_index["default/pending"]].any()
        # the owner leaves → next update clears the conflict
        snap.remove_pod("default/owner")
        t2, meta2 = snap.tensors()
        m2 = np.asarray(t2.dense_sched())
        assert m2[meta2.pod_index["default/pending"], :2].all()
        # full-pack parity
        full = compute_sched_mask(
            [snap.get_node("n0"), snap.get_node("n1")], [pending], [-1]
        )
        np.testing.assert_array_equal(m2[meta2.pod_index["default/pending"], :2],
                                      full[0])


class TestScaleDown:
    def test_shared_rwop_mover_makes_drain_infeasible(self):
        """A mover whose RWOP claim another pod uses cannot re-place
        anywhere → the drain is correctly judged infeasible."""
        from autoscaler_tpu.simulator.removal import RemovalSimulator

        snap = ClusterSnapshot()
        snap.add_node(build_test_node("n0", cpu_m=1000))
        snap.add_node(build_test_node("n1", cpu_m=10_000))
        mover = rwop_pod("mover")
        sharer = rwop_pod("sharer")
        snap.add_pod(mover, "n0")
        snap.add_pod(sharer, "n1")
        to_remove, unremovable = RemovalSimulator().find_nodes_to_remove(
            snap, ["n0"]
        )
        assert not to_remove
        assert unremovable and unremovable[0].node.name == "n0"
