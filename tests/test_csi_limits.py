"""CSI volume attach limits (NodeVolumeLimits filter analog).

Reference: the scheduler's NodeVolumeLimits plugin run per (pod, node) by
cluster-autoscaler/simulator/predicatechecker/schedulerbased.go:109-163;
limits come from CSINode spec.drivers[].allocatable.count. Here the verdict
is class-factorized in the packer (pod per-driver volume counts × node
attached-count/limit profile) with sparse self-cell overrides for placed
pods, parity-checked against a per-(pod,node) serial oracle.
"""
import numpy as np
import pytest

from autoscaler_tpu.snapshot.packer import (
    compute_factored_mask,
    compute_sched_mask,
)
from autoscaler_tpu.utils.test_utils import build_test_node, build_test_pod

from test_factored_mask import expand

DRIVER = "pd.csi.storage.gke.io"


def oracle_csi_fits(pod, node, placed_pods_on_node):
    """Serial NodeVolumeLimits: unique handles already attached on the node
    (from pods placed there, excluding this pod itself) plus the pod's
    unique new handles must stay within the driver limit."""
    attached = {}
    for other in placed_pods_on_node:
        if other is pod:
            continue
        for d, h in other.csi_volumes:
            attached.setdefault(d, set()).add(h)
    new = {}
    for d, h in pod.csi_volumes:
        new.setdefault(d, set()).add(h)
    for d, handles in new.items():
        limit = node.csi_attach_limits.get(d)
        if limit is None:
            continue
        if len(attached.get(d, set()) | handles) > limit:
            return False
    return True


def vol(i):
    return (DRIVER, f"vol-{i}")


class TestCsiAttachLimits:
    def test_pending_pod_blocked_at_limit(self):
        node = build_test_node("n0", cpu_m=8000)
        node.csi_attach_limits = {DRIVER: 3}
        # three volumes already attached via placed pods
        placed = [build_test_pod(f"placed{i}", cpu_m=10) for i in range(3)]
        for i, p in enumerate(placed):
            p.csi_volumes = (vol(i),)
            p.node_name = "n0"
        pending = build_test_pod("pending", cpu_m=10)
        pending.csi_volumes = (vol(99),)
        pods = placed + [pending]
        node_of_pod = [0, 0, 0, -1]
        mask = compute_sched_mask([node], pods, node_of_pod)
        assert not mask[3, 0]          # limit reached: pending blocked
        for i in range(3):
            assert mask[i, 0]          # placed pods keep fitting their node

    def test_pending_pod_fits_under_limit(self):
        node = build_test_node("n0", cpu_m=8000)
        node.csi_attach_limits = {DRIVER: 4}
        placed = build_test_pod("placed", cpu_m=10)
        placed.csi_volumes = (vol(0), vol(1))
        placed.node_name = "n0"
        pending = build_test_pod("pending", cpu_m=10)
        pending.csi_volumes = (vol(2), vol(3))
        mask = compute_sched_mask([node], [placed, pending], [0, -1])
        assert mask[1, 0]

    def test_multi_volume_pod_counts_unique_handles(self):
        node = build_test_node("n0")
        node.csi_attach_limits = {DRIVER: 2}
        pod = build_test_pod("p", cpu_m=10)
        # same handle twice (two mounts of one PVC) counts once
        pod.csi_volumes = (vol(0), vol(0), vol(1))
        mask = compute_sched_mask([node], [pod], [-1])
        assert mask[0, 0]

    def test_unlimited_driver_never_blocks(self):
        node = build_test_node("n0")  # no csi_attach_limits at all
        pods = []
        for i in range(10):
            p = build_test_pod(f"p{i}", cpu_m=10)
            p.csi_volumes = tuple(vol(10 * i + k) for k in range(5))
            pods.append(p)
        mask = compute_sched_mask([node], pods, [-1] * 10)
        assert mask.all()

    def test_other_driver_limit_irrelevant(self):
        node = build_test_node("n0")
        node.csi_attach_limits = {"ebs.csi.aws.com": 0}
        pod = build_test_pod("p", cpu_m=10)
        pod.csi_volumes = (vol(0),)
        mask = compute_sched_mask([node], [pod], [-1])
        assert mask[0, 0]

    @pytest.mark.parametrize("seed", range(4))
    def test_oracle_parity_random(self, seed):
        """Random worlds without cross-pod shared handles: the class factor
        must agree with the serial oracle exactly, for both mask paths."""
        rng = np.random.default_rng(seed)
        N, P = 8, 30
        nodes = []
        for j in range(N):
            n = build_test_node(f"n{j}", cpu_m=32000)
            if j % 2 == 0:
                n.csi_attach_limits = {DRIVER: int(rng.integers(1, 5))}
            nodes.append(n)
        pods, node_of_pod = [], []
        next_handle = 0
        for i in range(P):
            p = build_test_pod(f"p{i}", cpu_m=10)
            nvol = int(rng.integers(0, 4))
            p.csi_volumes = tuple(vol(next_handle + k) for k in range(nvol))
            next_handle += nvol
            j = int(rng.integers(0, N)) if rng.random() < 0.5 else -1
            if j >= 0:
                p.node_name = f"n{j}"
            node_of_pod.append(j)
            pods.append(p)

        mask = compute_sched_mask(nodes, pods, node_of_pod)
        fm = expand(
            compute_factored_mask(nodes, pods, node_of_pod), P, N
        )
        np.testing.assert_array_equal(fm, mask, err_msg=f"seed {seed}")
        for i, pod in enumerate(pods):
            for j, node in enumerate(nodes):
                on_node = [
                    q for q, oj in zip(pods, node_of_pod) if oj == j
                ]
                want = oracle_csi_fits(pod, node, on_node)
                assert mask[i, j] == want, (i, j, seed)

    def test_self_cell_judges_only_own_drivers(self):
        """A placed pod must not be evicted-on-paper because ANOTHER driver
        on its node is over limit (e.g. the limit shrank after placement):
        the self-cell verdict only counts the drivers the pod mounts."""
        other_driver = "ebs.csi.aws.com"
        node = build_test_node("n0", cpu_m=8000)
        node.csi_attach_limits = {DRIVER: 1, other_driver: 4}
        over = [build_test_pod(f"over{i}", cpu_m=10) for i in range(2)]
        for i, p in enumerate(over):
            p.csi_volumes = (vol(i),)  # DRIVER now 2 > limit 1
            p.node_name = "n0"
        q = build_test_pod("q", cpu_m=10)
        q.csi_volumes = ((other_driver, "h-q"),)
        q.node_name = "n0"
        ported = build_test_pod("ported", cpu_m=10)
        ported.host_ports = (9090,)
        ported.node_name = "n0"
        pods = over + [q, ported]
        mask = compute_sched_mask([node], pods, [0, 0, 0, 0])
        assert mask[2, 0]  # q mounts only the healthy driver
        assert mask[3, 0]  # ported mounts no CSI volumes at all
        on_node = pods
        assert oracle_csi_fits(q, node, on_node)
        assert oracle_csi_fits(ported, node, on_node)

    def test_shared_handle_pessimism_is_one_sided(self):
        """Documented divergence: a pending pod sharing a handle with a pod
        already placed on the node is counted pessimistically (as new). The
        class mask may under-admit but must never over-admit vs the oracle."""
        node = build_test_node("n0")
        node.csi_attach_limits = {DRIVER: 2}
        placed = build_test_pod("placed", cpu_m=10)
        placed.csi_volumes = (vol(0), vol(1))
        placed.node_name = "n0"
        sharer = build_test_pod("sharer", cpu_m=10)
        sharer.csi_volumes = (vol(0),)  # already attached there
        pods = [placed, sharer]
        mask = compute_sched_mask([node], pods, [0, -1])
        want = oracle_csi_fits(sharer, node, [placed])
        assert want is True          # oracle: nothing new to attach
        assert not mask[1, 0]        # ours: pessimistic — blocked, not over-admitted

    def test_inline_csi_volume_parsing(self):
        from autoscaler_tpu.kube.convert import pod_from_json

        obj = {
            "metadata": {"name": "p1", "namespace": "ns"},
            "spec": {
                "containers": [{"name": "c"}],
                "volumes": [
                    {"name": "scratch", "csi": {"driver": DRIVER}},
                    {"name": "tmp", "emptyDir": {}},
                ],
            },
        }
        pod = pod_from_json(obj)
        assert pod.csi_volumes == ((DRIVER, "ns/p1/scratch"),)
        assert pod.local_storage
