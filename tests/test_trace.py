"""Tick-tracing tests: span trees, flight recorder, /tracez, device-timing
correlation, and loadgen trace determinism (autoscaler_tpu/trace)."""
import json
import urllib.request

import pytest

from autoscaler_tpu import trace
from autoscaler_tpu.cloudprovider.test_provider import TestCloudProvider
from autoscaler_tpu.config.options import AutoscalingOptions
from autoscaler_tpu.core.static_autoscaler import StaticAutoscaler
from autoscaler_tpu.kube.api import FakeClusterAPI
from autoscaler_tpu.main import ObservabilityServer
from autoscaler_tpu.metrics.metrics import AutoscalerMetrics, MetricsRegistry
from autoscaler_tpu.utils.test_utils import GB, build_test_node, build_test_pod


class _CountClock:
    """1ms per reading — the loadgen driver's determinism trick."""

    def __init__(self):
        self.n = 0

    def __call__(self):
        self.n += 1
        return self.n * 1e-3


def make_autoscaler(pods=(), **opt_kw):
    provider = TestCloudProvider()
    api = FakeClusterAPI()
    provider.add_node_group("g", 0, 10, 1, build_test_node("t", cpu_m=1000, mem=2 * GB))
    node = build_test_node("g-0", cpu_m=1000, mem=2 * GB)
    provider.add_node("g", node)
    api.add_node(node)
    for p in pods:
        api.add_pod(p)
    return StaticAutoscaler(provider, api, AutoscalingOptions(**opt_kw))


class TestTracerCore:
    def test_span_tree_structure(self):
        t = trace.Tracer(clock=_CountClock())
        with t.tick("main", tick=7) as root:
            with trace.span("phaseA", x=1):
                with trace.span("inner"):
                    trace.add_event("marker", detail="d")
            with trace.span("phaseB"):
                pass
        traces = t.recorder.traces()
        assert len(traces) == 1
        spans = traces[0].spans
        assert [s.name for s in spans] == ["main", "phaseA", "inner", "phaseB"]
        assert spans[0].parent_id is None
        assert spans[1].parent_id == 0
        assert spans[2].parent_id == 1
        assert spans[3].parent_id == 0
        assert spans[0].attrs["tick"] == 7
        assert spans[0].attrs["trace_id"] == 0
        assert spans[2].events[0]["name"] == "marker"
        # injected clock: starts/ends strictly increase, deterministic
        assert spans[0].start < spans[1].start < spans[1].end < spans[0].end
        assert root.end is not None

    def test_metric_feed_choke_point(self):
        m = AutoscalerMetrics(MetricsRegistry())
        t = trace.Tracer(metrics=m)
        with t.tick("main"):
            with trace.span("buildSnapshot"):
                pass
            with trace.span("deviceDispatch", metric_label="deviceDispatch"):
                pass
            with trace.span("unfed", metric_label=None):
                pass
        assert m.function_duration.count(function="main") == 1
        assert m.function_duration.count(function="buildSnapshot") == 1
        assert m.function_duration.count(function="deviceDispatch") == 1
        assert m.function_duration.count(function="unfed") == 0
        # same vocabulary in both surfaces
        names = {s.name for s in t.recorder.traces()[0].spans}
        assert {"main", "buildSnapshot", "deviceDispatch"} <= names

    def test_span_metrics_override_inside_metricless_tracer(self):
        """span(metrics=...) must feed its registry even when the active
        tracer was built without one (a custom Tracer passed to
        StaticAutoscaler must not silently drop component series)."""
        m = AutoscalerMetrics(MetricsRegistry())
        t = trace.Tracer()  # no metrics
        with t.tick("main"):
            with trace.span("estimate", metrics=m):
                pass
        assert m.function_duration.count(function="estimate") == 1
        assert m.function_duration.count(function="main") == 0  # tracer has none

    def test_detached_span_still_feeds_metrics(self):
        """Outside any trace, span(metrics=...) records the duration series
        — bare component calls keep their observability."""
        m = AutoscalerMetrics(MetricsRegistry())
        with trace.span("estimate", metrics=m) as sp:
            assert sp is trace.NOOP_SPAN
            sp.set_attrs(ignored=True)  # must not raise
        assert m.function_duration.count(function="estimate") == 1

    def test_noop_outside_trace(self):
        assert trace.current_span() is None
        trace.add_event("nothing")  # no-op, no raise
        trace.set_attrs(x=1)
        with trace.span("orphan") as sp:
            assert sp is trace.NOOP_SPAN

    def test_error_span_annotated_and_trace_recorded(self):
        t = trace.Tracer(clock=_CountClock())
        with pytest.raises(ValueError):
            with t.tick("main"):
                with trace.span("phase"):
                    raise ValueError("boom")
        traces = t.recorder.traces()
        assert len(traces) == 1
        spans = traces[0].spans
        assert spans[1].attrs["error"] == "ValueError"
        assert spans[0].attrs["error"] == "ValueError"
        assert traces[0].summary()["error"] is True

    def test_wall_attrs_dropped_on_deterministic_tracer(self):
        t = trace.Tracer(clock=_CountClock())
        assert t.deterministic
        with t.tick("main"):
            with trace.span("phase") as sp:
                trace.set_wall_attrs(wall_thing=1.23)
                sp.set_attrs(kept=True)
        sp = t.recorder.traces()[0].spans[1]
        assert "wall_thing" not in sp.attrs and sp.attrs["kept"] is True

        prod = trace.Tracer()
        assert not prod.deterministic
        with prod.tick("main"):
            with trace.span("phase"):
                trace.set_wall_attrs(wall_thing=1.23)
        assert prod.recorder.traces()[0].spans[1].attrs["wall_thing"] == 1.23

    def test_context_attrs_stamped_on_next_tick_then_consumed(self):
        t = trace.Tracer(clock=_CountClock())
        t.set_context(scenario="s", tick=3)
        with t.tick("main"):
            pass
        with t.tick("main"):
            pass
        first, second = (tt.root for tt in t.recorder.traces())
        assert first.attrs["scenario"] == "s" and first.attrs["tick"] == 3
        # consumed: stale tags must not leak onto later ticks
        assert "scenario" not in second.attrs and "tick" not in second.attrs

    def test_byte_identical_exports_with_injected_clock(self):
        def run():
            t = trace.Tracer(clock=_CountClock())
            for i in range(3):
                with t.tick("main", tick=i):
                    with trace.span("phase", i=i):
                        trace.add_event("ev", n=i)
            return t.recorder.chrome()

        assert run() == run()


class TestFlightRecorder:
    def _trace(self, trace_id):
        tt = trace.TickTrace(trace_id=trace_id)
        sp = trace.Span(name="main", span_id=0, parent_id=None, start=0.0)
        sp.end = 1.0
        tt.spans.append(sp)
        return tt

    def test_ring_bounded_and_pinning_survives(self):
        rec = trace.FlightRecorder(capacity=4, pinned_capacity=2)
        rec.add(self._trace(0), pin=True)
        for i in range(1, 10):
            rec.add(self._trace(i))
        ids = [t.trace_id for t in rec.traces()]
        # ring kept the last 4; trace 0 survived only because it is pinned
        assert ids == [0, 6, 7, 8, 9]
        assert rec.get(0).pinned
        assert rec.get(3) is None

    def test_pinned_slot_bounded(self):
        rec = trace.FlightRecorder(capacity=2, pinned_capacity=2)
        for i in range(5):
            rec.add(self._trace(i), pin=True)
        pinned = [t.trace_id for t in rec.traces() if t.pinned]
        assert pinned == [3, 4]

    def test_chrome_export_shape(self):
        rec = trace.FlightRecorder()
        rec.add(self._trace(0))
        doc = json.loads(rec.chrome())
        assert doc["displayTimeUnit"] == "ms"
        complete = [e for e in doc["traceEvents"] if e["ph"] == "X"]
        assert complete[0]["name"] == "main"
        assert complete[0]["dur"] == 1_000_000  # 1s in µs
        assert rec.chrome(123) is None  # unknown id

    def test_chrome_doc_carries_schema_and_validates(self):
        """The chrome export is a ledger document like the others: it
        carries its schema tag and has a validator twin (GL017)."""
        rec = trace.FlightRecorder()
        rec.add(self._trace(0))
        doc = json.loads(rec.chrome())
        assert doc["schema"] == trace.CHROME_SCHEMA
        assert trace.validate_chrome_doc(doc) == []

    def test_chrome_validator_flags_drift(self):
        rec = trace.FlightRecorder()
        rec.add(self._trace(0))
        good = json.loads(rec.chrome())

        bad = dict(good, schema="nope/9")
        assert any("schema" in e for e in trace.validate_chrome_doc(bad))
        bad = dict(good, traceEvents="not a list")
        assert any(
            "traceEvents" in e for e in trace.validate_chrome_doc(bad)
        )
        events = [dict(e) for e in good["traceEvents"]]
        events[0]["ph"] = "Z"
        assert any(
            "ph" in e
            for e in trace.validate_chrome_doc(dict(good, traceEvents=events))
        )
        events = [dict(e) for e in good["traceEvents"]]
        x = next(e for e in events if e["ph"] == "X")
        x["dur"] = -1
        assert trace.validate_chrome_doc(dict(good, traceEvents=events))

    def test_slow_tick_pinned_and_dumped(self, caplog):
        import logging

        t = trace.Tracer(slow_tick_threshold_s=1e-9)
        with caplog.at_level(logging.WARNING, logger="trace"):
            with t.tick("main"):
                with trace.span("phase"):
                    pass
        tt = t.recorder.traces()[0]
        assert tt.pinned
        assert any("slow tick" in r.message for r in caplog.records)
        assert "phase" in tt.render()


class TestRunOnceTracing:
    def test_run_once_produces_span_tree(self):
        a = make_autoscaler(
            [
                build_test_pod("blocker", cpu_m=800, node_name="g-0"),
                build_test_pod("p", cpu_m=900, mem=1 * GB),
            ]
        )
        a.run_once(now_ts=0.0)
        traces = a.tracer.recorder.traces()
        assert len(traces) == 1
        spans = traces[0].spans
        names = [s.name for s in spans]
        assert names[0] == "main"
        for phase in ("poll", "updateClusterState", "buildSnapshot",
                      "filterOutSchedulable", "scaleUp", "scaleDown",
                      "findUnneeded", "estimate"):
            assert phase in names, phase
        by_name = {s.name: s for s in spans}
        # findUnneeded nests under scaleDown; estimate under scaleUp
        assert spans[by_name["findUnneeded"].parent_id].name == "scaleDown"
        assert spans[by_name["estimate"].parent_id].name == "scaleUp"
        # rung walk: deviceDispatch spans under the estimate span
        rungs = [s for s in spans if s.name == "deviceDispatch"]
        assert rungs and all(
            spans[s.parent_id].name == "estimate" for s in rungs
        )
        served = [s for s in rungs if s.attrs.get("outcome") == "ok"]
        assert served and "route" in served[0].attrs
        # root carries the tick verdict
        root = traces[0].root
        assert root.attrs["healthy"] is True and "pending" in root.attrs
        # metric counts came from the SAME spans (choke point)
        assert a.metrics.function_duration.count(function="main") == 1
        assert a.metrics.function_duration.count(function="scaleUp") == 1

    def test_ring_respects_options(self):
        a = make_autoscaler(trace_ring_size=2)
        for i in range(5):
            a.run_once(now_ts=float(i))
        ids = [t.trace_id for t in a.tracer.recorder.traces()]
        assert ids == [3, 4]

    def test_tracez_endpoints(self):
        a = make_autoscaler()
        a.run_once(now_ts=0.0)
        server = ObservabilityServer(a, "127.0.0.1:0")
        port = server.start()
        try:
            def get(path):
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}{path}"
                ) as r:
                    return r.status, r.read().decode()

            code, body = get("/tracez")
            assert code == 200
            listing = json.loads(body)
            assert listing["traces"][0]["name"] == "main"
            tid = listing["traces"][0]["trace_id"]
            code, body = get(f"/tracez?id={tid}")
            assert code == 200
            detail = json.loads(body)
            assert detail["trace_id"] == tid
            assert any(s["name"] == "buildSnapshot" for s in detail["spans"])
            code, body = get(f"/tracez?format=chrome&id={tid}")
            assert code == 200
            doc = json.loads(body)
            assert any(e["name"] == "main" for e in doc["traceEvents"])
            code, body = get("/tracez?format=chrome")
            assert code == 200 and json.loads(body)["traceEvents"]
        finally:
            server.stop()

    def test_tracez_gated_like_snapshotz(self):
        import urllib.error

        a = make_autoscaler(tracing_enabled=False)
        a.run_once(now_ts=0.0)
        server = ObservabilityServer(a, "127.0.0.1:0")
        port = server.start()
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(f"http://127.0.0.1:{port}/tracez")
            assert ei.value.code == 404
        finally:
            server.stop()

    def test_tracez_bad_requests(self):
        import urllib.error

        a = make_autoscaler()
        a.run_once(now_ts=0.0)
        server = ObservabilityServer(a, "127.0.0.1:0")
        port = server.start()
        try:
            for path, code in (
                ("/tracez?id=notanint", 400),
                ("/tracez?format=weird", 400),
                ("/tracez?id=99999", 404),
                ("/tracez?format=chrome&id=99999", 404),
            ):
                with pytest.raises(urllib.error.HTTPError) as ei:
                    urllib.request.urlopen(f"http://127.0.0.1:{port}{path}")
                assert ei.value.code == code, path
        finally:
            server.stop()

    def test_crashed_tick_recorded_with_error(self):
        a = make_autoscaler()

        def boom():
            raise RuntimeError("injected refresh crash")

        a.provider.refresh = boom
        res = a.run_once(now_ts=0.0)
        assert res.errors  # refresh failure is caught into the result
        trace_ = a.tracer.recorder.traces()[0]
        poll = [s for s in trace_.spans if s.name == "poll"]
        assert poll and poll[0].attrs.get("error") == "refresh_failed"


class TestDeviceCorrelation:
    def test_device_annotation_is_safe_everywhere(self):
        from autoscaler_tpu.trace.device import device_annotation

        with device_annotation("autoscaler/test"):
            x = 1 + 1
        assert x == 2

    def test_compile_execute_split_attrs(self):
        """First dispatch of a route marks cold; warm dispatches carry the
        estimated compile/execute split (production tracer only)."""
        from autoscaler_tpu.estimator.binpacking import BinpackingNodeEstimator

        est = BinpackingNodeEstimator()
        tracer = trace.Tracer()  # production mode: wall attrs allowed
        pods = [build_test_pod(f"p{i}", cpu_m=500) for i in range(4)]
        tmpl = build_test_node("tmpl", cpu_m=4000)
        with tracer.tick("main"):
            est.estimate_many(pods, {"g": tmpl})
            est.estimate_many(pods, {"g": tmpl})
        spans = [
            s
            for t in tracer.recorder.traces()
            for s in t.spans
            if s.name == "deviceDispatch" and s.attrs.get("outcome") == "ok"
        ]
        assert len(spans) == 2
        assert spans[0].attrs["cold"] is True
        assert "dispatch_s" in spans[0].attrs
        assert spans[1].attrs["cold"] is False
        assert "execute_est_s" in spans[1].attrs
        assert "compile_est_s" in spans[1].attrs

    def test_jax_profiler_dir_capture(self, tmp_path):
        import os

        a = make_autoscaler(
            [build_test_pod("p", cpu_m=900, mem=1 * GB)],
            jax_profiler_dir=str(tmp_path),
        )
        a.run_once(now_ts=0.0)
        # keyed by the tick id of the trace in the flight recorder
        tid = a.tracer.recorder.traces()[0].trace_id
        session = tmp_path / f"tick_{tid:06d}"
        # jax.profiler may be unavailable in exotic builds; when it IS
        # available the session directory must exist and be keyed right
        from autoscaler_tpu.trace import device as dev

        if not (dev._profiler_broken or dev._sessions_broken):
            assert session.exists()
            assert any(os.scandir(session))

    def test_session_failure_keeps_annotations_alive(self, monkeypatch):
        """A failed profiler session start disables sessions only — the
        TraceAnnotation path (device-timeline correlation of dispatches)
        must survive."""
        from contextlib import nullcontext

        from autoscaler_tpu.trace import device as dev

        monkeypatch.setattr(dev, "_sessions_broken", False)

        class FakeProf:
            def start_trace(self, path):
                raise RuntimeError("dir unwritable")

            def TraceAnnotation(self, name):
                return nullcontext("annotated")

        monkeypatch.setattr(dev, "_profiler", lambda: FakeProf())
        assert dev.start_profiler_session("/nonexistent", 1) is False
        assert dev._sessions_broken
        # sessions now refuse fast ...
        assert dev.start_profiler_session("/nonexistent", 2) is False
        # ... but annotations still flow through the profiler
        with dev.device_annotation("autoscaler/x") as tag:
            assert tag == "annotated"


@pytest.fixture(scope="module")
def ladder_replays():
    """Run the canned kernel-fault scenario twice (the acceptance
    workload): module-scoped, shared by the determinism and nesting
    assertions below."""
    from autoscaler_tpu.loadgen.driver import run_scenario
    from autoscaler_tpu.loadgen.spec import ScenarioSpec

    path = "benchmarks/scenarios/kernel_fault_ladder.json"
    r1 = run_scenario(ScenarioSpec.load(path))
    r2 = run_scenario(ScenarioSpec.load(path))
    return r1, r2


class TestLoadgenTraceDeterminism:
    def test_two_replays_export_byte_identical_chrome_traces(
        self, ladder_replays
    ):
        r1, r2 = ladder_replays
        c1, c2 = r1.recorder.chrome(), r2.recorder.chrome()
        assert c1 and c1 == c2
        # and they are valid chrome-trace documents
        doc = json.loads(c1)
        assert doc["traceEvents"]

    def test_rung_walk_nested_under_estimate_of_faulted_tick(
        self, ladder_replays
    ):
        """The acceptance criterion: the faulted tick shows the ladder walk
        (pallas fault → … → ok on a lower rung) as deviceDispatch spans
        nested under that tick's estimate span."""
        r1, _ = ladder_replays
        walked = None
        for t in r1.recorder.traces():
            spans = {s.span_id: s for s in t.spans}
            rungs = [s for s in t.spans if s.name == "deviceDispatch"]
            if any(
                s.attrs.get("rung") == "pallas"
                and s.attrs.get("outcome") == "fault"
                for s in rungs
            ):
                walked = (t, spans, rungs)
                break
        assert walked is not None, "no faulted tick found in the ring"
        t, spans, rungs = walked
        for s in rungs:
            assert spans[s.parent_id].name == "estimate"
        outcomes = [(s.attrs["rung"], s.attrs["outcome"]) for s in rungs]
        assert ("pallas", "fault") in outcomes
        # the walk ends on a serving rung (ok) below the faulted ones
        assert outcomes[-1][1] == "ok"
        # driver tagged the root with scenario sim-time coordinates
        root = t.root
        assert root.attrs["scenario"] == "kernel_fault_ladder"
        assert "sim_ts" in root.attrs and "tick" in root.attrs

    def test_breaker_transitions_visible_as_events(self, ladder_replays):
        r1, _ = ladder_replays
        events = [
            ev
            for t in r1.recorder.traces()
            for s in t.spans
            for ev in s.events
            if ev["name"] == "breaker.transition"
        ]
        assert events, "breaker trips must land on the tick trace"
        assert any(ev["attrs"]["to_state"] == "open" for ev in events)

    def test_scorer_per_phase_breakdown(self, ladder_replays):
        from autoscaler_tpu.loadgen.score import build_report

        r1, _ = ladder_replays
        report = build_report(r1)
        fd = report["function_duration"]
        for phase in ("main", "estimate", "deviceDispatch", "buildSnapshot",
                      "scaleDown"):
            assert phase in fd, phase
            assert {"count", "p50_s", "p99_s", "max_s"} <= set(fd[phase])

    def test_cli_chrome_trace_flag(self, tmp_path):
        from autoscaler_tpu.loadgen import cli

        out = tmp_path / "chrome.json"
        rc = cli.main(
            ["run", "benchmarks/scenarios/burst_small.json",
             "--chrome-trace", str(out)]
        )
        assert rc == 0
        doc = json.loads(out.read_text())
        assert any(e["name"] == "main" for e in doc["traceEvents"])
