"""Factored predicate mask (pod_class x node_class -> class_mask + exception
rows) must agree exactly with the dense [P, N] mask on every fixture — this
is the packer path that scales past the reference's 100k-node benchmark grid
(clustersnapshot_benchmark_test.go:71) without materializing ~GB of bool.
"""
import numpy as np
import jax.numpy as jnp
import pytest

from autoscaler_tpu.kube.objects import Taint, Toleration
from autoscaler_tpu.ops.schedule import greedy_schedule
from autoscaler_tpu.snapshot.cluster_snapshot import ClusterSnapshot
from autoscaler_tpu.snapshot.packer import (
    DENSE_MASK_CELL_LIMIT,
    compute_factored_mask,
    compute_sched_mask,
    pack,
)
from autoscaler_tpu.utils.test_utils import (
    anti_affinity,
    build_test_node,
    build_test_pod,
    pod_affinity,
)


def expand(fm, P, N):
    """Densify a FactoredMask for comparison."""
    mask = fm.class_mask[fm.pod_class][:, fm.node_class]
    for k in range(fm.cell_pod.shape[0]):
        if fm.cell_pod[k] >= 0:
            mask[fm.cell_pod[k], fm.cell_node[k]] = fm.cell_val[k]
    for i in range(P):
        if fm.pod_exc[i] >= 0:
            mask[i] = fm.exc_rows[fm.pod_exc[i]]
    return mask


def world(seed, P=40, N=12):
    """Random fixture exercising every rule family: taints, selectors,
    unschedulable, host ports, placed + pending affinity."""
    rng = np.random.default_rng(seed)
    nodes = []
    for j in range(N):
        labels = {"zone": f"z{j % 3}", "pool": f"p{j % 2}"}
        taints = [Taint("dedicated", "a", "NoSchedule")] if j % 4 == 0 else []
        n = build_test_node(f"n{j}", cpu_m=4000, labels=labels, taints=taints)
        n.unschedulable = j % 7 == 6
        nodes.append(n)
    pods = []
    node_of_pod = []
    for i in range(P):
        kw = {}
        if i % 5 == 0:
            kw["node_selector"] = {"pool": f"p{i % 2}"}
        if i % 4 == 0:
            kw["tolerations"] = [Toleration(key="dedicated", value="a")]
        if i % 6 == 3:
            kw["affinity"] = anti_affinity({"app": f"a{i % 3}"})
        if i % 6 == 5:
            kw["affinity"] = pod_affinity({"app": f"a{i % 3}"}, topology_key="zone")
        pod = build_test_pod(
            f"pod{i}", cpu_m=100, labels={"app": f"a{i % 3}"}, **kw
        )
        if i % 9 == 1:
            pod.host_ports = (8080,)
        placed = rng.random() < 0.5
        node_of_pod.append(int(rng.integers(0, N)) if placed else -1)
        pods.append(pod)
    return nodes, pods, node_of_pod


class TestFactoredParity:
    @pytest.mark.parametrize("seed", range(6))
    def test_factored_equals_dense(self, seed):
        nodes, pods, node_of_pod = world(seed)
        dense = compute_sched_mask(nodes, pods, node_of_pod)
        fm = compute_factored_mask(nodes, pods, node_of_pod)
        np.testing.assert_array_equal(
            expand(fm, len(pods), len(nodes)), dense, err_msg=f"seed {seed}"
        )

    @pytest.mark.parametrize("seed", range(3))
    def test_factored_parity_without_interpod(self, seed):
        nodes, pods, node_of_pod = world(seed)
        dense = compute_sched_mask(nodes, pods, node_of_pod, interpod=False)
        fm = compute_factored_mask(nodes, pods, node_of_pod, interpod=False)
        np.testing.assert_array_equal(expand(fm, len(pods), len(nodes)), dense)

    def test_exception_rows_are_sparse(self):
        # plain pods (no ports/affinity) should produce zero exceptions
        nodes = [build_test_node(f"n{j}") for j in range(4)]
        pods = [build_test_pod(f"p{i}") for i in range(16)]
        fm = compute_factored_mask(nodes, pods, [-1] * 16)
        assert (fm.pod_exc == -1).all()


class TestPackModes:
    def test_pack_auto_switches_to_factored(self):
        nodes = [build_test_node(f"n{j}") for j in range(3)]
        pods = [build_test_pod(f"p{i}") for i in range(5)]
        t_dense, _ = pack(nodes, pods, dense_mask=True)
        t_fact, _ = pack(nodes, pods, dense_mask=False)
        assert t_dense.sched_mask is not None
        assert t_fact.sched_mask is None
        np.testing.assert_array_equal(
            np.asarray(t_fact.dense_sched()), np.asarray(t_dense.sched_mask)
        )

    def test_dense_sched_matches_across_modes_with_rules(self):
        nodes, pods, node_of_pod = world(11, P=30, N=10)
        for i, pod in enumerate(pods):
            pod.node_name = nodes[node_of_pod[i]].name if node_of_pod[i] >= 0 else ""
        t_dense, _ = pack(nodes, pods, dense_mask=True)
        t_fact, _ = pack(nodes, pods, dense_mask=False)
        np.testing.assert_array_equal(
            np.asarray(t_fact.dense_sched()), np.asarray(t_dense.sched_mask)
        )

    def test_sched_row_gather(self):
        nodes, pods, node_of_pod = world(3, P=20, N=8)
        for i, pod in enumerate(pods):
            pod.node_name = nodes[node_of_pod[i]].name if node_of_pod[i] >= 0 else ""
        t_fact, meta = pack(nodes, pods, dense_mask=False)
        dense = np.asarray(t_fact.dense_sched())
        for i in (0, 3, 7, 19):
            np.testing.assert_array_equal(
                np.asarray(t_fact.sched_row(jnp.int32(i))), dense[i]
            )

    def test_kernels_run_in_factored_mode(self):
        # greedy_schedule via sched_row must behave identically in both modes
        nodes = [build_test_node(f"n{j}", cpu_m=1000) for j in range(4)]
        nodes[0].taints = [Taint("dedicated", "x", "NoSchedule")]
        pods = [build_test_pod(f"p{i}", cpu_m=400) for i in range(6)]
        for mode in (True, False):
            t, meta = pack(nodes, pods, dense_mask=mode)
            slots = jnp.arange(6, dtype=jnp.int32)
            hints = jnp.full((6,), -1, jnp.int32)
            res = greedy_schedule(t, slots, hints)
            placed = np.asarray(res.placed)
            dest = np.asarray(res.dest)
            # node 0 is tainted: 3 untainted nodes x 2 pods each = 6 placed
            assert placed.sum() == 6
            assert 0 not in dest[placed]

    def test_auto_threshold(self):
        # tiny world stays dense by default
        nodes = [build_test_node("n0")]
        pods = [build_test_pod("p0")]
        t, _ = pack(nodes, pods)
        assert t.sched_mask is not None
        assert DENSE_MASK_CELL_LIMIT == 1 << 24


class TestHostPortScaling:
    def test_hostport_daemonset_stays_class_structured(self):
        # A host-port DaemonSet pod on EVERY node (the node-exporter pattern)
        # must not explode into per-pod dense exception rows: port verdicts
        # are class data; only the self-cell corrections are per-pod (COO).
        N = 50
        nodes = [build_test_node(f"n{j}", cpu_m=4000) for j in range(N)]
        pods = []
        node_of_pod = []
        for j in range(N):
            ds = build_test_pod(f"ds-{j}", cpu_m=50)
            ds.host_ports = (9100,)
            ds.daemonset = True
            pods.append(ds)
            node_of_pod.append(j)
        pending = build_test_pod("web", cpu_m=100)
        pending.host_ports = (9100,)
        pods.append(pending)
        node_of_pod.append(-1)
        fm = compute_factored_mask(nodes, pods, node_of_pod)
        assert (fm.pod_exc == -1).all()          # zero dense rows
        assert (fm.cell_pod >= 0).sum() == N     # one override per placed pod
        dense = compute_sched_mask(nodes, pods, node_of_pod)
        np.testing.assert_array_equal(expand(fm, len(pods), N), dense)
        # semantics: the pending port pod fits nowhere; each DS pod still
        # "fits" its own node (self-contribution ignored)
        assert not dense[N].any()
        for j in range(N):
            assert dense[j, j]
            assert not dense[j, (j + 1) % N]


class TestFactoredScale:
    def test_large_world_packs_without_dense_mask(self):
        # 20k pods x 2k nodes = 40M cells: over the dense limit. The pack
        # must stay factored and fast (no [P, N] materialization).
        import time

        P, N = 20_000, 2_000
        nodes = [
            build_test_node(f"n{j}", cpu_m=4000, labels={"zone": f"z{j % 3}"})
            for j in range(N)
        ]
        pods = [
            build_test_pod(f"p{i}", cpu_m=100, labels={"app": f"a{i % 5}"})
            for i in range(P)
        ]
        t0 = time.monotonic()
        t, meta = pack(nodes, pods)
        dt = time.monotonic() - t0
        assert t.sched_mask is None
        assert t.class_mask.shape[0] <= 8  # handful of profiles
        assert dt < 30.0, f"pack took {dt:.1f}s"
