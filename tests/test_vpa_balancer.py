"""VPA (histograms, recommender, updater, checkpoints), balancer, and
addon-resizer tests — modeled on the reference's
vertical-pod-autoscaler/pkg/recommender/util/histogram_test.go,
logic/estimator_test.go, updater tests, and balancer/pkg/policy tests."""
import numpy as np
import pytest

from autoscaler_tpu.addonresizer.nanny import LinearEstimator, Nanny
from autoscaler_tpu.balancer.policy import (
    Target,
    distribute_by_priority,
    distribute_by_proportions,
    get_placement,
)
from autoscaler_tpu.core.scaledown.tracking import RemainingPdbTracker
from autoscaler_tpu.kube.objects import (
    LabelSelector,
    PodDisruptionBudget,
    Resources,
)
from autoscaler_tpu.utils.test_utils import GB, MB, build_test_pod
from autoscaler_tpu.vpa.histogram import (
    CPU_SPEC,
    HistogramBank,
    HistogramSpec,
)
from autoscaler_tpu.vpa.recommender import (
    CheckpointManager,
    ClusterStateModel,
    ContainerKey,
    PercentileRecommender,
)
from autoscaler_tpu.vpa.updater import (
    Updater,
    UpdatePriorityCalculator,
    apply_recommendation,
)

DAY = 86400.0


class TestHistogram:
    def test_bucket_mapping(self):
        spec = HistogramSpec(first_bucket=0.01, ratio=1.05, num_buckets=176)
        assert spec.bucket_of([0.001])[0] == 0   # below first bucket
        assert spec.bucket_of([0.01])[0] == 1
        b = spec.bucket_of([1.0])[0]
        assert spec.bucket_start(b) <= 1.0 <= spec.bucket_start(b + 1)

    def test_percentile_batched(self):
        bank = HistogramBank(3, CPU_SPEC)
        # series 0: constant 0.5 cores; series 1: constant 2.0; series 2: empty
        n = 100
        bank.add_samples(
            np.zeros(n, np.int64), np.full(n, 0.5), np.ones(n), np.zeros(n)
        )
        bank.add_samples(
            np.ones(n, np.int64), np.full(n, 2.0), np.ones(n), np.zeros(n)
        )
        p = np.asarray(bank.percentile(0.9))
        assert 0.5 <= p[0] <= 0.58   # bucket end covering 0.5
        assert 2.0 <= p[1] <= 2.2
        assert p[2] == 0.0

    def test_decay_halves_old_weight(self):
        bank = HistogramBank(1, CPU_SPEC, half_life_s=DAY)
        # old heavy samples at 0.1 cores, then fresh samples at 1.0 one
        # half-life later with half the count — equal effective weight
        bank.add_samples(np.zeros(4, np.int64), np.full(4, 0.1), np.ones(4), np.zeros(4))
        bank.add_samples(
            np.zeros(2, np.int64), np.full(2, 1.0), np.ones(2), np.full(2, DAY)
        )
        p50 = float(np.asarray(bank.percentile(0.5))[0])
        # effective: old 4*0.5=2, new 2*1=2 → p50 sits at the boundary (old bucket)
        assert p50 <= 0.2
        p75 = float(np.asarray(bank.percentile(0.9))[0])
        assert p75 >= 1.0

    def test_checkpoint_roundtrip(self):
        bank = HistogramBank(2, CPU_SPEC)
        bank.add_samples(
            np.zeros(50, np.int64),
            np.random.default_rng(0).uniform(0.1, 2.0, 50),
            np.ones(50),
            np.zeros(50),
        )
        before = float(np.asarray(bank.percentile(0.9))[0])
        ckpt = bank.checkpoint(0)
        bank2 = HistogramBank(2, CPU_SPEC)
        bank2.restore(0, ckpt)
        after = float(np.asarray(bank2.percentile(0.9))[0])
        # normalization quantizes; within a bucket or two
        assert after == pytest.approx(before, rel=0.15)


class TestRecommender:
    def test_end_to_end_recommendation(self):
        model = ClusterStateModel()
        key = ContainerKey("my-vpa", "app")
        rng = np.random.default_rng(1)
        ts = np.linspace(0, 8 * DAY, 500)
        model.add_cpu_samples([key] * 500, rng.normal(0.5, 0.05, 500).clip(0.01), ts)
        model.add_memory_peaks(
            [key] * 500, rng.normal(1e9, 5e7, 500).clip(1e8), ts
        )
        recs = PercentileRecommender(model).recommend(now_ts=8 * DAY)
        rec = recs[key]
        # target ≈ p90 * 1.15 margin
        assert 0.5 <= rec.target_cpu <= 0.8
        assert 1e9 <= rec.target_memory <= 1.5e9
        assert rec.lower_cpu <= rec.target_cpu <= rec.upper_cpu
        assert rec.lower_memory <= rec.target_memory <= rec.upper_memory

    def test_min_floor(self):
        model = ClusterStateModel()
        key = ContainerKey("v", "tiny")
        model.add_cpu_samples([key], [0.001], [0.0])
        model.add_memory_peaks([key], [1e6], [0.0])
        rec = PercentileRecommender(model).recommend(now_ts=DAY)[key]
        assert rec.target_cpu >= 0.025
        assert rec.target_memory >= 250 * 1024 * 1024

    def test_oom_bumps_memory_upper_bound(self):
        # one OOM among ten normal peaks moves the p95 upper bound (the
        # eviction quick-path is the updater's job, not the histogram's —
        # matching the reference's RecordOOM behavior)
        model = ClusterStateModel()
        key = ContainerKey("v", "app")
        model.add_memory_peaks([key] * 10, [5e8] * 10, list(range(10)))
        before = PercentileRecommender(model).recommend(now_ts=DAY)[key].upper_memory
        model.observe_oom(key, memory_at_oom=2e9, ts=11.0)
        after = PercentileRecommender(model).recommend(now_ts=DAY)[key].upper_memory
        assert after > before
        assert after >= 2e9  # covers the padded OOM sample

    def test_checkpoint_manager_roundtrip(self):
        model = ClusterStateModel()
        key = ContainerKey("v", "app")
        model.add_cpu_samples([key] * 20, [0.7] * 20, list(range(20)))
        model.add_memory_peaks([key] * 20, [8e8] * 20, list(range(20)))
        ckpts = CheckpointManager(model).store()
        model2 = ClusterStateModel()
        CheckpointManager(model2).load(ckpts)
        rec2 = PercentileRecommender(model2).recommend(now_ts=DAY)[key]
        rec1 = PercentileRecommender(model).recommend(now_ts=DAY)[key]
        assert rec2.target_cpu == pytest.approx(rec1.target_cpu, rel=0.15)


class TestUpdater:
    def _rec(self):
        from autoscaler_tpu.vpa.recommender import Recommendation

        return Recommendation(
            target_cpu=1.0, target_memory=1e9,
            lower_cpu=0.8, lower_memory=8e8,
            upper_cpu=1.3, upper_memory=1.3e9,
        )

    def test_no_evict_within_band(self):
        calc = UpdatePriorityCalculator()
        pod = build_test_pod("app-1", cpu_m=1000, mem=1e9)
        assert calc.priority_of(pod, self._rec(), now_ts=0.0) is None

    def test_evict_on_drift(self):
        calc = UpdatePriorityCalculator()
        pod = build_test_pod("app-1", cpu_m=300, mem=1e9)  # way under target
        p = calc.priority_of(pod, self._rec(), now_ts=0.0)
        assert p is not None and p.outside_recommended_range

    def test_oom_quick_path(self):
        calc = UpdatePriorityCalculator()
        pod = build_test_pod("app-1", cpu_m=950, mem=0.95e9)  # tiny drift
        p = calc.priority_of(pod, self._rec(), now_ts=100.0, last_oom_ts=50.0)
        assert p is not None and p.oom_quick_path

    def test_updater_respects_pdb_and_budget(self):
        pods = [build_test_pod(f"app-{i}", cpu_m=300, labels={"app": "x"}) for i in range(4)]
        pdb = PodDisruptionBudget(
            "pdb", "default", LabelSelector.from_dict({"app": "x"}), disruptions_allowed=1
        )
        tracker = RemainingPdbTracker([pdb])
        evicted_names = []
        updater = Updater()
        evicted = updater.run_once(
            pods_by_workload={"w": pods},
            recommendations={ContainerKey("v", "app"): self._rec()},
            vpa_of_workload={"w": "v"},
            now_ts=0.0,
            pdb_tracker=tracker,
            evict_fn=lambda p: evicted_names.append(p.name),
        )
        assert len(evicted) == 1  # PDB allows only one disruption
        assert evicted_names == [evicted[0].name]

    def test_apply_recommendation(self):
        pod = build_test_pod("app-1", cpu_m=100, mem=100 * MB)
        patched = apply_recommendation(pod, self._rec())
        assert patched.requests.cpu_m == pytest.approx(1000)
        assert patched.requests.memory == pytest.approx(1e9)
        assert pod.requests.cpu_m == 100  # original untouched


class TestBalancer:
    def test_priority_fill_order(self):
        targets = [
            Target("a", priority=0, max_replicas=3),
            Target("b", priority=1, max_replicas=10),
        ]
        p = distribute_by_priority(10, targets)
        assert p.assignments == {"a": 3, "b": 7}
        assert p.unassigned == 0

    def test_priority_minimums(self):
        targets = [
            Target("a", priority=0, max_replicas=10),
            Target("b", priority=1, min_replicas=2, max_replicas=10),
        ]
        p = distribute_by_priority(5, targets)
        assert p.assignments["b"] >= 2

    def test_proportional_split(self):
        targets = [
            Target("a", proportion=3.0),
            Target("b", proportion=1.0),
        ]
        p = distribute_by_proportions(8, targets)
        assert p.assignments == {"a": 6, "b": 2}

    def test_proportional_respects_max(self):
        targets = [
            Target("a", proportion=3.0, max_replicas=2),
            Target("b", proportion=1.0, max_replicas=10),
        ]
        p = distribute_by_proportions(8, targets)
        assert p.assignments["a"] == 2
        assert p.assignments["b"] == 6

    def test_failing_target_skipped(self):
        targets = [
            Target("a", priority=0, failing=True),
            Target("b", priority=1, max_replicas=10),
        ]
        p = get_placement(4, targets, "priority")
        assert p.assignments.get("a", 0) == 0
        assert p.assignments["b"] == 4

    def test_pod_summary(self):
        """summary.go CalculateSummary: running counts, pending counts, and
        pending-past-deadline trips the fallback trigger."""
        from autoscaler_tpu.balancer.summary import (
            calculate_summary,
            target_failing,
        )
        from autoscaler_tpu.utils.test_utils import GB, build_test_pod

        def pod(name, phase, created, node=""):
            p = build_test_pod(name, cpu_m=100, mem=GB, node_name=node)
            p.phase = phase
            p.creation_ts = created
            return p

        pods = [
            pod("r1", "Running", 0.0, node="n1"),
            pod("r2", "Running", 0.0, node="n1"),
            pod("young", "Pending", 95.0),     # within 60s tolerance
            pod("stuck", "Pending", 10.0),     # pending for 90s > 60s
            pod("done", "Succeeded", 0.0),     # terminal: not counted
            pod("dead", "Failed", 0.0),
        ]
        s = calculate_summary(pods, now_ts=100.0, startup_timeout_s=60.0)
        assert (s.total, s.running, s.not_started_within_deadline) == (4, 2, 1)
        assert target_failing(s)
        healthy = calculate_summary(pods[:3], now_ts=100.0, startup_timeout_s=60.0)
        assert not target_failing(healthy)

    def test_summary_phase_heuristic(self):
        """Objects without status.phase fall back to node_name: scheduled ≈
        Running, unscheduled ≈ Pending."""
        from autoscaler_tpu.balancer.summary import calculate_summary
        from autoscaler_tpu.utils.test_utils import GB, build_test_pod

        scheduled = build_test_pod("a", cpu_m=100, mem=GB, node_name="n1")
        pending = build_test_pod("b", cpu_m=100, mem=GB)
        pending.creation_ts = 0.0
        s = calculate_summary([scheduled, pending], now_ts=600.0,
                              startup_timeout_s=60.0)
        assert (s.total, s.running, s.not_started_within_deadline) == (2, 1, 1)

    def test_summary_feeds_placement_fallback(self):
        """A target whose pods missed the startup deadline is skipped by
        get_placement, wiring summary → Target.failing → fallback."""
        from autoscaler_tpu.balancer.policy import Target, get_placement
        from autoscaler_tpu.balancer.summary import (
            calculate_summary,
            target_failing,
        )
        from autoscaler_tpu.utils.test_utils import GB, build_test_pod

        stuck = build_test_pod("s", cpu_m=100, mem=GB)
        stuck.phase, stuck.creation_ts = "Pending", 0.0
        summaries = {
            "a": calculate_summary([stuck], now_ts=600.0, startup_timeout_s=60.0),
            "b": calculate_summary([], now_ts=600.0, startup_timeout_s=60.0),
        }
        targets = [
            Target(name=n, priority=i, failing=target_failing(s))
            for i, (n, s) in enumerate(sorted(summaries.items()))
        ]
        placement = get_placement(10, targets, policy="priority")
        assert placement.assignments.get("b") == 10
        assert "a" not in placement.assignments

    def test_overflow_unassigned(self):
        p = get_placement(10, [Target("a", max_replicas=4)], "priority")
        assert p.unassigned == 6


class TestNanny:
    def test_linear_estimate_and_deadband(self):
        est = LinearEstimator(
            base_cpu_m=100, cpu_per_node_m=10, base_memory=100 * MB, memory_per_node=5 * MB
        )
        want = est.estimate(100)
        assert want.cpu_m == 1100
        # within deadband → no update
        close = Resources(cpu_m=1050, memory=want.memory)
        assert est.needs_update(close, 100) is None
        far = Resources(cpu_m=500, memory=want.memory)
        assert est.needs_update(far, 100) is not None

    def test_nanny_applies_update(self):
        est = LinearEstimator(100, 10, 100 * MB, 5 * MB)
        applied = []
        nanny = Nanny(est, applied.append)
        assert nanny.poll(Resources(cpu_m=100, memory=100 * MB), 200)
        assert applied and applied[0].cpu_m == 2100
        # second poll with correct resources: no-op
        assert not nanny.poll(applied[0], 200)
