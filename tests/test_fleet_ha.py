"""Fleet HA (ISSUE 15): health-weighted multi-sidecar balancing, tenant
quota tiers, and rolling-restart chaos certification.

The headline contracts:

- the endpoint picker is a pure function of its injected clock + rng
  stream and the outcome sequence — two replays route every request
  identically (the ledger's endpoint-choice column byte-matches);
- a flapping/restarting replica is starved of first-attempt traffic
  (penalty scores, then breaker ejection) and earns it back through a
  single-flight half-open probe after cooldown;
- quota tiers are typed and ordered: per-tier shared buckets, queue-share
  slices, tier default deadlines, and bronze-sheds-before-gold service
  order under bounded capacity;
- the hedge leg never fires at an endpoint known to be draining, ejected,
  or mid-UNAVAILABLE-streak — no hedge beats a doomed hedge.
"""
import threading
import time

import numpy as np
import pytest

from autoscaler_tpu.fleet import (
    EndpointBalancer,
    FleetCoalescer,
    FleetOverloadError,
    FleetRequest,
    TierError,
    parse_tiers,
)
from autoscaler_tpu.fleet.admission import AdmissionController
from autoscaler_tpu.fleet.errors import SHED_QUEUE_FULL, SHED_QUOTA
from autoscaler_tpu.metrics.metrics import AutoscalerMetrics
from autoscaler_tpu.utils.circuit import BreakerState


def _request(rng, tenant, P=8, G=3, deadline_s=None):
    return FleetRequest(
        tenant_id=tenant,
        pod_req=rng.integers(1, 60, (P, 6)).astype(np.float32),
        pod_masks=rng.random((G, P)) > 0.3,
        template_allocs=rng.integers(50, 300, (G, 6)).astype(np.float32),
        node_caps=rng.integers(1, 8, G).astype(np.int32),
        max_nodes=P,
        deadline_s=deadline_s,
    )


def _seeded_balancer(endpoints, seed=7, **kw):
    gen = np.random.default_rng(seed)
    sim = {"t": 0.0}
    bal = EndpointBalancer(
        endpoints, clock=lambda: sim["t"],
        rng=lambda: float(gen.random()), **kw,
    )
    return bal, sim


# -- endpoint balancer --------------------------------------------------------


class TestEndpointBalancer:
    def test_rejects_empty_and_duplicate_endpoints(self):
        with pytest.raises(ValueError):
            EndpointBalancer([])
        with pytest.raises(ValueError):
            EndpointBalancer(["a", "a"])

    def test_pick_sequence_is_deterministic_on_the_seeded_seam(self):
        """Same rng stream + same outcome sequence → same picks. This is
        the property that makes the fleet ledger's endpoint-choice column
        replay byte-identically."""

        def run():
            bal, _ = _seeded_balancer(["a", "b", "c"], seed=42)
            picks = []
            for i in range(40):
                p = bal.pick()
                picks.append(p)
                if p == "b" and i % 3 == 0:
                    bal.record_failure(p)
                else:
                    bal.record_success(p, 0.01)
            return picks

        assert run() == run()

    def test_healthy_fleet_spreads_picks(self):
        """All-tied scores must not herd onto one index (the tie keeps
        the uniform first draw)."""
        bal, _ = _seeded_balancer(["a", "b", "c"], seed=3)
        counts = {}
        for _ in range(300):
            p = bal.pick()
            counts[p] = counts.get(p, 0) + 1
            bal.record_success(p, 0.01)
        assert set(counts) == {"a", "b", "c"}
        assert min(counts.values()) > 40, counts

    def test_failures_starve_an_endpoint_of_first_attempts(self):
        bal, _ = _seeded_balancer(["a", "b", "c"], seed=5)
        for ep in ("a", "b", "c"):
            bal.record_success(ep, 0.01)
        bal.record_failure("c", unavailable=True)
        picks = []
        for _ in range(60):
            p = bal.pick()
            picks.append(p)
            bal.record_success(p, 0.01)
        # P2C with a 0.5s penalty on c: c loses every pair it is drawn in
        assert "c" not in picks

    def test_ejection_and_single_probe_recovery(self):
        bal, sim = _seeded_balancer(
            ["a", "b"], seed=9, eject_failure_threshold=3,
            eject_cooldown_s=10.0,
        )
        for _ in range(3):
            bal.record_failure("b", unavailable=True)
        assert bal.snapshot()["b"]["breaker"] == "open"
        # while open and inside cooldown: never picked (a exists)
        for _ in range(20):
            assert bal.pick() == "a"
        # cooldown elapses: the NEXT pick is b's half-open probe (a probe
        # that had to out-score a healthy peer would never run), and the
        # single-flight slot keeps further picks off b while it is out
        sim["t"] = 11.0
        assert bal.pick() == "b"
        for _ in range(10):
            assert bal.pick() == "a"  # probe slot held: no stampede
        # probe success closes the breaker and clears the streak
        bal.record_success("b", 0.01)
        snap = bal.snapshot()["b"]
        assert snap["breaker"] == "closed"
        assert snap["consecutive_unavailable"] == 0

    def test_probe_failure_reopens_without_stampede(self):
        bal, sim = _seeded_balancer(
            ["a", "b"], seed=11, eject_failure_threshold=2,
            eject_cooldown_s=5.0,
        )
        bal.record_failure("b")
        bal.record_failure("b")
        sim["t"] = 6.0
        # the cooled-down endpoint probes immediately; failing the probe
        # re-opens a FULL window
        assert bal.pick() == "b"
        bal.record_failure("b")
        assert bal.snapshot()["b"]["breaker"] == "open"
        # inside the NEW cooldown window b is refused again
        sim["t"] = 7.0
        for _ in range(20):
            assert bal.pick() == "a"

    def test_all_ejected_still_picks_least_bad(self):
        bal, _ = _seeded_balancer(["a", "b"], seed=2,
                                  eject_failure_threshold=1,
                                  eject_cooldown_s=100.0)
        bal.record_failure("a")
        bal.record_failure("b")
        bal.record_failure("b")
        # everything open + inside cooldown: the call still has to go
        # somewhere — least-bad by score (a has the shorter streak)
        assert bal.pick() == "a"

    def test_exclude_exhaustion_returns_none(self):
        bal, _ = _seeded_balancer(["a", "b"])
        assert bal.pick(exclude=("a", "b")) is None

    def test_pick_hedge_skips_unhealthy(self):
        bal, _ = _seeded_balancer(["p", "s1", "s2"], seed=4)
        for ep in ("p", "s1", "s2"):
            bal.record_success(ep, 0.01)
        bal.record_drain("s1")
        for _ in range(20):
            assert bal.pick_hedge("p") == "s2"
        # streaking UNAVAILABLE disqualifies too
        bal.record_failure("s2", unavailable=True)
        assert bal.pick_hedge("p") is None

    def test_success_clears_drain_bit(self):
        bal, _ = _seeded_balancer(["a", "b"])
        bal.record_drain("b")
        assert not bal.healthy("b")
        bal.record_success("b", 0.01)
        assert bal.healthy("b")
        assert bal.snapshot()["b"]["drain_observed"] is False

    def test_deadline_failure_is_not_an_unavailable_streak(self):
        bal, _ = _seeded_balancer(["a", "b"])
        bal.record_failure("a", unavailable=False)
        snap = bal.snapshot()["a"]
        assert snap["consecutive_unavailable"] == 0
        assert snap["error_rate"] > 0


# -- tenant quota tiers -------------------------------------------------------


GOLD_BRONZE = (
    '{"gold": {"qps": 10, "burst": 20, "queue_share": 0.75, '
    '"default_deadline_s": 30, "shed_priority": 0, '
    '"tenants": ["g1", "g2"]}, '
    '"default": {"qps": 0.5, "burst": 1, "queue_share": 0.25, '
    '"default_deadline_s": 5, "shed_priority": 10}}'
)


class TestTierPolicy:
    def test_parse_and_resolve(self):
        policy = parse_tiers(GOLD_BRONZE)
        assert policy.names() == ("default", "gold")
        assert policy.tier_for("g1").name == "gold"
        assert policy.tier_for("anyone-else").name == "default"
        assert policy.tier_for("g2").default_deadline_s == 30.0
        assert parse_tiers("") is None
        assert parse_tiers("   ") is None

    def test_rejections(self):
        with pytest.raises(TierError):
            parse_tiers("{not json")
        with pytest.raises(TierError):
            parse_tiers('{"gold": {"qps": 1}}')  # no default catch-all
        with pytest.raises(TierError):
            parse_tiers('{"default": {"tenants": ["pinned"]}}')
        with pytest.raises(TierError):
            parse_tiers(
                '{"a": {"tenants": ["t"]}, "b": {"tenants": ["t"]}, '
                '"default": {}}'
            )  # tenant pinned twice
        with pytest.raises(TierError):
            parse_tiers('{"default": {"queue_share": 0.0}}')
        with pytest.raises(TierError):
            parse_tiers('{"default": {"queue_share": 1.5}}')
        with pytest.raises(TierError):
            parse_tiers('{"default": {"qpz": 3}}')  # typo'd field
        with pytest.raises(TierError):
            parse_tiers('{"default": {"shed_priority": -1}}')

    def test_tier_bucket_is_shared_across_the_tiers_tenants(self):
        """One budget per TIER: two gold tenants drain the same bucket."""
        ctl = AdmissionController(
            tiers=parse_tiers(
                '{"gold": {"qps": 1.0, "burst": 2, "tenants": ["g1", "g2"]},'
                ' "default": {}}'
            )
        )
        assert ctl.admit("g1", 0, 0.0).admitted
        assert ctl.admit("g2", 0, 0.0).admitted
        verdict = ctl.admit("g1", 0, 0.0)
        assert verdict.outcome == SHED_QUOTA
        assert verdict.tier == "gold"
        assert verdict.retry_after_s > 0

    def test_unmetered_tier_never_quota_sheds(self):
        ctl = AdmissionController(
            tiers=parse_tiers('{"default": {"qps": 0}}')
        )
        for _ in range(50):
            assert ctl.admit("t", 0, 0.0).admitted

    def test_queue_share_sheds_low_tier_while_gold_slice_stays_open(self):
        ctl = AdmissionController(
            max_queue_depth=4,
            tiers=parse_tiers(
                '{"gold": {"queue_share": 1.0, "shed_priority": 0, '
                '"tenants": ["g"]}, '
                '"default": {"queue_share": 0.25, "shed_priority": 10}}'
            ),
        )
        # bronze slice = max(1, int(0.25 * 4)) = 1: second bronze sheds
        assert ctl.admit("b", 0, 0.0, tier_depth=0).admitted
        verdict = ctl.admit("b", 1, 0.0, tier_depth=1)
        assert verdict.outcome == SHED_QUEUE_FULL
        assert verdict.tier == "default"
        # gold still admits at the same global depth
        assert ctl.admit("g", 1, 0.0, tier_depth=0).admitted
        # the GLOBAL bound still rules everyone
        assert ctl.admit("g", 4, 0.0, tier_depth=0).outcome == SHED_QUEUE_FULL

    def test_tiers_supersede_global_tenant_qps(self):
        ctl = AdmissionController(
            tenant_qps=0.0001,  # would shed almost everything
            tiers=parse_tiers('{"default": {"qps": 100, "burst": 100}}'),
        )
        for _ in range(20):
            assert ctl.admit("t", 0, 0.0).admitted


class TestCoalescerTiers:
    def _co(self, tiers=GOLD_BRONZE, **kw):
        sim = {"t": 0.0}
        kw.setdefault("clock", lambda: sim["t"])
        co = FleetCoalescer(
            buckets="16x4x8", window_s=0.002, batch_scenarios=8,
            tenant_tiers=tiers, **kw,
        )
        return co, sim

    def test_tier_default_deadline_binds_lazy_clients(self):
        co, sim = self._co()
        sim["t"] = 100.0
        rng = np.random.default_rng(0)
        ticket = co.submit(_request(rng, "g1"))  # gold: 30s default
        assert ticket.tier == "gold"
        assert ticket.deadline_ts == pytest.approx(130.0)
        # an explicit deadline wins over the tier default
        ticket2 = co.submit(_request(rng, "g1", deadline_s=2.0))
        assert ticket2.deadline_ts == pytest.approx(102.0)
        co.flush()

    def test_tier_labels_on_admission_and_sli_series(self):
        m = AutoscalerMetrics()
        co, _ = self._co(metrics=m)
        rng = np.random.default_rng(1)
        t = co.submit(_request(rng, "g1"))
        co.flush()
        t.result(timeout=0.0)
        assert m.fleet_admission_total.get(
            outcome="admitted", tenant="g1", tier="gold"
        ) == 1
        assert m.fleet_e2e_seconds.count(
            tenant="g1", bucket="16x4x8", tier="gold"
        ) == 1
        assert m.fleet_queue_wait_seconds.count(
            tenant="g1", bucket="16x4x8", tier="gold"
        ) == 1
        # bronze storm past its shared bucket: the shed carries its tier
        with pytest.raises(FleetOverloadError):
            for _ in range(5):
                co.submit(_request(rng, "noname"))
        assert m.fleet_admission_total.get(
            outcome="shed_quota", tenant="noname", tier="default"
        ) >= 1

    def test_flush_serves_gold_before_bronze_under_bounded_capacity(self):
        """The tier shed order: bronze submitted FIRST, gold second —
        bounded service (flush limit 1) must still serve gold and leave
        the bronze tail queued."""
        co, _ = self._co(
            tiers='{"gold": {"shed_priority": 0, "tenants": ["g"]}, '
                  '"default": {"shed_priority": 10}}'
        )
        rng = np.random.default_rng(2)
        bronze = co.submit(_request(rng, "b"))
        gold = co.submit(_request(rng, "g"))
        served = co.flush(limit=1)
        assert served == 1
        assert gold.done() and not bronze.done()
        assert co.queue_depth() == 1
        co.flush()
        assert bronze.done()

    def test_without_tiers_submission_order_is_preserved(self):
        co, _ = self._co(tiers="")
        rng = np.random.default_rng(3)
        first = co.submit(_request(rng, "a"))
        second = co.submit(_request(rng, "b"))
        co.flush(limit=1)
        assert first.done() and not second.done()
        co.flush()

    def test_from_options_wires_tenant_tiers(self):
        from autoscaler_tpu.config.options import AutoscalingOptions

        co = FleetCoalescer.from_options(AutoscalingOptions(
            fleet_prewarm=False, fleet_tenant_tiers=GOLD_BRONZE,
        ))
        assert co.tiers is not None
        assert co.tier_name("g1") == "gold"
        assert co.tier_name("stranger") == "default"
        co2 = FleetCoalescer.from_options(
            AutoscalingOptions(fleet_prewarm=False)
        )
        assert co2.tiers is None and co2.tier_name("x") == ""

    def test_drain_racing_abandoned_ticket_stamps_no_sli(self):
        """Satellite: a late winner (the caller departed — e.g. its hedge
        leg answered elsewhere) resolved by the DRAIN flush must count
        `abandoned`, never stamp lifecycle SLIs for a ghost."""
        m = AutoscalerMetrics()
        co, _ = self._co(metrics=m)
        rng = np.random.default_rng(4)
        ticket = co.submit(_request(rng, "g1"))
        with pytest.raises(TimeoutError):
            ticket.result(timeout=0.0)  # departed before the answer
        before = m.fleet_e2e_seconds.count(
            tenant="g1", bucket="16x4x8", tier="gold"
        )
        co.stop()  # the drain path's final flush resolves it late
        assert ticket.done()
        assert m.fleet_e2e_seconds.count(
            tenant="g1", bucket="16x4x8", tier="gold"
        ) == before
        assert m.fleet_ticket_outcomes_total.get(
            outcome="abandoned", tenant="g1"
        ) == 1


# -- client hedge-leg health --------------------------------------------------


class _FakeFuture:
    def __init__(self, result=None, ready=True):
        self._result = result
        self._ready = ready
        self.cancelled = False

    def done(self):
        return self._ready

    def add_done_callback(self, cb):
        if self._ready:
            cb(self)

    def result(self):
        return self._result

    def cancel(self):
        self.cancelled = True
        self._ready = True


class _FutureChannel:
    def __init__(self, fut):
        self.fut = fut

    def unary_unary(self, *a, **k):
        fut = self.fut

        class RPC:
            def future(self, request, timeout=None, metadata=None):
                return fut

        return RPC()

    def close(self):
        pass


class _Resp:
    @staticmethod
    def FromString(data):  # noqa: N802 — protobuf API shape
        return data


class TestHedgeHealthGating:
    def test_hedge_skips_drain_observed_endpoint(self, monkeypatch):
        """Satellite bugfix: the hedge leg must consult failover/drain
        state — a hedge fired at a draining sidecar burns deadline budget
        for a guaranteed UNAVAILABLE. With the only alternative drained,
        NO hedge channel may be built; the primary keeps the budget."""
        from autoscaler_tpu.rpc import service as service_mod
        from autoscaler_tpu.rpc.service import TpuSimulationClient

        client = TpuSimulationClient(
            ["primary:1", "secondary:2"], default_timeout_s=0.2, hedge=True,
        )
        client.HEDGE_MIN_DELAY_S = 0.01
        client._balancer.record_drain("secondary:2")
        client._channel = _FutureChannel(_FakeFuture(ready=False))
        monkeypatch.setattr(
            service_mod.grpc, "insecure_channel",
            lambda target: pytest.fail(
                f"hedge channel built toward drained {target}"
            ),
        )
        with pytest.raises(TimeoutError):
            client._hedged_send("Estimate", object(), 0.05, None, _Resp)

    def test_exhausted_budget_never_takes_a_hedge_pick(self, monkeypatch):
        """Regression (graftlint GL016): pick_hedge may hand out a
        half-open probe slot, and a pick taken with the deadline budget
        already burned can never reach an outcome — the slot would leak
        until restart. The budget check must come BEFORE the pick."""
        from autoscaler_tpu.rpc.service import TpuSimulationClient

        client = TpuSimulationClient(
            ["p:1", "s:2"], default_timeout_s=0.2, hedge=True,
        )
        client.HEDGE_MIN_DELAY_S = 0.0
        for ep in ("p:1", "s:2"):
            client._balancer.record_success(ep, 0.01)
        monkeypatch.setattr(
            client._balancer, "pick_hedge",
            lambda *a, **k: pytest.fail(
                "pick_hedge taken with the budget already exhausted"
            ),
        )
        client._channel = _FutureChannel(_FakeFuture(ready=False))
        with pytest.raises(TimeoutError):
            client._hedged_send("Estimate", object(), 0.0, None, _Resp)

    def test_hedge_targets_a_healthy_endpoint_not_the_next_in_list(
        self, monkeypatch
    ):
        """The hedge target is a balancer pick, not `next index`: with
        the list-adjacent endpoint drained, the hedge must land on the
        healthy one further down."""
        from autoscaler_tpu.rpc import service as service_mod
        from autoscaler_tpu.rpc.service import TpuSimulationClient

        client = TpuSimulationClient(
            ["p:1", "s1:2", "s2:3"], default_timeout_s=5.0, hedge=True,
        )
        client.HEDGE_MIN_DELAY_S = 0.01
        for ep in ("p:1", "s1:2", "s2:3"):
            client._balancer.record_success(ep, 0.01)
        client._balancer.record_drain("s1:2")  # the next-in-list endpoint
        client._channel = _FutureChannel(_FakeFuture(ready=False))
        built = []
        monkeypatch.setattr(
            service_mod.grpc, "insecure_channel",
            lambda target: built.append(target)
            or _FutureChannel(_FakeFuture(result="hedged")),
        )
        result = client._hedged_send("Estimate", object(), 5.0, None, _Resp)
        assert result == "hedged"
        assert built == ["s2:3"]


# -- replica chaos through the fleet driver -----------------------------------


def _rolling_spec(seed=6):
    from autoscaler_tpu.loadgen.spec import ScenarioSpec

    return ScenarioSpec.from_dict({
        "name": "ha_smoke", "seed": seed, "ticks": 6,
        "tick_interval_s": 10.0,
        "fleet": {
            "replicas": 3,
            "tenants": [
                {"name": "g1", "pods": 6, "groups": 2, "max_nodes": 8},
                {"name": "b1", "pods": 6, "groups": 2, "max_nodes": 8,
                 "requests_per_round": 3},
            ],
        },
        "events": [
            {"at_tick": 1, "kind": "fault",
             "fault": {"kind": "replica_restart", "replica": 0,
                       "end_tick": 2}},
            {"at_tick": 3, "kind": "fault",
             "fault": {"kind": "endpoint_flap", "replica": 2,
                       "probability": 0.7, "end_tick": 2}},
        ],
        "options": {
            "fleet_shape_buckets": "16x4x8", "fleet_prewarm": False,
            "fleet_batch_scenarios": 8, "perf_cost_model": False,
            "fleet_max_queue_depth": 8,
            "fleet_tenant_tiers": (
                '{"gold": {"qps": 5, "burst": 10, "queue_share": 0.75, '
                '"shed_priority": 0, "tenants": ["g1"]}, '
                '"default": {"qps": 0.1, "burst": 1, "queue_share": 0.5, '
                '"shed_priority": 10}}'
            ),
        },
    })


def test_new_replica_fault_kinds_roundtrip_and_validate():
    from autoscaler_tpu.loadgen.spec import FaultSpec, ScenarioSpec, SpecError

    spec = _rolling_spec()
    assert ScenarioSpec.from_json(spec.to_json()) == spec
    assert spec.fleet.replicas == 3
    with pytest.raises(SpecError):
        FaultSpec(kind="replica_restart")  # replica target required
    with pytest.raises(SpecError):
        FaultSpec(kind="endpoint_flap")
    with pytest.raises(SpecError):
        FaultSpec(kind="kernel_fault", replica=1)  # wrong kind
    with pytest.raises(SpecError):
        FaultSpec(kind="replica_restart", replica=0, group="g")
    with pytest.raises(SpecError):
        ScenarioSpec.from_dict({
            "name": "x", "fleet": {"replicas": 0, "tenants": [
                {"name": "t"}]},
        })


def test_driver_routes_around_a_restarting_replica():
    """Rolling restart with 3 replicas: the kill window loses NOTHING —
    every request reroutes, replica-0 serves zero requests while down,
    gold never sheds, and the endpoint column is complete."""
    from autoscaler_tpu.loadgen.fleetdrive import run_fleet_scenario

    result = run_fleet_scenario(_rolling_spec())
    assert result.unresolved == 0
    assert result.injected_faults.get("replica_restart", 0) > 0
    # restart window = ticks 1..2: replica-0 must serve nothing there,
    # yet every round answers its full admitted set
    for rec in result.records:
        for t in rec.tenants:
            assert t.endpoint.startswith("replica-"), t
            if rec.tick in (1, 2):
                assert t.endpoint != "replica-0", rec.tick
    # no outage sheds: only tier backpressure (bronze quota) appears
    reasons = {row["reason"] for r in result.records for row in r.shed}
    assert "replica_restart" not in reasons
    assert reasons <= {"shed_quota", "shed_queue_full"}, reasons
    # gold always answered, never shed
    gold_sheds = [row for r in result.records for row in r.shed
                  if row["tenant"] == "g1"]
    assert not gold_sheds
    for rec in result.records:
        assert "g1" in {t.tenant for t in rec.tenants}
    # tier provenance on rows
    assert all(
        t.tier in ("gold", "default")
        for r in result.records for t in r.tenants
    )


def test_endpoint_choice_column_replays_byte_identically():
    """Satellite: balancer determinism — two replays of the same spec
    produce byte-identical fleet ledgers INCLUDING the endpoint-choice
    column, and the per-verdict endpoint sequences match exactly."""
    from autoscaler_tpu.loadgen.fleetdrive import run_fleet_scenario
    from autoscaler_tpu.loadgen.spec import ScenarioSpec

    spec = _rolling_spec()
    a = run_fleet_scenario(spec)
    b = run_fleet_scenario(ScenarioSpec.from_dict(spec.to_dict()))
    assert a.decision_ledger_lines() == b.decision_ledger_lines()
    assert a.slo_ledger_lines() == b.slo_ledger_lines()
    col_a = [(r.tick, t.tenant, t.endpoint, t.failovers)
             for r in a.records for t in r.tenants]
    col_b = [(r.tick, t.tenant, t.endpoint, t.failovers)
             for r in b.records for t in r.tenants]
    assert col_a == col_b
    assert len({e for _, _, e, _ in col_a}) >= 2  # genuinely multi-replica


def test_full_outage_sheds_typed_and_burns_budget():
    """Every replica down at once: submits shed unavailable (typed), the
    SLO charges bad budget, and recovery restores service."""
    from autoscaler_tpu.loadgen.fleetdrive import run_fleet_scenario
    from autoscaler_tpu.loadgen.spec import ScenarioSpec
    from autoscaler_tpu.slo import SLI_FLEET_E2E

    spec = ScenarioSpec.from_dict({
        "name": "outage", "seed": 8, "ticks": 4, "tick_interval_s": 10.0,
        "fleet": {"replicas": 2, "tenants": [
            {"name": "t", "pods": 6, "groups": 2, "max_nodes": 8},
        ]},
        "events": [
            {"at_tick": 1, "kind": "fault",
             "fault": {"kind": "replica_restart", "replica": 0,
                       "end_tick": 1}},
            {"at_tick": 1, "kind": "fault",
             "fault": {"kind": "replica_restart", "replica": 1,
                       "end_tick": 1}},
        ],
        "options": {"fleet_shape_buckets": "16x4x8", "fleet_prewarm": False,
                    "perf_cost_model": False},
    })
    result = run_fleet_scenario(spec)
    outage = result.records[1]
    assert outage.outcomes["resolved"] == 0
    assert outage.outcomes["shed"] == 1
    assert outage.shed[0]["reason"] == "replica_restart"
    assert outage.shed[0]["error"] == "FleetUnavailableError"
    final = result.slo_records[-1]["slos"][SLI_FLEET_E2E]
    assert final["events_bad"] >= 1
    # recovery: the rounds after the outage answer again
    assert result.records[2].outcomes["resolved"] == 1
    assert result.unresolved == 0


def test_ha_report_section():
    from autoscaler_tpu.loadgen.fleetdrive import run_fleet_scenario
    from autoscaler_tpu.loadgen.score import build_fleet_report

    report = build_fleet_report(run_fleet_scenario(_rolling_spec()))
    ha = report["ha"]
    assert sum(ha["endpoint_requests"].values()) == report["answers"]
    assert set(ha["endpoint_requests"]) <= {
        "replica-0", "replica-1", "replica-2"
    }
    assert ha["sheds_by_tier"].get("default", 0) > 0
    assert "gold" not in ha["sheds_by_tier"]


def test_fleet_ha_bench_gate():
    """bench.py --fleet-ha: the balanced-vs-static contrast is a pure
    sim-clock computation and its gate must hold (exit 0)."""
    import io
    import json
    from contextlib import redirect_stdout

    import bench

    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = bench._fleet_ha_bench_main()
    report = json.loads(buf.getvalue())
    assert rc == 0, report
    assert report["balanced"]["p99_s"] < report["static"]["p99_s"]
    assert (report["balanced"]["deadline_misses"]
            <= report["static"]["deadline_misses"])


def test_fleet_ledger_validator_and_bench_gate(tmp_path):
    """The fleet round ledger now has a validator twin (GL017): a real
    run's ledger validates clean through `bench.py --fleet-ledger`, and
    a hung ticket — the deadline-deadlock bug class — fails it."""
    import io
    import json
    from contextlib import redirect_stdout

    import bench
    from autoscaler_tpu.fleet import validate_fleet_records
    from autoscaler_tpu.loadgen.fleetdrive import run_fleet_scenario

    result = run_fleet_scenario(_rolling_spec())
    records = result.decision_log()
    assert validate_fleet_records(records) == []
    path = tmp_path / "fleet.jsonl"
    path.write_text(result.decision_ledger_lines())
    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = bench._fleet_ledger_main(str(path))
    report = json.loads(buf.getvalue())
    assert rc == 0 and report["valid"], report
    assert report["rounds"] == len(records)
    assert report["outcomes"].get("unresolved", 0) == 0
    # a hung ticket must never validate clean
    bad = [dict(r) for r in records]
    bad[0] = dict(bad[0], outcomes=dict(bad[0]["outcomes"], unresolved=1))
    assert any("unresolved" in e for e in validate_fleet_records(bad))
    # ...and an unreadable ledger is exit 2, not a crash
    with redirect_stdout(io.StringIO()):
        assert bench._fleet_ledger_main(str(tmp_path / "absent.jsonl")) == 2


# -- review-hardening regressions ---------------------------------------------


class _RecordingChannel:
    """unary_unary channel that counts calls and returns a canned answer."""

    def __init__(self, answer="ok"):
        self.calls = 0
        self.answer = answer

    def unary_unary(self, *a, **k):
        def call(request, timeout=None, metadata=None):
            self.calls += 1
            return self.answer

        return call

    def close(self):
        pass


def test_duplicate_endpoints_are_deduped_not_a_crash():
    """A repeated --rpc-address was harmless under the PR-14 static
    rotation (failover just revisited the endpoint); the balancer's
    one-health-record-per-endpoint rule must not turn that config wrinkle
    into a startup crash."""
    from autoscaler_tpu.rpc.service import TpuSimulationClient

    client = TpuSimulationClient(
        ["a:1", "a:1", "b:2", "a:1,b:2"], default_timeout_s=1.0,
    )
    assert client._targets == ["a:1", "b:2"]
    assert client._balancer.endpoints == ["a:1", "b:2"]
    client.close()


def test_replica_fault_out_of_range_is_rejected():
    """An out-of-range replica index would be silently inert — the chaos
    gate would 'pass' without ever exercising failover. Fail loudly like
    every other misapplied fault field."""
    from autoscaler_tpu.loadgen.spec import ScenarioSpec, SpecError

    base = {
        "name": "oob", "seed": 1, "ticks": 4, "tick_interval_s": 10.0,
        "fleet": {
            "replicas": 3,
            "tenants": [
                {"name": "t", "pods": 4, "groups": 2, "max_nodes": 8},
            ],
        },
    }
    with pytest.raises(SpecError, match="out of range"):
        ScenarioSpec.from_dict({
            **base,
            "events": [
                {"at_tick": 1, "kind": "fault",
                 "fault": {"kind": "replica_restart", "replica": 3}},
            ],
        })
    with pytest.raises(SpecError, match="out of range"):
        ScenarioSpec.from_dict({
            **base,
            "faults": [{"kind": "endpoint_flap", "replica": 7,
                        "probability": 0.5}],
        })
    # and a replica fault in a fleet-less scenario targets nothing at all
    with pytest.raises(SpecError, match="fleet"):
        ScenarioSpec.from_dict({
            "name": "no-fleet", "seed": 1, "ticks": 4,
            "tick_interval_s": 10.0,
            "node_groups": [{"name": "g", "cpu_m": 4000, "mem_mb": 16384,
                             "max_size": 8}],
            "faults": [{"kind": "replica_restart", "replica": 0}],
        })


def test_call_does_not_double_record_hedged_failures(monkeypatch):
    """_hedged_send does its own per-leg health accounting and the error
    it re-raises may be the HEDGE leg's — _call recording it again would
    double-charge the primary's streak (tripping the breaker early) or
    charge the primary with a status another endpoint returned."""
    import grpc

    from autoscaler_tpu.rpc.service import TpuSimulationClient

    class Err(_FakeRpcErrorHA, grpc.RpcError):
        pass

    client = TpuSimulationClient(
        ["p:1", "s:2"], default_timeout_s=1.0, hedge=True,
        sleep=lambda s: None,
    )
    monkeypatch.setattr(
        client, "_hedged_send",
        lambda *a, **k: (_ for _ in ()).throw(
            Err(grpc.StatusCode.UNAVAILABLE)
        ),
    )
    with pytest.raises(grpc.RpcError):
        client._call("Estimate", object())
    # the (stubbed) hedged path recorded nothing, so nothing may appear:
    # _call must not add its own charges on the hedged path
    for ep, h in client.endpoint_health().items():
        assert h["consecutive_unavailable"] == 0, (ep, h)
        assert h["breaker"] == "closed", (ep, h)
    client.close()


def test_send_rides_the_attempts_target_channel(monkeypatch):
    """The channel used by send() must be the ATTEMPT'S target, not the
    shared active channel: a concurrent failover rewriting self._channel
    between the pick and the send would feed the balancer an outcome from
    an endpoint this call never talked to."""
    from autoscaler_tpu.rpc.service import TpuSimulationClient

    client = TpuSimulationClient(["a:1", "b:2"], default_timeout_s=1.0)
    chan_a, chan_b = _RecordingChannel(), _RecordingChannel()
    client._channels = {"a:1": chan_a, "b:2": chan_b}
    # simulate the race: the pick already resolved to b:2, but a racing
    # thread rewrote the SHARED channel back to a:1 before the send
    monkeypatch.setattr(client, "_ensure_primary", lambda: "b:2")
    client._channel = chan_a
    resp = client._call("Estimate", object())
    assert resp == "ok"
    assert (chan_a.calls, chan_b.calls) == (0, 1)
    # and the success accrued to b:2 (the endpoint actually used), not to
    # the endpoint the stale shared channel pointed at
    health = client.endpoint_health()
    assert health["b:2"]["ewma_latency_s"] > 0.0
    assert health["a:1"]["ewma_latency_s"] == 0.0
    client.close()


class _FakeRpcErrorHA(Exception):
    """Duck-typed grpc.RpcError carrying code/details/trailing metadata."""

    def __init__(self, code, details="", trailing=()):
        self._code = code
        self._details = details
        self._trailing = tuple(trailing)

    def code(self):
        return self._code

    def details(self):
        return self._details

    def trailing_metadata(self):
        return self._trailing


def test_non_outage_response_resolves_a_half_open_probe():
    """A probe that comes back RESOURCE_EXHAUSTED (or any other
    non-outage status) proves the endpoint is ALIVE — it must resolve
    the half-open probe instead of holding the single-flight slot
    forever and wedging the endpoint out of rotation."""
    bal, sim = _seeded_balancer(
        ["a", "b"], seed=13, eject_failure_threshold=2, eject_cooldown_s=5.0,
    )
    bal.record_failure("b")
    bal.record_failure("b")
    assert bal.snapshot()["b"]["breaker"] == "open"
    sim["t"] = 6.0
    assert bal.pick() == "b"  # the half-open probe
    bal.record_response("b")
    snap = bal.snapshot()["b"]
    assert snap["breaker"] == "closed"
    assert snap["consecutive_unavailable"] == 0


def test_released_probe_slot_can_probe_again():
    """A pick whose call never reaches an outcome (hedge leg cancelled)
    must RETURN the probe slot: no outcome will ever arrive, and a held
    slot permanently ejects the endpoint."""
    bal, sim = _seeded_balancer(
        ["a", "b"], seed=17, eject_failure_threshold=2, eject_cooldown_s=5.0,
    )
    bal.record_failure("b")
    bal.record_failure("b")
    sim["t"] = 6.0
    assert bal.pick() == "b"  # probe slot now held
    for _ in range(10):
        assert bal.pick() == "a"  # single-flight: no second probe
    bal.release("b")
    assert bal.pick() == "b"  # the returned slot admits a fresh probe


def test_client_resource_exhausted_probe_does_not_wedge():
    """End-to-end through _call: a half-open probe answered with a
    terminal RESOURCE_EXHAUSTED (no retry-after) must close the breaker,
    not wedge the endpoint HALF_OPEN forever."""
    import grpc

    from autoscaler_tpu.rpc.service import TpuSimulationClient

    class Err(_FakeRpcErrorHA, grpc.RpcError):
        pass

    class ShedChannel:
        def unary_unary(self, *a, **k):
            def call(request, timeout=None, metadata=None):
                raise Err(grpc.StatusCode.RESOURCE_EXHAUSTED)

            return call

        def close(self):
            pass

    sim = {"t": 0.0}
    client = TpuSimulationClient(
        ["a:1", "b:2"], default_timeout_s=100.0,
        clock=lambda: sim["t"], sleep=lambda s: None,
    )
    client._channels = {"a:1": ShedChannel(), "b:2": ShedChannel()}
    for _ in range(3):
        client._balancer.record_failure("b:2")
    assert client.endpoint_health()["b:2"]["breaker"] == "open"
    sim["t"] = 10.0  # past the ejection cooldown: next pick probes b:2
    with pytest.raises(grpc.RpcError):
        client._call("Estimate", object())
    snap = client.endpoint_health()["b:2"]
    assert snap["breaker"] == "closed", snap
    assert snap["consecutive_unavailable"] == 0
    client.close()
