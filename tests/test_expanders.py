"""Expander strategy tests: price, priority, scenario (what-if), chain
composition (modeled on the reference's expander/*/ *_test.go suites)."""
import numpy as np
import pytest

from autoscaler_tpu.cloudprovider.test_provider import TestCloudProvider
from autoscaler_tpu.expander.core import (
    ChainStrategy,
    Option,
    RandomStrategy,
    build_strategy,
)
from autoscaler_tpu.expander.price import PriceFilter
from autoscaler_tpu.expander.priority import PriorityFilter
from autoscaler_tpu.expander.scenario import ScenarioStrategy
from autoscaler_tpu.utils.test_utils import GB, MB, build_test_node, build_test_pod


def provider_with_groups():
    p = TestCloudProvider()
    p.add_node_group(
        "cheap-pool", 0, 10, 0, build_test_node("c", cpu_m=4000, mem=8 * GB), price_per_hour=0.5
    )
    p.add_node_group(
        "pricey-pool", 0, 10, 0, build_test_node("e", cpu_m=4000, mem=8 * GB), price_per_hour=5.0
    )
    return p


def options_for(p, counts=(2, 2)):
    gs = {g.id(): g for g in p.node_groups()}
    pods = [build_test_pod(f"p{i}", cpu_m=1000, mem=1 * GB) for i in range(4)]
    return [
        Option(gs["cheap-pool"], counts[0], pods),
        Option(gs["pricey-pool"], counts[1], pods),
    ]


class TestPriceExpander:
    def test_picks_cheaper(self):
        p = provider_with_groups()
        f = PriceFilter(p.pricing())
        best = f.best_options(options_for(p))
        assert [o.node_group.id() for o in best] == ["cheap-pool"]

    def test_pod_value_matters(self):
        # a modestly pricier group that schedules far more pod-value per node
        # wins (score = node cost / pod value, price.go:90)
        p = provider_with_groups()
        gs = {g.id(): g for g in p.node_groups()}
        gs["pricey-pool"].price_per_hour = 1.0  # 2x cheap, but 4x pod coverage
        few = [build_test_pod("a", cpu_m=3000, mem=1 * GB)]
        many = [build_test_pod(f"b{i}", cpu_m=3000, mem=1 * GB) for i in range(40)]
        opts = [
            Option(gs["cheap-pool"], 1, few),
            Option(gs["pricey-pool"], 10, many),
        ]
        f = PriceFilter(p.pricing())
        best = f.best_options(opts)
        assert [o.node_group.id() for o in best] == ["pricey-pool"]


class TestPriorityExpander:
    def test_highest_tier_wins(self):
        p = provider_with_groups()
        f = PriorityFilter({10: [".*cheap.*"], 50: [".*pricey.*"]})
        best = f.best_options(options_for(p))
        assert [o.node_group.id() for o in best] == ["pricey-pool"]

    def test_unmatched_groups_lose(self):
        p = provider_with_groups()
        f = PriorityFilter({10: ["cheap-pool"]})
        best = f.best_options(options_for(p))
        assert [o.node_group.id() for o in best] == ["cheap-pool"]

    def test_hot_swap(self):
        p = provider_with_groups()
        f = PriorityFilter({10: ["cheap-pool"]})
        f.set_priorities({10: ["pricey-pool"]})
        best = f.best_options(options_for(p))
        assert [o.node_group.id() for o in best] == ["pricey-pool"]

    def test_in_chain(self):
        p = provider_with_groups()
        strat = build_strategy(["priority"], priorities={5: ["pricey-pool"]})
        assert strat.best_option(options_for(p)).node_group.id() == "pricey-pool"


class TestScenarioStrategy:
    def test_prefers_cheap_across_scenarios(self):
        p = provider_with_groups()
        opts = options_for(p)
        strat = ScenarioStrategy(
            base_prices={"cheap-pool": 0.5, "pricey-pool": 5.0},
            num_scenarios=8,
            seed=3,
        )
        best = strat.best_option(opts)
        assert best.node_group.id() == "cheap-pool"

    def test_single_option_short_circuit(self):
        p = provider_with_groups()
        opts = options_for(p)[:1]
        strat = ScenarioStrategy(base_prices={})
        assert strat.best_option(opts) is opts[0]

    def test_handles_unequal_pod_sets(self):
        p = provider_with_groups()
        gs = {g.id(): g for g in p.node_groups()}
        pods_a = [build_test_pod(f"a{i}", cpu_m=500, mem=512 * MB) for i in range(6)]
        pods_b = pods_a[:2]
        opts = [
            Option(gs["cheap-pool"], 1, pods_a),
            Option(gs["pricey-pool"], 1, pods_b),
        ]
        strat = ScenarioStrategy(
            base_prices={"cheap-pool": 1.0, "pricey-pool": 1.0}, num_scenarios=4, seed=0
        )
        # cheap-pool schedules all pods → fewer unscheduled-penalties → wins
        assert strat.best_option(opts).node_group.id() == "cheap-pool"


class TestFileWatchingPriority:
    """Hot reload without restart (reference expander/priority/priority.go:
    the ConfigMap is re-read on every BestOptions)."""

    def _write(self, path, content, mtime):
        import os

        path.write_text(content)
        os.utime(path, (mtime, mtime))  # mtime granularity-proof

    def test_reload_mid_run(self, tmp_path):
        from autoscaler_tpu.expander.priority import FileWatchingPriorityFilter

        cfg = tmp_path / "priorities.json"
        self._write(cfg, '{"10": ["cheap-pool"]}', 1000)
        p = provider_with_groups()
        f = FileWatchingPriorityFilter(str(cfg))
        assert [o.node_group.id() for o in f.best_options(options_for(p))] == [
            "cheap-pool"
        ]
        # operator flips the preference mid-run — no restart
        self._write(cfg, '{"10": ["pricey-pool"]}', 2000)
        assert [o.node_group.id() for o in f.best_options(options_for(p))] == [
            "pricey-pool"
        ]

    def test_broken_edit_keeps_last_good_config(self, tmp_path):
        from autoscaler_tpu.expander.priority import FileWatchingPriorityFilter

        cfg = tmp_path / "priorities.json"
        self._write(cfg, '{"10": ["cheap-pool"]}', 1000)
        p = provider_with_groups()
        f = FileWatchingPriorityFilter(str(cfg))
        self._write(cfg, '{"10": [unbalanced', 2000)
        assert [o.node_group.id() for o in f.best_options(options_for(p))] == [
            "cheap-pool"
        ]
        assert f.last_error is not None

    def test_missing_file_uses_fallback(self, tmp_path):
        from autoscaler_tpu.expander.priority import FileWatchingPriorityFilter

        p = provider_with_groups()
        f = FileWatchingPriorityFilter(
            str(tmp_path / "absent.json"), fallback={5: ["pricey-pool"]}
        )
        assert [o.node_group.id() for o in f.best_options(options_for(p))] == [
            "pricey-pool"
        ]

    def test_build_strategy_with_path(self, tmp_path):
        cfg = tmp_path / "priorities.json"
        cfg.write_text('{"7": ["cheap-pool"]}')
        p = provider_with_groups()
        strat = build_strategy(["priority"], priorities_path=str(cfg))
        assert strat.best_option(options_for(p)).node_group.id() == "cheap-pool"


class TestConfigMapPriority:
    """Live-ConfigMap tiers — the reference's actual mechanism
    (expander/priority/priority.go re-reads the ConfigMap per BestOptions)."""

    def _api_with(self, payload):
        from autoscaler_tpu.kube.api import FakeClusterAPI

        api = FakeClusterAPI()
        api.write_configmap(
            "kube-system", "cluster-autoscaler-priority-expander",
            {"priorities": payload},
        )
        return api

    def _filter(self, api):
        from autoscaler_tpu.expander.priority import ConfigMapPriorityFilter

        return ConfigMapPriorityFilter(
            lambda: api.read_configmap(
                "kube-system", "cluster-autoscaler-priority-expander"
            )
        )

    def test_reference_yaml_payload(self):
        """The reference's ConfigMap carries YAML (priority.go) — exactly
        that shape must parse."""
        api = self._api_with("10:\n  - cheap-.*\n50:\n  - pricey-.*\n")
        p = provider_with_groups()
        f = self._filter(api)
        assert [o.node_group.id() for o in f.best_options(options_for(p))] == [
            "pricey-pool"
        ]

    def test_update_applies_without_restart(self):
        api = self._api_with('{"10": ["cheap-pool"]}')
        p = provider_with_groups()
        f = self._filter(api)
        assert [o.node_group.id() for o in f.best_options(options_for(p))] == [
            "cheap-pool"
        ]
        api.write_configmap(
            "kube-system", "cluster-autoscaler-priority-expander",
            {"priorities": '{"10": ["pricey-pool"]}'},
        )
        assert [o.node_group.id() for o in f.best_options(options_for(p))] == [
            "pricey-pool"
        ]

    def test_broken_payload_keeps_last_good(self):
        api = self._api_with('{"10": ["cheap-pool"]}')
        p = provider_with_groups()
        f = self._filter(api)
        f.best_options(options_for(p))
        api.write_configmap(
            "kube-system", "cluster-autoscaler-priority-expander",
            {"priorities": "{10: [unbalanced"},
        )
        assert [o.node_group.id() for o in f.best_options(options_for(p))] == [
            "cheap-pool"
        ]
        assert f.last_error is not None

    def test_bad_regex_payload_keeps_last_good(self):
        """re.error/TypeError shapes must surface as ValueError inside
        parse_priorities so a broken ConfigMap edit can never crash a
        scale-up decision."""
        api = self._api_with('{"10": ["cheap-pool"]}')
        p = provider_with_groups()
        f = self._filter(api)
        f.best_options(options_for(p))
        for broken in (
            "10:\n  - '['\n",      # invalid regex → re.error path
            "10: 5\n",              # scalar tier → TypeError path
            "10: cheap-.*\n",       # scalar string tier (not a list)
            "notanint:\n  - a\n",  # non-integer key
        ):
            api.write_configmap(
                "kube-system", "cluster-autoscaler-priority-expander",
                {"priorities": broken},
            )
            assert [o.node_group.id() for o in f.best_options(options_for(p))] == [
                "cheap-pool"
            ], broken
            assert f.last_error is not None

    def test_deleted_configmap_disables_filtering(self):
        """ConfigMap deleted after a good load → options pass through
        unfiltered (priority.go returns everything on reload error) instead
        of pinning decisions to stale tiers forever; restore re-enables."""
        api = self._api_with('{"10": ["cheap-pool"]}')
        p = provider_with_groups()
        f = self._filter(api)
        assert [o.node_group.id() for o in f.best_options(options_for(p))] == [
            "cheap-pool"
        ]
        api.delete_configmap("kube-system", "cluster-autoscaler-priority-expander")
        got = {o.node_group.id() for o in f.best_options(options_for(p))}
        assert got == {o.node_group.id() for o in options_for(p)}  # unfiltered
        assert f.last_error == "configmap absent"
        # operator recreates it → tiers apply again, no restart
        api.write_configmap(
            "kube-system", "cluster-autoscaler-priority-expander",
            {"priorities": '{"10": ["pricey-pool"]}'},
        )
        assert [o.node_group.id() for o in f.best_options(options_for(p))] == [
            "pricey-pool"
        ]
        assert f.last_error is None

    def test_malformed_restoration_does_not_resurrect_stale_tiers(self):
        """ConfigMap deleted then recreated with a typo'd payload: the
        passthrough must HOLD (not resurrect pre-deletion tiers) until the
        payload actually parses."""
        api = self._api_with('{"10": ["cheap-pool"]}')
        p = provider_with_groups()
        f = self._filter(api)
        f.best_options(options_for(p))
        api.delete_configmap("kube-system", "cluster-autoscaler-priority-expander")
        f.best_options(options_for(p))
        api.write_configmap(
            "kube-system", "cluster-autoscaler-priority-expander",
            {"priorities": "{10: [unbalanced"},
        )
        got = {o.node_group.id() for o in f.best_options(options_for(p))}
        assert got == {"cheap-pool", "pricey-pool"}  # still unfiltered
        assert f.last_error is not None
        # operator fixes the payload → the NEW tiers apply
        api.write_configmap(
            "kube-system", "cluster-autoscaler-priority-expander",
            {"priorities": '{"10": ["pricey-pool"]}'},
        )
        assert [o.node_group.id() for o in f.best_options(options_for(p))] == [
            "pricey-pool"
        ]

    def test_persistently_malformed_restoration_parses_once(self, monkeypatch):
        """A recreated-but-malformed ConfigMap re-parses ONCE on the
        gone→present transition, then hits the bad-payload cache — no
        per-call re-parse/warn storm while the typo persists."""
        from autoscaler_tpu.expander import priority as priority_mod

        api = self._api_with('{"10": ["cheap-pool"]}')
        p = provider_with_groups()
        f = self._filter(api)
        f.best_options(options_for(p))
        api.delete_configmap("kube-system", "cluster-autoscaler-priority-expander")
        f.best_options(options_for(p))
        api.write_configmap(
            "kube-system", "cluster-autoscaler-priority-expander",
            {"priorities": "{10: [unbalanced"},
        )
        calls = []
        real_parse = priority_mod.parse_priorities
        monkeypatch.setattr(
            priority_mod, "parse_priorities",
            lambda text: (calls.append(text), real_parse(text))[1],
        )
        for _ in range(4):
            f.best_options(options_for(p))
        assert len(calls) == 1  # one transition parse, then cached

    def test_deleted_configmap_reverts_to_fallback(self):
        """With operator-provided fallback tiers, source-gone reverts to the
        fallback rather than disabling prioritization."""
        from autoscaler_tpu.expander.priority import ConfigMapPriorityFilter

        api = self._api_with('{"10": ["cheap-pool"]}')
        p = provider_with_groups()
        f = ConfigMapPriorityFilter(
            lambda: api.read_configmap(
                "kube-system", "cluster-autoscaler-priority-expander"
            ),
            fallback={5: ["pricey-pool"]},
        )
        assert [o.node_group.id() for o in f.best_options(options_for(p))] == [
            "cheap-pool"
        ]
        api.delete_configmap("kube-system", "cluster-autoscaler-priority-expander")
        assert [o.node_group.id() for o in f.best_options(options_for(p))] == [
            "pricey-pool"
        ]

    def test_configmap_flag_requires_kube_api(self):
        from autoscaler_tpu.main import main

        rc = main([
            "--expander", "priority",
            "--expander-priority-config-map", "cluster-autoscaler-priority-expander",
            "--max-iterations", "1",
        ])
        assert rc == 2

    def test_absent_configmap_uses_fallback(self):
        from autoscaler_tpu.expander.priority import ConfigMapPriorityFilter
        from autoscaler_tpu.kube.api import FakeClusterAPI

        api = FakeClusterAPI()
        p = provider_with_groups()
        f = ConfigMapPriorityFilter(
            lambda: api.read_configmap("kube-system", "nope"),
            fallback={5: ["pricey-pool"]},
        )
        assert [o.node_group.id() for o in f.best_options(options_for(p))] == [
            "pricey-pool"
        ]
        assert f.last_error == "configmap absent"

    def test_wired_through_autoscaler(self):
        """options.priority_config_map → orchestrator → decision flips when
        the operator edits the ConfigMap mid-run, no restart."""
        from autoscaler_tpu.cloudprovider.test_provider import TestCloudProvider
        from autoscaler_tpu.config.options import AutoscalingOptions
        from autoscaler_tpu.core.static_autoscaler import StaticAutoscaler
        from autoscaler_tpu.kube.api import FakeClusterAPI
        from autoscaler_tpu.utils.test_utils import GB, build_test_node, build_test_pod

        provider = TestCloudProvider()
        api = FakeClusterAPI()
        for gid in ("alpha", "beta"):
            provider.add_node_group(
                gid, 0, 10, 0, build_test_node(f"{gid}-tmpl", cpu_m=4000, mem=8 * GB)
            )
        api.write_configmap(
            "kube-system", "cluster-autoscaler-priority-expander",
            {"priorities": "10:\n  - alpha\n"},
        )
        opts = AutoscalingOptions(
            expander="priority",
            priority_config_map="cluster-autoscaler-priority-expander",
        )
        a = StaticAutoscaler(provider, api, opts)
        api.add_pod(build_test_pod("p0", cpu_m=3000, mem=GB))
        a.run_once(now_ts=0.0)
        assert provider._groups["alpha"].target_size() == 1
        # operator flips the tier — next loop scales the other group
        api.write_configmap(
            "kube-system", "cluster-autoscaler-priority-expander",
            {"priorities": "10:\n  - beta\n"},
        )
        api.add_pod(build_test_pod("p1", cpu_m=3000, mem=GB))
        a.run_once(now_ts=700.0)
        assert provider._groups["beta"].target_size() >= 1


class TestScenarioPallasRoute:
    def test_tpu_routes_whatif_through_pallas(self, monkeypatch):
        """On a TPU backend the what-if dispatch uses the Pallas kernel
        (scenario_loop under shard_map — the dryrun-certified config);
        the winner must match the XLA route."""
        import autoscaler_tpu.ops.pallas_binpack as pb

        p = provider_with_groups()
        opts = options_for(p)
        strat = ScenarioStrategy(
            base_prices={"cheap-pool": 0.5, "pricey-pool": 5.0},
            num_scenarios=8,
            seed=3,
        )
        want = strat.best_option(opts).node_group.id()

        calls = []
        real = pb.ffd_binpack_groups_pallas

        def spy(*args, **kw):
            calls.append(1)
            # pin interpret: under the spoofed backend the kernel's default
            # would pick Mosaic on CPU
            kw["interpret"] = True
            return real(*args, **kw)

        monkeypatch.setattr(pb, "ffd_binpack_groups_pallas", spy)
        import jax as _jax

        monkeypatch.setattr(_jax, "default_backend", lambda: "tpu",
                            raising=True)
        import logging

        records = []

        class _Grab(logging.Handler):
            def emit(self, record):
                records.append(record)

        h = _Grab()
        logging.getLogger("expander").addHandler(h)
        try:
            got = strat.best_option(opts).node_group.id()
        finally:
            logging.getLogger("expander").removeHandler(h)
        assert calls, "pallas what-if route was not taken"
        # a silent fallback would make this test pass with a broken kernel
        assert not records, f"pallas route fell back: {records[0].getMessage()}"
        assert got == want
