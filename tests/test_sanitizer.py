"""Runtime determinism sanitizer (analysis/sanitizer.py): patch-based
trapping of ambient clock/rng/env reads, direct-caller frame attribution,
pragma declassification, clean teardown, the loadgen --sanitize wiring,
and the static ⊇ runtime cross-check — the sanitizer's findings on real
executions must be a subset of GL010's static source inventory
(static analysis is never less complete than what actually fired).
"""
from __future__ import annotations

import os
import textwrap
import time
from pathlib import Path

import pytest

from autoscaler_tpu.analysis.dataflow import source_sites
from autoscaler_tpu.analysis.engine import FileModel
from autoscaler_tpu.analysis.sanitizer import DeterminismSanitizer

REPO = Path(__file__).resolve().parent.parent

# a virtual replay-scoped module: the compile() filename is what frame
# attribution sees, no file need exist on disk
_FIXTURE_SRC = textwrap.dedent("""
    import time
    import random
    import os


    def wall():
        return time.time()


    def rng():
        return random.random()


    def env():
        return os.getenv("AUTOSCALER_FIXTURE_PROBE")


    def clean(clock):
        return clock()
""")


def _load_fixture(virtual_path: str, src: str = _FIXTURE_SRC) -> dict:
    ns: dict = {}
    exec(compile(src, virtual_path, "exec"), ns)
    return ns


def test_trap_and_direct_caller_attribution():
    ns = _load_fixture("/x/autoscaler_tpu/loadgen/sanfix.py")
    with DeterminismSanitizer() as san:
        ns["wall"]()
        ns["rng"]()
        ns["env"]()
    kinds = {(e.kind, e.func) for e in san.events}
    assert ("wall-clock", "time.time") in kinds
    assert ("ambient-rng", "random.random") in kinds
    assert ("environment-read", "os.getenv") in kinds
    for e in san.events:
        assert e.path == "autoscaler_tpu/loadgen/sanfix.py"
        assert e.line > 0
    # the wall-clock event points at the exact `return time.time()` line
    wall = [e for e in san.events if e.func == "time.time"][0]
    assert _FIXTURE_SRC.splitlines()[wall.line - 1].strip() == "return time.time()"


def test_non_replay_frames_are_ignored():
    # same calls from a frame outside any replay scope: legal, no events
    ns = _load_fixture("/x/somewhere/tool.py")
    with DeterminismSanitizer() as san:
        ns["wall"]()
        ns["rng"]()
        time.sleep(0)  # test frame: not replay-scoped either
    assert san.events == []


def test_library_internals_below_replay_frames_are_ignored():
    """Direct-caller attribution: a non-replay helper reading the clock
    while CALLED FROM replay code is the library's implementation detail,
    not a replay artifact input — no event."""
    helper = _load_fixture("/x/lib/third_party_helper.py")
    caller_src = textwrap.dedent("""
        def tick(helper_fn):
            return helper_fn()
    """)
    caller = _load_fixture("/x/autoscaler_tpu/core/sanfix2.py", caller_src)
    with DeterminismSanitizer() as san:
        caller["tick"](helper["wall"])
    assert san.events == []


def test_pragma_on_trapped_line_declassifies(tmp_path):
    """The runtime monitor honors the same inline seams the static rules
    honor — trace.timeline_now()'s own GL001-pragma'd fallback must not
    fire the sanitizer either."""
    pkg = tmp_path / "autoscaler_tpu" / "trace"
    pkg.mkdir(parents=True)
    f = pkg / "sanfix3.py"
    f.write_text(textwrap.dedent("""
        import time


        def fallback():
            return time.monotonic()  # graftlint: disable=GL001 — fixture: the seam's own fallback


        def bare():
            return time.monotonic()
    """))
    ns: dict = {}
    exec(compile(f.read_text(), str(f), "exec"), ns)
    with DeterminismSanitizer() as san:
        ns["fallback"]()
        ns["bare"]()
    assert [e.func for e in san.events] == ["time.monotonic"]
    trapped = f.read_text().splitlines()[san.events[0].line - 1]
    assert "time.monotonic()" in trapped and "graftlint" not in trapped


def test_environment_write_trapped_via_audit_hook():
    src = textwrap.dedent("""
        import os


        def poke():
            os.putenv("AUTOSCALER_SANITIZER_PROBE", "1")
    """)
    ns = _load_fixture("/x/autoscaler_tpu/loadgen/sanfix4.py", src)
    with DeterminismSanitizer() as san:
        ns["poke"]()
    kinds = {e.kind for e in san.events}
    assert "environment-write" in kinds


def test_uninstall_restores_originals_and_lifo_nesting():
    """Installations nest LIFO (the AUTOSCALER_TPU_SANITIZE session
    sanitizer + a per-test one): only the INNERMOST records, uninstall
    must be LIFO, and originals are restored exactly."""
    orig_time, orig_random = time.time, __import__("random").random
    ns = _load_fixture("/x/autoscaler_tpu/loadgen/sanfix7.py")
    outer = DeterminismSanitizer().install()
    try:
        assert time.time is not orig_time
        inner = DeterminismSanitizer().install()
        try:
            ns["wall"]()
            # out-of-order uninstall is refused (would resurrect a dead
            # wrapper chain)
            with pytest.raises(RuntimeError):
                outer.uninstall()
        finally:
            inner.uninstall()
        ns["wall"]()
        assert len(inner.events) == 1   # the nested window's event
        assert len(outer.events) == 1   # only the post-nesting event
    finally:
        outer.uninstall()
    assert time.time is orig_time
    assert __import__("random").random is orig_random
    assert not outer._installed


def test_timeline_now_inside_active_trace_is_silent():
    """Inside a loadgen-style trace the timeline seam returns the injected
    clock — no ambient read fires at all."""
    from autoscaler_tpu.trace.tracer import Tracer, span
    from autoscaler_tpu import trace as trace_mod

    ticks = iter(float(i) for i in range(100))
    tracer = Tracer(clock=lambda: next(ticks))
    with DeterminismSanitizer() as san:
        with tracer.tick("main"):
            with span("estimate"):
                trace_mod.timeline_now()
    assert san.events == []


def test_static_source_inventory_is_superset_of_runtime():
    """The acceptance cross-check: every event the sanitizer traps on a
    real execution maps to a site in GL010's static source inventory —
    the static analysis is never LESS complete than the runtime monitor."""
    vpath = "autoscaler_tpu/loadgen/sanfix5.py"
    ns = _load_fixture("/x/" + vpath)
    with DeterminismSanitizer() as san:
        ns["wall"]()
        ns["rng"]()
        ns["env"]()
        ns["clean"](lambda: 0.0)  # injected seam: must fire nothing
    assert san.events, "fixture produced no runtime events"
    static = source_sites([FileModel(vpath, _FIXTURE_SRC)])
    static_sites = {(s.path, s.line) for s in static}
    for e in san.sorted_events():
        assert (e.path, e.line) in static_sites, (
            f"runtime event {e.render()} has no static GL010 source site — "
            f"static inventory: {sorted(static_sites)}"
        )


@pytest.mark.slow
def test_full_canned_replay_clean_and_subset_of_static():
    """End-to-end: the kernel_fault_ladder scenario replays CLEAN under
    the sanitizer (zero trapped reads — the hack/verify.sh gate), and the
    (empty) runtime finding set is trivially a subset of the repo-wide
    static inventory, which must itself be non-empty only at pragma'd
    seams (all declassified)."""
    from autoscaler_tpu.loadgen.driver import run_scenario
    from autoscaler_tpu.loadgen.score import build_report
    from autoscaler_tpu.loadgen.spec import ScenarioSpec

    spec = ScenarioSpec.load(
        str(REPO / "benchmarks" / "scenarios" / "kernel_fault_ladder.json")
    )
    with DeterminismSanitizer() as san:
        result = run_scenario(spec)
        report = build_report(result)
    assert report["replays"]["certified"] if "replays" in report else True
    assert san.events == [], san.report()

    models = []
    pkg = REPO / "autoscaler_tpu"
    for f in sorted(pkg.rglob("*.py")):
        if "__pycache__" in f.parts:
            continue
        models.append(FileModel(str(f), f.read_text(encoding="utf-8")))
    static_sites = {(s.path, s.line) for s in source_sites(models)}
    for e in san.events:
        assert (e.path, e.line) in static_sites


@pytest.mark.slow
def test_loadgen_cli_sanitize_flag_clean_scenario():
    """--sanitize wiring: a deterministic scenario exits 0 under the
    sanitizer (the verify.sh step in miniature, on the smallest spec —
    slow-marked: verify.sh drives the CLI path on kernel_fault_ladder,
    and test_loadgen_cli_sanitize_fails_on_events covers the wiring)."""
    import json as json_mod

    from autoscaler_tpu.loadgen.cli import main as loadgen_main

    scenarios = sorted(
        (REPO / "benchmarks" / "scenarios").glob("*.json"),
        key=lambda p: p.stat().st_size,
    )
    spec_path = str(scenarios[0])
    # skip fleet specs: the smallest non-fleet spec drives run_scenario
    for p in scenarios:
        doc = json_mod.loads(p.read_text())
        if "fleet" not in doc or not doc["fleet"]:
            spec_path = str(p)
            break
    rc = loadgen_main(["run", spec_path, "--sanitize"])
    assert rc == 0


def test_loadgen_cli_sanitize_fails_on_events(capsys):
    """The --sanitize failure contract: any trapped event turns a clean
    exit into 1 with the attributed report on stderr."""
    from autoscaler_tpu.loadgen.cli import _sanitized

    ns = _load_fixture("/x/autoscaler_tpu/loadgen/sanfix6.py")

    def run_fn():
        ns["wall"]()
        return 0

    rc = _sanitized(run_fn)
    assert rc == 1
    err = capsys.readouterr().err
    assert "autoscaler_tpu/loadgen/sanfix6.py" in err
    assert "wall-clock" in err


def test_pragma_trailing_code_does_not_leak_downward(tmp_path):
    """engine._suppressed parity: only a COMMENT-ONLY pragma line above
    declassifies the next line — a pragma trailing unrelated code must
    not disable runtime detection below it."""
    pkg = tmp_path / "autoscaler_tpu" / "loadgen"
    pkg.mkdir(parents=True)
    f = pkg / "prag2.py"
    f.write_text(textwrap.dedent("""
        import time


        def bad():
            x = 1  # graftlint: disable=GL001 — fixture: trailing-code pragma
            return time.time()


        def ok():
            # graftlint: disable=GL001 — fixture: comment-only pragma above
            return time.time()
    """))
    ns: dict = {}
    exec(compile(f.read_text(), str(f), "exec"), ns)
    with DeterminismSanitizer() as san:
        ns["bad"]()
        ns["ok"]()
    assert len(san.events) == 1, san.report()
    trapped = f.read_text().splitlines()[san.events[0].line - 1]
    assert "return time.time()" in trapped
