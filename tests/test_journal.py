"""Flight-journal tests (autoscaler_tpu/journal): the byte-exact delta
codec, keyframe promotion policy, time-travel reconstruction parity
against a keyframe-only ground truth, double-replay byte identity, the
typed corruption matrix (truncation, missing keyframe, tick disorder,
schema drift — always a typed error, never a wrong reconstruction),
live-vs-replay divergence probes, /journalz (gating, drill-down, diff,
ring-eviction race), the CLI, and the bench gates (--journal-ledger,
--trend)."""
import json
import subprocess
import sys
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from autoscaler_tpu.cloudprovider.test_provider import TestCloudProvider
from autoscaler_tpu.config.options import AutoscalingOptions
from autoscaler_tpu.core.static_autoscaler import StaticAutoscaler
from autoscaler_tpu.journal import (
    KEYFRAME_REASONS,
    JournalReader,
    MissingKeyframeError,
    OutOfOrderTickError,
    SCHEMA,
    SchemaDriftError,
    TruncatedJournalError,
    record_line,
    summarize,
    validate_records,
)
from autoscaler_tpu.journal.codec import (
    apply_ops,
    changed_rows,
    decode_array,
    delta_ops,
    encode_array,
)
from autoscaler_tpu.journal.replay import replay_journal
from autoscaler_tpu.kube.api import FakeClusterAPI
from autoscaler_tpu.main import ObservabilityServer
from autoscaler_tpu.utils.test_utils import GB, build_test_node, build_test_pod

STORM = "benchmarks/scenarios/preemption_storm.json"


# ---------------------------------------------------------------- helpers
def make_autoscaler(pods=(), **opt_kw):
    provider = TestCloudProvider()
    api = FakeClusterAPI()
    provider.add_node_group(
        "g", 0, 10, 1, build_test_node("t", cpu_m=1000, mem=2 * GB)
    )
    node = build_test_node("g-0", cpu_m=1000, mem=2 * GB)
    provider.add_node("g", node)
    api.add_node(node)
    for p in pods:
        api.add_pod(p)
    return StaticAutoscaler(provider, api, AutoscalingOptions(**opt_kw))


@pytest.fixture(scope="module")
def storm_replays():
    """The acceptance workload: the preemption storm journaled twice with
    the default keyframe policy, plus a keyframe-every-tick ground-truth
    run for reconstruction parity."""
    from autoscaler_tpu.loadgen.driver import run_scenario
    from autoscaler_tpu.loadgen.spec import ScenarioSpec

    r1 = run_scenario(ScenarioSpec.load(STORM))
    r2 = run_scenario(ScenarioSpec.load(STORM))
    truth_spec = ScenarioSpec.load(STORM)
    truth_spec.options["journal_keyframe_interval"] = 1
    rt = run_scenario(truth_spec)
    return r1, r2, rt


# ------------------------------------------------------------------ codec
class TestDeltaCodec:
    def test_row_comparison_is_byte_exact(self):
        """-0.0 == 0.0 and NaN != NaN under value comparison — the codec
        must diff raw bytes or reconstruction is not bit-exact."""
        a = np.array([[0.0, 1.0], [np.nan, 2.0]], dtype=np.float32)
        b = np.array([[-0.0, 1.0], [np.nan, 2.0]], dtype=np.float32)
        assert changed_rows(a, b).tolist() == [0]  # -0.0 differs in bits
        # same NaN bits: unchanged
        assert changed_rows(a, a.copy()).tolist() == []

    def test_encode_decode_roundtrip_preserves_bits(self):
        for arr in (
            np.array([np.nan, -0.0, np.inf], dtype=np.float64),
            np.arange(6, dtype=np.int32).reshape(2, 3),
            np.array(7, dtype=np.int64),  # 0-d scalar field
            np.zeros((0, 4), dtype=np.float32),  # empty axis
        ):
            out = decode_array(encode_array(arr))
            assert out.dtype == arr.dtype and out.shape == arr.shape
            assert out.tobytes() == arr.tobytes()

    def test_delta_ops_scatter_roundtrip(self):
        base = {"x": np.arange(12, dtype=np.float32).reshape(4, 3),
                "n": np.array(3, dtype=np.int32)}
        new = {"x": base["x"].copy(), "n": np.array(4, dtype=np.int32)}
        new["x"][2] = [9.0, 9.0, 9.0]
        ops = delta_ops(base, new)
        fields = {k: v.copy() for k, v in base.items()}
        apply_ops(fields, ops)
        for k in new:
            assert fields[k].tobytes() == new[k].tobytes()
        # only the touched row and the scalar travel, not the full tensors
        assert {op["field"] for op in ops} == {"x", "n"}

    def test_apply_ops_rejects_drift(self):
        base = {"x": np.zeros((2, 2), dtype=np.float32)}
        with pytest.raises((KeyError, ValueError)):
            apply_ops(base, [{"field": "ghost", "axis": 0, "idx": 0,
                              "payload": encode_array(np.zeros(2))}])

    def test_changed_rows_rejects_shape_drift(self):
        with pytest.raises(ValueError):
            changed_rows(np.zeros((2, 2)), np.zeros((3, 2)))


# -------------------------------------------------- keyframe policy
class TestKeyframePolicy:
    def test_reason_vocabulary_closed(self):
        assert "init" in KEYFRAME_REASONS
        assert "interval" in KEYFRAME_REASONS
        assert "reseed:capacity_growth" in KEYFRAME_REASONS

    def test_storm_promotes_beyond_init(self, storm_replays):
        """The storm grows new pools (schema-change reseeds) and runs past
        the default interval — both promotion paths must fire."""
        r1, _, _ = storm_replays
        records = r1.journal_records
        assert records[0]["kind"] == "keyframe"
        assert records[0]["reason"] == "init"
        reasons = [r["reason"] for r in records if r["kind"] == "keyframe"]
        assert set(reasons) <= KEYFRAME_REASONS
        assert len(reasons) > 1, "no promotion beyond the init frame"
        assert any(r != "init" for r in reasons)
        assert any(r["kind"] == "delta" for r in records)

    def test_keyframe_every_tick_override(self, storm_replays):
        _, _, rt = storm_replays
        assert all(r["kind"] == "keyframe" for r in rt.journal_records)


# --------------------------------------------- reconstruction parity
class TestReconstructionParity:
    def test_two_replays_write_byte_identical_journals(self, storm_replays):
        r1, r2, _ = storm_replays
        l1, l2 = r1.journal_ledger_lines(), r2.journal_ledger_lines()
        assert l1 and l1 == l2
        records = [json.loads(line) for line in l1.splitlines()]
        assert validate_records(records) == []

    def test_every_tick_reconstructs_bit_exact(self, storm_replays):
        """Keyframe+delta chains must reproduce the keyframe-only ground
        truth bit-for-bit at EVERY tick — fields, name tables, ext."""
        r1, _, rt = storm_replays
        reader = JournalReader(r1.journal_records)
        truth = {r["tick"]: r for r in rt.journal_records}
        assert reader.ticks() == sorted(truth)
        for tick in reader.ticks():
            state = reader.reconstruct(tick)
            want = truth[tick]["state"]
            want_fields = {
                k: decode_array(doc) for k, doc in want["fields"].items()
            }
            assert set(state.fields) == set(want_fields), tick
            for k, arr in want_fields.items():
                got = state.fields[k]
                assert got.dtype == arr.dtype and got.shape == arr.shape
                assert got.tobytes() == arr.tobytes(), (tick, k)
            assert state.names == want["names"], tick
            assert list(state.ext) == list(want["ext"]), tick

    def test_reconstructed_tensors_and_evictable(self, storm_replays):
        r1, _, _ = storm_replays
        reader = JournalReader(r1.journal_records)
        state = reader.reconstruct(reader.ticks()[-1])
        t = state.tensors()
        # tensors are capacity-padded; name tables cover the live rows
        assert t.num_pods == state.fields["pod_req"].shape[0]
        assert 0 < len(state.names["pods"]) <= t.num_pods
        assert 0 < len(state.names["nodes"]) <= t.num_nodes
        ev = state.evictable()
        assert ev.shape == (t.num_pods,)
        # pod_evictable is journaled state, not a SnapshotTensors field
        assert "pod_evictable" in state.fields
        assert not hasattr(t, "pod_evictable")

    def test_summarize_counts(self, storm_replays):
        r1, _, _ = storm_replays
        agg = summarize(r1.journal_records)
        assert agg["ticks"] == r1.spec.ticks
        assert agg["keyframes"] + agg["deltas"] == agg["ticks"]
        assert agg["keyframe_reasons"]["init"] == 1


# ------------------------------------------------- corruption matrix
class TestCorruptionMatrix:
    """A damaged journal must raise its typed error — never return a
    wrong reconstruction."""

    def test_truncated_file(self, storm_replays, tmp_path):
        r1, _, _ = storm_replays
        text = r1.journal_ledger_lines()
        cut = tmp_path / "cut.jsonl"
        cut.write_text(text[: len(text) // 2])  # mid-line cut
        with pytest.raises(TruncatedJournalError):
            JournalReader.from_path(str(cut))

    def test_missing_keyframe(self, storm_replays):
        r1, _, _ = storm_replays
        deltas = [r for r in r1.journal_records if r["kind"] == "delta"]
        reader = JournalReader(deltas)
        with pytest.raises(MissingKeyframeError):
            reader.reconstruct(deltas[0]["tick"])
        # a never-journaled tick is the same typed refusal
        with pytest.raises(MissingKeyframeError):
            JournalReader(r1.journal_records).reconstruct(99999)

    def test_out_of_order_ticks(self, storm_replays):
        r1, _, _ = storm_replays
        records = [dict(r) for r in r1.journal_records]
        records[1], records[2] = records[2], records[1]
        with pytest.raises(OutOfOrderTickError):
            JournalReader(records)
        assert any(
            "not increasing" in e or "monotonic" in e or "order" in e
            for e in validate_records(records)
        ) or validate_records(records)

    def test_schema_drift(self, storm_replays):
        r1, _, _ = storm_replays
        records = [json.loads(record_line(r)) for r in r1.journal_records]
        bad = [dict(records[0], schema="autoscaler_tpu.journal.tick/999")]
        with pytest.raises(SchemaDriftError):
            JournalReader(bad + records[1:])
        # an undecodable delta payload must refuse, not scatter garbage
        corrupt = [json.loads(record_line(r)) for r in records]
        victim = next(r for r in corrupt if r["kind"] == "delta"
                      and r["state"]["ops"])
        victim["state"]["ops"][0]["field"] = "no_such_field"
        reader = JournalReader(corrupt)
        with pytest.raises(SchemaDriftError):
            reader.reconstruct(victim["tick"])
        # ticks before the corruption still reconstruct
        first = corrupt[0]["tick"]
        assert reader.reconstruct(first).tick == first

    def test_validate_records_flags_corruption(self, storm_replays):
        r1, _, _ = storm_replays
        records = [dict(r) for r in r1.journal_records]
        assert validate_records(records) == []
        records[0] = dict(records[0], schema="nope")
        assert validate_records(records)

    def test_validator_covers_ctx_and_keyframe_options(self, storm_replays):
        """Regression (graftlint GL017): ctx and the keyframe options
        document are declared in SCHEMA_FIELDS but the validator never
        read them — a journal missing its reconstruction anchor passed
        validation silently."""
        r1, _, _ = storm_replays
        records = [dict(r) for r in r1.journal_records]
        records[0] = dict(records[0], ctx=[])
        assert any("ctx" in e for e in validate_records(records))
        records = [dict(r) for r in r1.journal_records]
        kf = next(i for i, r in enumerate(records) if r["kind"] == "keyframe")
        records[kf] = {
            k: v for k, v in records[kf].items() if k != "options"
        }
        assert any("options" in e for e in validate_records(records))


# ------------------------------------------------ replay + divergence
class TestReplayDivergence:
    def _ledger(self, result):
        lines = result.explain_ledger_lines().splitlines(keepends=True)
        return [json.loads(l) for l in lines], lines

    def test_replay_reproduces_every_tick(self, storm_replays):
        r1, _, _ = storm_replays
        records, lines = self._ledger(r1)
        results = replay_journal(
            JournalReader(r1.journal_records), records, lines
        )
        assert len(results) == r1.spec.ticks
        assert all(not r["divergence"] for r in results), [
            r for r in results if r["divergence"]
        ][:2]
        assert sum(1 for r in results if r["replayed"]) > 0

    def test_tampered_ledger_diverges(self, storm_replays):
        """Dropping one recorded eviction row must surface BOTH probes:
        the line-hash pin and the re-derived decision comparison."""
        r1, _, _ = storm_replays
        records, lines = self._ledger(r1)
        idx = next(
            i for i, r in enumerate(records)
            if (r.get("preemption") or {}).get("evictions")
        )
        records[idx]["preemption"]["evictions"] = []
        from autoscaler_tpu.explain import record_line as explain_line

        lines[idx] = explain_line(records[idx])
        results = replay_journal(
            JournalReader(r1.journal_records), records, lines
        )
        bad = next(r for r in results if r["tick"] == records[idx]["tick"])
        assert bad["divergence"]
        joined = " ".join(bad["divergence"])
        assert "hash" in joined
        assert "diverged" in joined

    def test_probe_reports_no_drift_live(self):
        pods = [build_test_pod("p", cpu_m=600, mem=GB)]
        a = make_autoscaler(pods=pods)
        a.run_once(now_ts=0.0)
        a.run_once(now_ts=10.0)
        verdict = a.journal.probe()
        assert verdict["checked"] and not verdict["drift"]
        assert verdict["fit_drift"] is False

    def test_in_loop_probe_interval_counts_clean(self):
        pods = [build_test_pod("p", cpu_m=600, mem=GB)]
        a = make_autoscaler(pods=pods, journal_probe_interval=1)
        for i in range(3):
            a.run_once(now_ts=float(i) * 10.0)
        assert a.metrics.journal_records_total.get() == 3
        assert a.metrics.journal_probe_drift_total.get() == 0


# ----------------------------------------------------------- /journalz
class TestJournalzEndpoint:
    def _get(self, port, path):
        with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}") as r:
            return r.status, r.read().decode()

    def test_list_detail_diff(self):
        pods = [build_test_pod("p", cpu_m=600, mem=GB)]
        a = make_autoscaler(pods=pods)
        a.run_once(now_ts=0.0)
        a.run_once(now_ts=10.0)
        server = ObservabilityServer(a, "127.0.0.1:0")
        port = server.start()
        try:
            code, body = self._get(port, "/journalz")
            listing = json.loads(body)
            assert code == 200 and listing["schema"] == SCHEMA
            ticks = [t["tick"] for t in listing["ticks"]]
            assert len(ticks) == 2
            code, body = self._get(port, f"/journalz?tick={ticks[-1]}")
            doc = json.loads(body)
            assert code == 200 and doc["tick"] == ticks[-1]
            code, body = self._get(
                port, f"/journalz?diff={ticks[0]},{ticks[-1]}"
            )
            assert code == 200 and "pods_added" in json.loads(body)
            with pytest.raises(urllib.error.HTTPError) as ei:
                self._get(port, "/journalz?tick=99999")
            assert ei.value.code == 404
            with pytest.raises(urllib.error.HTTPError) as ei:
                self._get(port, "/journalz?tick=bogus")
            assert ei.value.code == 400
            with pytest.raises(urllib.error.HTTPError) as ei:
                self._get(port, "/journalz?diff=bogus")
            assert ei.value.code == 400
        finally:
            server.stop()

    def test_gated_like_explainz(self):
        a = make_autoscaler(journal_enabled=False)
        a.run_once(now_ts=0.0)
        server = ObservabilityServer(a, "127.0.0.1:0")
        port = server.start()
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                self._get(port, "/journalz")
            assert ei.value.code == 404
        finally:
            server.stop()

    def test_concurrent_ring_eviction_race(self):
        """Satellite: /journalz racing a writer that overflows the 2-deep
        ring — every response must be well-formed JSON, never a torn
        record or a half-applied delta chain."""
        pods = [build_test_pod("p", cpu_m=600, mem=GB)]
        a = make_autoscaler(pods=pods, journal_ring_size=2)
        a.run_once(now_ts=0.0)  # warm compile so writer iterations are fast
        server = ObservabilityServer(a, "127.0.0.1:0")
        port = server.start()
        stop = threading.Event()
        errors = []

        def writer():
            t = 10.0
            while not stop.is_set():
                a.run_once(now_ts=t)
                t += 10.0

        def reader():
            while not stop.is_set():
                try:
                    code, body = self._get(port, "/journalz")
                    listing = json.loads(body)
                    ticks = [t["tick"] for t in listing["ticks"]]
                    for t in ticks:
                        self._get(port, f"/journalz?tick={t}")
                    if len(ticks) == 2:
                        self._get(
                            port, f"/journalz?diff={ticks[0]},{ticks[1]}"
                        )
                except urllib.error.HTTPError as e:
                    # a tick evicted between list and drill-down is a 404,
                    # not an error; a diff across an evicted keyframe is a
                    # clean 404 too — torn state would be a 500
                    if e.code not in (404,):
                        errors.append(e)
                except Exception as e:  # noqa: BLE001 — collected for assert
                    errors.append(e)

        threads = [threading.Thread(target=writer)] + [
            threading.Thread(target=reader) for _ in range(2)
        ]
        try:
            for t in threads:
                t.start()
            import time

            time.sleep(1.5)
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=30)
            server.stop()
        assert not errors, errors[:3]


# ------------------------------------------------------- CLI + gates
class TestJournalCli:
    @pytest.fixture()
    def journaled_run(self, storm_replays, tmp_path):
        r1, _, _ = storm_replays
        journal = tmp_path / "journal.jsonl"
        ledger = tmp_path / "explain.jsonl"
        journal.write_text(r1.journal_ledger_lines())
        ledger.write_text(r1.explain_ledger_lines())
        return journal, ledger

    def _run(self, *argv):
        return subprocess.run(
            [sys.executable, "-m", "autoscaler_tpu.journal", *argv],
            capture_output=True, text=True,
        )

    def test_reconstruct_and_diff(self, journaled_run):
        journal, _ = journaled_run
        proc = self._run("reconstruct", str(journal))
        assert proc.returncode == 0, proc.stderr
        assert "pod_req" in proc.stdout
        ticks = JournalReader.from_path(str(journal)).ticks()
        proc = self._run("diff", str(journal), str(ticks[0]),
                         str(ticks[-1]))
        assert proc.returncode == 0, proc.stderr
        doc = json.loads(proc.stdout)
        assert doc["ticks"] == [ticks[0], ticks[-1]]
        assert "capacity_drift" in doc

    def test_replay_clean_and_diverged(self, journaled_run, tmp_path):
        journal, ledger = journaled_run
        proc = self._run("replay", str(journal),
                         "--explain-ledger", str(ledger))
        assert proc.returncode == 0, proc.stderr
        verdict = json.loads(proc.stdout.splitlines()[-1])
        assert verdict["diverged"] == 0
        assert verdict["replayed"] > 0
        # flip one byte of one ledger line: exit 1 + DIVERGED on stderr
        lines = ledger.read_text().splitlines(keepends=True)
        lines[-1] = lines[-1].replace('"tick"', '"tick_"', 1)
        bad = tmp_path / "tampered.jsonl"
        bad.write_text("".join(lines))
        proc = self._run("replay", str(journal),
                         "--explain-ledger", str(bad))
        assert proc.returncode == 1
        assert "DIVERGED" in proc.stderr

    def test_loadgen_journal_flag(self, tmp_path):
        from autoscaler_tpu.loadgen.cli import main as loadgen_main

        out = tmp_path / "journal.jsonl"
        rc = loadgen_main([
            "run", "benchmarks/scenarios/burst_small.json",
            "--journal", str(out),
        ])
        assert rc == 0
        records = [json.loads(l) for l in out.read_text().splitlines()]
        assert records and validate_records(records) == []

    def test_bench_journal_ledger_gate(self, journaled_run, tmp_path):
        journal, _ = journaled_run
        proc = subprocess.run(
            [sys.executable, "bench.py", "--journal-ledger", str(journal)],
            capture_output=True, text=True,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        report = json.loads(proc.stdout)
        assert report["valid"]
        assert report["reconstructed"] == report["ticks"]
        # keyframe-less journal → validation errors, exit 1
        records = [json.loads(l) for l in
                   journal.read_text().splitlines()]
        bad = tmp_path / "headless.jsonl"
        bad.write_text("".join(record_line(r) for r in records
                               if r["kind"] == "delta"))
        proc = subprocess.run(
            [sys.executable, "bench.py", "--journal-ledger", str(bad)],
            capture_output=True, text=True,
        )
        assert proc.returncode == 1
        # unreadable journal → exit 2
        proc = subprocess.run(
            [sys.executable, "bench.py", "--journal-ledger",
             str(tmp_path / "missing.jsonl")],
            capture_output=True, text=True,
        )
        assert proc.returncode == 2


class TestBenchTrendGate:
    """--trend satellite: the committed BENCH_r*.json trajectory is the
    floor; newest round wins per config; no live capture = no gate."""

    @pytest.fixture()
    def trend_repo(self, tmp_path, monkeypatch):
        bench = pytest.importorskip("bench")
        monkeypatch.setattr(bench, "__file__", str(tmp_path / "bench.py"))
        (tmp_path / "BENCH_r01.json").write_text(json.dumps({
            "n": 1, "cmd": "", "rc": 0, "tail": "",
            "parsed": {"metric": "m", "platform": "tpu", "value": 50.0},
        }))
        # newest round carries the TPU number nested in a CPU fallback —
        # it must supersede round 1's direct capture for ("m", "tpu")
        (tmp_path / "BENCH_r02.json").write_text(json.dumps({
            "n": 2, "cmd": "", "rc": 0, "tail": "",
            "parsed": {
                "metric": "other", "platform": "cpu", "value": 1.0,
                "last_tpu_capture": {
                    "metric": "m", "platform": "tpu", "value": 100.0,
                },
            },
        }))
        out = tmp_path / "benchmarks" / "out"
        out.mkdir(parents=True)
        return bench, out / "bench_last_tpu.json"

    def _capture(self, path, value, metric="m"):
        path.write_text(json.dumps(
            {"metric": metric, "platform": "tpu", "value": value}
        ))

    def test_on_trend_passes(self, trend_repo, capsys):
        bench, cap = trend_repo
        self._capture(cap, 95.0)  # >= 90% of the newest round's 100
        assert bench._trend_main() == 0
        report = json.loads(capsys.readouterr().out)
        assert report["committed_round"] == 2
        assert report["committed_value"] == 100.0

    def test_regression_fails(self, trend_repo, capsys):
        bench, cap = trend_repo
        self._capture(cap, 80.0)  # < 90% floor
        assert bench._trend_main() == 1

    def test_unknown_config_and_no_capture_pass(self, trend_repo, capsys):
        bench, cap = trend_repo
        self._capture(cap, 1.0, metric="brand_new")
        assert bench._trend_main() == 0
        cap.unlink()
        assert bench._trend_main() == 0
        assert "no live capture" in capsys.readouterr().out

    def test_legacy_root_capture_still_read(self, trend_repo, tmp_path,
                                            capsys):
        bench, cap = trend_repo
        legacy = tmp_path / "bench_last_tpu.json"
        legacy.write_text(json.dumps(
            {"metric": "m", "platform": "tpu", "value": 95.0}
        ))
        assert bench._trend_main() == 0
        report = json.loads(capsys.readouterr().out)
        assert report["live_value"] == 95.0
