"""Concurrency stress: the race-detection analog of the reference's
`go test -race` runs (SURVEY §5 — the reference has no custom sanitizer;
its concurrency safety is mutexes exercised under the race detector).

Here the shared structures the drain worker pool mutates concurrently —
NodeDeletionTracker, NodeDeletionBatcher, the FakeClusterAPI object store,
and ClusterStateRegistry — are hammered from many threads and checked for
exact accounting afterwards: every node accounted once, zero in-flight
deletions left, no lost or duplicated results.

Reference anchors: core/scaledown/actuation/actuator.go:234 (parallel
deleteNodesAsync), delete_in_batch.go:71, deletiontracker/
nodedeletiontracker.go:32, clusterstate.go:112 (sync.Mutex).
"""
import random
import threading

from autoscaler_tpu.cloudprovider.test_provider import TestCloudProvider
from autoscaler_tpu.config.options import AutoscalingOptions
from autoscaler_tpu.core.scaledown.actuator import ScaleDownActuator
from autoscaler_tpu.core.scaledown.planner import ScaleDownPlan
from autoscaler_tpu.core.scaledown.tracking import NodeDeletionTracker
from autoscaler_tpu.kube.api import FakeClusterAPI
from autoscaler_tpu.simulator.removal import NodeToRemove
from autoscaler_tpu.utils.test_utils import GB, build_test_node, build_test_pod


def build_world(n_nodes, pods_per_drain=3):
    provider = TestCloudProvider()
    api = FakeClusterAPI()
    provider.add_node_group(
        "g", 0, n_nodes * 2, n_nodes,
        build_test_node("tmpl", cpu_m=8000, mem=32 * GB),
    )
    empty, drain = [], []
    for i in range(n_nodes):
        node = build_test_node(f"n{i}", cpu_m=8000, mem=32 * GB)
        provider.add_node("g", node)
        api.add_node(node)
        if i % 2 == 0:
            empty.append(NodeToRemove(node=node))
        else:
            pods = []
            for j in range(pods_per_drain):
                p = build_test_pod(f"p{i}-{j}", cpu_m=100, mem=256 * 1024 * 1024,
                                   node_name=node.name)
                api.add_pod(p)
                pods.append(p)
            drain.append(NodeToRemove(node=node, pods_to_reschedule=pods))
    return provider, api, empty, drain


class TestActuatorStress:
    def test_60_node_wave_exact_accounting(self):
        n = 60
        provider, api, empty, drain = build_world(n)
        opts = AutoscalingOptions()
        opts.max_empty_bulk_delete = n
        opts.max_drain_parallelism = n
        tracker = NodeDeletionTracker()
        actuator = ScaleDownActuator(provider, opts, api, tracker)
        # transient eviction failures on a third of the drained pods:
        # retries must not double-count or lose nodes
        for i, r in enumerate(drain):
            if i % 3 == 0:
                for p in r.pods_to_reschedule[:1]:
                    api.eviction_failures[p.key()] = 1
        plan = ScaleDownPlan(empty=list(empty), drain=list(drain))
        result = actuator.start_deletion(plan, now_ts=0.0)

        all_names = {r.node.name for r in empty} | {r.node.name for r in drain}
        done = set(result.deleted_empty) | set(result.deleted_drain)
        failed = set(result.failed)
        # every node accounted exactly once, none both done and failed
        assert done | failed == all_names
        assert not (done & failed)
        assert len(result.deleted_empty) + len(result.deleted_drain) + len(
            result.failed
        ) == len(all_names)
        # tracker drained back to zero in-flight
        assert tracker.in_flight_names() == []
        assert tracker.deletions_in_group("g") == 0
        # the cloud saw each deleted node exactly once
        deleted_cloud = [name for _, name in provider.scale_down_calls]
        assert sorted(deleted_cloud) == sorted(done)
        # every drained pod of a deleted node was evicted exactly once
        evicted = [k for k in api.evicted]
        assert len(evicted) == len(set(evicted))

    def test_repeated_waves_under_jitter(self):
        """Several back-to-back waves with scheduling jitter — results must
        stay exact regardless of thread interleaving."""
        rng = random.Random(7)
        for wave in range(3):
            n = 24
            provider, api, empty, drain = build_world(n, pods_per_drain=2)
            opts = AutoscalingOptions()
            opts.max_empty_bulk_delete = n
            opts.max_drain_parallelism = rng.choice([2, 5, n])
            tracker = NodeDeletionTracker()
            actuator = ScaleDownActuator(provider, opts, api, tracker)
            plan = ScaleDownPlan(empty=list(empty), drain=list(drain))
            result = actuator.start_deletion(plan, now_ts=float(wave))
            # the drain budget CROPS the wave (actuator.go:126): cropped
            # nodes are deferred to the next loop, not failed
            expect_drained = min(len(drain), opts.max_drain_parallelism)
            assert len(result.deleted_empty) == len(empty)
            assert len(result.deleted_drain) == expect_drained
            assert result.failed == {}
            assert tracker.in_flight_names() == []


class TestTrackerThreadSafety:
    def test_hammer_deletion_tracker(self):
        """64 threads × 50 ops on one tracker: counts must balance."""
        tracker = NodeDeletionTracker()
        errors = []

        def worker(tid):
            try:
                for i in range(50):
                    name = f"t{tid}-n{i}"
                    tracker.start_deletion("g", name, drain=bool(i % 2))
                    tracker.register_eviction(f"t{tid}-p{i}", float(i))
                    assert tracker.is_being_deleted(name)
                    tracker.end_deletion("g", name, ok=(i % 5 != 0),
                                         error="" if i % 5 else "boom")
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=worker, args=(t,)) for t in range(64)]
        [t.start() for t in threads]
        [t.join() for t in threads]
        assert errors == []
        assert tracker.in_flight_names() == []
        assert tracker.deletions_in_group("g") == 0
        assert len(tracker.recent_evictions(0.0)) == 64 * 50


class TestRegistryThreadSafety:
    def test_concurrent_scaleup_registration(self):
        """Concurrent scale-up registrations + failures against one registry
        (clusterstate.go guards this with a mutex; bursts of parallel
        RegisterOrUpdateScaleUp/RegisterFailedScaleUp must not corrupt)."""
        from autoscaler_tpu.clusterstate.registry import ClusterStateRegistry

        provider = TestCloudProvider()
        for g in ("a", "b", "c", "d"):
            provider.add_node_group(
                g, 0, 1000, 0, build_test_node(f"{g}-t", cpu_m=4000, mem=8 * GB)
            )
        csr = ClusterStateRegistry(provider, AutoscalingOptions())
        errors = []

        def worker(tid):
            try:
                rng = random.Random(tid)
                for i in range(100):
                    gid = rng.choice(["a", "b", "c", "d"])
                    csr.register_or_update_scale_up(gid, 1, now_ts=float(i))
                    if i % 7 == 0:
                        csr.register_failed_scale_up(gid, "cloud", now_ts=float(i))
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=worker, args=(t,)) for t in range(32)]
        [t.start() for t in threads]
        [t.join() for t in threads]
        assert errors == []
