#!/usr/bin/env bash
# CI gate — the analog of the reference's hack/verify-all.sh +
# hack/for-go-proj.sh test pipeline: static checks, unit tests, compile
# checks of the driver entry points.
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== python syntax/compile check =="
python -m compileall -q autoscaler_tpu bench.py __graft_entry__.py

echo "== graftlint (AST invariant gate: determinism, taxonomy, ladder, locks, boundaries, jit purity, kernel contracts, lock order, flag wiring, taint flow, thread escape, surface gating, interprocedural taint, host-sync leaks, recompile hazards, obligation typestate, ledger-schema drift) =="
# Fatal. Exits nonzero on ANY finding: the grandfather ledger
# (hack/lint-baseline.json) was burned down to zero and deleted in PR 20,
# so every rule now holds at full strength with no debt. The text run
# prints the per-rule findings/suppressions summary table (GL001–GL017)
# so CI logs show ratchet drift at a glance. Dataflow findings are fixed
# at the source, never baselined. Rule catalog:
# autoscaler_tpu/analysis/RULES.md
python -m autoscaler_tpu.analysis autoscaler_tpu/

echo "== graftlint determinism + incremental cache parity (three runs must emit byte-identical JSON) =="
# The analyzer polices replay determinism; it must hold itself to the same
# bar — finding order stable regardless of dict/set iteration — and the
# --cache path (per-file + whole-program finding cache keyed by content
# hash) must reproduce the uncached document byte-for-byte, cold and warm.
lint_tmp=$(mktemp -d)
python -m autoscaler_tpu.analysis --format=json autoscaler_tpu/ > "$lint_tmp/a.json"
python -m autoscaler_tpu.analysis --format=json --cache --cache-dir "$lint_tmp/cache" autoscaler_tpu/ > "$lint_tmp/b.json"
python -m autoscaler_tpu.analysis --format=json --cache --cache-dir "$lint_tmp/cache" autoscaler_tpu/ > "$lint_tmp/c.json"
if ! diff -q "$lint_tmp/a.json" "$lint_tmp/b.json" >/dev/null; then
    echo "ERROR: graftlint cold --cache output differs from the uncached run:" >&2
    diff "$lint_tmp/a.json" "$lint_tmp/b.json" | head -20 >&2
    exit 1
fi
if ! diff -q "$lint_tmp/a.json" "$lint_tmp/c.json" >/dev/null; then
    echo "ERROR: graftlint warm --cache output differs from the uncached run:" >&2
    diff "$lint_tmp/a.json" "$lint_tmp/c.json" | head -20 >&2
    exit 1
fi
echo "graftlint determinism + cache parity ok"

echo "== graftlint-v2 gate (--jobs fan-out parity, analysis/ self-scan, baseline freshness, SARIF emission, KERNEL_CONTRACTS purity certification) =="
# the --jobs fork pool must reproduce the serial document byte-for-byte
# (per-file rules fan out, fold-back is deferred to sorted path order)
python -m autoscaler_tpu.analysis --format=json --jobs 4 autoscaler_tpu/ > "$lint_tmp/jobs.json"
if ! diff -q "$lint_tmp/a.json" "$lint_tmp/jobs.json" >/dev/null; then
    echo "ERROR: graftlint --jobs output differs from the serial run:" >&2
    diff "$lint_tmp/a.json" "$lint_tmp/jobs.json" | head -20 >&2
    exit 1
fi
# the analyzer's own package must scan clean with NO baseline and NO
# pragmas doing load-bearing work — the tool that polices the tree cannot
# carry debt of its own
python -m autoscaler_tpu.analysis --no-baseline autoscaler_tpu/analysis/
# baseline freshness: the debt ledger may hold no entry the scan no
# longer reproduces (the main gate already fails on staleness; this
# asserts the machine-readable document agrees)
python - "$lint_tmp/a.json" <<'EOF'
import json, sys
doc = json.load(open(sys.argv[1]))
assert not doc["stale"], f"stale baseline entries: {doc['stale'][:3]}"
assert not doc["findings"], f"unbaselined findings: {doc['findings'][:3]}"
print(f"baseline fresh ({doc['files']} files)")
EOF
# SARIF 2.1.0 emission: exit 0 on the clean tree, document parses, every
# registered rule is listed, taint codeFlows shape is intact
python -m autoscaler_tpu.analysis --format=sarif autoscaler_tpu/ > "$lint_tmp/scan.sarif"
python - "$lint_tmp/scan.sarif" <<'EOF'
import json, sys
from autoscaler_tpu.analysis.rules import RULE_CATALOG
doc = json.load(open(sys.argv[1]))
assert doc["version"] == "2.1.0", doc["version"]
driver = doc["runs"][0]["tool"]["driver"]
assert driver["name"] == "graftlint"
ids = {r["id"] for r in driver["rules"]}
missing = set(RULE_CATALOG) - ids
assert not missing, f"rules absent from SARIF metadata: {sorted(missing)}"
print(f"sarif ok ({len(ids)} rules, {len(doc['runs'][0]['results'])} results)")
EOF
# GL015 cross-check: every kernel a KERNEL_CONTRACTS table names must be
# statically certified recompile-hazard-free over its transitive reach —
# hazardous AND unknown verdicts both fail (a contract the analyzer
# cannot resolve is a contract it cannot stand behind)
python - <<'EOF'
from pathlib import Path
from autoscaler_tpu.analysis.callgraph import CallGraph
from autoscaler_tpu.analysis.engine import FileModel, iter_python_files
from autoscaler_tpu.analysis.purity import certify_kernels
models = [FileModel(f, Path(f).read_text(encoding="utf-8"))
          for f in iter_python_files(["autoscaler_tpu"])]
verdicts = certify_kernels(CallGraph(models))
assert verdicts, "no KERNEL_CONTRACTS kernels found — vacuous certification"
bad = {k: v for k, v in verdicts.items() if v[0] != "certified"}
assert not bad, f"uncertified kernels: {bad}"
print(f"kernel purity certification ok ({len(verdicts)} kernels certified)")
EOF

echo "== graftlint-v3 gate (CFG obligation typestate + ledger-schema drift: seeded fixtures must fire with full witness paths, the shipped tree stays clean, the baseline ledger stays deleted, the cache salt covers the v3 sources) =="
# the grandfather ledger is GONE: the last GL005 debt was fixed at the
# source and the file deleted — it must never quietly come back
if [ -f hack/lint-baseline.json ]; then
    echo "ERROR: hack/lint-baseline.json reappeared — the debt ledger was burned down to zero; fix findings at the source instead" >&2
    exit 1
fi
python - "$lint_tmp/scan.sarif" <<'EOF'
import json, sys
from pathlib import Path
from autoscaler_tpu.analysis import analyze_sources
from autoscaler_tpu.analysis.sarif import to_sarif

# (1) seeded GL016: a coalescer ticket that leaks on the exception path
# must fire, carrying a multi-step witness that names the raising call
leak = '''
class FleetCoalescer:
    def submit(self, req):
        return object()

def _validate(req):
    if not req:
        raise ValueError("empty")

class Driver:
    def run(self, req):
        c = FleetCoalescer()
        t = c.submit(req)
        _validate(req)
        t.resolve(None)
'''
found, _ = analyze_sources({"autoscaler_tpu/seed/gl016.py": leak})
gl016 = [f for f in found if f.rule == "GL016"]
assert len(gl016) == 1, f"seeded obligation leak did not fire: {found}"
(f16,) = gl016
assert len(f16.flow) >= 2, f"GL016 witness path too short: {f16.flow}"
notes = " | ".join(step[2] for step in f16.flow)
assert "_validate" in notes or "raise" in notes.lower(), \
    f"witness never names the raising step: {notes}"
sarif = to_sarif(gl016)
(res,) = sarif["runs"][0]["results"]
assert res.get("codeFlows"), "GL016 SARIF result lost its codeFlows"
locs = res["codeFlows"][0]["threadFlows"][0]["locations"]
assert len(locs) == len(f16.flow), (len(locs), len(f16.flow))

# (2) seeded GL017: a producer emitting a field the SCHEMA_FIELDS
# manifest never declared (the unbumped-version drift) must fire
ledger = '''
SCHEMA = "autoscaler_tpu.seed.row/1"
SCHEMA_FIELDS = {SCHEMA: {"required": ("tick",), "optional": ()}}

def validate_records(records):
    errors = []
    for i, rec in enumerate(records):
        if rec.get("schema") != SCHEMA:
            errors.append("bad schema")
        if not isinstance(rec.get("tick"), int):
            errors.append("bad tick")
    return errors
'''
producer = '''
from autoscaler_tpu.seed.ledger import SCHEMA

def make(tick):
    return {"schema": SCHEMA, "tick": tick, "drifted": 1}
'''
found, _ = analyze_sources({
    "autoscaler_tpu/seed/ledger.py": ledger,
    "autoscaler_tpu/seed/producer.py": producer,
})
gl017 = [f for f in found if f.rule == "GL017"]
assert gl017, "seeded manifest drift did not fire"
assert any("drifted" in f.message for f in gl017), gl017

# (3) cache-salt coverage: the v3 sources live in the package glob the
# salt hashes, so editing any of them rotates every cache entry
pkg = Path("autoscaler_tpu/analysis")
hashed = {p.name for p in pkg.glob("*.py")}
for src in ("cfg.py", "obligations.py", "schema.py"):
    assert src in hashed, f"cache salt does not cover analysis/{src}"

# (4) the repo-scan SARIF metadata carries the v3 rules with prose docs
doc = json.load(open(sys.argv[1]))
rules = {r["id"]: r for r in doc["runs"][0]["tool"]["driver"]["rules"]}
for rid in ("GL016", "GL017"):
    assert rid in rules, f"{rid} absent from SARIF metadata"
    assert rules[rid]["fullDescription"]["text"], f"{rid} undocumented"
assert len(rules) >= 17, f"rule metadata shrank: {sorted(rules)}"
print(f"graftlint-v3 gate ok (witness {len(f16.flow)} steps, "
      f"{len(gl017)} drift findings, {len(rules)} rules documented)")
EOF
rm -rf "$lint_tmp"

echo "== proto freshness check =="
tmp=$(mktemp -d)
protoc --python_out="$tmp" --proto_path=autoscaler_tpu/rpc/protos \
    autoscaler_tpu/rpc/protos/autoscaler.proto
if ! diff -q "$tmp/autoscaler_pb2.py" autoscaler_tpu/rpc/autoscaler_pb2.py >/dev/null; then
    echo "ERROR: autoscaler_pb2.py is stale — re-run protoc" >&2
    exit 1
fi
rm -rf "$tmp"

echo "== native build check =="
python -c "
from autoscaler_tpu.native_bridge import available, build_error
assert available(), f'native build failed: {build_error()}'
print('native ok')
"

echo "== loadgen scenario validation (specs must parse + round-trip) =="
for scenario in benchmarks/scenarios/*.json; do
    python -m autoscaler_tpu.loadgen validate "$scenario"
done

echo "== trace + perf-ledger determinism check (two replays must export byte-identical Chrome traces AND perf JSONL ledgers) =="
trace_tmp=$(mktemp -d)
python -m autoscaler_tpu.loadgen run benchmarks/scenarios/kernel_fault_ladder.json \
    --chrome-trace "$trace_tmp/a.json" --perf-ledger "$trace_tmp/a.perf.jsonl" >/dev/null
python -m autoscaler_tpu.loadgen run benchmarks/scenarios/kernel_fault_ladder.json \
    --chrome-trace "$trace_tmp/b.json" --perf-ledger "$trace_tmp/b.perf.jsonl" >/dev/null
if ! diff -q "$trace_tmp/a.json" "$trace_tmp/b.json" >/dev/null; then
    echo "ERROR: trace export is nondeterministic across identical replays:" >&2
    diff "$trace_tmp/a.json" "$trace_tmp/b.json" | head -20 >&2
    exit 1
fi
if ! diff -q "$trace_tmp/a.perf.jsonl" "$trace_tmp/b.perf.jsonl" >/dev/null; then
    echo "ERROR: perf JSONL ledger is nondeterministic across identical replays:" >&2
    diff "$trace_tmp/a.perf.jsonl" "$trace_tmp/b.perf.jsonl" | head -20 >&2
    exit 1
fi
python - "$trace_tmp/a.json" <<'EOF'
import json, sys
doc = json.load(open(sys.argv[1]))
events = doc["traceEvents"]
assert events, "empty chrome trace"
names = {e["name"] for e in events}
for required in ("main", "estimate", "deviceDispatch", "buildSnapshot", "perfRecord"):
    assert required in names, f"trace schema missing {required!r} spans"
# Perfetto track metadata: every tick process carries naming "M" events
pids = {e["pid"] for e in events if e["ph"] == "X"}
for meta in ("process_name", "thread_name"):
    named = {e["pid"] for e in events if e["ph"] == "M" and e["name"] == meta}
    assert pids <= named, f"ticks missing {meta} metadata: {sorted(pids - named)}"
# perf acceptance surface: every served deviceDispatch span carries compile
# telemetry; warm ones carry the compile/execute split; cost-model attrs
# appear on the costed (device-kernel) routes
dd = [e for e in events
      if e["name"] == "deviceDispatch" and e["ph"] == "X"
      and e["args"].get("outcome") == "ok"]
assert dd, "no served deviceDispatch spans in the replay"
for e in dd:
    a = e["args"]
    assert "cache" in a and "dispatch_s" in a, f"span missing compile telemetry: {a}"
warm = [e for e in dd if e["args"].get("cache") == "hit"]
assert warm, "replay produced no warm dispatches"
for e in warm:
    a = e["args"]
    assert "compile_est_s" in a and "execute_est_s" in a, \
        f"warm dispatch missing compile/execute split: {a}"
assert any("model_flops" in e["args"] for e in dd), \
    "no cost-model attrs on any deviceDispatch span"
print(f"trace determinism ok ({len(events)} events, {len(dd)} served dispatches)")
EOF

echo "== runtime determinism sanitizer (replay must trap zero ambient reads) =="
# the dynamic half of the GL010 contract: the same canned scenario replays
# under analysis/sanitizer.py (patched clock/rng/env sources, direct-caller
# frame attribution) and fails on ANY trapped read in a replay-scoped
# frame — what static resolution might miss cannot fire unnoticed either
python -m autoscaler_tpu.loadgen run benchmarks/scenarios/kernel_fault_ladder.json \
    --sanitize >/dev/null
echo "runtime sanitizer ok"

echo "== perf-ledger schema + steady-state-compile regression gate =="
# validates the JSONL schema, tick monotonicity, and compile-cache
# coherence (a cache miss for an already-seen (route, shape signature) is
# a compile-on-steady-state-tick regression)
python bench.py --perf-ledger "$trace_tmp/a.perf.jsonl" >/dev/null
echo "perf ledger ok"
rm -rf "$trace_tmp"

echo "== decision-ledger determinism + provenance gate (two replays must write byte-identical explain JSONL) =="
explain_tmp=$(mktemp -d)
python -m autoscaler_tpu.loadgen run benchmarks/scenarios/skip_reasons.json \
    --explain-ledger "$explain_tmp/a.explain.jsonl" >/dev/null
python -m autoscaler_tpu.loadgen run benchmarks/scenarios/skip_reasons.json \
    --explain-ledger "$explain_tmp/b.explain.jsonl" >/dev/null
if ! diff -q "$explain_tmp/a.explain.jsonl" "$explain_tmp/b.explain.jsonl" >/dev/null; then
    echo "ERROR: decision ledger is nondeterministic across identical replays:" >&2
    diff "$explain_tmp/a.explain.jsonl" "$explain_tmp/b.explain.jsonl" | head -20 >&2
    exit 1
fi
# schema + provenance cross-checks (every executed scale-up has its
# recorded winning score; every still-pending pod has a closed-vocabulary
# reason) and the every-SkipReason coverage the scenario exists for
python bench.py --explain-ledger "$explain_tmp/a.explain.jsonl" > "$explain_tmp/report.json"
python - "$explain_tmp/report.json" <<'EOF'
import json, sys
report = json.load(open(sys.argv[1]))
assert report["valid"], report["errors"]
skips = report["skip_reasons"]
for reason in ("unhealthy_or_backed_off", "max_size_reached", "no_template"):
    assert skips.get(reason, 0) > 0, f"scenario never exercised SkipReason {reason!r}: {skips}"
assert report["expander_wins"], "no expander wins recorded"
print(f"decision ledger ok ({report['ticks']} ticks, skips={skips})")
EOF
rm -rf "$explain_tmp"

echo "== fleet serving determinism + fairness gate (two replays must write byte-identical fleet decision + perf ledgers; every tenant answer byte-identical to solo) =="
fleet_tmp=$(mktemp -d)
python -m autoscaler_tpu.loadgen run benchmarks/scenarios/fleet_tenants.json \
    --log "$fleet_tmp/a.fleet.jsonl" --perf-ledger "$fleet_tmp/a.perf.jsonl" \
    > "$fleet_tmp/a.report.json"
python -m autoscaler_tpu.loadgen run benchmarks/scenarios/fleet_tenants.json \
    --log "$fleet_tmp/b.fleet.jsonl" --perf-ledger "$fleet_tmp/b.perf.jsonl" >/dev/null
if ! diff -q "$fleet_tmp/a.fleet.jsonl" "$fleet_tmp/b.fleet.jsonl" >/dev/null; then
    echo "ERROR: fleet decision ledger is nondeterministic across identical replays:" >&2
    diff "$fleet_tmp/a.fleet.jsonl" "$fleet_tmp/b.fleet.jsonl" | head -20 >&2
    exit 1
fi
if ! diff -q "$fleet_tmp/a.perf.jsonl" "$fleet_tmp/b.perf.jsonl" >/dev/null; then
    echo "ERROR: fleet perf ledger is nondeterministic across identical replays:" >&2
    diff "$fleet_tmp/a.perf.jsonl" "$fleet_tmp/b.perf.jsonl" | head -20 >&2
    exit 1
fi
python bench.py --perf-ledger "$fleet_tmp/a.perf.jsonl" >/dev/null
# fleet round-ledger schema gate: the autoscaler_tpu.fleet.round/3
# validator twin (fleet/ledger.py) must pass the real replay's ledger —
# accounting identities included (zero hung tickets, shed tally exact)
python bench.py --fleet-ledger "$fleet_tmp/a.fleet.jsonl" >/dev/null
echo "fleet ledger ok"
python - "$fleet_tmp/a.fleet.jsonl" "$fleet_tmp/a.report.json" <<'EOF'
import json, sys
rounds = [json.loads(l) for l in open(sys.argv[1])]
assert rounds, "empty fleet decision ledger"
for r in rounds:
    assert r["schema"] == "autoscaler_tpu.fleet.round/3", r["schema"]
    for t in r["tenants"]:
        assert t["match_solo"], (
            f"tenant {t['tenant']} fleet answer diverged from solo in round "
            f"{r['tick']} (route {t['route']})"
        )
routes = {t["route"] for r in rounds for t in r["tenants"]}
# the canned scenario injects a batched-rung fault: both rungs must have
# served, and parity held on BOTH (batch isolation through degradation)
assert routes == {"fleet_batched", "fleet_oracle"}, routes
report = json.load(open(sys.argv[2]))
assert report["parity"]["certified"], report["parity"]
assert report["fleet"]["prewarmed_buckets"], "no buckets pre-warmed"
print(f"fleet fairness ok ({len(rounds)} rounds, routes={sorted(routes)})")
EOF
rm -rf "$fleet_tmp"

echo "== SLO mission-control gate (double replay byte-identical SLO ledgers; serving spans carry client parent context; exemplar trace ids resolve in the flight recorder) =="
slo_tmp=$(mktemp -d)
python -m autoscaler_tpu.loadgen run benchmarks/scenarios/fleet_tenants.json \
    --slo-ledger "$slo_tmp/a.slo.jsonl" >/dev/null
python -m autoscaler_tpu.loadgen run benchmarks/scenarios/fleet_tenants.json \
    --slo-ledger "$slo_tmp/b.slo.jsonl" >/dev/null
if ! diff -q "$slo_tmp/a.slo.jsonl" "$slo_tmp/b.slo.jsonl" >/dev/null; then
    echo "ERROR: SLO window ledger is nondeterministic across identical replays:" >&2
    diff "$slo_tmp/a.slo.jsonl" "$slo_tmp/b.slo.jsonl" | head -20 >&2
    exit 1
fi
python - <<'EOF'
import json, re
import numpy as np
from autoscaler_tpu import trace
from autoscaler_tpu.fleet import FleetCoalescer
from autoscaler_tpu.loadgen.fleetdrive import run_fleet_scenario
from autoscaler_tpu.loadgen.spec import ScenarioSpec
from autoscaler_tpu.rpc.service import TpuSimulationClient, serve
from autoscaler_tpu.slo import SLI_FLEET_E2E, validate_records

# (1) cross-process propagation: every served BatchEstimate span adopts
# its client's trace id and names the exact rpcCall parent span
side_tracer = trace.Tracer(recorder=trace.FlightRecorder(capacity=16))
co = FleetCoalescer(buckets="16x4x8", window_s=0.002, batch_scenarios=4)
server, port = serve(fleet=co, tracer=side_tracer)
client = TpuSimulationClient(f"127.0.0.1:{port}", default_timeout_s=30.0)
rng = np.random.default_rng(0)
client_tracer = trace.Tracer(recorder=trace.FlightRecorder(capacity=4))
with client_tracer.tick("main"):
    for _ in range(2):
        client.batch_estimate(
            rng.integers(1, 100, (9, 6)).astype(np.float32),
            rng.random((3, 9)) > 0.2,
            rng.integers(100, 500, (3, 6)).astype(np.float32),
            ["g0", "g1", "g2"],
            rng.integers(1, 16, 3).astype(np.int32),
            max_nodes=16, tenant_id="verify",
        )
client.close(); server.stop(0); co.stop()
client_trace = client_tracer.recorder.traces()[-1]
rpc_span_ids = {s.span_id for s in client_trace.spans if s.name == "rpcCall"}
served = [t for t in side_tracer.recorder.traces()
          if t.root.attrs.get("method") == "BatchEstimate"]
assert len(served) == 2, f"expected 2 served BatchEstimate traces, got {len(served)}"
for t in served:
    assert t.trace_id == client_trace.trace_id, \
        f"served span lost its client trace id: {t.trace_id} != {client_trace.trace_id}"
    assert t.root.attrs.get("parent_span_id") in rpc_span_ids, \
        f"served span missing its client parent context: {t.root.attrs}"

# (2) in-process fleet replay: SLO ledger validates, the fleet objective
# saw every answer, and every /metrics exemplar trace id resolves in the
# run's flight recorder
spec = ScenarioSpec.load("benchmarks/scenarios/fleet_tenants.json")
result = run_fleet_scenario(spec)
assert result.all_match(), "fleet parity broke under the SLO drill"
recs = result.slo_records
assert validate_records(recs) == [], validate_records(recs)[:5]
answers = sum(len(r.tenants) for r in result.records)
assert recs[-1]["slos"][SLI_FLEET_E2E]["events_total"] == answers, \
    "fleet_e2e SLI did not see every answered ticket"
expo = result.metrics.registry.expose(openmetrics=True)
ex_ids = {int(x) for x in re.findall(r'# \{trace_id="(\d+)"\}', expo)}
trace_ids = {t.trace_id for t in result.recorder.traces()}
assert ex_ids, "no exemplars in the exposition"
assert ex_ids <= trace_ids, f"unresolvable exemplar trace ids: {sorted(ex_ids - trace_ids)}"
print(f"slo mission control ok ({len(recs)} window records, "
      f"{answers} fleet answers, {len(ex_ids)} exemplar ids resolve)")
EOF
python bench.py --slo-ledger "$slo_tmp/a.slo.jsonl" >/dev/null
echo "slo ledger ok"
rm -rf "$slo_tmp"

echo "== fleet batched-throughput gate (batched >= 2x sequential at >= 4 tenants) =="
python bench.py --fleet 8 >/dev/null
echo "fleet bench gate ok"

echo "== fleet overload chaos gate (double-replay fleet_overload.json: byte-identical fleet+SLO+perf ledgers; typed sheds with retry-after; burn alert fires during the outage and clears; zero hung tickets) =="
chaos_tmp=$(mktemp -d)
python -m autoscaler_tpu.loadgen run benchmarks/scenarios/fleet_overload.json \
    --log "$chaos_tmp/a.fleet.jsonl" --slo-ledger "$chaos_tmp/a.slo.jsonl" \
    --perf-ledger "$chaos_tmp/a.perf.jsonl" > "$chaos_tmp/a.report.json"
python -m autoscaler_tpu.loadgen run benchmarks/scenarios/fleet_overload.json \
    --log "$chaos_tmp/b.fleet.jsonl" --slo-ledger "$chaos_tmp/b.slo.jsonl" \
    --perf-ledger "$chaos_tmp/b.perf.jsonl" >/dev/null
for ledger in fleet slo perf; do
    if ! diff -q "$chaos_tmp/a.$ledger.jsonl" "$chaos_tmp/b.$ledger.jsonl" >/dev/null; then
        echo "ERROR: $ledger ledger is nondeterministic across chaos replays:" >&2
        diff "$chaos_tmp/a.$ledger.jsonl" "$chaos_tmp/b.$ledger.jsonl" | head -20 >&2
        exit 1
    fi
done
python bench.py --slo-ledger "$chaos_tmp/a.slo.jsonl" >/dev/null
python bench.py --fleet-ledger "$chaos_tmp/a.fleet.jsonl" >/dev/null
python - "$chaos_tmp/a.fleet.jsonl" "$chaos_tmp/a.slo.jsonl" "$chaos_tmp/a.report.json" <<'EOF'
import json, sys
SHED_REASONS = {"shed_queue_full", "shed_quota", "shed_draining",
                "shed_deadline", "sidecar_crash", "sidecar_partition"}
rounds = [json.loads(l) for l in open(sys.argv[1])]
assert rounds, "empty fleet decision ledger"
sheds = [row for r in rounds for row in r["shed"]]
assert sheds, "overload scenario shed nothing — the storm never hit a gate"
for row in sheds:
    assert row["reason"] in SHED_REASONS, f"untyped shed reason: {row}"
    assert row["error"], f"shed row without a typed error class: {row}"
    if row["reason"] in ("shed_queue_full", "shed_quota"):
        assert row["retry_after_s"] > 0, f"overload shed without retry-after: {row}"
reasons = {row["reason"] for row in sheds}
assert "shed_quota" in reasons, f"tenant storm never hit its quota: {reasons}"
assert "sidecar_crash" in reasons, f"outage never shed unavailable: {reasons}"
# zero hung tickets, every round: resolved + failed + expired + shed
# accounts for every posted request
for r in rounds:
    assert r["outcomes"]["unresolved"] == 0, f"hung tickets in round {r['tick']}"
    posted = len(r["tenants"]) + len(r["shed"]) + r["outcomes"]["failed"]
    accounted = (r["outcomes"]["resolved"] + r["outcomes"]["shed"]
                 + r["outcomes"]["expired"] + r["outcomes"]["failed"])
    assert r["outcomes"]["resolved"] == len(r["tenants"]), r["outcomes"]
    assert posted == accounted, f"ticket leak in round {r['tick']}: {r['outcomes']}"
for r in rounds:
    for t in r["tenants"]:
        assert t["match_solo"], f"parity broke under overload: {t['tenant']}"
# SLO: the burn alert fired during the injected outage and cleared by run end
slo = [json.loads(l) for l in open(sys.argv[2])]
alerting = [rec["tick"] for rec in slo if rec["slos"]["fleet_e2e"]["alerting"]]
assert alerting, "burn alert never fired during the sidecar outage"
assert any(8 <= t <= 15 for t in alerting), f"alert missed the outage window: {alerting[:5]}"
assert not slo[-1]["slos"]["fleet_e2e"]["alerting"], "burn alert never cleared after recovery"
report = json.load(open(sys.argv[3]))
assert report["overload"]["unresolved"] == 0, report["overload"]
assert report["injected_faults"].get("rpc_slow", 0) > 0, report["injected_faults"]
print(f"chaos ledger ok ({len(rounds)} rounds, {len(sheds)} typed sheds, "
      f"alert ticks {alerting[0]}..{alerting[-1]} cleared by {slo[-1]['tick']})")
EOF
rm -rf "$chaos_tmp"

echo "== fleet HA rolling-restart gate (double replay byte-identical fleet+SLO ledgers incl. the endpoint-choice column; gold tier never sheds and stays inside SLO while bronze sheds first; downed replicas serve nothing) =="
ha_tmp=$(mktemp -d)
python -m autoscaler_tpu.loadgen run benchmarks/scenarios/fleet_rolling_restart.json \
    --log "$ha_tmp/a.fleet.jsonl" --slo-ledger "$ha_tmp/a.slo.jsonl" > "$ha_tmp/a.report.json"
python -m autoscaler_tpu.loadgen run benchmarks/scenarios/fleet_rolling_restart.json \
    --log "$ha_tmp/b.fleet.jsonl" --slo-ledger "$ha_tmp/b.slo.jsonl" >/dev/null
for ledger in fleet slo; do
    if ! diff -q "$ha_tmp/a.$ledger.jsonl" "$ha_tmp/b.$ledger.jsonl" >/dev/null; then
        echo "ERROR: $ledger ledger is nondeterministic across rolling-restart replays:" >&2
        diff "$ha_tmp/a.$ledger.jsonl" "$ha_tmp/b.$ledger.jsonl" | head -20 >&2
        exit 1
    fi
done
python bench.py --slo-ledger "$ha_tmp/a.slo.jsonl" >/dev/null
python bench.py --fleet-ledger "$ha_tmp/a.fleet.jsonl" >/dev/null
python - "$ha_tmp/a.fleet.jsonl" "$ha_tmp/a.slo.jsonl" "$ha_tmp/a.report.json" <<'EOF'
import json, sys
rounds = [json.loads(l) for l in open(sys.argv[1])]
assert rounds, "empty fleet decision ledger"
GOLD = {"gold-a", "gold-b"}
# (1) gold tier: never shed, answered every round, parity intact — the
# "gold stays inside SLO while bronze sheds first" half of the gate
gold_sheds = [s for r in rounds for s in r["shed"] if s["tenant"] in GOLD]
assert not gold_sheds, f"gold-tier requests were shed: {gold_sheds[:3]}"
for r in rounds:
    answered = {t["tenant"] for t in r["tenants"]}
    assert GOLD <= answered, f"round {r['tick']} lost gold answers: {answered}"
    assert r["outcomes"]["unresolved"] == 0, f"hung tickets in round {r['tick']}"
    for t in r["tenants"]:
        assert t["match_solo"], f"parity broke: {t['tenant']} round {r['tick']}"
# (2) bronze/default shed first AND by both tier gates (shared bucket
# quota + queue-share slice)
sheds = [s for r in rounds for s in r["shed"]]
assert sheds, "the storm never hit a tier gate"
tiers = {s["tier"] for s in sheds}
assert tiers and "gold" not in tiers, tiers
reasons = {s["reason"] for s in sheds}
assert "shed_quota" in reasons and "shed_queue_full" in reasons, reasons
# (3) the endpoint-choice column: every answer names its replica, the
# fleet spread across >= 2 replicas, and a restarting replica served
# NOTHING during its kill window (the client rebalanced)
endpoints = {t["endpoint"] for r in rounds for t in r["tenants"]}
assert len(endpoints) >= 2 and "" not in endpoints, endpoints
WINDOWS = {"replica-0": range(5, 9), "replica-1": range(11, 15),
           "replica-2": range(16, 20)}
for rep, win in WINDOWS.items():
    hits = [(r["tick"], t["tenant"]) for r in rounds if r["tick"] in win
            for t in r["tenants"] if t["endpoint"] == rep]
    assert not hits, f"{rep} served during its restart window: {hits[:5]}"
# (4) the fleet_e2e burn alert stays quiet: rolling restarts with a
# rebalancing client are a non-event, not an SLO incident
slo = [json.loads(l) for l in open(sys.argv[2])]
assert not slo[-1]["slos"]["fleet_e2e"]["alerting"], "alert stuck at run end"
report = json.load(open(sys.argv[3]))
assert report["overload"]["unresolved"] == 0, report["overload"]
assert report["parity"]["certified"], report["parity"]
assert report["ha"]["endpoint_requests"], report["ha"]
print(f"fleet HA rolling restart ok ({len(rounds)} rounds, "
      f"{len(sheds)} low-tier sheds, endpoints={sorted(endpoints)})")
EOF
rm -rf "$ha_tmp"

echo "== fleet HA balanced-vs-static bench gate (balanced routing strictly beats the static list on p99 and sheds under replica flap) =="
python bench.py --fleet-ha >/dev/null
echo "fleet-ha bench gate ok"

echo "== live two-sidecar rolling-restart drill (SIGKILL one replica mid-storm: the client rebalances, zero in-deadline requests lost beyond typed sheds) =="
python - <<'EOF'
import re, signal, subprocess, sys, threading, time
import numpy as np
import grpc
from autoscaler_tpu.rpc.service import TpuSimulationClient

TIERS = ('{"gold": {"qps": 50, "burst": 100, "queue_share": 0.75, '
         '"shed_priority": 0, "tenants": ["drill-gold"]}, '
         '"default": {"qps": 50, "burst": 100, "queue_share": 0.5, '
         '"shed_priority": 10}}')

def start_sidecar():
    # stderr joins stdout so a crash can never orphan the output pipe
    proc = subprocess.Popen(
        [sys.executable, "-m", "autoscaler_tpu.rpc", "--address",
         "127.0.0.1:0", "--health-port", "0", "--fleet-prewarm", "false",
         "--fleet-shape-buckets", "16x4x8", "--fleet-coalesce-window-ms",
         "5", "--fleet-tenant-tiers", TIERS],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    line = proc.stdout.readline()
    port = int(re.search(r"serving on port (\d+)", line).group(1))
    return proc, port

proc_a, port_a = start_sidecar()
proc_b, port_b = start_sidecar()
try:
    client = TpuSimulationClient(
        [f"127.0.0.1:{port_a}", f"127.0.0.1:{port_b}"],
        default_timeout_s=30.0, failover_base_sleep_s=0.001)
    rng = np.random.default_rng(7)
    def world():
        return (rng.integers(1, 100, (9, 6)).astype(np.float32),
                rng.random((3, 9)) > 0.2,
                rng.integers(100, 500, (3, 6)).astype(np.float32),
                ["g0", "g1", "g2"], rng.integers(1, 16, 3).astype(np.int32))
    worlds = [world() for _ in range(24)]
    outcomes = []
    lock = threading.Lock()
    def storm(i):
        try:
            client.batch_estimate(*worlds[i], max_nodes=16,
                                  tenant_id="drill-gold")
            with lock: outcomes.append("answered")
        except grpc.RpcError as e:
            with lock: outcomes.append(f"typed:{e.code().name}")
    threads = [threading.Thread(target=storm, args=(i,)) for i in range(24)]
    for i, t in enumerate(threads):
        t.start()
        if i == 8:
            proc_a.kill()  # SIGKILL mid-storm: no drain, no goodbye
        time.sleep(0.005)
    for t in threads:
        t.join(timeout=60.0)
        assert not t.is_alive(), "a storm call hung through the replica kill"
    # zero in-deadline requests lost beyond the typed shed budget: every
    # call either answered (failover absorbed the kill) or surfaced a
    # TYPED status — never a hang, never an untyped loss. With 30s
    # deadlines and a live peer, quota off, everything must answer.
    assert len(outcomes) == 24, outcomes
    lost = [o for o in outcomes if o != "answered"]
    assert not lost, f"in-deadline requests lost beyond typed sheds: {lost}"
    # the client REBALANCED: the killed endpoint's health shows the
    # UNAVAILABLE streak / ejection, the survivor stays clean and took
    # the traffic
    health = client.endpoint_health()
    dead, live = health[f"127.0.0.1:{port_a}"], health[f"127.0.0.1:{port_b}"]
    assert dead["consecutive_unavailable"] > 0 or dead["breaker"] != "closed", dead
    assert live["breaker"] == "closed" and live["consecutive_unavailable"] == 0, live
    # and new first attempts now route to the survivor, not the corpse
    post = []
    for i in range(4):
        counts_, _s, _m = client.batch_estimate(*world(), max_nodes=16,
                                                tenant_id="drill-gold")
        post.append(counts_.shape)
    assert all(s == (3,) for s in post), post
    client.close()
    rc_b = proc_b.poll()
    assert rc_b is None, f"survivor sidecar died mid-drill: {rc_b}"
    print(f"two-sidecar drill ok (24/24 answered through a SIGKILL; "
          f"dead endpoint health: streak={dead['consecutive_unavailable']}, "
          f"breaker={dead['breaker']})")
finally:
    for p in (proc_a, proc_b):
        if p.poll() is None:
            p.kill()
EOF

echo "== live sidecar SIGTERM drain gate (readiness flips, admission refuses with drain detail, in-flight tickets resolve, clean exit) =="
python - <<'EOF'
import re, signal, subprocess, sys, threading, urllib.error, urllib.request
import numpy as np
import grpc
from autoscaler_tpu.rpc.service import DRAIN_DETAIL, TpuSimulationClient

# stderr joins the stdout pipe so a failure can never leave an orphan
# holding this gate's output pipe open (tail would wait forever)
proc = subprocess.Popen(
    [sys.executable, "-m", "autoscaler_tpu.rpc", "--address", "127.0.0.1:0",
     "--health-port", "-1", "--fleet-prewarm", "false",
     "--fleet-shape-buckets", "16x4x8", "--fleet-coalesce-window-ms", "20",
     "--fleet-drain-grace-s", "5.0"],
    stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
try:
    line = proc.stdout.readline()
    port = int(re.search(r"serving on port (\d+)", line).group(1))
    health = int(re.search(r"health_port=(\d+)", line).group(1))
    assert urllib.request.urlopen(
        f"http://127.0.0.1:{health}/healthz", timeout=10).status == 200

    client = TpuSimulationClient(f"127.0.0.1:{port}", default_timeout_s=30.0)
    rng = np.random.default_rng(0)
    world = lambda: (rng.integers(1, 100, (9, 6)).astype(np.float32),
                     rng.random((3, 9)) > 0.2,
                     rng.integers(100, 500, (3, 6)).astype(np.float32),
                     ["g0", "g1", "g2"], rng.integers(1, 16, 3).astype(np.int32))
    outcomes = []
    def call():
        try:
            client.batch_estimate(*world(), max_nodes=16, tenant_id="drain")
            outcomes.append("answered")
        except grpc.RpcError as e:
            outcomes.append(f"typed:{e.code().name}")

    # in-flight requests ride the 20ms coalescing window while the drain fires
    threads = [threading.Thread(target=call) for _ in range(4)]
    for t in threads: t.start()
    # preStop analog first (readiness down + admission closed), then SIGTERM
    urllib.request.urlopen(f"http://127.0.0.1:{health}/drain", timeout=10)
    try:
        urllib.request.urlopen(f"http://127.0.0.1:{health}/healthz", timeout=10)
        raise SystemExit("readiness did not flip on drain")
    except urllib.error.HTTPError as e:
        assert e.code == 503, e.code
    # a FRESH probe client (its own channel — the shared client's threads
    # are mid-failover) must see the typed drain refusal
    probe = TpuSimulationClient(f"127.0.0.1:{port}", default_timeout_s=10.0,
                                failover_base_sleep_s=0.001)
    try:
        probe.estimate(*world(), max_nodes=16)
        raise SystemExit("draining sidecar served a new request")
    except grpc.RpcError as e:
        assert e.code() == grpc.StatusCode.UNAVAILABLE, e.code()
        assert DRAIN_DETAIL in (e.details() or ""), e.details()
    probe.close()
    proc.send_signal(signal.SIGTERM)
    for t in threads:
        t.join(timeout=30.0)
        assert not t.is_alive(), "a client call hung through the drain"
    assert len(outcomes) == 4 and all(
        o == "answered" or o.startswith("typed:") for o in outcomes
    ), outcomes
    client.close()
    rc = proc.wait(timeout=20)
    assert rc == 0, f"sidecar exited {rc}"
    print(f"live drain ok (in-flight outcomes: {sorted(outcomes)})")
finally:
    if proc.poll() is None:
        proc.kill()
EOF

echo "== fleet overload-contrast bench gate (admission on: p99 within 2x unloaded while shed absorbs excess; off: queue+e2e grow monotonically) =="
python bench.py --fleet-overload >/dev/null
echo "overload bench gate ok"

echo "== resident-arena determinism + parity gate (churn double-replay byte-identical; arena decisions byte-identical to cold-repack; ledger proves no steady-state compile or unexplained full upload) =="
arena_tmp=$(mktemp -d)
# churn-heavy canned scenario: add/remove/reassign storms crossing a
# bucket boundary, plus an injected arena_fault (double-buffer rollback)
python -m autoscaler_tpu.loadgen run benchmarks/scenarios/arena_churn.json \
    --perf-ledger "$arena_tmp/a.perf.jsonl" --explain-ledger "$arena_tmp/a.explain.jsonl" >/dev/null
python -m autoscaler_tpu.loadgen run benchmarks/scenarios/arena_churn.json \
    --perf-ledger "$arena_tmp/b.perf.jsonl" --explain-ledger "$arena_tmp/b.explain.jsonl" >/dev/null
if ! diff -q "$arena_tmp/a.perf.jsonl" "$arena_tmp/b.perf.jsonl" >/dev/null; then
    echo "ERROR: arena perf ledger is nondeterministic across identical replays:" >&2
    diff "$arena_tmp/a.perf.jsonl" "$arena_tmp/b.perf.jsonl" | head -20 >&2
    exit 1
fi
if ! diff -q "$arena_tmp/a.explain.jsonl" "$arena_tmp/b.explain.jsonl" >/dev/null; then
    echo "ERROR: arena decision ledger is nondeterministic across identical replays:" >&2
    diff "$arena_tmp/a.explain.jsonl" "$arena_tmp/b.explain.jsonl" | head -20 >&2
    exit 1
fi
# the SAME scenario on the cold-repack path: decisions must be
# byte-identical — the arena changes how tensors reach the device,
# never what the autoscaler decides
python -m autoscaler_tpu.loadgen run benchmarks/scenarios/arena_churn.json \
    --set arena_enabled=false --explain-ledger "$arena_tmp/c.explain.jsonl" >/dev/null
if ! diff -q "$arena_tmp/a.explain.jsonl" "$arena_tmp/c.explain.jsonl" >/dev/null; then
    echo "ERROR: arena-path decisions diverge from the cold-repack path:" >&2
    diff "$arena_tmp/a.explain.jsonl" "$arena_tmp/c.explain.jsonl" | head -20 >&2
    exit 1
fi
# ledger gates: compile-cache coherence (no steady-state compile) and
# arena upload coherence (full uploads only with a promotion/rollback),
# plus proof the scenario actually exercised both paths
python bench.py --perf-ledger "$arena_tmp/a.perf.jsonl" > "$arena_tmp/report.json"
python - "$arena_tmp/report.json" <<'EOF'
import json, sys
report = json.load(open(sys.argv[1]))
assert report["valid"], report["errors"]
arena = report.get("arena") or {}
assert arena.get("delta_rows", 0) > 0, f"no delta scatters recorded: {arena}"
assert arena.get("promotions", 0) > 0, f"scenario never crossed a bucket boundary: {arena}"
assert arena.get("rollbacks", 0) > 0, f"scenario never exercised the fault rollback: {arena}"
print(f"arena churn ledger ok ({report['ticks']} ticks, arena={arena})")
EOF
rm -rf "$arena_tmp"

echo "== resident-arena steady-state gate (20k-pod CPU config: e2e <= 1.15x device, zero steady-state compiles/full uploads) =="
python bench.py --arena >/dev/null
echo "arena bench gate ok"

echo "== preemption gate (storm double-replay byte-identical; every eviction row names its evictor; disabled flag reproduces the preemption-less decisions byte-for-byte) =="
preempt_tmp=$(mktemp -d)
# priority storm on a capped pool: high-priority waves can only land by
# evicting low-priority residents — the engine plans, the ledger names
# every victim's evictor, and two replays must byte-match
python -m autoscaler_tpu.loadgen run benchmarks/scenarios/preemption_storm.json \
    --log "$preempt_tmp/a.log.json" --explain-ledger "$preempt_tmp/a.explain.jsonl" >/dev/null
python -m autoscaler_tpu.loadgen run benchmarks/scenarios/preemption_storm.json \
    --log "$preempt_tmp/b.log.json" --explain-ledger "$preempt_tmp/b.explain.jsonl" >/dev/null
if ! diff -q "$preempt_tmp/a.explain.jsonl" "$preempt_tmp/b.explain.jsonl" >/dev/null; then
    echo "ERROR: preemption decision ledger is nondeterministic across identical replays:" >&2
    diff "$preempt_tmp/a.explain.jsonl" "$preempt_tmp/b.explain.jsonl" | head -20 >&2
    exit 1
fi
if ! diff -q "$preempt_tmp/a.log.json" "$preempt_tmp/b.log.json" >/dev/null; then
    echo "ERROR: preemption decision log is nondeterministic across identical replays:" >&2
    exit 1
fi
# schema /2 validation (closed eviction vocabulary, every row names its
# evictor) plus proof the storm actually planned and actuated evictions
python bench.py --explain-ledger "$preempt_tmp/a.explain.jsonl" > "$preempt_tmp/report.json"
python - "$preempt_tmp/report.json" "$preempt_tmp/a.explain.jsonl" "$preempt_tmp/a.log.json" <<'EOF'
import json, sys
report = json.load(open(sys.argv[1]))
assert report["valid"], report["errors"]
assert report.get("evictions", 0) > 0, "storm planned no evictions"
rows = 0
for line in open(sys.argv[2]):
    rec = json.loads(line)
    for row in (rec.get("preemption") or {}).get("evictions", []):
        assert row.get("by"), f"eviction row without an evictor: {row}"
        assert row.get("reason") == "preempted_by", row
        rows += 1
log = json.load(open(sys.argv[3]))
actuated = sum(len(r["preempted"]) for r in log)
assert actuated > 0, "storm actuated no evictions"
print(f"preemption storm ok ({rows} eviction rows, {actuated} actuated, "
      f"all name their evictor)")
EOF
# the SAME scenario with the feature flag off must reproduce the
# decisions of a spec that never mentions preemption — byte-for-byte
# (the engine, the schema section and the churn filter all disengage)
python - "$preempt_tmp/stripped.json" <<'EOF'
import json, sys
doc = json.load(open("benchmarks/scenarios/preemption_storm.json"))
doc["options"].pop("preemption_enabled", None)
doc["options"].pop("preemption_churn_weight", None)
json.dump(doc, open(sys.argv[1], "w"), indent=2)
EOF
python -m autoscaler_tpu.loadgen run benchmarks/scenarios/preemption_storm.json \
    --set preemption_enabled=false \
    --log "$preempt_tmp/off.log.json" --explain-ledger "$preempt_tmp/off.explain.jsonl" >/dev/null
python -m autoscaler_tpu.loadgen run "$preempt_tmp/stripped.json" \
    --log "$preempt_tmp/base.log.json" --explain-ledger "$preempt_tmp/base.explain.jsonl" >/dev/null
if ! diff -q "$preempt_tmp/off.explain.jsonl" "$preempt_tmp/base.explain.jsonl" >/dev/null \
   || ! diff -q "$preempt_tmp/off.log.json" "$preempt_tmp/base.log.json" >/dev/null; then
    echo "ERROR: preemption_enabled=false diverges from the preemption-less baseline:" >&2
    diff "$preempt_tmp/off.explain.jsonl" "$preempt_tmp/base.explain.jsonl" | head -20 >&2
    exit 1
fi
rm -rf "$preempt_tmp"
echo "preemption disabled-path parity ok"

echo "== preemption contrast bench gate (aware admits strictly more than priority-blind; kernel-vs-oracle eviction sets agree on every world) =="
python bench.py --preempt 8 >/dev/null
echo "preempt bench gate ok"

echo "== flight-journal gate (storm double-journal byte-identical; every tick reconstructs and replays byte-for-byte against the decision ledger; keyframe promotions exercised) =="
journal_tmp=$(mktemp -d)
# the storm drives schema-change reseeds (new pools appear) on top of the
# every-K interval policy, so the journal must exercise keyframe
# promotion beyond the tick-0 init frame — and two identical replays must
# write byte-identical journals (the determinism contract /journalz and
# post-mortem replay both lean on)
python -m autoscaler_tpu.loadgen run benchmarks/scenarios/preemption_storm.json \
    --explain-ledger "$journal_tmp/a.explain.jsonl" \
    --journal "$journal_tmp/a.journal.jsonl" >/dev/null
python -m autoscaler_tpu.loadgen run benchmarks/scenarios/preemption_storm.json \
    --explain-ledger "$journal_tmp/b.explain.jsonl" \
    --journal "$journal_tmp/b.journal.jsonl" >/dev/null
if ! diff -q "$journal_tmp/a.journal.jsonl" "$journal_tmp/b.journal.jsonl" >/dev/null; then
    echo "ERROR: flight journal is nondeterministic across identical replays:" >&2
    diff "$journal_tmp/a.journal.jsonl" "$journal_tmp/b.journal.jsonl" | head -20 >&2
    exit 1
fi
# schema /1 validation plus proof every journaled tick reconstructs into
# state (keyframe + delta chains all apply cleanly)
python bench.py --journal-ledger "$journal_tmp/a.journal.jsonl" > "$journal_tmp/report.json"
python - "$journal_tmp/report.json" <<'EOF'
import json, sys
report = json.load(open(sys.argv[1]))
assert report["valid"], report["errors"]
assert report["reconstructed"] == report["ticks"], report
reasons = report["keyframe_reasons"]
promoted = sum(v for k, v in reasons.items() if k != "init")
assert promoted > 0, f"no keyframe promotion beyond init exercised: {reasons}"
print(f"journal ok ({report['ticks']} ticks, {report['keyframes']} keyframes, "
      f"reasons={reasons})")
EOF
# time-travel replay: reconstruct EVERY tick's decision-input state and
# re-execute the preemption decision path on it — each re-derived ledger
# section must byte-match the recorded explain line (exit 1 = divergence)
python -m autoscaler_tpu.journal replay "$journal_tmp/a.journal.jsonl" \
    --explain-ledger "$journal_tmp/a.explain.jsonl"
rm -rf "$journal_tmp"
echo "flight-journal replay parity ok"

echo "== bench trend gate (live TPU capture must stay within 10% of the committed BENCH_r* trajectory) =="
python bench.py --trend >/dev/null
echo "bench trend gate ok"

echo "== policy-gym tuning gate (double tune byte-identical; best score non-decreasing; winner strictly beats the all-defaults policy) =="
gym_tmp=$(mktemp -d)
# 2 generations x 4 candidates over the canned suite (diurnal + spike +
# drain-heavy + kernel-fault, shared seeds): ALL randomness rides the
# seeded PolicyRng and rollouts are loadgen-deterministic, so two tunes —
# including their concurrent fleet-coalesced rollouts — must write
# byte-identical tuning ledgers
python -m autoscaler_tpu.gym tune benchmarks/scenarios/gym_suite.json \
    --generations 2 --population 4 --seed 12 --ledger "$gym_tmp/a.jsonl" >/dev/null
python -m autoscaler_tpu.gym tune benchmarks/scenarios/gym_suite.json \
    --generations 2 --population 4 --seed 12 --ledger "$gym_tmp/b.jsonl" >/dev/null
if ! diff -q "$gym_tmp/a.jsonl" "$gym_tmp/b.jsonl" >/dev/null; then
    echo "ERROR: tuning ledger is nondeterministic across identical tunes:" >&2
    diff "$gym_tmp/a.jsonl" "$gym_tmp/b.jsonl" | head -20 >&2
    exit 1
fi
# schema + generation monotonicity + the improvement invariant
# (best_so_far never decreases), then the acceptance gate: the tuned
# winner strictly beats the gen-0 all-defaults baseline on the suite's
# weighted objective
python bench.py --gym-ledger "$gym_tmp/a.jsonl" > "$gym_tmp/report.json"
python - "$gym_tmp/report.json" <<'EOF'
import json, sys
report = json.load(open(sys.argv[1]))
assert report["valid"], report["errors"]
assert report["beats_baseline"], (
    f"tuned winner {report['winner']['total']} does not beat the "
    f"all-defaults baseline {report['baseline_total']}"
)
traj = report["best_trajectory"]
assert traj == sorted(traj), f"best-of-generation decreased: {traj}"
print(f"gym tune ok ({report['generations']} generations, "
      f"{report['rollouts']} rollouts, improvement {report['improvement']})")
EOF
rm -rf "$gym_tmp"

echo "== unit tests (8-device virtual CPU mesh) =="
python -m pytest tests/ -q -x

echo "== timing gate (FATAL; bound calibrated to worker speed in-run) =="
AUTOSCALER_TPU_TIMING_ASSERTS=1 python -m pytest tests/test_scale_1000.py -q

echo "== graft entry compile check =="
XLA_FLAGS=--xla_force_host_platform_device_count=8 python - <<'EOF'
import jax
jax.config.update("jax_platforms", "cpu")
import __graft_entry__ as ge
fn, args = ge.entry()
jax.block_until_ready(jax.jit(fn)(*args))
ge.dryrun_multichip(8)
print("graft entry ok")
EOF

echo "ALL CHECKS PASSED"
