"""Headline benchmark: batched TPU scale-up estimation at the north-star
scale vs a compiled serial baseline.

Workload: the BASELINE.json north-star — 100k pending heterogeneous pods
(cpu/mem/GPU requests) x 500 node groups, max 1000 nodes per group
(the reference's --max-nodes-per-scaleup default, main.go:215), estimated in
ONE batched device dispatch (ops/binpack.ffd_binpack_groups).

Baseline: the C++ serial FFD (native/ffd_serial.cpp), which mirrors the Go
BinpackingNodeEstimator's algorithm (binpacking_estimator.go:65-141) as the
reference's serial per-group loop would run it — a deliberately STRONG
stand-in: it strips the scheduler-framework plugin overhead the real
reference pays per (pod, node) check (its binpacking budget is 10s/group,
main.go:216; the compiled loop here does ~0.1s/group). Sampled on 3 groups
and scaled linearly in group count (groups are independent and identically
distributed). Falls back to the numpy oracle if no C++ toolchain exists.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
vs_baseline = serial_baseline_time / tpu_time on identical work (single
chip; the group axis additionally shards across chips via shard_map —
see __graft_entry__.dryrun_multichip). tpu_time is the better of the
strictly-serial e2e (device compute + result-blob tunnel fetch) and the
pipelined steady-state per-estimate cost (fetch of estimate k overlapped
with device compute of k+1 — the production control-loop shape); the JSON
reports device_complete_s / fetch_s / e2e_s / pipelined_per_dispatch_s
separately so both claims stay auditable per the r4 verdict.

Capture is defensive (round-1 lesson: a hung axon backend init produced
rc=1 and no JSON): the parent process runs the measured bench in a child
subprocess with bounded timeouts, retries a wedged TPU backend init once,
then falls back to a CPU run with "platform" labeled honestly in the JSON.
Whatever happens, exactly one parseable JSON line lands on stdout.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

_CHILD_ENV = "AUTOSCALER_TPU_BENCH_CHILD"
_PLATFORM_ENV = "AUTOSCALER_TPU_BENCH_PLATFORM"
# generous: first TPU compile ~20-40s, the tunnel adds latency
_ATTEMPTS = (
    # (platform intent, timeout_s); "default" = whatever the env pins (axon)
    ("default", 600),
    ("default", 600),   # one retry for a transiently wedged tunnel/backend
    ("cpu", 1800),
)

# The CPU fallback runs a SMALLER workload: the full 100k×500 scan measured
# >40min on this host's CPU — past any sane attempt budget — and a CPU
# number is only a liveness signal, not the round's evidence. The shape is
# embedded in the metric name and the JSON's p/g fields, so a fallback can
# never masquerade as the north-star capture (which requires platform=tpu).
_CPU_FALLBACK_SHAPE = {"AUTOSCALER_TPU_BENCH_P": "20000",
                       "AUTOSCALER_TPU_BENCH_G": "100"}


def build_workload(P=100_000, G=500, seed=0):
    from autoscaler_tpu.kube.objects import CPU, GPU, MEMORY, PODS

    rng = np.random.default_rng(seed)
    pod_req = np.zeros((P, 6), np.float32)
    pod_req[:, CPU] = rng.integers(50, 2000, P)
    pod_req[:, MEMORY] = rng.integers(64, 8192, P)
    gpu_pods = rng.random(P) < 0.1
    pod_req[gpu_pods, GPU] = rng.integers(1, 4, int(gpu_pods.sum()))
    pod_req[:, PODS] = 1

    allocs = np.zeros((G, 6), np.float32)
    allocs[:, CPU] = rng.choice([4000, 8000, 16000, 32000], G)
    allocs[:, MEMORY] = rng.choice([8192, 16384, 32768, 65536], G)
    gpu_groups = rng.random(G) < 0.2
    allocs[gpu_groups, GPU] = 8
    allocs[:, PODS] = 110

    # simulated non-resource predicate outcomes (taints/selectors)
    masks = rng.random((G, P)) > 0.05
    # gpu pods only schedulable on gpu groups
    masks[np.ix_(~gpu_groups, gpu_pods)] = False
    caps = np.full(G, 1000, np.int32)
    return pod_req, masks, allocs, caps


def _bench_main():
    import jax

    if os.environ.get(_PLATFORM_ENV) == "cpu":
        # env JAX_PLATFORMS alone is not enough here: the axon site hook
        # re-pins the platform at import, so override via config like
        # tests/conftest.py does
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from autoscaler_tpu.ops.binpack import ffd_binpack_groups

    # env knobs exist for smoke-testing the capture pipeline only; the
    # driver-run bench always uses the north-star 100k x 500 defaults
    P = int(os.environ.get("AUTOSCALER_TPU_BENCH_P", 100_000))
    G = int(os.environ.get("AUTOSCALER_TPU_BENCH_G", 500))
    MAX_NODES = 1000
    pod_req, masks, allocs, caps = build_workload(P, G)

    jreq = jnp.asarray(pod_req)
    jmasks = jnp.asarray(masks)
    jallocs = jnp.asarray(allocs)
    jcaps = jnp.asarray(caps)

    from autoscaler_tpu.ops.bits import pack_result_blob, unpack_result_blob

    def make_blob(binpack_fn):
        """Enqueue one full estimate + on-device blob pack. Purely async —
        nothing here blocks; the caller decides when (and how much) to
        fetch. counts + scheduled ship as ONE fused blob, bit-packed 8:1
        (raw [G, P] bools cost ~1.2s of pure tunnel transfer at 100k×500,
        and a separate counts fetch costs a second full round-trip)."""
        out = binpack_fn(
            jreq, jmasks, jallocs, max_nodes=MAX_NODES, node_caps=jcaps
        )
        return pack_result_blob(out.node_count, out.scheduled)

    def run_serial(binpack_fn):
        """One measured estimate, split into the two costs the r4 verdict
        asked to see separately: device-complete (dispatch + all device
        compute, fenced by a 4-byte checksum fetch — the only reliable
        completion barrier through the axon relay, where block_until_ready
        returns in ~83µs) and the result-blob tunnel fetch."""
        t0 = time.perf_counter()
        blob = make_blob(binpack_fn)
        fence = jnp.sum(blob.astype(jnp.int32), dtype=jnp.int32)
        int(fence)  # 4-byte fetch: blocks until every queued op is done
        t_dev = time.perf_counter() - t0
        t1 = time.perf_counter()
        host_blob = np.asarray(blob)
        t_fetch = time.perf_counter() - t1
        return unpack_result_blob(host_blob, G, P), t_dev, t_fetch

    def run_pipelined(binpack_fn, n):
        """Steady-state throughput: rep i's blob fetch overlaps rep i+1's
        device compute (the dispatch for i+1 is enqueued BEFORE blocking on
        i's fetch; the device works through its in-order queue while the
        tunnel drains the previous result). This is the production shape —
        the control loop consumes estimate k while estimate k+1 runs — and
        it takes the tunnel out of the critical path exactly when fetch
        time < device time. Returns wall/n, the per-estimate cost with
        overlap. All n results are fully fetched and the last is returned
        for a parity check against the serial path."""
        t0 = time.perf_counter()
        cur = make_blob(binpack_fn)
        for _ in range(n - 1):
            nxt = make_blob(binpack_fn)      # enqueue next BEFORE fetching
            host_blob = np.asarray(cur)      # fetch overlaps next compute
            cur = nxt
        host_blob = np.asarray(cur)
        wall = time.perf_counter() - t0
        return wall / n, unpack_result_blob(host_blob, G, P)

    def run():
        return run_serial(ffd_binpack_groups)

    (res_counts, res_sched), _, _ = run()  # compile + warm
    dev_times, fetch_times = [], []
    for _ in range(3):
        _, t_dev, t_fetch = run()
        dev_times.append(t_dev)
        fetch_times.append(t_fetch)
    t_xla_dev = float(np.median(dev_times))
    t_xla_fetch = float(np.median(fetch_times))
    t_xla = t_xla_dev + t_xla_fetch

    # Pallas VMEM fast path, gated on exact same-run parity with the XLA
    # scan on the full workload: the headline number never comes from an
    # unvalidated kernel (ROADMAP Scale #1). TPU only — interpret mode on
    # CPU is orders of magnitude slower and validated separately in CI.
    # The headline kernel is whichever VALIDATED path is faster this run
    # (round-3 lesson: the first hardware capture showed Pallas slower than
    # the XLA scan until its layout was fixed — parity alone must not pick
    # the kernel).
    kernel = "xla_scan"
    kernel_fn = ffd_binpack_groups
    t_dev, t_fetch, t_e2e = t_xla_dev, t_xla_fetch, t_xla
    t_pallas = None
    pallas_parity = None
    if jax.default_backend() == "tpu":
        try:
            from autoscaler_tpu.ops.pallas_binpack import ffd_binpack_groups_pallas

            def run_pallas():
                return run_serial(ffd_binpack_groups_pallas)

            (p_counts, p_sched), _, _ = run_pallas()  # compile + warm
            if (p_counts == res_counts).all() and (p_sched == res_sched).all():
                pdev, pfetch = [], []
                for _ in range(3):
                    _, td, tf = run_pallas()
                    pdev.append(td)
                    pfetch.append(tf)
                p_dev = float(np.median(pdev))
                p_fetch = float(np.median(pfetch))
                t_pallas = p_dev + p_fetch
                pallas_parity = "ok"
                if t_pallas < t_xla:
                    t_dev, t_fetch, t_e2e = p_dev, p_fetch, t_pallas
                    kernel = "pallas"
                    kernel_fn = ffd_binpack_groups_pallas
            else:
                diff = int((p_sched != res_sched).sum())
                pallas_parity = (
                    f"FAILED: {int((p_counts != res_counts).sum())} group "
                    f"counts and {diff} scheduled bits diverge — using xla_scan"
                )
        except Exception as e:  # noqa: BLE001 — any kernel failure → xla path
            pallas_parity = f"pallas path error: {type(e).__name__}: {e}"

    # Pipelined throughput of the chosen (validated) kernel: the metric is
    # evals/sec, and in steady state the result fetch of estimate k rides
    # under estimate k+1's device compute — so the honest per-estimate cost
    # is wall/n over back-to-back overlapped reps, bounded below by
    # max(device, fetch). The r4 verdict asked for exactly this: tunnel out
    # of the critical path, device-complete and e2e reported separately,
    # and the committed claim the one that holds in every tunnel window.
    n_pipe = 4 if jax.default_backend() == "tpu" else 2
    t_pipe, (pp_counts, pp_sched) = run_pipelined(kernel_fn, n_pipe)
    pipe_parity = "ok"
    if not ((pp_counts == res_counts).all() and (pp_sched == res_sched).all()):
        # a diverged pipelined rep must not kill the capture — the serial
        # parity-checked measurements stand; degrade the headline to them
        pipe_parity = (
            f"FAILED: {int((pp_counts != res_counts).sum())} counts / "
            f"{int((pp_sched != res_sched).sum())} bits diverged — "
            "pipelined number discarded"
        )
        t_pipe = float("inf")
    t_tpu = min(t_e2e, t_pipe)
    headline_mode = "pipelined" if t_pipe < t_e2e else "serial_e2e"

    # One RTT of pure tunnel fence cost (4-byte fetch of a trivial
    # computation): device_complete_s above includes exactly one such
    # round-trip, so report it for the split's audit trail and take it
    # back out of the device-side speedup claim.
    rtt_samples = []
    for _ in range(3):
        t0 = time.perf_counter()
        int(jnp.sum(jnp.ones((8,), jnp.int32), dtype=jnp.int32))
        rtt_samples.append(time.perf_counter() - t0)
    fence_rtt = float(np.median(rtt_samples))
    # meaningful only when device time clearly dominates the fence RTT —
    # otherwise the subtraction is jitter and the "device" speedup would
    # be an absurd inflated claim (the failure mode this split prevents)
    t_dev_pure = t_dev - fence_rtt if t_dev > 2 * fence_rtt else None

    # Serial compiled baseline, sampled over >=32 groups (round-3 VERDICT:
    # a 3-group sample scaled x500 turned a few hundred ms of host jitter
    # into a +/-30% headline swing). Per group we keep the best of 2 reps
    # (discards scheduler preemption spikes, only ever understates the
    # baseline); across groups we report min/median/max and scale the
    # MEDIAN by G (groups are iid by construction in build_workload).
    try:
        from autoscaler_tpu.native_bridge import ffd_binpack_native as baseline_ffd

        baseline = "cpp"
    except Exception:
        baseline = "numpy"
    SAMPLE = min(32, G)
    stride = max(1, G // SAMPLE)   # spread the sample across the group range
    sample_times = []
    for g in range(0, SAMPLE * stride, stride):
        best = None
        for rep in range(2):
            t0 = time.perf_counter()
            if baseline == "cpp":
                ref_count, ref_sched = baseline_ffd(
                    pod_req, masks[g], allocs[g], MAX_NODES
                )
            else:
                from autoscaler_tpu.estimator.reference_impl import (
                    ffd_binpack_reference,
                )

                ref_count, ref_sched = ffd_binpack_reference(
                    pod_req, masks[g], allocs[g], MAX_NODES
                )
            dt = time.perf_counter() - t0
            best = dt if best is None else min(best, dt)
        sample_times.append(best)
        assert ref_count == int(res_counts[g]), (
            f"parity violation on group {g}: ref={ref_count} tpu={int(res_counts[g])}"
        )
        np.testing.assert_array_equal(res_sched[g], ref_sched)
    t_ref = float(np.median(sample_times)) * G

    value = P * G / t_tpu
    print(
        json.dumps(
            {
                # derived from the actual workload so a knob-shrunk smoke
                # run can never masquerade as the north-star capture
                "metric": f"scaleup_estimator_throughput_{P // 1000}kpods_{G}groups",
                "value": round(value, 1),
                "unit": "pod-group-evals/sec",
                "vs_baseline": round(t_ref / t_tpu, 2),
                "platform": jax.default_backend(),
                "p": P,
                "g": G,
                "device_time_s": round(t_tpu, 4),
                # the split the r4 verdict asked for: what the chip did vs
                # what the tunnel cost, plus the overlapped steady-state
                # device_complete_s includes ONE fence round-trip
                # (fence_rtt_s); vs_baseline_device backs it out
                "device_complete_s": round(t_dev, 4),
                "fence_rtt_s": round(fence_rtt, 4),
                "fetch_s": round(t_fetch, 4),
                "e2e_s": round(t_e2e, 4),
                **(
                    {"pipelined_per_dispatch_s": round(t_pipe, 4)}
                    if np.isfinite(t_pipe)
                    else {}
                ),
                "pipeline_reps": n_pipe,
                "pipe_parity": pipe_parity,
                "headline_mode": headline_mode,
                "vs_baseline_e2e": round(t_ref / t_e2e, 2),
                **(
                    {"vs_baseline_device": round(t_ref / t_dev_pure, 2)}
                    if t_dev_pure
                    else {}
                ),
                "xla_scan_time_s": round(t_xla, 4),
                **({"pallas_time_s": round(t_pallas, 4)} if t_pallas else {}),
                "kernel": kernel,
                **({"pallas_parity": pallas_parity} if pallas_parity else {}),
                "baseline_time_s": round(t_ref, 2),
                "baseline_kind": baseline,
                "baseline_sample_groups": len(sample_times),
                "baseline_group_min_s": round(float(np.min(sample_times)), 4),
                "baseline_group_median_s": round(
                    float(np.median(sample_times)), 4
                ),
                "baseline_group_max_s": round(float(np.max(sample_times)), 4),
                # BASELINE.json secondary metric: p50 latency of ONE full
                # batched estimator dispatch (all G groups in one call) —
                # this is the serial e2e (device + fetch), NOT the
                # amortized pipelined cost, so it stays comparable with
                # r3/r4 captures
                "p50_latency_s": round(t_e2e, 4),
            }
        )
    )


def _run_child(platform: str, timeout_s: int):
    """Run the measured bench in a subprocess.

    Returns (parsed_json | None, note, kind) with kind in
    {"ok", "timeout", "error"} — a deterministic child error (e.g. a parity
    assertion) must not be retried through the whole attempt chain."""
    env = dict(os.environ)
    env[_CHILD_ENV] = "1"
    if platform != "default":
        env[_PLATFORM_ENV] = platform
    if platform == "cpu":
        for k, v in _CPU_FALLBACK_SHAPE.items():
            env.setdefault(k, v)  # explicit operator knobs still win
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=env,
            capture_output=True,
            text=True,
            timeout=timeout_s,
        )
    except subprocess.TimeoutExpired:
        return None, f"timeout after {timeout_s}s (platform={platform})", "timeout"
    for line in reversed(proc.stdout.splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line), "ok", "ok"
            except json.JSONDecodeError:
                continue
    tail = (proc.stderr or proc.stdout or "").strip().splitlines()[-3:]
    note = f"rc={proc.returncode} (platform={platform}): " + " | ".join(tail)
    return None, note, "error"


def _arena_bench_main(pods: int = 20_000, ticks: int = 12) -> int:
    """``bench.py --arena [P]``: steady-state tick benchmark of the
    resident device arena (ISSUE 11 acceptance).

    Drives the REAL IncrementalPacker + DeviceArena through a cold tick
    (full pack + seed) and then steady-state ticks that each perturb a
    handful of pods, dispatching one snapshot-consuming fit kernel per
    tick. Reports the e2e-vs-device convergence the arena exists to buy
    — steady-state ``e2e_s <= 1.15 x device_complete_s`` — and validates
    the in-run perf ledger: ZERO compile-cache misses and ZERO arena
    full uploads on steady-state ticks (ticks >= 1). Exit 0 = gates met,
    1 = missed, 2 = setup failure."""
    import jax
    import jax.numpy as jnp

    from autoscaler_tpu.kube.objects import NUM_RESOURCES
    from autoscaler_tpu.ops.binpack import ffd_binpack_groups
    from autoscaler_tpu.ops.fit import fits_any_node
    from autoscaler_tpu.ops.schedule import greedy_schedule
    from autoscaler_tpu.perf import PerfObservatory, validate_records
    from autoscaler_tpu.snapshot.arena import DeviceArena
    from autoscaler_tpu.snapshot.incremental import IncrementalPacker
    from autoscaler_tpu.snapshot.tensors import bucket_size
    from autoscaler_tpu.utils.test_utils import GB, MB, build_test_node, build_test_pod

    if pods < 64:
        print(json.dumps({"metric": "arena_bench", "error": "pods < 64"}))
        return 2
    rng = np.random.default_rng(11)
    n_nodes = max(pods // 40, 64)
    PP, NN = bucket_size(pods), bucket_size(n_nodes)
    obs = PerfObservatory(cost_model=False, ring_capacity=ticks + 1)
    arena = DeviceArena(buckets=f"{PP}x{NN}x8", observatory=obs)
    t0 = time.perf_counter()
    prewarm_calls = arena.prewarm(R=NUM_RESOURCES)
    prewarm_s = time.perf_counter() - t0
    packer = IncrementalPacker(arena=arena)

    nodes = {
        f"n{j}": build_test_node(
            f"n{j}", cpu_m=int(rng.choice([4000, 8000, 16000])), mem=16 * GB
        )
        for j in range(n_nodes)
    }
    node_names = list(nodes)
    # persistent item list + assign dict, mutated in place per tick: the
    # bench measures the PACKER's steady-state cost, not harness rebuild
    items = []
    assigns = {}
    item_row = {}
    for i in range(pods):
        # ~14% stay pending — the schedule/fit/binpack kernels' live rows
        # (a scale-up-pressure tick shape: the pending scan is the
        # dominant device work, as in the real filterOutSchedulable)
        assign = "" if i % 7 == 0 else node_names[i % n_nodes]
        p = build_test_pod(
            f"p{i}", cpu_m=int(rng.integers(50, 1500)),
            mem=int(rng.integers(64, 2048)) * MB,
        )
        item_row[p.key()] = len(items)
        items.append((p.key(), p))
        if assign:
            assigns[p.key()] = assign

    node_list = list(nodes.values())
    fit_fn = jax.jit(fits_any_node)
    sched_fn = jax.jit(greedy_schedule, static_argnames=())

    def tick(tick_id: int, meta_holder: list):
        """One steady-state reconcile tick: packer delta update, then the
        tick's device work — greedy schedule of pending pods onto free
        capacity (filterOutSchedulable), pending fit, and one batched
        binpack over synthetic templates (scale-up estimation) — all
        dispatched against resident arena handles. Returns (e2e wall,
        kernel-window wall)."""
        t_start = time.perf_counter()
        obs.begin_tick(tick_id, float(tick_id))
        tensors, meta = packer.update(node_list, items, assigns)
        t_dispatch = time.perf_counter()
        leaves = tuple(jax.tree_util.tree_leaves(tensors))
        obs.note_kernel(fit_fn, leaves, {})
        sched = sched_fn(tensors, pending_slots, no_hints)
        fits = fit_fn(tensors)
        pending_req = tensors.pod_req[pending_slots_clamped]
        pack = ffd_binpack_groups(
            pending_req, tmpl_masks, tmpl_allocs,
            max_nodes=1024, node_caps=tmpl_caps,
        )
        fence = int(
            jnp.sum(sched.placed.astype(jnp.int32), dtype=jnp.int32)
            + jnp.sum(fits.astype(jnp.int32), dtype=jnp.int32)
            + jnp.sum(pack.node_count, dtype=jnp.int32)
        )
        t_end = time.perf_counter()
        obs.on_dispatch("bench_tick_kernels", t_end - t_dispatch)
        obs.note_arena(arena.take_stats())
        rec = obs.end_tick()
        meta_holder.append((rec, fence))
        return t_end - t_start, t_end - t_dispatch

    # pending slots (row indices) are stable across ticks: mutations swap
    # pod objects/requests and reshuffle assignments among the ASSIGNED
    # set, so the device-side slot vector uploads once
    first_tensors, first_meta = packer.update(node_list, items, assigns)
    pending_rows = sorted(
        first_meta.pod_index[k] for k, _p in items if k not in assigns
    )
    K = bucket_size(len(pending_rows))
    slot_arr = np.full((K,), -1, np.int32)
    slot_arr[: len(pending_rows)] = pending_rows
    pending_slots = jnp.asarray(slot_arr)
    pending_slots_clamped = jnp.asarray(np.maximum(slot_arr, 0))
    no_hints = jnp.full((K,), -1, jnp.int32)
    tmpl_allocs = jnp.asarray(
        np.tile(
            np.array([[16000, 64 * GB, 0, 0, 0, 110]], np.float32),
            (4, 1),
        )
    )
    tmpl_masks = jnp.asarray(np.ones((4, K), bool))
    tmpl_caps = jnp.asarray(np.full((4,), 1000, np.int32))

    # tick 0: cold — full pack already done above; this tick seeds the
    # arena and compiles the tick kernels (excluded from the steady-state
    # gates, like the fleet bench's warm-up round)
    recs0: list = []
    e2e0, dev0 = tick(0, recs0)
    e2e_samples, dev_samples = [], []
    keys = [k for k, _p in items]
    rec_holder: list = []
    for t in range(1, ticks):
        # steady-state churn: a handful of pods change requests, a few
        # reassign — the packer ships delta scatters, never full tensors
        for key in rng.choice(keys, size=12, replace=False):
            row = item_row[key]
            old = items[row][1]
            p = build_test_pod(
                old.name, cpu_m=int(rng.integers(50, 1500)),
                mem=int(rng.integers(64, 2048)) * MB,
            )
            items[row] = (key, p)
        for key in rng.choice(keys, size=4, replace=False):
            if key in assigns:  # keep the pending set stable
                assigns[key] = node_names[int(rng.integers(0, n_nodes))]
        e2e, dev = tick(t, rec_holder)
        e2e_samples.append(e2e)
        dev_samples.append(dev)

    def device_window(sample_idx: int) -> float:
        """Kernel window + this tick's arena scatter walls: the scatters
        ARE device work (donated in-place row updates), enqueued during
        the packer update — on a TPU they overlap host diffing, on CPU
        they execute inline; either way they belong to the device side
        of the split."""
        rec = rec_holder[sample_idx][0] or {}
        scatter = sum(
            d.get("dispatch_s", 0.0)
            for d in rec.get("dispatches", ())
            if d.get("route", "").startswith("arena_")
        )
        return dev_samples[sample_idx] + scatter

    e2e_s = float(np.median(e2e_samples))
    device_complete_s = float(
        np.median([device_window(i) for i in range(len(dev_samples))])
    )
    ratio = e2e_s / device_complete_s if device_complete_s > 0 else float("inf")
    records = obs.records()
    errors = validate_records(records)
    steady_misses = sum(
        1
        for rec in records
        if rec["tick"] >= 1
        for d in rec["dispatches"]
        if d.get("cache") == "miss"
    )
    steady_full_uploads = sum(
        rec.get("arena", {}).get("full_uploads", 0)
        for rec in records
        if rec["tick"] >= 1
    )
    delta_rows = sum(r.get("arena", {}).get("delta_rows", 0) for r in records)
    gate = (
        ratio <= 1.15
        and not errors
        and steady_misses == 0
        and steady_full_uploads == 0
    )
    print(json.dumps({
        "metric": f"arena_steady_state_{pods // 1000}kpods",
        "platform": jax.default_backend(),
        "pods": pods,
        "nodes": n_nodes,
        "ticks": ticks,
        "prewarm_calls": prewarm_calls,
        "prewarm_s": round(prewarm_s, 3),
        "cold_tick_e2e_s": round(e2e0, 4),
        "e2e_s": round(e2e_s, 4),
        "device_complete_s": round(device_complete_s, 4),
        "e2e_over_device": round(ratio, 3),
        "steady_state_compiles": steady_misses,
        "steady_state_full_uploads": steady_full_uploads,
        "delta_rows_total": int(delta_rows),
        "ledger_errors": errors[:5],
        "unit": "seconds/tick",
        "gate_e2e_within_1p15x_device": gate,
    }, indent=2, sort_keys=True))
    return 0 if gate else 1


def _probe_backend(timeout_s: int = 150) -> str | None:
    """Cheap subprocess check that the default (TPU) backend initializes at
    all, so a wedged tunnel costs one short probe instead of full bench
    timeouts. Returns None if healthy, else a note."""
    try:
        proc = subprocess.run(
            [sys.executable, "-c", "import jax; print(jax.devices())"],
            capture_output=True,
            text=True,
            timeout=timeout_s,
        )
    except subprocess.TimeoutExpired:
        return f"backend init probe hung >{timeout_s}s"
    if proc.returncode != 0:
        tail = (proc.stderr or "").strip().splitlines()[-2:]
        return f"backend init probe rc={proc.returncode}: " + " | ".join(tail)
    return None


def _perf_ledger_main(path: str) -> int:
    """``bench.py --perf-ledger <ledger.jsonl>``: validate a perf JSONL
    ledger (schema, tick monotonicity, compile-cache coherence — a
    ``cache: miss`` for an already-seen (route, shape signature) is a
    compile-on-steady-state-tick regression) and print the per-route
    compile-vs-execute report. Exit 0 = valid, 1 = regression/schema
    errors, 2 = unreadable ledger. hack/verify.sh gates on this."""
    from autoscaler_tpu.perf import load_jsonl, summarize, validate_records

    try:
        records = load_jsonl(path)
    except (OSError, ValueError) as e:
        print(json.dumps({"metric": "perf_ledger", "error": str(e)}))
        return 2
    errors = validate_records(records)
    report = {
        "metric": "perf_ledger",
        "ledger": os.path.basename(path),
        "valid": not errors,
        # bounded: a corrupted ledger must not flood CI logs
        "errors": errors[:20],
        "errors_total": len(errors),
        # summarize only what validated: aggregating a malformed ledger
        # would crash on the very shapes validation just rejected
        **(summarize(records) if not errors else {}),
    }
    print(json.dumps(report, indent=2, sort_keys=True))
    return 1 if errors else 0


def _explain_ledger_main(path: str) -> int:
    """``bench.py --explain-ledger <ledger.jsonl>``: validate a decision
    JSONL ledger (schema, tick monotonicity, closed reason vocabularies,
    and the provenance cross-checks — every executed scale-up carries its
    recorded winning score, every still-pending pod carries a reason) and
    print the aggregated reason/win report. Exit 0 = valid, 1 = schema or
    provenance errors, 2 = unreadable ledger. hack/verify.sh gates on
    this."""
    from autoscaler_tpu.explain import load_jsonl, summarize, validate_records

    try:
        records = load_jsonl(path)
    except (OSError, ValueError) as e:
        print(json.dumps({"metric": "explain_ledger", "error": str(e)}))
        return 2
    errors = validate_records(records)
    report = {
        "metric": "explain_ledger",
        "ledger": os.path.basename(path),
        "valid": not errors,
        # bounded: a corrupted ledger must not flood CI logs
        "errors": errors[:20],
        "errors_total": len(errors),
        **(summarize(records) if not errors else {}),
    }
    print(json.dumps(report, indent=2, sort_keys=True))
    return 1 if errors else 0


def _slo_ledger_main(path: str) -> int:
    """``bench.py --slo-ledger <ledger.jsonl>``: validate an SLO window
    JSONL ledger (schema, window monotonicity — ticks strictly increase,
    now_ts never goes backwards, lifetime event counters never decrease —
    and the burn-rate arithmetic cross-check: error_rate == bad/total and
    burn_rate == error_rate/(1 − target) in every window, with the
    alerting bit agreeing with the multiwindow predicate) and print the
    aggregated per-SLO report. Exit 0 = valid, 1 = schema/arithmetic
    errors, 2 = unreadable ledger. hack/verify.sh gates on this."""
    from autoscaler_tpu.slo import load_jsonl, summarize, validate_records

    try:
        records = load_jsonl(path)
    except (OSError, ValueError) as e:
        print(json.dumps({"metric": "slo_ledger", "error": str(e)}))
        return 2
    errors = validate_records(records)
    report = {
        "metric": "slo_ledger",
        "ledger": os.path.basename(path),
        "valid": not errors,
        # bounded: a corrupted ledger must not flood CI logs
        "errors": errors[:20],
        "errors_total": len(errors),
        **(summarize(records) if not errors else {}),
    }
    print(json.dumps(report, indent=2, sort_keys=True))
    return 1 if errors else 0


def _gym_ledger_main(path: str) -> int:
    """``bench.py --gym-ledger <ledger.jsonl>``: validate a tuning JSONL
    ledger (schema, generation monotonicity, candidate/score shapes, the
    gen-0 all-defaults baseline, and the improvement invariant —
    best-so-far score never decreases) and print the aggregated report
    (winner, trajectory, improvement over the baseline — the number
    hack/verify.sh gates on). Exit 0 = valid, 1 = schema/invariant
    errors, 2 = unreadable ledger."""
    from autoscaler_tpu.gym import load_jsonl, summarize, validate_records

    try:
        records = load_jsonl(path)
    except (OSError, ValueError) as e:
        print(json.dumps({"metric": "gym_ledger", "error": str(e)}))
        return 2
    errors = validate_records(records)
    report = {
        "metric": "gym_ledger",
        "ledger": os.path.basename(path),
        "valid": not errors,
        # bounded: a corrupted ledger must not flood CI logs
        "errors": errors[:20],
        "errors_total": len(errors),
        **(summarize(records) if not errors else {}),
    }
    print(json.dumps(report, indent=2, sort_keys=True))
    return 1 if errors else 0


def _fleet_ledger_main(path: str) -> int:
    """``bench.py --fleet-ledger <ledger.jsonl>``: validate a fleet
    round JSONL ledger (schema, round monotonicity, tenant shares and
    outcome accounting — admitted + shed splits must reconcile with the
    totals) and print the aggregated report. Exit 0 = valid, 1 =
    schema/accounting errors, 2 = unreadable ledger. hack/verify.sh
    gates on this."""
    from autoscaler_tpu.fleet import (
        summarize_fleet_ledger,
        validate_fleet_records,
    )
    from autoscaler_tpu.fleet.ledger import load_jsonl

    try:
        records = load_jsonl(path)
    except (OSError, ValueError) as e:
        print(json.dumps({"metric": "fleet_ledger", "error": str(e)}))
        return 2
    errors = validate_fleet_records(records)
    report = {
        "metric": "fleet_ledger",
        "ledger": os.path.basename(path),
        "valid": not errors,
        # bounded: a corrupted ledger must not flood CI logs
        "errors": errors[:20],
        "errors_total": len(errors),
        **(summarize_fleet_ledger(records) if not errors else {}),
    }
    print(json.dumps(report, indent=2, sort_keys=True))
    return 1 if errors else 0


def _journal_ledger_main(path: str) -> int:
    """``bench.py --journal-ledger <journal.jsonl>``: validate a flight
    journal (schema, strict tick monotonicity, keyframe-first ordering,
    closed keyframe-reason vocabulary, fingerprint/hash presence) and
    prove every journaled tick reconstructs — a keyframe+delta chain that
    validates but cannot be replayed into state is exactly the corruption
    the typed reader errors exist to catch. Exit 0 = valid, 1 = schema or
    reconstruction errors, 2 = unreadable journal. hack/verify.sh gates
    on this."""
    from autoscaler_tpu.journal import (
        JournalError,
        JournalReader,
        load_jsonl,
        summarize,
        validate_records,
    )

    try:
        records = load_jsonl(path)
    except (OSError, ValueError) as e:
        print(json.dumps({"metric": "journal_ledger", "error": str(e)}))
        return 2
    errors = validate_records(records)
    reconstructed = 0
    if not errors:
        try:
            reader = JournalReader(records)
            for tick in reader.ticks():
                reader.reconstruct(tick)
                reconstructed += 1
        except JournalError as e:
            errors = [f"{type(e).__name__}: {e}"]
    report = {
        "metric": "journal_ledger",
        "ledger": os.path.basename(path),
        "valid": not errors,
        # bounded: a corrupted journal must not flood CI logs
        "errors": errors[:20],
        "errors_total": len(errors),
        "reconstructed": reconstructed,
        **(summarize(records) if not errors else {}),
    }
    print(json.dumps(report, indent=2, sort_keys=True))
    return 1 if errors else 0


def _last_tpu_cache_path(repo_dir: str) -> str | None:
    """Resolve the persisted TPU capture for reading: the current home
    (``benchmarks/out/``) first, then the legacy repo-root location older
    rounds wrote to. Returns None when neither exists."""
    for cand in (
        os.path.join(repo_dir, "benchmarks", "out", "bench_last_tpu.json"),
        os.path.join(repo_dir, "bench_last_tpu.json"),
    ):
        if os.path.exists(cand):
            return cand
    return None


# committed benchmark trajectory: BENCH_r*.json at the repo root, one per
# recorded round, each carrying the round's parsed headline record (and,
# on CPU-fallback rounds, the last real TPU capture nested inside it)
_TREND_GLOB = "BENCH_r*.json"
# a live capture within 10% of the committed trajectory is noise; below
# that it is a throughput regression the gate fails on
_TREND_TOLERANCE = 0.9


def _trend_points(repo_dir: str):
    """Yield (round_n, record) benchmark points from every committed
    BENCH_r*.json — the round's own parsed record plus any nested
    last_tpu_capture (a stale-but-real TPU number a CPU fallback round
    carried forward). Unreadable rounds are skipped: the gate judges the
    trajectory that exists, it does not fail on archive rot."""
    import glob

    for p in sorted(glob.glob(os.path.join(repo_dir, _TREND_GLOB))):
        try:
            with open(p) as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        if not isinstance(doc, dict):
            continue
        n = doc.get("n")
        parsed = doc.get("parsed")
        if not isinstance(n, int) or not isinstance(parsed, dict):
            continue
        if isinstance(parsed.get("value"), (int, float)):
            yield n, parsed
        cap = parsed.get("last_tpu_capture")
        if isinstance(cap, dict) and isinstance(cap.get("value"), (int, float)):
            yield n, cap


def _trend_main() -> int:
    """``bench.py --trend``: gate the live TPU capture against the
    committed BENCH_r*.json trajectory. For each (metric, platform)
    config the newest committed round wins; a live capture below
    ``_TREND_TOLERANCE`` of that committed value is a throughput
    regression and fails the gate. No live capture (CPU-only host that
    never ran the TPU bench) exits 0 — the gate judges regressions, it
    does not demand a TPU. Exit 0 = on-trend or no evidence, 1 =
    regression, 2 = unreadable live capture."""
    repo_dir = os.path.dirname(os.path.abspath(__file__))
    cache = _last_tpu_cache_path(repo_dir)
    if cache is None:
        print(json.dumps({
            "metric": "bench_trend",
            "status": "no live capture — nothing to gate",
        }, indent=2, sort_keys=True))
        return 0
    try:
        with open(cache) as f:
            live = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        print(json.dumps({"metric": "bench_trend", "error": str(e)}))
        return 2
    if not isinstance(live, dict) or not isinstance(
        live.get("value"), (int, float)
    ):
        print(json.dumps({
            "metric": "bench_trend",
            "error": f"live capture {os.path.basename(cache)} has no value",
        }))
        return 2

    # newest committed round wins per (metric, platform) config
    committed = {}
    for n, rec in _trend_points(repo_dir):
        key = (rec.get("metric"), rec.get("platform"))
        prev = committed.get(key)
        if prev is None or n >= prev[0]:
            committed[key] = (n, rec["value"])
    key = (live.get("metric"), live.get("platform"))
    baseline = committed.get(key)
    report = {
        "metric": "bench_trend",
        "live_metric": live.get("metric"),
        "live_platform": live.get("platform"),
        "live_value": live["value"],
        "rounds": len(committed),
    }
    if baseline is None:
        # a brand-new config has no trajectory yet — it becomes one when
        # its round is committed
        report["status"] = "no committed round matches this config"
        print(json.dumps(report, indent=2, sort_keys=True))
        return 0
    floor = baseline[1] * _TREND_TOLERANCE
    report.update({
        "committed_round": baseline[0],
        "committed_value": baseline[1],
        "floor": floor,
        "ok": live["value"] >= floor,
    })
    print(json.dumps(report, indent=2, sort_keys=True))
    if live["value"] < floor:
        print(
            f"bench trend: {live.get('metric')} regressed to "
            f"{live['value']:.1f} < {floor:.1f} "
            f"(90% of round {baseline[0]}'s {baseline[1]:.1f})",
            file=sys.stderr,
        )
        return 1
    return 0


def _fleet_bench_main(tenants: int = 8) -> int:
    """``bench.py --fleet [K]``: the BASELINE config-5 mode — K simulated
    tenants through the coalescing fleet path vs. K sequential per-tenant
    dispatches, CPU-mesh sized. Reports throughput both ways; the gate is
    batched >= 2x sequential at >= 4 tenants (ISSUE 8 acceptance). Exit
    0 = gate met, 1 = missed, 2 = setup failure. hack/verify.sh runs it."""
    import statistics

    import numpy as np

    from autoscaler_tpu.fleet import FleetCoalescer, FleetRequest
    from autoscaler_tpu.parallel.mesh import fleet_solo_estimate, make_mesh

    if tenants < 1:
        print(json.dumps({"metric": "fleet_bench", "error": "tenants < 1"}))
        return 2
    # bucket-exact shapes (R=8 fills the bucket's resource axis) so batched
    # and sequential pay identical per-tenant arithmetic and the measured
    # difference is dispatch amortization — the thing coalescing exists to
    # buy: N tenants, one kernel launch instead of N
    P, G, R, K = 32, 8, 8, tenants
    rng = np.random.default_rng(42)
    requests = [
        FleetRequest(
            tenant_id=f"bench-{t}",
            pod_req=rng.integers(1, 100, (P, R)).astype(np.float32),
            pod_masks=rng.random((G, P)) > 0.2,
            template_allocs=rng.integers(100, 500, (G, R)).astype(np.float32),
            node_caps=rng.integers(1, 16, G).astype(np.int32),
            max_nodes=P,
        )
        for t in range(K)
    ]
    co = FleetCoalescer(
        buckets=f"{P}x{G}x{R}", batch_scenarios=max(K, 1), mesh=make_mesh()
    )
    co.prewarm()

    def run_batched() -> float:
        t0 = time.perf_counter()
        tickets = [co.submit(r) for r in requests]
        co.flush()
        for tk in tickets:
            tk.result(timeout=0.0)
        return time.perf_counter() - t0

    def run_sequential() -> float:
        t0 = time.perf_counter()
        for r in requests:
            fleet_solo_estimate(
                r.pod_req, r.pod_masks, r.template_allocs, r.node_caps,
                r.max_nodes,
            )
        return time.perf_counter() - t0

    run_sequential()  # warm the solo kernel's compile cache
    run_batched()
    reps = 15
    seq = statistics.median(run_sequential() for _ in range(reps))
    bat = statistics.median(run_batched() for _ in range(reps))
    seq_tput = K / seq if seq > 0 else 0.0
    bat_tput = K / bat if bat > 0 else 0.0
    speedup = bat_tput / seq_tput if seq_tput > 0 else 0.0
    gate = K >= 4 and speedup >= 2.0
    import jax

    print(json.dumps({
        "metric": "fleet_batched_vs_sequential",
        "platform": jax.default_backend(),
        "tenants": K,
        "shape": {"pods": P, "groups": G, "resources": R},
        "sequential_req_per_s": round(seq_tput, 1),
        "batched_req_per_s": round(bat_tput, 1),
        "sequential_round_s": round(seq, 5),
        "batched_round_s": round(bat, 5),
        "speedup": round(speedup, 2),
        "unit": "tenant-requests/sec",
        "gate_2x_at_4_tenants": gate,
    }, indent=2, sort_keys=True))
    return 0 if gate else 1


def _fleet_overload_bench_main() -> int:
    """``bench.py --fleet-overload``: the admission-control contrast gate
    (ISSUE 14 acceptance). A sim-clock overload drill — offered load 3x
    service capacity — run three ways:

    - unloaded (offered == capacity): the baseline p99 e2e;
    - admission ON (queue bound = capacity): admitted-request p99 e2e must
      stay within 2x the unloaded figure while the shed rate absorbs the
      excess, and the queue stays bounded;
    - admission OFF: queue depth and e2e grow monotonically — the failure
      mode the armor exists to prevent.

    Everything runs on an injected clock (ticket e2e = sim-clock stamps;
    service modeled as ``flush(limit=capacity)`` per round), so the gate
    is deterministic — no wall-clock flake. Exit 0 = gate met, 1 =
    missed."""
    import numpy as np

    from autoscaler_tpu.fleet import (
        FleetCoalescer,
        FleetOverloadError,
        FleetRequest,
    )
    from autoscaler_tpu.parallel.mesh import make_mesh

    ROUNDS, OFFERED, CAPACITY, ROUND_S = 20, 24, 8, 1.0
    P, G, R = 12, 3, 6

    def request(round_: int, i: int, deadline: float) -> "FleetRequest":
        rng = np.random.default_rng((97, round_, i))
        return FleetRequest(
            tenant_id=f"t{i % 6}",
            pod_req=rng.integers(1, 80, (P, R)).astype(np.float32),
            pod_masks=rng.random((G, P)) > 0.25,
            template_allocs=rng.integers(80, 400, (G, R)).astype(np.float32),
            node_caps=rng.integers(1, 10, G).astype(np.int32),
            max_nodes=P,
            deadline_s=deadline or None,
        )

    def run(offered: int, max_queue_depth: int, deadline: float):
        sim = {"t": 0.0}
        co = FleetCoalescer(
            buckets="16x4x8", batch_scenarios=8, mesh=make_mesh(),
            clock=lambda: sim["t"], max_queue_depth=max_queue_depth,
        )
        tickets, shed, depths = [], 0, []
        for round_ in range(ROUNDS):
            for i in range(offered):
                try:
                    tickets.append(co.submit(request(round_, i, deadline)))
                except FleetOverloadError:
                    shed += 1
            depths.append(co.queue_depth())
            sim["t"] += ROUND_S
            co.flush(limit=CAPACITY)
        # drain the tail so EVERY ticket terminates (the zero-hang
        # discipline holds even for the unarmored baseline)
        while co.queue_depth():
            sim["t"] += ROUND_S
            co.flush(limit=CAPACITY)
        e2e, expired = [], 0
        for tk in tickets:
            try:
                tk.result(timeout=0.0)
                e2e.append(tk.t_resolve - tk.t_submit)
            except Exception:  # noqa: BLE001 — typed deadline sheds
                expired += 1
        assert all(tk.done() for tk in tickets), "hung tickets in bench"
        e2e.sort()
        p99 = e2e[max(0, int(0.99 * len(e2e)) - 1)] if e2e else 0.0
        return {
            "served": len(e2e),
            "shed": shed,
            "expired": expired,
            "p99_e2e_s": round(p99, 4),
            "queue_depths": depths,
        }

    unloaded = run(CAPACITY, 0, 0.0)
    armored = run(OFFERED, CAPACITY, 4.0)
    baseline = run(OFFERED, 0, 0.0)
    depths = baseline["queue_depths"]
    baseline_monotonic = all(b > a for a, b in zip(depths, depths[1:]))
    armored_bounded = max(armored["queue_depths"]) <= CAPACITY
    excess = (OFFERED - CAPACITY) * ROUNDS
    gate = (
        armored["p99_e2e_s"] <= 2.0 * unloaded["p99_e2e_s"] + 1e-9
        and armored["shed"] + armored["expired"] >= excess * 0.5
        and armored_bounded
        and baseline_monotonic
        and baseline["p99_e2e_s"] > 2.0 * unloaded["p99_e2e_s"]
    )
    import jax

    print(json.dumps({
        "metric": "fleet_overload_contrast",
        "platform": jax.default_backend(),
        "rounds": ROUNDS,
        "offered_per_round": OFFERED,
        "capacity_per_round": CAPACITY,
        "unloaded_p99_e2e_s": unloaded["p99_e2e_s"],
        "admission_on": {
            "p99_e2e_s": armored["p99_e2e_s"],
            "served": armored["served"],
            "shed": armored["shed"],
            "expired": armored["expired"],
            "max_queue_depth_seen": max(armored["queue_depths"]),
        },
        "admission_off": {
            "p99_e2e_s": baseline["p99_e2e_s"],
            "served": baseline["served"],
            "queue_depth_monotonic": baseline_monotonic,
            "final_queue_depth": depths[-1],
        },
        "unit": "sim-clock seconds",
        "gate_p99_within_2x_and_contrast": gate,
    }, indent=2, sort_keys=True))
    return 0 if gate else 1


def _fleet_ha_bench_main() -> int:
    """``bench.py --fleet-ha``: the balanced-vs-static routing contrast
    gate (ISSUE 15 acceptance). Three replica endpoints, one flapping
    (down on alternating windows, slow when up); the same deterministic
    request stream runs two client models:

    - **balanced** — fleet/balance.EndpointBalancer picks (health-weighted
      P2C + breaker ejection): after a few failures the flapper is starved
      of first attempts, so the tail stops paying its failover/slow cost;
    - **static** — the PR-14 rotation (round-robin first attempts): 1/3 of
      first attempts keep landing on the flapper forever.

    Everything runs on an injected sim clock and a seeded rng — no wall
    time, no flake. The gate: balanced p99 strictly beats static p99 AND
    balanced deadline-misses (the shed analog) <= static. Exit 0 = gate
    met, 1 = missed."""
    import numpy as np

    from autoscaler_tpu.fleet.balance import EndpointBalancer

    ENDPOINTS = ["replica-a", "replica-b", "replica-c"]
    FLAKY = "replica-c"
    N = 4000
    HEALTHY_S = 0.010        # healthy endpoint service time
    FLAKY_UP_S = 0.250       # the flapper is SLOW even when it answers
    FAILOVER_PAUSE_S = 0.050  # per failed attempt (connect fail + backoff)
    DEADLINE_S = 0.200       # per-request budget; over = a lost request
    FLAP_PERIOD = 50         # requests per up/down half-window

    def flap_down(k: int) -> bool:
        return (k // FLAP_PERIOD) % 2 == 0

    def run(policy: str):
        sim = {"t": 0.0}
        rng = np.random.default_rng(1234)
        bal = EndpointBalancer(
            ENDPOINTS, clock=lambda: sim["t"],
            rng=lambda: float(rng.random()), eject_cooldown_s=10.0,
        )
        latencies, misses, first_to_flaky = [], 0, 0
        for k in range(N):
            cost, served = 0.0, False
            tried = []
            for attempt in range(len(ENDPOINTS)):
                if policy == "balanced":
                    ep = bal.pick(exclude=tried)
                    if ep is None:
                        break
                else:
                    ep = ENDPOINTS[(k + attempt) % len(ENDPOINTS)]
                if attempt == 0 and ep == FLAKY:
                    first_to_flaky += 1
                if ep == FLAKY and flap_down(k):
                    cost += FAILOVER_PAUSE_S
                    if policy == "balanced":
                        bal.record_failure(ep, unavailable=True)
                    tried.append(ep)
                    continue
                cost += FLAKY_UP_S if ep == FLAKY else HEALTHY_S
                if policy == "balanced":
                    bal.record_success(
                        ep, FLAKY_UP_S if ep == FLAKY else HEALTHY_S
                    )
                served = True
                break
            sim["t"] += cost
            if not served or cost > DEADLINE_S:
                misses += 1
            if served:
                latencies.append(cost)
        latencies.sort()
        p99 = (
            latencies[max(0, int(0.99 * len(latencies)) - 1)]
            if latencies else float("inf")
        )
        p50 = latencies[len(latencies) // 2] if latencies else float("inf")
        return {
            "p50_s": round(p50, 4),
            "p99_s": round(p99, 4),
            "deadline_misses": misses,
            "first_attempts_to_flapper": first_to_flaky,
        }

    balanced = run("balanced")
    static = run("static")
    gate = (
        balanced["p99_s"] < static["p99_s"]
        and balanced["deadline_misses"] <= static["deadline_misses"]
        and balanced["first_attempts_to_flapper"]
        < static["first_attempts_to_flapper"]
    )
    print(json.dumps({
        "metric": "fleet_ha_balanced_vs_static",
        "requests": N,
        "endpoints": len(ENDPOINTS),
        "deadline_s": DEADLINE_S,
        "balanced": balanced,
        "static": static,
        "unit": "sim-clock seconds",
        "gate_balanced_beats_static_p99_and_sheds": gate,
    }, indent=2, sort_keys=True))
    return 0 if gate else 1


def _preempt_bench_main(trials: int = 24) -> int:
    """``bench.py --preempt [T]``: preemption contrast gate (ISSUE 16).

    T randomized storm worlds (full-ish clusters of low-priority residents
    plus a high-priority pending wave) through the eviction-capable packer
    twice: preemption-AWARE (priority channels live) vs priority-BLIND
    (flat priorities — nothing may evict, the pre-PR packing semantics).
    Gates:

    - oracle agreement: the device kernel's full decision triple —
      admissions, placements AND the eviction set with each victim's
      evictor — matches the serial numpy oracle on every world;
    - dominance: aware admits >= blind on every world and strictly more
      in aggregate (the storm shapes guarantee eviction helps);
    - throughput envelope: steady-state aware dispatch stays within 25x
      the blind dispatch median (same kernel, same shapes — the priority
      channels must not blow up the scan) and under 2s absolute.

    Exit 0 = gates met, 1 = missed, 2 = setup failure. hack/verify.sh
    runs it with a small T."""
    import jax

    from autoscaler_tpu.estimator.reference_impl import (
        ffd_binpack_preempt_reference,
    )
    from autoscaler_tpu.ops.preempt import ffd_binpack_preempt

    rng = np.random.default_rng(1601)
    P, N, R = 96, 12, 2
    aware_admits, blind_admits, evictions = [], [], 0
    aware_walls, blind_walls = [], []
    mismatches = []
    for t in range(trials):
        node_alloc = np.zeros((N, R), np.float32)
        node_alloc[:, 0] = rng.choice([4000.0, 8000.0], size=N)
        node_alloc[:, 1] = 16384.0
        node_valid = np.ones((N,), bool)
        pod_req = np.zeros((P, R), np.float32)
        pod_valid = np.zeros((P,), bool)
        pod_node = np.full((P,), -1, np.int32)
        pod_prio = np.zeros((P,), np.int32)
        can_preempt = np.zeros((P,), bool)
        evictable = np.zeros((P,), bool)
        node_used = np.zeros((N, R), np.float32)
        # residents: low-priority filler packed ~85% full round-robin
        i = 0
        for n in range(N):
            while node_used[n, 0] < 0.85 * node_alloc[n, 0] and i < P - 24:
                req = np.array(
                    [float(rng.integers(300, 1200)),
                     float(rng.integers(256, 1024))], np.float32,
                )
                if node_used[n, 0] + req[0] > node_alloc[n, 0]:
                    break
                pod_req[i] = req
                pod_valid[i] = True
                pod_node[i] = n
                pod_prio[i] = int(rng.integers(0, 20))
                evictable[i] = rng.random() > 0.1
                node_used[n] += req
                i += 1
        # pending wave: high-priority, a few pinned preemptionPolicy=Never
        n_pending = 24
        for j in range(i, i + n_pending):
            pod_req[j] = (
                float(rng.integers(800, 2500)),
                float(rng.integers(512, 2048)),
            )
            pod_valid[j] = True
            pod_prio[j] = int(rng.integers(50, 200))
            can_preempt[j] = rng.random() > 0.2
        sched_mask = np.ones((P, N), bool)
        flat_prio = np.zeros((P,), np.int32)
        no_preempt = np.zeros((P,), bool)

        def dispatch(prio, preempt):
            t0 = time.perf_counter()
            out = ffd_binpack_preempt(
                pod_req, pod_valid, pod_node, prio, preempt, evictable,
                node_alloc, node_used, node_valid, sched_mask,
            )
            res = tuple(np.asarray(x) for x in out)
            return res, time.perf_counter() - t0

        (a_sched, a_place, a_vict), a_wall = dispatch(pod_prio, can_preempt)
        (b_sched, _b_place, b_vict), b_wall = dispatch(flat_prio, no_preempt)
        if t > 0:  # skip the compile tick in the envelope
            aware_walls.append(a_wall)
            blind_walls.append(b_wall)
        r_sched, r_place, r_vict = ffd_binpack_preempt_reference(
            pod_req, pod_valid, pod_node, pod_prio, can_preempt, evictable,
            node_alloc, node_used, node_valid, sched_mask,
        )
        if not (
            np.array_equal(a_sched, r_sched)
            and np.array_equal(a_place, r_place)
            and np.array_equal(a_vict, r_vict)
        ):
            mismatches.append(t)
        pending = pod_valid & (pod_node < 0)
        aware_admits.append(int(np.sum(a_sched & pending)))
        blind_admits.append(int(np.sum(b_sched & pending)))
        evictions += int(np.sum(a_vict >= 0))
        if int(np.sum(b_vict >= 0)) != 0:
            mismatches.append(("blind-evicted", t))

    med = lambda xs: sorted(xs)[len(xs) // 2] if xs else 0.0
    aware_med, blind_med = med(aware_walls), med(blind_walls)
    dominated = all(a >= b for a, b in zip(aware_admits, blind_admits))
    gained = sum(aware_admits) > sum(blind_admits)
    envelope_ok = aware_med <= max(25.0 * blind_med, 1e-4) and aware_med < 2.0
    ok = not mismatches and dominated and gained and evictions > 0 and envelope_ok
    report = {
        "metric": "preempt_bench",
        "platform": jax.default_backend(),
        "trials": trials,
        "pods": P,
        "nodes": N,
        "oracle_agreement": not mismatches,
        "mismatched_trials": mismatches[:10],
        "aware_admitted": sum(aware_admits),
        "blind_admitted": sum(blind_admits),
        "evictions": evictions,
        "dominates_blind": dominated,
        "strictly_gains": gained,
        "aware_dispatch_median_s": round(aware_med, 5),
        "blind_dispatch_median_s": round(blind_med, 5),
        "envelope_ok": envelope_ok,
        "gates_met": ok,
    }
    print(json.dumps(report, indent=2, sort_keys=True))
    return 0 if ok else 1


def main():
    if "--fleet-ha" in sys.argv:
        sys.exit(_fleet_ha_bench_main())
    if "--preempt" in sys.argv:
        idx = sys.argv.index("--preempt")
        arg = sys.argv[idx + 1] if idx + 1 < len(sys.argv) else ""
        sys.exit(_preempt_bench_main(int(arg) if arg.isdigit() else 24))
    if "--arena" in sys.argv:
        idx = sys.argv.index("--arena")
        arg = sys.argv[idx + 1] if idx + 1 < len(sys.argv) else ""
        pods = int(arg) if arg.isdigit() else 20_000
        sys.exit(_arena_bench_main(pods))
    if "--fleet-overload" in sys.argv:
        sys.exit(_fleet_overload_bench_main())
    if "--fleet" in sys.argv:
        idx = sys.argv.index("--fleet")
        arg = sys.argv[idx + 1] if idx + 1 < len(sys.argv) else ""
        tenants = int(arg) if arg.isdigit() else 8
        sys.exit(_fleet_bench_main(tenants))
    if "--perf-ledger" in sys.argv:
        idx = sys.argv.index("--perf-ledger")
        if idx + 1 >= len(sys.argv):
            print("usage: bench.py --perf-ledger <ledger.jsonl>", file=sys.stderr)
            sys.exit(2)
        sys.exit(_perf_ledger_main(sys.argv[idx + 1]))
    if "--explain-ledger" in sys.argv:
        idx = sys.argv.index("--explain-ledger")
        if idx + 1 >= len(sys.argv):
            print("usage: bench.py --explain-ledger <ledger.jsonl>",
                  file=sys.stderr)
            sys.exit(2)
        sys.exit(_explain_ledger_main(sys.argv[idx + 1]))
    if "--slo-ledger" in sys.argv:
        idx = sys.argv.index("--slo-ledger")
        if idx + 1 >= len(sys.argv):
            print("usage: bench.py --slo-ledger <ledger.jsonl>",
                  file=sys.stderr)
            sys.exit(2)
        sys.exit(_slo_ledger_main(sys.argv[idx + 1]))
    if "--gym-ledger" in sys.argv:
        idx = sys.argv.index("--gym-ledger")
        if idx + 1 >= len(sys.argv):
            print("usage: bench.py --gym-ledger <ledger.jsonl>",
                  file=sys.stderr)
            sys.exit(2)
        sys.exit(_gym_ledger_main(sys.argv[idx + 1]))
    if "--journal-ledger" in sys.argv:
        idx = sys.argv.index("--journal-ledger")
        if idx + 1 >= len(sys.argv):
            print("usage: bench.py --journal-ledger <journal.jsonl>",
                  file=sys.stderr)
            sys.exit(2)
        sys.exit(_journal_ledger_main(sys.argv[idx + 1]))
    if "--fleet-ledger" in sys.argv:
        idx = sys.argv.index("--fleet-ledger")
        if idx + 1 >= len(sys.argv):
            print("usage: bench.py --fleet-ledger <ledger.jsonl>",
                  file=sys.stderr)
            sys.exit(2)
        sys.exit(_fleet_ledger_main(sys.argv[idx + 1]))
    if "--trend" in sys.argv:
        sys.exit(_trend_main())
    if os.environ.get(_CHILD_ENV) == "1":
        _bench_main()
        return
    # --probe-timeout SECS: total budget for the TPU backend-init probe
    # chain. The default chain (3 probes of up to 150s with 45s/90s
    # backoffs) burns 200s+ before a CPU fallback even starts — on a
    # known-CPU host, `--probe-timeout 10` makes the fallback decision in
    # seconds instead (BENCH_r05 fallback_reason lesson).
    probe_budget = None
    if "--probe-timeout" in sys.argv:
        idx = sys.argv.index("--probe-timeout")
        arg = sys.argv[idx + 1] if idx + 1 < len(sys.argv) else ""
        try:
            probe_budget = max(float(arg), 1.0)
        except ValueError:
            print("usage: bench.py --probe-timeout <seconds>", file=sys.stderr)
            sys.exit(2)
    notes = []
    skip = set()
    for platform, timeout_s in _ATTEMPTS:
        if platform in skip:
            continue
        if platform == "default":
            # Wedge-resilient probe (r4 verdict #1a): the axon tunnel can
            # hang backend init transiently, and the hang sometimes clears
            # within minutes. Each probe is a bounded child (subprocess.run
            # kills it on timeout); between failures we back off and retry
            # rather than writing the TPU round off on the first hang —
            # all capped by the --probe-timeout budget when one is given.
            deadline = (
                time.monotonic() + probe_budget
                if probe_budget is not None else None
            )
            note = None
            probes = 0
            for backoff_s in (0, 45, 90):
                if backoff_s:
                    if (
                        deadline is not None
                        and time.monotonic() + backoff_s >= deadline
                    ):
                        break  # budget can't cover the backoff + a probe
                    print(
                        f"bench: retrying backend probe in {backoff_s}s",
                        file=sys.stderr,
                    )
                    time.sleep(backoff_s)
                probe_timeout = 150
                if deadline is not None:
                    probe_timeout = max(
                        min(150.0, deadline - time.monotonic()), 1.0
                    )
                note = _probe_backend(timeout_s=probe_timeout)
                probes += 1
                if note is None:
                    break
                print(f"bench: {note}", file=sys.stderr)
                if deadline is not None and time.monotonic() >= deadline:
                    break  # probe budget exhausted — fall back NOW
            if note is not None:
                notes.append(note + f" ({probes} probes)")
                skip.add(platform)
                print(f"bench: {note} — falling back", file=sys.stderr)
                continue
        result, note, kind = _run_child(platform, timeout_s)
        if result is not None:
            if notes and result.get("platform") != "tpu":
                # a fallback capture must say WHY the TPU attempt failed
                result["fallback_reason"] = "; ".join(notes)
            # Persist TPU captures; on a CPU fallback attach the last real
            # TPU capture (clearly labeled, with its own timestamp) so a
            # wedged tunnel degrades the round's evidence instead of
            # erasing it. The headline value/vs_baseline stay the honest
            # numbers of THIS run's platform.
            repo_dir = os.path.dirname(os.path.abspath(__file__))
            # captures live under benchmarks/out/ (gitignored); reads fall
            # back to the legacy repo-root file older rounds left behind
            out_dir = os.path.join(repo_dir, "benchmarks", "out")
            cache = os.path.join(out_dir, "bench_last_tpu.json")
            if result.get("platform") == "tpu":
                try:
                    os.makedirs(out_dir, exist_ok=True)
                    with open(cache, "w") as f:
                        json.dump({**result, "captured_at": time.time()}, f)
                except OSError:
                    pass
            elif (cache := _last_tpu_cache_path(repo_dir)) is not None:
                try:
                    with open(cache) as f:
                        cap = json.load(f)
                    if isinstance(cap, dict):
                        cap["age_s"] = round(
                            time.time() - cap.get("captured_at", 0)
                        )
                        result["last_tpu_capture"] = cap
                except (OSError, json.JSONDecodeError):
                    pass
            print(json.dumps(result))
            return
        notes.append(note)
        print(f"bench attempt failed: {note}", file=sys.stderr)
        if kind == "error":
            # deterministic failure — retrying the same platform is waste
            skip.add(platform)
    # Total failure still yields one parseable JSON line for the driver.
    print(
        json.dumps(
            {
                "metric": "scaleup_estimator_throughput_100kpods_500groups",
                "value": 0,
                "unit": "pod-group-evals/sec",
                "vs_baseline": 0,
                "error": "; ".join(notes),
            }
        )
    )
    sys.exit(1)


if __name__ == "__main__":
    main()
