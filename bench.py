"""Headline benchmark: batched TPU scale-up estimation at the north-star
scale vs a compiled serial baseline.

Workload: the BASELINE.json north-star — 100k pending heterogeneous pods
(cpu/mem/GPU requests) x 500 node groups, max 1000 nodes per group
(the reference's --max-nodes-per-scaleup default, main.go:215), estimated in
ONE batched device dispatch (ops/binpack.ffd_binpack_groups).

Baseline: the C++ serial FFD (native/ffd_serial.cpp), which mirrors the Go
BinpackingNodeEstimator's algorithm (binpacking_estimator.go:65-141) as the
reference's serial per-group loop would run it — a deliberately STRONG
stand-in: it strips the scheduler-framework plugin overhead the real
reference pays per (pod, node) check (its binpacking budget is 10s/group,
main.go:216; the compiled loop here does ~0.1s/group). Sampled on 3 groups
and scaled linearly in group count (groups are independent and identically
distributed). Falls back to the numpy oracle if no C++ toolchain exists.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
vs_baseline = serial_baseline_time / tpu_time on identical work (single
chip; the group axis additionally shards across chips via shard_map —
see __graft_entry__.dryrun_multichip).
"""
from __future__ import annotations

import json
import time

import numpy as np


def build_workload(P=100_000, G=500, seed=0):
    from autoscaler_tpu.kube.objects import CPU, GPU, MEMORY, PODS

    rng = np.random.default_rng(seed)
    pod_req = np.zeros((P, 6), np.float32)
    pod_req[:, CPU] = rng.integers(50, 2000, P)
    pod_req[:, MEMORY] = rng.integers(64, 8192, P)
    gpu_pods = rng.random(P) < 0.1
    pod_req[gpu_pods, GPU] = rng.integers(1, 4, int(gpu_pods.sum()))
    pod_req[:, PODS] = 1

    allocs = np.zeros((G, 6), np.float32)
    allocs[:, CPU] = rng.choice([4000, 8000, 16000, 32000], G)
    allocs[:, MEMORY] = rng.choice([8192, 16384, 32768, 65536], G)
    gpu_groups = rng.random(G) < 0.2
    allocs[gpu_groups, GPU] = 8
    allocs[:, PODS] = 110

    # simulated non-resource predicate outcomes (taints/selectors)
    masks = rng.random((G, P)) > 0.05
    # gpu pods only schedulable on gpu groups
    masks[np.ix_(~gpu_groups, gpu_pods)] = False
    caps = np.full(G, 1000, np.int32)
    return pod_req, masks, allocs, caps


def main():
    import jax
    import jax.numpy as jnp

    from autoscaler_tpu.ops.binpack import ffd_binpack_groups

    P, G, MAX_NODES = 100_000, 500, 1000
    pod_req, masks, allocs, caps = build_workload(P, G)

    jreq = jnp.asarray(pod_req)
    jmasks = jnp.asarray(masks)
    jallocs = jnp.asarray(allocs)
    jcaps = jnp.asarray(caps)

    def run():
        out = ffd_binpack_groups(
            jreq, jmasks, jallocs, max_nodes=MAX_NODES, node_caps=jcaps
        )
        # Host fetch forces completion (async dispatch through the axon relay
        # under-reports otherwise) and is what the control plane consumes.
        return np.asarray(out.node_count), np.asarray(out.scheduled)

    res_counts, res_sched = run()  # compile + warm
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        run()
        times.append(time.perf_counter() - t0)
    t_tpu = float(np.median(times))

    # Serial compiled baseline on a 3-group sample, scaled to G.
    try:
        from autoscaler_tpu.native_bridge import ffd_binpack_native as baseline_ffd

        baseline = "cpp"
    except Exception:
        baseline = "numpy"
    SAMPLE = 3
    sample_times = []
    for g in range(SAMPLE):
        t0 = time.perf_counter()
        if baseline == "cpp":
            ref_count, ref_sched = baseline_ffd(pod_req, masks[g], allocs[g], MAX_NODES)
        else:
            from autoscaler_tpu.estimator.reference_impl import ffd_binpack_reference

            ref_count, ref_sched = ffd_binpack_reference(
                pod_req, masks[g], allocs[g], MAX_NODES
            )
        sample_times.append(time.perf_counter() - t0)
        assert ref_count == int(res_counts[g]), (
            f"parity violation on group {g}: ref={ref_count} tpu={int(res_counts[g])}"
        )
        np.testing.assert_array_equal(res_sched[g], ref_sched)
    t_ref = float(np.median(sample_times)) * G

    value = P * G / t_tpu
    print(
        json.dumps(
            {
                "metric": "scaleup_estimator_throughput_100kpods_500groups",
                "value": round(value, 1),
                "unit": "pod-group-evals/sec",
                "vs_baseline": round(t_ref / t_tpu, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
