"""Headline benchmark: batched TPU scale-up estimation vs the serial
reference algorithm.

Workload is BASELINE config #2: 10k heterogeneous pods (cpu/mem/GPU requests)
x 50 node groups, estimated in ONE batched device dispatch
(ops/binpack.ffd_binpack_groups), versus the serial per-group x per-pod x
per-node loop the reference runs (cluster-autoscaler/estimator/
binpacking_estimator.go:65-141 inside core/scaleup/orchestrator/
orchestrator.go:139-179). The baseline is the numpy serial oracle
(autoscaler_tpu/estimator/reference_impl.py) that mirrors the Go algorithm's
structure, timed on a group subsample and scaled linearly in group count
(each group's estimate is independent and identically sized, so the
extrapolation is exact in expectation).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""
from __future__ import annotations

import json
import time

import numpy as np


def build_workload(P=10_000, G=50, seed=0):
    from autoscaler_tpu.kube.objects import CPU, GPU, MEMORY, PODS

    rng = np.random.default_rng(seed)
    pod_req = np.zeros((P, 6), np.float32)
    pod_req[:, CPU] = rng.integers(50, 2000, P)
    pod_req[:, MEMORY] = rng.integers(64, 8192, P)
    gpu_pods = rng.random(P) < 0.1
    pod_req[gpu_pods, GPU] = rng.integers(1, 4, int(gpu_pods.sum()))
    pod_req[:, PODS] = 1

    allocs = np.zeros((G, 6), np.float32)
    allocs[:, CPU] = rng.choice([4000, 8000, 16000, 32000], G)
    allocs[:, MEMORY] = rng.choice([8192, 16384, 32768, 65536], G)
    gpu_groups = rng.random(G) < 0.2
    allocs[gpu_groups, GPU] = 8
    allocs[:, PODS] = 110

    # simulated non-resource predicate outcomes (taints/selectors)
    masks = rng.random((G, P)) > 0.05
    # gpu pods only schedulable on gpu groups
    masks[np.ix_(~gpu_groups, gpu_pods)] = False
    caps = np.full(G, 128, np.int32)
    return pod_req, masks, allocs, caps


def main():
    import jax
    import jax.numpy as jnp

    from autoscaler_tpu.estimator.reference_impl import ffd_binpack_reference
    from autoscaler_tpu.ops.binpack import ffd_binpack_groups

    P, G, MAX_NODES = 10_000, 50, 128
    pod_req, masks, allocs, caps = build_workload(P, G)

    jreq = jnp.asarray(pod_req)
    jmasks = jnp.asarray(masks)
    jallocs = jnp.asarray(allocs)
    jcaps = jnp.asarray(caps)

    def run():
        out = ffd_binpack_groups(
            jreq, jmasks, jallocs, max_nodes=MAX_NODES, node_caps=jcaps
        )
        # Force completion with a host fetch of everything the control plane
        # actually consumes (block_until_ready alone under-reports through
        # the axon relay: dispatch is async and buffers resolve lazily).
        return np.asarray(out.node_count), np.asarray(out.scheduled)

    res_counts, res_sched = run()  # compile + warm
    times = []
    for _ in range(5):
        t0 = time.perf_counter()
        run()
        times.append(time.perf_counter() - t0)
    t_tpu = float(np.median(times))

    # Serial baseline on a subsample of groups, scaled to G.
    SAMPLE = 2
    t0 = time.perf_counter()
    for g in range(SAMPLE):
        ref_count, ref_sched = ffd_binpack_reference(pod_req, masks[g], allocs[g], MAX_NODES)
        assert ref_count == int(res_counts[g]), (
            f"parity violation on group {g}: ref={ref_count} tpu={int(res_counts[g])}"
        )
        np.testing.assert_array_equal(res_sched[g], ref_sched)
    t_ref = (time.perf_counter() - t0) / SAMPLE * G

    value = P * G / t_tpu
    print(
        json.dumps(
            {
                "metric": "scaleup_estimator_throughput_10kpods_50groups",
                "value": round(value, 1),
                "unit": "pod-group-evals/sec",
                "vs_baseline": round(t_ref / t_tpu, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
