"""Headline benchmark: batched TPU scale-up estimation at the north-star
scale vs a compiled serial baseline.

Workload: the BASELINE.json north-star — 100k pending heterogeneous pods
(cpu/mem/GPU requests) x 500 node groups, max 1000 nodes per group
(the reference's --max-nodes-per-scaleup default, main.go:215), estimated in
ONE batched device dispatch (ops/binpack.ffd_binpack_groups).

Baseline: the C++ serial FFD (native/ffd_serial.cpp), which mirrors the Go
BinpackingNodeEstimator's algorithm (binpacking_estimator.go:65-141) as the
reference's serial per-group loop would run it — a deliberately STRONG
stand-in: it strips the scheduler-framework plugin overhead the real
reference pays per (pod, node) check (its binpacking budget is 10s/group,
main.go:216; the compiled loop here does ~0.1s/group). Sampled on 3 groups
and scaled linearly in group count (groups are independent and identically
distributed). Falls back to the numpy oracle if no C++ toolchain exists.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
vs_baseline = serial_baseline_time / tpu_time on identical work (single
chip; the group axis additionally shards across chips via shard_map —
see __graft_entry__.dryrun_multichip).

Capture is defensive (round-1 lesson: a hung axon backend init produced
rc=1 and no JSON): the parent process runs the measured bench in a child
subprocess with bounded timeouts, retries a wedged TPU backend init once,
then falls back to a CPU run with "platform" labeled honestly in the JSON.
Whatever happens, exactly one parseable JSON line lands on stdout.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

_CHILD_ENV = "AUTOSCALER_TPU_BENCH_CHILD"
_PLATFORM_ENV = "AUTOSCALER_TPU_BENCH_PLATFORM"
# generous: first TPU compile ~20-40s, the tunnel adds latency
_ATTEMPTS = (
    # (platform intent, timeout_s); "default" = whatever the env pins (axon)
    ("default", 600),
    ("default", 600),   # one retry for a transiently wedged tunnel/backend
    ("cpu", 1800),
)

# The CPU fallback runs a SMALLER workload: the full 100k×500 scan measured
# >40min on this host's CPU — past any sane attempt budget — and a CPU
# number is only a liveness signal, not the round's evidence. The shape is
# embedded in the metric name and the JSON's p/g fields, so a fallback can
# never masquerade as the north-star capture (which requires platform=tpu).
_CPU_FALLBACK_SHAPE = {"AUTOSCALER_TPU_BENCH_P": "20000",
                       "AUTOSCALER_TPU_BENCH_G": "100"}


def build_workload(P=100_000, G=500, seed=0):
    from autoscaler_tpu.kube.objects import CPU, GPU, MEMORY, PODS

    rng = np.random.default_rng(seed)
    pod_req = np.zeros((P, 6), np.float32)
    pod_req[:, CPU] = rng.integers(50, 2000, P)
    pod_req[:, MEMORY] = rng.integers(64, 8192, P)
    gpu_pods = rng.random(P) < 0.1
    pod_req[gpu_pods, GPU] = rng.integers(1, 4, int(gpu_pods.sum()))
    pod_req[:, PODS] = 1

    allocs = np.zeros((G, 6), np.float32)
    allocs[:, CPU] = rng.choice([4000, 8000, 16000, 32000], G)
    allocs[:, MEMORY] = rng.choice([8192, 16384, 32768, 65536], G)
    gpu_groups = rng.random(G) < 0.2
    allocs[gpu_groups, GPU] = 8
    allocs[:, PODS] = 110

    # simulated non-resource predicate outcomes (taints/selectors)
    masks = rng.random((G, P)) > 0.05
    # gpu pods only schedulable on gpu groups
    masks[np.ix_(~gpu_groups, gpu_pods)] = False
    caps = np.full(G, 1000, np.int32)
    return pod_req, masks, allocs, caps


def _bench_main():
    import jax

    if os.environ.get(_PLATFORM_ENV) == "cpu":
        # env JAX_PLATFORMS alone is not enough here: the axon site hook
        # re-pins the platform at import, so override via config like
        # tests/conftest.py does
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from autoscaler_tpu.ops.binpack import ffd_binpack_groups

    # env knobs exist for smoke-testing the capture pipeline only; the
    # driver-run bench always uses the north-star 100k x 500 defaults
    P = int(os.environ.get("AUTOSCALER_TPU_BENCH_P", 100_000))
    G = int(os.environ.get("AUTOSCALER_TPU_BENCH_G", 500))
    MAX_NODES = 1000
    pod_req, masks, allocs, caps = build_workload(P, G)

    jreq = jnp.asarray(pod_req)
    jmasks = jnp.asarray(masks)
    jallocs = jnp.asarray(allocs)
    jcaps = jnp.asarray(caps)

    from autoscaler_tpu.ops.bits import pack_result_blob, unpack_result_blob

    def run_with(binpack_fn):
        out = binpack_fn(
            jreq, jmasks, jallocs, max_nodes=MAX_NODES, node_caps=jcaps
        )
        # Host fetch forces completion (block_until_ready does NOT reliably
        # block through the axon relay — measured 83µs "completions") and is
        # what the control plane consumes. counts + scheduled ship as ONE
        # fused blob, bit-packed 8:1 (raw [G, P] bools cost ~1.2s of pure
        # tunnel transfer at 100k×500, and a separate counts fetch costs a
        # second full round-trip).
        blob = np.asarray(pack_result_blob(out.node_count, out.scheduled))
        return unpack_result_blob(blob, G, P)

    def run():
        return run_with(ffd_binpack_groups)

    res_counts, res_sched = run()  # compile + warm
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        run()
        times.append(time.perf_counter() - t0)
    t_xla = float(np.median(times))

    # Pallas VMEM fast path, gated on exact same-run parity with the XLA
    # scan on the full workload: the headline number never comes from an
    # unvalidated kernel (ROADMAP Scale #1). TPU only — interpret mode on
    # CPU is orders of magnitude slower and validated separately in CI.
    # The headline kernel is whichever VALIDATED path is faster this run
    # (round-3 lesson: the first hardware capture showed Pallas slower than
    # the XLA scan until its layout was fixed — parity alone must not pick
    # the kernel).
    kernel = "xla_scan"
    t_tpu = t_xla
    t_pallas = None
    pallas_parity = None
    if jax.default_backend() == "tpu":
        try:
            from autoscaler_tpu.ops.pallas_binpack import ffd_binpack_groups_pallas

            def run_pallas():
                return run_with(ffd_binpack_groups_pallas)

            p_counts, p_sched = run_pallas()  # compile + warm
            if (p_counts == res_counts).all() and (p_sched == res_sched).all():
                ptimes = []
                for _ in range(3):
                    t0 = time.perf_counter()
                    run_pallas()
                    ptimes.append(time.perf_counter() - t0)
                t_pallas = float(np.median(ptimes))
                pallas_parity = "ok"
                if t_pallas < t_xla:
                    t_tpu = t_pallas
                    kernel = "pallas"
            else:
                diff = int((p_sched != res_sched).sum())
                pallas_parity = (
                    f"FAILED: {int((p_counts != res_counts).sum())} group "
                    f"counts and {diff} scheduled bits diverge — using xla_scan"
                )
        except Exception as e:  # noqa: BLE001 — any kernel failure → xla path
            pallas_parity = f"pallas path error: {type(e).__name__}: {e}"

    # Serial compiled baseline, sampled over >=32 groups (round-3 VERDICT:
    # a 3-group sample scaled x500 turned a few hundred ms of host jitter
    # into a +/-30% headline swing). Per group we keep the best of 2 reps
    # (discards scheduler preemption spikes, only ever understates the
    # baseline); across groups we report min/median/max and scale the
    # MEDIAN by G (groups are iid by construction in build_workload).
    try:
        from autoscaler_tpu.native_bridge import ffd_binpack_native as baseline_ffd

        baseline = "cpp"
    except Exception:
        baseline = "numpy"
    SAMPLE = min(32, G)
    stride = max(1, G // SAMPLE)   # spread the sample across the group range
    sample_times = []
    for g in range(0, SAMPLE * stride, stride):
        best = None
        for rep in range(2):
            t0 = time.perf_counter()
            if baseline == "cpp":
                ref_count, ref_sched = baseline_ffd(
                    pod_req, masks[g], allocs[g], MAX_NODES
                )
            else:
                from autoscaler_tpu.estimator.reference_impl import (
                    ffd_binpack_reference,
                )

                ref_count, ref_sched = ffd_binpack_reference(
                    pod_req, masks[g], allocs[g], MAX_NODES
                )
            dt = time.perf_counter() - t0
            best = dt if best is None else min(best, dt)
        sample_times.append(best)
        assert ref_count == int(res_counts[g]), (
            f"parity violation on group {g}: ref={ref_count} tpu={int(res_counts[g])}"
        )
        np.testing.assert_array_equal(res_sched[g], ref_sched)
    t_ref = float(np.median(sample_times)) * G

    value = P * G / t_tpu
    print(
        json.dumps(
            {
                # derived from the actual workload so a knob-shrunk smoke
                # run can never masquerade as the north-star capture
                "metric": f"scaleup_estimator_throughput_{P // 1000}kpods_{G}groups",
                "value": round(value, 1),
                "unit": "pod-group-evals/sec",
                "vs_baseline": round(t_ref / t_tpu, 2),
                "platform": jax.default_backend(),
                "p": P,
                "g": G,
                "device_time_s": round(t_tpu, 4),
                "xla_scan_time_s": round(t_xla, 4),
                **({"pallas_time_s": round(t_pallas, 4)} if t_pallas else {}),
                "kernel": kernel,
                **({"pallas_parity": pallas_parity} if pallas_parity else {}),
                "baseline_time_s": round(t_ref, 2),
                "baseline_kind": baseline,
                "baseline_sample_groups": len(sample_times),
                "baseline_group_min_s": round(float(np.min(sample_times)), 4),
                "baseline_group_median_s": round(
                    float(np.median(sample_times)), 4
                ),
                "baseline_group_max_s": round(float(np.max(sample_times)), 4),
                # BASELINE.json secondary metric: p50 latency of one full
                # batched estimator dispatch (all G groups in one call);
                # t_tpu is already the median of the headline kernel's runs
                "p50_latency_s": round(t_tpu, 4),
            }
        )
    )


def _run_child(platform: str, timeout_s: int):
    """Run the measured bench in a subprocess.

    Returns (parsed_json | None, note, kind) with kind in
    {"ok", "timeout", "error"} — a deterministic child error (e.g. a parity
    assertion) must not be retried through the whole attempt chain."""
    env = dict(os.environ)
    env[_CHILD_ENV] = "1"
    if platform != "default":
        env[_PLATFORM_ENV] = platform
    if platform == "cpu":
        for k, v in _CPU_FALLBACK_SHAPE.items():
            env.setdefault(k, v)  # explicit operator knobs still win
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=env,
            capture_output=True,
            text=True,
            timeout=timeout_s,
        )
    except subprocess.TimeoutExpired:
        return None, f"timeout after {timeout_s}s (platform={platform})", "timeout"
    for line in reversed(proc.stdout.splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line), "ok", "ok"
            except json.JSONDecodeError:
                continue
    tail = (proc.stderr or proc.stdout or "").strip().splitlines()[-3:]
    note = f"rc={proc.returncode} (platform={platform}): " + " | ".join(tail)
    return None, note, "error"


def _probe_backend(timeout_s: int = 150) -> str | None:
    """Cheap subprocess check that the default (TPU) backend initializes at
    all, so a wedged tunnel costs one short probe instead of full bench
    timeouts. Returns None if healthy, else a note."""
    try:
        proc = subprocess.run(
            [sys.executable, "-c", "import jax; print(jax.devices())"],
            capture_output=True,
            text=True,
            timeout=timeout_s,
        )
    except subprocess.TimeoutExpired:
        return f"backend init probe hung >{timeout_s}s"
    if proc.returncode != 0:
        tail = (proc.stderr or "").strip().splitlines()[-2:]
        return f"backend init probe rc={proc.returncode}: " + " | ".join(tail)
    return None


def main():
    if os.environ.get(_CHILD_ENV) == "1":
        _bench_main()
        return
    notes = []
    skip = set()
    for platform, timeout_s in _ATTEMPTS:
        if platform in skip:
            continue
        if platform == "default":
            note = _probe_backend()
            if note is not None:
                print(f"bench: {note}", file=sys.stderr)
                # one more probe before writing the backend off
                note = _probe_backend()
            if note is not None:
                notes.append(note)
                skip.add(platform)
                print(f"bench: {note} — falling back", file=sys.stderr)
                continue
        result, note, kind = _run_child(platform, timeout_s)
        if result is not None:
            if notes and result.get("platform") != "tpu":
                # a fallback capture must say WHY the TPU attempt failed
                result["fallback_reason"] = "; ".join(notes)
            # Persist TPU captures; on a CPU fallback attach the last real
            # TPU capture (clearly labeled, with its own timestamp) so a
            # wedged tunnel degrades the round's evidence instead of
            # erasing it. The headline value/vs_baseline stay the honest
            # numbers of THIS run's platform.
            cache = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                 "bench_last_tpu.json")
            if result.get("platform") == "tpu":
                try:
                    with open(cache, "w") as f:
                        json.dump({**result, "captured_at": time.time()}, f)
                except OSError:
                    pass
            elif os.path.exists(cache):
                try:
                    with open(cache) as f:
                        cap = json.load(f)
                    if isinstance(cap, dict):
                        cap["age_s"] = round(
                            time.time() - cap.get("captured_at", 0)
                        )
                        result["last_tpu_capture"] = cap
                except (OSError, json.JSONDecodeError):
                    pass
            print(json.dumps(result))
            return
        notes.append(note)
        print(f"bench attempt failed: {note}", file=sys.stderr)
        if kind == "error":
            # deterministic failure — retrying the same platform is waste
            skip.add(platform)
    # Total failure still yields one parseable JSON line for the driver.
    print(
        json.dumps(
            {
                "metric": "scaleup_estimator_throughput_100kpods_500groups",
                "value": 0,
                "unit": "pod-group-evals/sec",
                "vs_baseline": 0,
                "error": "; ".join(notes),
            }
        )
    )
    sys.exit(1)


if __name__ == "__main__":
    main()
