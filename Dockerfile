# Build/deploy image for the tpu-autoscaler process.
# Equivalent of the reference's builder/Dockerfile (Go build image) +
# charts/cluster-autoscaler packaging: one image runs the control plane; the
# same image with TPU-enabled jax runs the device sidecar.
FROM python:3.12-slim AS base

RUN apt-get update && apt-get install -y --no-install-recommends \
    g++ protobuf-compiler && rm -rf /var/lib/apt/lists/*

WORKDIR /app
COPY pyproject.toml README.md ./
COPY autoscaler_tpu ./autoscaler_tpu
COPY native ./native

# host control plane needs cpu jax; the sidecar image layers libtpu on top
RUN pip install --no-cache-dir .[rpc] && \
    python -c "import autoscaler_tpu"

# prebuild the native baseline/fallback library
RUN g++ -O3 -shared -fPIC -std=c++17 native/ffd_serial.cpp -o native/libffd_serial.so

EXPOSE 8085
ENTRYPOINT ["tpu-autoscaler"]
CMD ["--address=:8085"]
